"""Minimal pure-Python PostgreSQL wire-protocol client.

Plays the role of the JDBC driver + scalikejdbc connection layer under
the reference's default storage backend
(`storage/jdbc/src/main/scala/.../JDBC{LEvents,Models,...}.scala`,
`JDBCUtils.scala`). No psycopg/pg8000 is assumed — this speaks the v3
frontend/backend protocol directly over a socket:

  - startup + authentication: trust, cleartext password, md5, and
    SCRAM-SHA-256 (RFC 5802/7677; channel binding not used)
  - the EXTENDED query protocol (Parse/Bind/Describe/Execute/Sync) with
    text-format parameters, so values never interpolate into SQL
  - OID-aware result decoding (ints, bools, bytea hex, text), so DAO
    code sees Python types

Thread safety follows the sqlite driver's model: one connection guarded
by an RLock owned by the storage client.

Scope note: this is a storage driver, not a general DBAPI — it
implements exactly what the DAO layer (`sqldao.py`) needs.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import socket
import struct
from base64 import b64decode, b64encode
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple


class PgError(Exception):
    """Server-reported error; `code` is the SQLSTATE (e.g. 23505 =
    unique_violation)."""

    def __init__(self, fields: Dict[str, str]):
        self.fields = fields
        self.code = fields.get("C", "")
        super().__init__(fields.get("M", "postgres error"))


UNIQUE_VIOLATION = "23505"


# -- message encoding (pure functions; unit-tested directly) ---------------

def _msg(tag: bytes, payload: bytes) -> bytes:
    return tag + struct.pack("!I", len(payload) + 4) + payload


def encode_startup(user: str, database: str) -> bytes:
    body = struct.pack("!I", 196608)   # protocol 3.0
    for k, v in (("user", user), ("database", database)):
        body += k.encode() + b"\0" + v.encode() + b"\0"
    body += b"\0"
    return struct.pack("!I", len(body) + 4) + body


def encode_password(password: str) -> bytes:
    return _msg(b"p", password.encode() + b"\0")


def encode_md5_password(user: str, password: str, salt: bytes) -> bytes:
    inner = hashlib.md5(password.encode() + user.encode()).hexdigest()
    outer = hashlib.md5(inner.encode() + salt).hexdigest()
    return encode_password("md5" + outer)


def encode_parse(sql: str) -> bytes:
    return _msg(b"P", b"\0" + sql.encode() + b"\0" + struct.pack("!H", 0))


def encode_bind(params: Sequence[Optional[bytes]]) -> bytes:
    body = b"\0\0"                          # unnamed portal + statement
    body += struct.pack("!H", 1) + struct.pack("!H", 0)   # all text fmt
    body += struct.pack("!H", len(params))
    for p in params:
        if p is None:
            body += struct.pack("!i", -1)
        else:
            body += struct.pack("!I", len(p)) + p
    body += struct.pack("!H", 0)            # result formats: default text
    return _msg(b"B", body)


def encode_describe_portal() -> bytes:
    return _msg(b"D", b"P\0")


def encode_execute() -> bytes:
    return _msg(b"E", b"\0" + struct.pack("!I", 0))


def encode_sync() -> bytes:
    return _msg(b"S", b"")


# -- SCRAM-SHA-256 (RFC 5802), client side ----------------------------------

class ScramClient:
    """SCRAM-SHA-256 without channel binding. Exposed for direct
    unit-testing against the RFC 7677 example exchange."""

    def __init__(self, user: str, password: str,
                 nonce: Optional[str] = None):
        self.user = user
        self.password = password
        self.nonce = nonce or b64encode(os.urandom(18)).decode()
        self.gs2 = "n,,"
        self.client_first_bare = f"n={user},r={self.nonce}"

    def client_first(self) -> str:
        return self.gs2 + self.client_first_bare

    def client_final(self, server_first: str) -> str:
        attrs = dict(kv.split("=", 1) for kv in server_first.split(","))
        combined_nonce = attrs["r"]
        if not combined_nonce.startswith(self.nonce):
            raise PgError({"M": "SCRAM server nonce mismatch", "C": ""})
        salt = b64decode(attrs["s"])
        iters = int(attrs["i"])
        salted = hashlib.pbkdf2_hmac("sha256", self.password.encode(),
                                     salt, iters)
        client_key = hmac.new(salted, b"Client Key", hashlib.sha256).digest()
        stored_key = hashlib.sha256(client_key).digest()
        channel = "c=" + b64encode(self.gs2.encode()).decode()
        final_no_proof = f"{channel},r={combined_nonce}"
        auth_message = ",".join(
            (self.client_first_bare, server_first, final_no_proof)).encode()
        signature = hmac.new(stored_key, auth_message,
                             hashlib.sha256).digest()
        proof = bytes(a ^ b for a, b in zip(client_key, signature))
        self._server_key = hmac.new(salted, b"Server Key",
                                    hashlib.sha256).digest()
        self._auth_message = auth_message
        return final_no_proof + ",p=" + b64encode(proof).decode()

    def verify_server_final(self, server_final: str) -> bool:
        attrs = dict(kv.split("=", 1) for kv in server_final.split(","))
        expect = hmac.new(self._server_key, self._auth_message,
                          hashlib.sha256).digest()
        return hmac.compare_digest(b64decode(attrs["v"]), expect)


# -- result decoding --------------------------------------------------------

_INT_OIDS = {20, 21, 23, 26, 28}
_BOOL_OID = 16
_BYTEA_OID = 17
_FLOAT_OIDS = {700, 701, 1700}


def decode_value(raw: Optional[bytes], oid: int):
    if raw is None:
        return None
    if oid in _INT_OIDS:
        return int(raw)
    if oid == _BOOL_OID:
        return raw == b"t"
    if oid == _BYTEA_OID:
        text = raw.decode()
        if text.startswith("\\x"):
            return bytes.fromhex(text[2:])
        return raw   # legacy escape format not expected from PG >= 9
    if oid in _FLOAT_OIDS:
        return float(raw)
    return raw.decode("utf-8")


def encode_param(v) -> Optional[bytes]:
    if v is None:
        return None
    if isinstance(v, bool):
        return b"true" if v else b"false"
    if isinstance(v, (bytes, bytearray, memoryview)):
        return b"\\x" + bytes(v).hex().encode()
    return str(v).encode("utf-8")


@dataclass
class QueryResult:
    rows: List[tuple]
    rowcount: int


class PgConnection:
    """One socket speaking the extended query protocol, autocommit."""

    # SSLRequest magic (protocol 1234.5679, Postgres docs 55.2.10)
    _SSL_REQUEST = struct.pack("!II", 8, 80877103)

    def __init__(self, host: str = "localhost", port: int = 5432, *,
                 user: str = "postgres", password: str = "",
                 database: str = "postgres", timeout: float = 10.0,
                 allow_cleartext: bool = False,
                 sslmode: str = "prefer"):
        """`sslmode` follows the libpq subset: 'disable' (never TLS),
        'prefer' (TLS if the server supports it, else plaintext — the
        libpq default), 'require' (TLS or fail, no cert verification),
        'verify-full' (TLS with CA + hostname verification)."""
        if sslmode not in ("disable", "prefer", "require", "verify-full"):
            raise ValueError(f"unknown sslmode {sslmode!r}")
        sock = socket.create_connection((host, port), timeout=timeout)
        tls_verified = False
        if sslmode != "disable":
            sock.sendall(self._SSL_REQUEST)
            resp = sock.recv(1)
            if resp not in (b"S", b"N"):
                # EOF or an ErrorResponse from a pre-SSL server:
                # anything but S/N is a hard error (libpq semantics) —
                # proceeding would desynchronize the protocol
                sock.close()
                raise PgError({"M": "SSL negotiation failed: unexpected "
                                    f"server response {resp!r}", "C": ""})
            if resp == b"S":
                import ssl as _ssl
                if sslmode == "verify-full":
                    ctx = _ssl.create_default_context()
                    tls_verified = True
                else:
                    # encryption without authentication (libpq's
                    # require semantics): stops passive sniffing; only
                    # verify-full defends against an active MITM
                    ctx = _ssl.SSLContext(_ssl.PROTOCOL_TLS_CLIENT)
                    ctx.check_hostname = False
                    ctx.verify_mode = _ssl.CERT_NONE
                sock = ctx.wrap_socket(sock, server_hostname=host)
            elif sslmode in ("require", "verify-full"):
                sock.close()
                raise PgError({"M": f"server does not support SSL but "
                                    f"sslmode={sslmode}", "C": ""})
            # 'N' + prefer: continue in plaintext
        self.sock = sock
        self._buf = b""
        self.user = user
        # Cleartext password auth (AuthenticationCleartextPassword) sends
        # the password unencrypted on the socket; a MITM'd or
        # misconfigured server could harvest it. Allowed on loopback
        # (no wire to tap) and on VERIFIED TLS channels
        # (sslmode=verify-full — common with hosted Postgres; an
        # unverified require/prefer channel could be attacker-terminated,
        # so it does NOT qualify), else only by explicit opt-in — md5
        # and SCRAM stay available everywhere.
        try:
            peer = self.sock.getpeername()[0]
        except OSError:
            peer = ""
        self._cleartext_ok = (allow_cleartext or tls_verified
                              or peer in ("127.0.0.1", "::1"))
        self.sock.sendall(encode_startup(user, database))
        self._authenticate(password)
        # drain until ReadyForQuery
        self._wait_ready()

    # -- low-level framing --------------------------------------------------
    def _recv_message(self) -> Tuple[bytes, bytes]:
        while len(self._buf) < 5:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise PgError({"M": "connection closed by server", "C": ""})
            self._buf += chunk
        tag = self._buf[:1]
        (length,) = struct.unpack("!I", self._buf[1:5])
        while len(self._buf) < 1 + length:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise PgError({"M": "connection closed by server", "C": ""})
            self._buf += chunk
        payload = self._buf[5:1 + length]
        self._buf = self._buf[1 + length:]
        return tag, payload

    @staticmethod
    def _error_fields(payload: bytes) -> Dict[str, str]:
        fields = {}
        for part in payload.split(b"\0"):
            if part:
                fields[chr(part[0])] = part[1:].decode("utf-8", "replace")
        return fields

    # -- auth ----------------------------------------------------------------
    def _authenticate(self, password: str) -> None:
        scram: Optional[ScramClient] = None
        while True:
            tag, payload = self._recv_message()
            if tag == b"E":
                raise PgError(self._error_fields(payload))
            if tag != b"R":
                continue   # parameter status / backend key before auth done
            (code,) = struct.unpack("!I", payload[:4])
            if code == 0:
                return
            if code == 3:
                if not self._cleartext_ok:
                    raise PgError({
                        "M": "server requested cleartext password "
                             "authentication over a non-loopback "
                             "connection; refusing (pass "
                             "allow_cleartext=True / set "
                             "PIO_STORAGE_SOURCES_<N>_ALLOW_CLEARTEXT "
                             "to override)", "C": ""})
                self.sock.sendall(encode_password(password))
            elif code == 5:
                self.sock.sendall(encode_md5_password(
                    self.user, password, payload[4:8]))
            elif code == 10:
                mechs = payload[4:].split(b"\0")
                if b"SCRAM-SHA-256" not in mechs:
                    raise PgError({"M": f"unsupported SASL mechanisms "
                                        f"{mechs}", "C": ""})
                scram = ScramClient(self.user, password)
                first = scram.client_first().encode()
                body = (b"SCRAM-SHA-256\0"
                        + struct.pack("!I", len(first)) + first)
                self.sock.sendall(_msg(b"p", body))
            elif code == 11:
                assert scram is not None
                final = scram.client_final(payload[4:].decode())
                self.sock.sendall(_msg(b"p", final.encode()))
            elif code == 12:
                assert scram is not None
                if not scram.verify_server_final(payload[4:].decode()):
                    raise PgError({"M": "SCRAM server signature invalid",
                                   "C": ""})
            else:
                raise PgError({"M": f"unsupported auth method {code}",
                               "C": ""})

    def _wait_ready(self) -> None:
        err = None
        while True:
            tag, payload = self._recv_message()
            if tag == b"E":
                err = PgError(self._error_fields(payload))
            elif tag == b"Z":
                if err:
                    raise err
                return

    # -- queries -------------------------------------------------------------
    def execute(self, sql: str, params: Sequence = ()) -> QueryResult:
        """Run one statement via the extended protocol; `$1..$n`
        placeholders; returns typed rows + affected rowcount."""
        self.sock.sendall(
            encode_parse(sql)
            + encode_bind([encode_param(p) for p in params])
            + encode_describe_portal()
            + encode_execute()
            + encode_sync())
        oids: List[int] = []
        rows: List[tuple] = []
        rowcount = 0
        err: Optional[PgError] = None
        while True:
            tag, payload = self._recv_message()
            if tag == b"T":                       # RowDescription
                (nf,) = struct.unpack("!H", payload[:2])
                off = 2
                oids = []
                for _ in range(nf):
                    end = payload.index(b"\0", off)
                    off = end + 1
                    _table, _attr, oid = struct.unpack(
                        "!IhI", payload[off:off + 10])
                    off += 18
                    oids.append(oid)
            elif tag == b"D":                     # DataRow
                (nf,) = struct.unpack("!H", payload[:2])
                off = 2
                vals = []
                for i in range(nf):
                    (ln,) = struct.unpack("!i", payload[off:off + 4])
                    off += 4
                    if ln == -1:
                        vals.append(None)
                    else:
                        vals.append(decode_value(payload[off:off + ln],
                                                 oids[i]))
                        off += ln
                rows.append(tuple(vals))
            elif tag == b"C":                     # CommandComplete
                words = payload.rstrip(b"\0").split()
                if words and words[-1].isdigit():
                    rowcount = int(words[-1])
            elif tag == b"E":
                err = PgError(self._error_fields(payload))
            elif tag == b"Z":                     # ReadyForQuery
                if err:
                    raise err
                return QueryResult(rows, rowcount)
            # ignore: ParseComplete(1) BindComplete(2) NoData(n)
            # ParameterStatus(S) NoticeResponse(N) EmptyQueryResponse(I)

    def close(self) -> None:
        try:
            self.sock.sendall(_msg(b"X", b""))
        except OSError:
            pass
        self.sock.close()

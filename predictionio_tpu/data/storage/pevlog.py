"""PEVLOG storage driver: the scalable INDEXED event store (HBase role).

The reference's "scalable" event tier is HBase with a designed rowkey —
MD5(entityType-entityId)[16B] ++ millis[8B] ++ uuid[8B] — so entity and
time-range finds become prefix/range scans with filter pushdown
(`storage/hbase/src/main/scala/.../HBEventsUtil.scala:54,77-110`). The
flat EVLOG journal answers every find with a full scan; PEVLOG is the
design that scales: events partition into TIME-BUCKETED segment journals
(one CRC-framed native journal per bucket, `native/eventlog.cpp`), and
each segment carries a sidecar index with

  - min/max event time  -> time-range finds prune whole segments
  - a Bloom filter over (entityType, entityId)  -> entity finds skip
    segments that never saw the entity (the role of HBase's MD5-prefix
    rowkey locality)

Event ids encode their segment bucket (`<bucket_us_hex>-<uuid>`, the
analog of HBase's rowkey-as-eventId, HBEventsUtil.scala:112-135), so
get/delete/duplicate-checks touch exactly one segment. Externally
supplied ids without the prefix still work via full scan.

Sidecar indexes are rebuildable caches: each records the journal byte
size it summarizes ("synced"); a mismatch (crash between append and
index flush, or external appends) triggers a rebuild from the journal —
the journal is always the source of truth. Deletes append tombstone
frames to a per-partition `tombstones.log` that is always replayed
(deletes are rare; segment immutability is what buys the pruning).

Config: PIO_STORAGE_SOURCES_<N>_TYPE=PEVLOG, ..._PATH=<dir>,
..._BUCKET_HOURS=<int, default 24>.
"""

from __future__ import annotations

import hashlib
import json
import threading
import uuid as uuidlib
from base64 import b64decode, b64encode
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Set, Tuple

from predictionio_tpu.data.event import DataMap, Event
from predictionio_tpu.data.storage import base
from predictionio_tpu.data.storage.evlog import (
    _from_us, _payload_to_event, _us,
)
from predictionio_tpu.native.eventlog import EventLog


def _compact_payload(e: Event) -> bytes:
    """PEVLOG's journal codec: microsecond ints instead of ISO-8601
    strings (the evlog codec spends most of its time formatting/parsing
    datetimes — measured ~2x the whole serialization cost at 10M-event
    ingest). `_decode_payload` still reads the evlog JSON form, so
    journals are migratable between the two drivers."""
    obj = {"id": e.event_id, "e": e.event, "et": e.entity_type,
           "ei": e.entity_id, "tus": _us(e.event_time),
           "cus": _us(e.creation_time)}
    if e.target_entity_type:
        obj["tet"] = e.target_entity_type
        obj["tei"] = e.target_entity_id
    if not e.properties.is_empty:
        obj["p"] = dict(e.properties.fields)
    if e.tags:
        obj["g"] = list(e.tags)
    if e.pr_id:
        obj["pr"] = e.pr_id
    return json.dumps(obj, separators=(",", ":")).encode()


def _decode_payload(obj: dict) -> Event:
    if "tus" not in obj:               # evlog-format frame
        return _payload_to_event(obj)
    return Event(
        event=obj["e"], entity_type=obj["et"], entity_id=obj["ei"],
        target_entity_type=obj.get("tet"),
        target_entity_id=obj.get("tei"),
        properties=DataMap(obj.get("p", {})),
        event_time=_from_us(obj["tus"]),
        creation_time=_from_us(obj["cus"]),
        event_id=obj["id"], tags=tuple(obj.get("g", ())),
        pr_id=obj.get("pr"))

_BLOOM_BITS = 1 << 16          # 8 KiB per segment
_BLOOM_HASHES = 4
_IDX_FLUSH_EVERY = 256         # appends between index persists


def _bloom_positions(entity_type: str, entity_id: str) -> List[int]:
    digest = hashlib.md5(
        f"{entity_type}\x00{entity_id}".encode()).digest()
    return [int.from_bytes(digest[i * 4:i * 4 + 4], "little") % _BLOOM_BITS
            for i in range(_BLOOM_HASHES)]


class _SegmentIndex:
    """min/max event time + entity Bloom for one segment journal."""

    def __init__(self):
        self.min_us = None
        self.max_us = None
        self.count = 0
        self.synced = 0          # journal bytes the PERSISTED idx covers
        self.bloom = bytearray(_BLOOM_BITS // 8)
        self.dirty = 0           # appends since last persist
        self.mem_size = 0        # journal bytes the in-memory state covers

    def add(self, ev: Event) -> None:
        t = _us(ev.event_time)
        self.min_us = t if self.min_us is None else min(self.min_us, t)
        self.max_us = t if self.max_us is None else max(self.max_us, t)
        self.count += 1
        for pos in _bloom_positions(ev.entity_type, ev.entity_id):
            self.bloom[pos // 8] |= 1 << (pos % 8)

    def may_contain(self, entity_type: str, entity_id: str) -> bool:
        return all(self.bloom[p // 8] & (1 << (p % 8))
                   for p in _bloom_positions(entity_type, entity_id))

    def overlaps(self, start_us: Optional[int],
                 until_us: Optional[int]) -> bool:
        if self.min_us is None:
            return False
        if start_us is not None and self.max_us < start_us:
            return False
        if until_us is not None and self.min_us >= until_us:
            return False
        return True

    def dump(self) -> dict:
        return {"min_us": self.min_us, "max_us": self.max_us,
                "count": self.count, "synced": self.synced,
                "bloom": b64encode(bytes(self.bloom)).decode()}

    @classmethod
    def load(cls, obj: dict) -> "_SegmentIndex":
        ix = cls()
        ix.min_us = obj["min_us"]
        ix.max_us = obj["max_us"]
        ix.count = obj["count"]
        ix.synced = obj["synced"]
        ix.bloom = bytearray(b64decode(obj["bloom"]))
        return ix


class PevlogStorageClient:
    def __init__(self, config):
        self.base_dir = Path(config.get("PATH", "./.pio_store/pevlog"))
        self.base_dir.mkdir(parents=True, exist_ok=True)
        self.bucket_us = int(config.get("BUCKET_HOURS", 24)) * 3600 * 1_000_000
        self.lock = threading.RLock()
        # seg path -> (size snapshot, {event_id: Event})
        self.replay_cache: Dict[str, Tuple[int, Dict[str, Event]]] = {}
        self.index_cache: Dict[str, _SegmentIndex] = {}
        # observability + the sublinearity contract's test hook
        self.stats = {"segments_pruned": 0, "segments_scanned": 0}

    def close(self) -> None:
        with self.lock:
            for seg, ix in self.index_cache.items():
                if ix.dirty:
                    _persist_index(Path(seg), ix)
                    ix.dirty = 0


def _persist_index(seg_path: Path, ix: _SegmentIndex) -> None:
    ix.synced = seg_path.stat().st_size if seg_path.exists() else 0
    tmp = seg_path.with_suffix(".idx.tmp")
    tmp.write_text(json.dumps(ix.dump()))
    tmp.replace(seg_path.with_suffix(".idx"))


class PevlogEvents(base.EventStore):
    def __init__(self, client: PevlogStorageClient):
        self.c = client

    # -- layout --------------------------------------------------------------
    def _part_dir(self, app_id: int, channel_id: Optional[int]) -> Path:
        suffix = f"_{channel_id}" if channel_id is not None else ""
        return self.c.base_dir / f"app_{app_id}{suffix}"

    def _segment_path(self, part: Path, bucket_us: int) -> Path:
        return part / f"seg_{bucket_us:016x}.log"

    def _bucket_of(self, ev: Event) -> int:
        return (_us(ev.event_time) // self.c.bucket_us) * self.c.bucket_us

    @staticmethod
    def _bucket_from_id(event_id: str) -> Optional[int]:
        head, _, _ = event_id.partition("-")
        try:
            return int(head, 16)
        except ValueError:
            return None

    def _segments(self, part: Path) -> List[Path]:
        if not part.exists():
            return []
        return sorted(part.glob("seg_*.log"))

    # -- index ---------------------------------------------------------------
    def _index(self, seg: Path) -> _SegmentIndex:
        """In-memory index if it covers the journal exactly; else the
        persisted sidecar if IT does; else rebuild from the journal
        (source of truth — covers crashes mid-flush and appends by other
        processes)."""
        key = str(seg)
        size = seg.stat().st_size if seg.exists() else 0
        ix = self.c.index_cache.get(key)
        if ix is not None and ix.mem_size == size:
            return ix
        idx_path = seg.with_suffix(".idx")
        ix = None
        if idx_path.exists():
            try:
                ix = _SegmentIndex.load(json.loads(idx_path.read_text()))
            except (ValueError, KeyError):
                ix = None
        if ix is None or ix.synced != size:
            ix = _SegmentIndex()
            for ev in self._replay_segment(seg).values():
                ix.add(ev)
            _persist_index(seg, ix)
        ix.mem_size = size
        self.c.index_cache[key] = ix
        return ix

    # -- replay --------------------------------------------------------------
    def _replay_segment(self, seg: Path) -> Dict[str, Event]:
        size = seg.stat().st_size if seg.exists() else 0
        cached = self.c.replay_cache.get(str(seg))
        if cached is not None and cached[0] == size:
            return cached[1]
        table: Dict[str, Event] = {}
        for payload in EventLog(str(seg)).payloads():
            obj = json.loads(payload)
            if "$tombstone" in obj:      # migrated evlog journals
                table.pop(obj["$tombstone"], None)
                continue
            e = _decode_payload(obj)
            table[e.event_id] = e
        self.c.replay_cache[str(seg)] = (size, table)
        return table

    def _tombstones(self, part: Path) -> Set[str]:
        path = part / "tombstones.log"
        if not path.exists():
            return set()
        size = path.stat().st_size
        cached = self.c.replay_cache.get(str(path))
        if cached is not None and cached[0] == size:
            return cached[1]
        dead = {json.loads(p)["$tombstone"]
                for p in EventLog(str(path)).payloads()}
        self.c.replay_cache[str(path)] = (size, dead)
        return dead

    # -- contract ------------------------------------------------------------
    def init(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        self._part_dir(app_id, channel_id).mkdir(parents=True,
                                                 exist_ok=True)
        return True

    def remove(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        part = self._part_dir(app_id, channel_id)
        with self.c.lock:
            if part.exists():
                for p in part.iterdir():
                    self.c.replay_cache.pop(str(p), None)
                    self.c.index_cache.pop(str(p), None)
                    p.unlink()
                part.rmdir()
        return True

    def close(self) -> None:
        self.c.close()

    def _new_id(self, ev: Event) -> str:
        return f"{self._bucket_of(ev):016x}-{uuidlib.uuid4().hex}"

    def _insert(self, event: Event, app_id: int,
                channel_id: Optional[int] = None) -> str:
        return self._insert_many([event], app_id, channel_id)[0]

    def _insert_many(self, events, app_id, channel_id=None) -> List[str]:
        """Bulk path: group by segment, one blob append + one index
        update per touched segment."""
        part = self._part_dir(app_id, channel_id)
        part.mkdir(parents=True, exist_ok=True)
        out_ids: List[str] = []
        by_seg: Dict[int, List[Event]] = {}
        batch_ids: Set[str] = set()
        with self.c.lock:
            for event in events:
                if event.event_id:
                    # only externally supplied ids can collide; generated
                    # ids are uuid4 (checking them would force a replay
                    # of the segment per batch — O(N^2) ingest)
                    e = event
                    bucket = self._bucket_of(e)
                    seg = self._segment_path(part, bucket)
                    if (e.event_id in batch_ids
                            or e.event_id in self._replay_segment(seg)):
                        raise base.StorageWriteError(
                            f"Duplicate event id {e.event_id}")
                    batch_ids.add(e.event_id)
                else:
                    e = event.with_id(self._new_id(event))
                    # routing is ALWAYS by event time; an id prefix does
                    # not redirect the event
                    bucket = self._bucket_of(e)
                by_seg.setdefault(bucket, []).append(e)
                out_ids.append(e.event_id)
            for bucket, evs in by_seg.items():
                seg = self._segment_path(part, bucket)
                ix = self._index(seg)
                EventLog(str(seg)).append_many(
                    [_compact_payload(e) for e in evs])
                for e in evs:
                    ix.add(e)
                ix.mem_size = seg.stat().st_size
                ix.dirty += len(evs)
                if ix.dirty >= _IDX_FLUSH_EVERY:
                    _persist_index(seg, ix)
                    ix.dirty = 0
        return out_ids

    def _insert_batch(self, events, app_id, channel_id=None) -> List[str]:
        return self._insert_many(events, app_id, channel_id)

    def get(self, event_id: str, app_id: int,
            channel_id: Optional[int] = None) -> Optional[Event]:
        part = self._part_dir(app_id, channel_id)
        if event_id in self._tombstones(part):
            return None
        bucket = self._bucket_from_id(event_id)
        if bucket is not None:
            seg = self._segment_path(part, bucket)
            ev = self._replay_segment(seg).get(event_id)
            if ev is not None:
                return ev
            # an EXTERNAL id can coincidentally parse as a bucket prefix
            # (e.g. a standard UUID's hex head); fall through to the
            # full scan rather than trusting the fast path's miss
        for seg in self._segments(part):
            ev = self._replay_segment(seg).get(event_id)
            if ev is not None:
                return ev
        return None

    def delete(self, event_id: str, app_id: int,
               channel_id: Optional[int] = None) -> bool:
        with self.c.lock:
            if self.get(event_id, app_id, channel_id) is None:
                return False
            part = self._part_dir(app_id, channel_id)
            EventLog(str(part / "tombstones.log")).append(
                json.dumps({"$tombstone": event_id}).encode())
        return True

    def find(self, app_id: int, channel_id: Optional[int] = None, *,
             start_time=None, until_time=None, entity_type=None,
             entity_id=None, event_names=None,
             target_entity_type=base._UNSET,
             target_entity_id=base._UNSET,
             limit: Optional[int] = None,
             reversed: bool = False) -> Iterator[Event]:
        part = self._part_dir(app_id, channel_id)
        start_us = _us(start_time) if start_time is not None else None
        until_us = _us(until_time) if until_time is not None else None
        dead = self._tombstones(part)
        events: List[Event] = []
        for seg in self._segments(part):
            ix = self._index(seg)
            if not ix.overlaps(start_us, until_us):
                self.c.stats["segments_pruned"] += 1
                continue
            if entity_type is not None and entity_id is not None \
                    and not ix.may_contain(entity_type, entity_id):
                self.c.stats["segments_pruned"] += 1
                continue
            self.c.stats["segments_scanned"] += 1
            for e in self._replay_segment(seg).values():
                if e.event_id in dead:
                    continue
                if base.match_event(
                        e, start_time=start_time, until_time=until_time,
                        entity_type=entity_type, entity_id=entity_id,
                        event_names=event_names,
                        target_entity_type=target_entity_type,
                        target_entity_id=target_entity_id):
                    events.append(e)
        events.sort(key=lambda e: e.event_time, reverse=reversed)
        if limit is not None and limit > 0:
            events = events[:limit]
        return iter(events)

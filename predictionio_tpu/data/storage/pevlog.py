"""PEVLOG storage driver: the scalable INDEXED event store (HBase role).

The reference's "scalable" event tier is HBase with a designed rowkey —
MD5(entityType-entityId)[16B] ++ millis[8B] ++ uuid[8B] — so entity and
time-range finds become prefix/range scans with filter pushdown
(`storage/hbase/src/main/scala/.../HBEventsUtil.scala:54,77-110`). The
flat EVLOG journal answers every find with a full scan; PEVLOG is the
design that scales: events partition into TIME-BUCKETED segment journals
(one CRC-framed native journal per bucket, `native/eventlog.cpp`), and
each segment carries a sidecar index with

  - min/max event time  -> time-range finds prune whole segments
  - a Bloom filter over (entityType, entityId)  -> entity finds skip
    segments that never saw the entity (the role of HBase's MD5-prefix
    rowkey locality)
  - an exact event-name set + a (targetEntityType, targetEntityId)
    Bloom + a (property-name, value) Bloom -> event-name,
    target-entity, and exact property-value finds prune too: the
    field-query pushdown the reference fills with Elasticsearch's
    query DSL (`storage/elasticsearch/.../ESLEvents.scala:308`), at
    segment (skip-index) granularity

Event ids encode their segment bucket (`<bucket_us_hex>-<uuid>`, the
analog of HBase's rowkey-as-eventId, HBEventsUtil.scala:112-135), so
get/delete/duplicate-checks touch exactly one segment. Externally
supplied ids without the prefix still work via full scan.

Sidecar indexes are rebuildable caches: each records the journal byte
size it summarizes ("synced"); a mismatch (crash between append and
index flush, or external appends) triggers a rebuild from the journal —
the journal is always the source of truth. Coverage is computed from the
append's returned byte offsets, never a post-append stat(), so a
concurrent flock'd writer interleaving between index snapshot and append
forces a rebuild instead of silently under-indexed coverage.

Deletes append timed tombstone frames to a per-partition
`tombstones.log` that is always replayed (deletes are rare; segment
immutability is what buys the pruning). An event frame is dead iff a
tombstone for its id carries a deletion time >= the frame's creation
time — so delete-then-reinsert resurrects the id (EVLOG parity) and the
stale frame in the original segment stays dead.

Externally supplied ids are recorded in a per-partition
`external_ids.log` (id -> bucket), giving cross-bucket duplicate
detection and targeted get() without full scans; generated ids are
uuid-fresh and live in their prefix segment, so a fast-path miss on a
generated-shape id is authoritative.

Config: PIO_STORAGE_SOURCES_<N>_TYPE=PEVLOG, ..._PATH=<dir>,
..._BUCKET_HOURS=<int, default 24>.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import re
import threading
from base64 import b64decode, b64encode
from dataclasses import replace
from datetime import datetime, timezone
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Set, Tuple

from predictionio_tpu.data import integrity
from predictionio_tpu.data.event import DataMap, Event
from predictionio_tpu.data.storage import base, columns
from predictionio_tpu.data.storage._scanworker import scan_chunk
from predictionio_tpu.data.storage.evlog import (
    _from_us, _payload_to_event, _us,
)
from predictionio_tpu.native.eventlog import (
    EventLog, MAGIC, _HEADER, framed_size,
)


def _compact_payload(e: Event) -> bytes:
    """PEVLOG's journal codec: microsecond ints instead of ISO-8601
    strings (the evlog codec spends most of its time formatting/parsing
    datetimes — measured ~2x the whole serialization cost at 10M-event
    ingest). `_decode_payload` still reads the evlog JSON form, so
    journals are migratable between the two drivers."""
    return _payload_for(e, e.event_id, _us(e.event_time))


# printable ASCII minus '"' and '\' — strings whose JSON literal is just
# quotes around the raw bytes, needing no escape pass
_JSON_SIMPLE = re.compile(r'^[ -!#-\[\]-~]*$')
_ESC_CACHE: Dict[str, str] = {}


def _jstr(s: str) -> str:
    # fullmatch, not match: '$' would also match before a trailing
    # newline, embedding the raw control character in the frame and
    # corrupting the segment for every future replay
    if _JSON_SIMPLE.fullmatch(s):
        return f'"{s}"'
    return json.dumps(s)


def _jstr_cached(s: str) -> str:
    """Escaped JSON literal for low-cardinality strings (event names,
    entity types): computed once, reused across the whole ingest."""
    r = _ESC_CACHE.get(s)
    if r is None:
        if len(_ESC_CACHE) > 4096:
            _ESC_CACHE.clear()
        r = _ESC_CACHE[s] = json.dumps(s)
    return r


def _payload_for(e: Event, eid: str, t_us: int,
                 eid_safe: bool = False) -> bytes:
    """Journal frame payload with the id/time supplied by the caller —
    the bulk-ingest hot path builds the common frame shape (no target,
    no properties, no tags) by string assembly instead of dict +
    json.dumps, a measured ~3x serialization win at 10M-event scale.
    `eid_safe` skips the JSON-escape check for ids this driver just
    generated (hex + dash, always literal-safe)."""
    if (e.target_entity_type is None and e.properties.is_empty
            and not e.tags and e.pr_id is None):
        idj = f'"{eid}"' if eid_safe else _jstr(eid)
        ct = e.creation_time
        if ct.tzinfo is None:            # _us inlined: ingest hot path
            ct = ct.replace(tzinfo=timezone.utc)
        return (f'{{"id":{idj},"e":{_jstr_cached(e.event)},'
                f'"et":{_jstr_cached(e.entity_type)},'
                f'"ei":{_jstr(e.entity_id)},'
                f'"tus":{t_us},'
                f'"cus":{int(ct.timestamp() * 1_000_000)}}}').encode()
    obj = {"id": eid, "e": e.event, "et": e.entity_type,
           "ei": e.entity_id, "tus": t_us,
           "cus": _us(e.creation_time)}
    if e.target_entity_type:
        obj["tet"] = e.target_entity_type
        obj["tei"] = e.target_entity_id
    if not e.properties.is_empty:
        obj["p"] = dict(e.properties.fields)
    if e.tags:
        obj["g"] = list(e.tags)
    if e.pr_id:
        obj["pr"] = e.pr_id
    return json.dumps(obj, separators=(",", ":")).encode()


def _decode_payload(obj: dict) -> Event:
    if "tus" not in obj:               # evlog-format frame
        return _payload_to_event(obj)
    # trusted construction: frames were validated at insert and
    # CRC-checked at read, and each json.loads dict is owned by this
    # frame — skip the dataclass __init__ and DataMap copy/re-check
    # (measured ~25% of segment replay)
    e = object.__new__(Event)
    e.__dict__.update(
        event=obj["e"], entity_type=obj["et"], entity_id=obj["ei"],
        target_entity_type=obj.get("tet"),
        target_entity_id=obj.get("tei"),
        properties=DataMap._trusted(obj.get("p")),
        event_time=_from_us(obj["tus"]),
        creation_time=_from_us(obj["cus"]),
        event_id=obj["id"], tags=tuple(obj.get("g", ())),
        pr_id=obj.get("pr"))
    return e

_BLOOM_BITS = 1 << 16          # initial size: 8 KiB per segment
_BLOOM_HASHES = 4
# grow the filter when more than 1/_BLOOM_MAX_FILL of its bits are set
# (fp rate at 1/3 fill with 4 hashes ~ 1.2%); a fixed 64k-bit filter
# saturates around ~20k entities per segment, silently disabling the
# pruning that is this driver's whole point
_BLOOM_MAX_FILL = 3
# ~16 bits per expected entity keeps fill ~ 0.22 after sizing
_BLOOM_BITS_PER_ENTITY = 16
# sidecar persist cadence: flush when at least this many appends AND at
# least 1/_IDX_FLUSH_FRACTION of the segment is unpersisted. The
# proportional rule bounds a cold reader's catch-up work (the stale
# tail `_extend_index` decodes) to ~12% of any segment while keeping the
# persist count per segment O(log growth); the absolute floor keeps
# singleton-insert workloads from persisting every event.
_IDX_FLUSH_MIN = 1024
_IDX_FLUSH_FRACTION = 8


def _bloom_bits_for(n: int) -> int:
    bits = _BLOOM_BITS
    while bits < _BLOOM_BITS_PER_ENTITY * max(1, n):
        bits *= 2
    return bits


_DIGEST_CACHE: Dict[tuple, bytes] = {}


def _bloom_digest(key_type: str, key_id: str) -> bytes:
    # entities recur across events (a user has many events): memoize
    # the md5, bounded
    k = (key_type, key_id)
    d = _DIGEST_CACHE.get(k)
    if d is None:
        if len(_DIGEST_CACHE) > (1 << 18):
            _DIGEST_CACHE.clear()
        d = _DIGEST_CACHE[k] = hashlib.md5(
            f"{key_type}\x00{key_id}".encode()).digest()
    return d


def _positions_from(digest: bytes, bits: int) -> List[int]:
    return [int.from_bytes(digest[i * 4:i * 4 + 4], "little") % bits
            for i in range(_BLOOM_HASHES)]


def _bloom_positions(entity_type: str, entity_id: str,
                     bits: int) -> List[int]:
    return _positions_from(_bloom_digest(entity_type, entity_id), bits)


# per-stream cap on remembered digests: beyond this, an index stops
# tracking (and regrows fall back to a journal replay). 1M digests =
# 16 MB — the bound on per-segment tracking memory.
_DIGEST_TRACK_MAX = 1 << 20


def _norm_value(v):
    """Collapse ==-equal values onto one representative: the post-filter
    compares with Python ==, where 10 == 10.0 == True's 1, so the Bloom
    key must not distinguish them (a typed key would falsely PRUNE a
    segment whose event matches; mapping distinct-but-float-colliding
    ints together only adds a false positive, which is just a scan)."""
    if isinstance(v, (bool, int, float)):
        return float(v)
    if isinstance(v, list):
        return [_norm_value(x) for x in v]
    if isinstance(v, dict):
        return {k: _norm_value(x) for k, x in v.items()}
    return v


def _value_key(value) -> str:
    """Canonical string form of a property value for the property Bloom
    (dict key order and numeric type must not change the hash)."""
    return json.dumps(_norm_value(value), sort_keys=True,
                      separators=(",", ":"))


class _SegmentIndex:
    """Per-segment sidecar: min/max event time, entity Bloom, exact
    event-name set, target-entity Bloom, and a (property-name, value)
    Bloom. The field indexes give `find` pushdown on event names,
    target entities, and exact property values — the role the reference
    fills with Elasticsearch's query DSL (`ESLEvents.scala:308`), at
    segment (skip-index) granularity, like HBase filter pushdown for
    the entity/time axes."""

    def __init__(self, bits: int = _BLOOM_BITS):
        self.min_us = None
        self.max_us = None
        self.count = 0
        self.synced = 0          # journal bytes the PERSISTED idx covers
        self.bits = bits
        self.filled = 0          # set bits (saturation tracking)
        self.bloom = bytearray(bits // 8)
        # target-entity and property Blooms share bits/growth with the
        # entity Bloom
        self.tbloom = bytearray(bits // 8)
        self.tfilled = 0
        self.pbloom = bytearray(bits // 8)
        self.pfilled = 0
        self.event_names: Set[str] = set()   # exact: low cardinality
        # True while event_names is known NOT to cover every frame (a
        # legacy sidecar loaded without an 'events' key, then appended
        # to): pruning must be disabled and the partial set must never
        # be persisted, or queries naming only pre-upgrade events would
        # silently skip this segment
        self.names_incomplete = False
        # md5 digests added to each Bloom (entity/target/property) since
        # this object was built. While complete, a saturation regrow
        # re-mods the remembered digests against the bigger filter — no
        # journal replay, no re-hash (the replay-per-regrow was the
        # single largest measured bulk-ingest cost). An index loaded
        # from a sidecar does not know its keys, so it starts incomplete
        # and regrows the slow way once (becoming complete after).
        self.digests: Tuple[list, list, list] = ([], [], [])
        self.digests_complete = True
        self.dirty = 0           # appends since last persist
        self.mem_size = 0        # journal bytes the in-memory state covers

    def _bits_add(self, buf: bytearray, key_type: str, key_id: str,
                  stream: int) -> int:
        d = _bloom_digest(key_type, key_id)
        if self.digests_complete:
            dg = self.digests[stream]
            if len(dg) < _DIGEST_TRACK_MAX:
                dg.append(d)
            else:                      # cap hit: stop tracking, free
                self.digests_complete = False
                self.digests = ([], [], [])
        return self._bits_add_digest(buf, d)

    def _bits_add_digest(self, buf: bytearray, d: bytes) -> int:
        # bits is always a power of two, so `% bits` == `& (bits-1)` of
        # the same little-endian 32-bit word — one 128-bit from_bytes +
        # shifts is bit-compatible with _positions_from and measurably
        # cheaper than four 4-byte reads on the ingest hot path
        v = int.from_bytes(d, "little")
        m = self.bits - 1
        new = 0
        for sh in (0, 32, 64, 96):
            pos = (v >> sh) & m
            byte, bit = pos >> 3, 1 << (pos & 7)
            if not buf[byte] & bit:
                buf[byte] |= bit
                new += 1
        return new

    def _bloom_add(self, entity_type: str, entity_id: str) -> None:
        self.filled += self._bits_add(self.bloom, entity_type, entity_id, 0)

    def add_parts(self, t_us: int, entity_type: str, entity_id: str,
                  event_name: str, tet, tei, props) -> None:
        """Ingest-hot-path add: the caller has already split the event
        into parts (and computed t_us ONCE — datetime conversions were a
        measured double-digit % of bulk-ingest wall-clock)."""
        if self.min_us is None:
            self.min_us = self.max_us = t_us
        else:
            if t_us < self.min_us:
                self.min_us = t_us
            if t_us > self.max_us:
                self.max_us = t_us
        self.count += 1
        self.filled += self._bits_add(self.bloom, entity_type, entity_id,
                                      0)
        self.event_names.add(event_name)
        if tet and tei:
            self.tfilled += self._bits_add(self.tbloom, tet, tei, 1)
        if props:
            for k, v in props.items():
                self.pfilled += self._bits_add(self.pbloom, k,
                                               _value_key(v), 2)

    def add(self, ev: Event) -> None:
        self.add_parts(_us(ev.event_time), ev.entity_type, ev.entity_id,
                       ev.event, ev.target_entity_type,
                       ev.target_entity_id,
                       None if ev.properties.is_empty
                       else ev.properties.fields)

    def _bits_contain(self, buf: bytearray, key_type: str,
                      key_id: str) -> bool:
        return all(buf[p // 8] & (1 << (p % 8))
                   for p in _bloom_positions(key_type, key_id, self.bits))

    def may_contain(self, entity_type: str, entity_id: str) -> bool:
        return self._bits_contain(self.bloom, entity_type, entity_id)

    def may_contain_target(self, tet: str, tei: str) -> bool:
        return self._bits_contain(self.tbloom, tet, tei)

    def may_contain_property(self, name: str, value) -> bool:
        return self._bits_contain(self.pbloom, name, _value_key(value))

    def may_contain_event(self, names) -> bool:
        # empty or incomplete set = a legacy sidecar that never (fully)
        # recorded names: no pruning evidence, must scan
        if self.names_incomplete or not self.event_names:
            return True
        return any(n in self.event_names for n in names)

    @property
    def bloom_saturated(self) -> bool:
        return max(self.filled, self.tfilled,
                   self.pfilled) * _BLOOM_MAX_FILL > self.bits

    def with_grown_bloom(self, events) -> "_SegmentIndex":
        """A NEW index with a filter resized for `events` (this object
        is never mutated: concurrent lock-free readers keep seeing the
        old filter, which is monotonic — saturated-but-correct. The
        caller swaps the new object into the index cache, an atomic
        dict assignment)."""
        events = list(events)
        ix = _SegmentIndex(
            bits=max(_bloom_bits_for(len(events)), self.bits * 2))
        ix.min_us, ix.max_us = self.min_us, self.max_us
        ix.count, ix.synced = self.count, self.synced
        ix.mem_size, ix.dirty = self.mem_size, self.dirty
        # `events` is the full segment: rebuild the name set from it, so
        # a names_incomplete legacy index heals here instead of carrying
        # the flag forward
        ix.event_names = {ev.event for ev in events}
        for ev in events:
            ix._bloom_add(ev.entity_type, ev.entity_id)
            if ev.target_entity_type and ev.target_entity_id:
                ix.tfilled += ix._bits_add(
                    ix.tbloom, ev.target_entity_type, ev.target_entity_id,
                    1)
            if not ev.properties.is_empty:
                for k, v in ev.properties.fields.items():
                    ix.pfilled += ix._bits_add(ix.pbloom, k, _value_key(v),
                                               2)
        return ix

    def regrow_from_digests(self) -> "Optional[_SegmentIndex]":
        """A NEW index with doubled-or-resized filters rebuilt from the
        remembered digests — the cheap regrow (no journal replay, no
        re-hash). None when this index does not know all its keys (it
        was loaded from a sidecar, or tracking hit its cap); the caller
        then falls back to `with_grown_bloom` over a full replay.
        Same immutability contract as with_grown_bloom: this object is
        never mutated, concurrent readers keep a valid filter."""
        if not self.digests_complete:
            return None
        biggest = max(len(s) for s in self.digests)
        # size one doubling AHEAD of the current key count: bulk ingest
        # keeps appending to the segment, and regrowing once per batch
        # re-adds every digest each time (measured ~40% of the Bloom
        # cost at 10M-event scale)
        ix = _SegmentIndex(
            bits=max(_bloom_bits_for(biggest * 2), self.bits * 2))
        ix.min_us, ix.max_us = self.min_us, self.max_us
        ix.count, ix.synced = self.count, self.synced
        ix.mem_size, ix.dirty = self.mem_size, self.dirty
        ix.names_incomplete = self.names_incomplete
        ix.event_names = set(self.event_names)
        # the digest lists transfer: writers are lock-serialized, and
        # the abandoned old object never appends again
        ix.digests = self.digests
        for buf, attr, dg in ((ix.bloom, "filled", self.digests[0]),
                              (ix.tbloom, "tfilled", self.digests[1]),
                              (ix.pbloom, "pfilled", self.digests[2])):
            n = 0
            for d in dg:
                n += ix._bits_add_digest(buf, d)
            setattr(ix, attr, n)
        return ix

    def overlaps(self, start_us: Optional[int],
                 until_us: Optional[int]) -> bool:
        if self.min_us is None:
            return False
        if start_us is not None and self.max_us < start_us:
            return False
        if until_us is not None and self.min_us >= until_us:
            return False
        return True

    def dump(self) -> dict:
        # zlib-compressed filters under NEW key names — pre-sized
        # megabit Blooms are mostly zeros, and persisting them raw was
        # a measured slice of bulk ingest. The rename (zbloom, not
        # bloom+flag) is deliberate: an older reader sharing the store
        # hits KeyError on the missing "bloom", which its loader
        # already treats as a corrupt sidecar and rebuilds from the
        # journal — instead of misreading compressed bytes as a raw
        # filter
        import zlib as _zlib
        enc = lambda b: b64encode(_zlib.compress(bytes(b), 1)).decode()  # noqa: E731
        out = {"min_us": self.min_us, "max_us": self.max_us,
               "count": self.count, "synced": self.synced,
               "bits": self.bits,
               "zbloom": enc(self.bloom),
               "ztbloom": enc(self.tbloom),
               "zpbloom": enc(self.pbloom)}
        # an incomplete name set must not be persisted as if exhaustive:
        # omitting the key keeps the sidecar in legacy (never-prune)
        # form until a full rebuild supplies a complete set
        if not self.names_incomplete:
            out["events"] = sorted(self.event_names)
        return out

    @classmethod
    def load(cls, obj: dict) -> "_SegmentIndex":
        import zlib as _zlib
        ix = cls()
        ix.min_us = obj["min_us"]
        ix.max_us = obj["max_us"]
        ix.count = obj["count"]
        ix.synced = obj["synced"]
        if "zbloom" in obj:              # current compressed form
            dec = lambda s: bytearray(_zlib.decompress(b64decode(s)))  # noqa: E731
            ix.bloom = dec(obj["zbloom"])
            ix.bits = obj.get("bits", len(ix.bloom) * 8)
            ix.tbloom = dec(obj["ztbloom"])
            ix.pbloom = dec(obj["zpbloom"])
        else:                            # legacy raw sidecars
            ix.bloom = bytearray(b64decode(obj["bloom"]))
            ix.bits = obj.get("bits", len(ix.bloom) * 8)
            if "tbloom" in obj:
                ix.tbloom = bytearray(b64decode(obj["tbloom"]))
            else:      # no pruning evidence: never prune
                ix.tbloom = bytearray(b"\xff" * (ix.bits // 8))
            if "pbloom" in obj:
                ix.pbloom = bytearray(b64decode(obj["pbloom"]))
            else:      # pre-property-Bloom sidecar: never prune (the
                # all-ones filter also reads as saturated, so the first
                # append regrows it from a full replay — the heal path)
                ix.pbloom = bytearray(b"\xff" * (ix.bits // 8))
        ix.filled = int.from_bytes(bytes(ix.bloom), "little").bit_count()
        ix.tfilled = int.from_bytes(bytes(ix.tbloom),
                                    "little").bit_count()
        ix.pfilled = int.from_bytes(bytes(ix.pbloom),
                                    "little").bit_count()
        ix.event_names = set(obj.get("events", ()))
        # a legacy sidecar (pre-'events') covers frames whose names were
        # never recorded: appends may NOT flip the set to "non-empty and
        # trusted" — that would prune queries naming only legacy events
        ix.names_incomplete = "events" not in obj
        # a loaded index does not know the keys behind its persisted
        # bits: saturation regrows must replay the journal once
        ix.digests_complete = False
        return ix


class PevlogStorageClient:
    def __init__(self, config):
        self.base_dir = Path(config.get("PATH", "./.pio_store/pevlog"))
        self.base_dir.mkdir(parents=True, exist_ok=True)
        self.bucket_us = int(config.get("BUCKET_HOURS", 24)) * 3600 * 1_000_000
        self.lock = threading.RLock()
        # journal path -> (watermark size, consumed frame-boundary
        # offset, state) where state is an {event_id: Event} table for
        # segments, {id: tomb_us} for tombstones.log, or {id: [buckets]}
        # for external_ids.log (see _scan_journal)
        self.replay_cache: Dict[str, Tuple[int, int, dict]] = {}
        self.index_cache: Dict[str, _SegmentIndex] = {}
        # observability + the sublinearity contract's test hook
        self.stats = {"segments_pruned": 0, "segments_scanned": 0}

    def close(self) -> None:
        with self.lock:
            for seg, ix in self.index_cache.items():
                if ix.dirty:
                    _persist_index(Path(seg), ix)
                    ix.dirty = 0


def _persist_index(seg_path: Path, ix: _SegmentIndex) -> None:
    # synced = the bytes the in-memory state is KNOWN to cover (append
    # offsets, not stat(): a concurrent writer may have grown the file
    # past what this index has seen)
    ix.synced = ix.mem_size
    integrity.atomic_write_bytes(seg_path.with_suffix(".idx"),
                                 json.dumps(ix.dump()).encode())


# generated ids are <16-hex bucket>-<32-hex uuid4>; anything else is an
# externally supplied id (evlog's 32-hex ids don't match: no dash)
_GEN_ID = re.compile(r"^[0-9a-f]{16}-[0-9a-f]{32}$")


def _now_us() -> int:
    return _us(datetime.now(timezone.utc))


# deletion time assigned to tombstone frames written before tombstones
# carried times: far enough in the future to always cover the frame
# (the old semantics), and recognizably out of the valid range so the
# reinsert path can refuse instead of minting an absurd creation time
_LEGACY_TOMB_US = 1 << 62


class PevlogEvents(base.EventStore):
    def __init__(self, client: PevlogStorageClient):
        self.c = client

    # -- layout --------------------------------------------------------------
    def _part_dir(self, app_id: int, channel_id: Optional[int]) -> Path:
        suffix = f"_{channel_id}" if channel_id is not None else ""
        return self.c.base_dir / f"app_{app_id}{suffix}"

    def _segment_path(self, part: Path, bucket_us: int) -> Path:
        return part / f"seg_{bucket_us:016x}.log"

    def _bucket_of(self, ev: Event) -> int:
        return (_us(ev.event_time) // self.c.bucket_us) * self.c.bucket_us

    @staticmethod
    def _bucket_from_id(event_id: str) -> Optional[int]:
        if not _GEN_ID.match(event_id):
            return None
        return int(event_id[:16], 16)

    def _segments(self, part: Path) -> List[Path]:
        if not part.exists():
            return []
        return sorted(part.glob("seg_*.log"))

    # -- index ---------------------------------------------------------------
    def _index(self, seg: Path) -> _SegmentIndex:
        """In-memory index if it covers the journal exactly; else the
        persisted sidecar — EXTENDED over the journal's append-only tail
        when it covers a prefix (`_extend_index`: a cold reader after a
        crash or an unflushed writer decodes only the few-% stale tail,
        never the whole segment); else rebuild from the journal (source
        of truth — covers shrunk journals and corrupt sidecars)."""
        key = str(seg)
        size = seg.stat().st_size if seg.exists() else 0
        ix = self.c.index_cache.get(key)
        if ix is not None and ix.mem_size == size:
            return ix
        idx_path = seg.with_suffix(".idx")
        ix = None
        if idx_path.exists():
            try:
                ix = _SegmentIndex.load(json.loads(idx_path.read_text()))
            except (ValueError, KeyError):
                ix = None
        if ix is not None and ix.synced == size:
            ix.mem_size = ix.synced
        elif ix is not None and 0 < ix.synced < size:
            self._extend_index(seg, ix, size)
        else:
            table = self._replay_segment(seg)
            ix = _SegmentIndex(bits=_bloom_bits_for(len(table)))
            # coverage = the size snapshot the replay was keyed on (the
            # replay may have read past it if a writer raced — the index
            # then over-covers, which can only disable pruning, never
            # cause a false prune)
            snap = self.c.replay_cache[str(seg)][0]
            for ev in table.values():
                ix.add(ev)
            ix.mem_size = snap
            _persist_index(seg, ix)
        self.c.index_cache[key] = ix
        return ix

    def _extend_index(self, seg: Path, ix: _SegmentIndex,
                      size: int) -> None:
        """Catch a prefix-covering sidecar up over the journal tail —
        indexes are add-only, so decoding frames from `synced` onward
        and adding their parts is equivalent to a full rebuild at a
        fraction of the cost (no Event construction, no re-decode of
        covered frames). Migrated-evlog tombstone frames are skipped:
        they only remove table entries, and Bloom bits are monotonic."""
        consumed = ix.synced
        added = 0
        for payload, end in EventLog(str(seg)).scan_from(ix.synced):
            # str input: json.loads on bytes runs detect_encoding
            # per frame (measured ~6% of replay)
            obj = json.loads(payload.decode())
            if "$tombstone" not in obj:
                if "tus" in obj:
                    ix.add_parts(obj["tus"], obj["et"], obj["ei"],
                                 obj["e"], obj.get("tet"),
                                 obj.get("tei"), obj.get("p"))
                else:               # evlog-format frame
                    ix.add(_payload_to_event(obj))
                added += 1
            consumed = end
        ix.mem_size = consumed
        ix.dirty += added
        if added:
            try:
                _persist_index(seg, ix)
                ix.dirty = 0
            except OSError:         # read-only mount: stay in-memory
                pass

    # -- replay --------------------------------------------------------------
    def _scan_journal(self, path: Path, apply_frame) -> dict:
        """Incremental size-keyed journal decode. Cache entries are
        (watermark_size, consumed_offset, state): growth past the
        watermark decodes only the tail from `consumed` (append-only
        journals), with copy-on-write state so lock-free concurrent
        readers keep a consistent snapshot."""
        size = path.stat().st_size if path.exists() else 0
        key = str(path)
        cached = self.c.replay_cache.get(key)
        if cached is not None and cached[1] > size:
            cached = None   # journal shrank (remove/rollback): rescan
        if cached is not None and cached[0] == size:
            return cached[2]
        if cached is not None:
            consumed, state = cached[1], dict(cached[2])
        else:
            consumed, state = 0, {}
        for payload, end in EventLog(key).scan_from(consumed):
            # str input: json.loads on bytes runs detect_encoding
            # per frame (measured ~6% of replay)
            apply_frame(state, json.loads(payload.decode()))
            consumed = end
        self.c.replay_cache[key] = (size, consumed, state)
        return state

    @staticmethod
    def _apply_event_frame(table: dict, obj: dict) -> None:
        if "$tombstone" in obj:          # migrated evlog journals
            table.pop(obj["$tombstone"], None)
            return
        e = _decode_payload(obj)
        table[e.event_id] = e

    def _replay_segment(self, seg: Path) -> Dict[str, Event]:
        return self._scan_journal(seg, self._apply_event_frame)

    @staticmethod
    def _apply_tombstone_frame(dead: dict, obj: dict) -> None:
        tus = obj.get("tus", _LEGACY_TOMB_US)
        key = obj["$tombstone"]
        dead[key] = max(dead.get(key, -1), tus)

    def _tombstones(self, part: Path) -> Dict[str, int]:
        """id -> latest deletion time (us). A frame is dead iff its
        creation time <= that. Legacy untimed tombstones read as
        +inf-ish (always dead, no resurrect)."""
        return self._scan_journal(part / "tombstones.log",
                                  self._apply_tombstone_frame)

    @staticmethod
    def _live(e: Event, dead: Dict[str, int]) -> bool:
        return dead.get(e.event_id, -1) < _us(e.creation_time)

    @staticmethod
    def _apply_ext_frame(ext: dict, obj: dict) -> None:
        # copy-on-write for the inner lists too: concurrent readers may
        # hold the previous snapshot's list objects
        buckets = list(ext.get(obj["x"], ()))
        if obj["b"] not in buckets:
            buckets.append(obj["b"])
        ext[obj["x"]] = buckets

    def _ext_index(self, part: Path) -> Dict[str, List[int]]:
        """id -> buckets an externally supplied id was appended to."""
        return self._scan_journal(part / "external_ids.log",
                                  self._apply_ext_frame)

    # -- contract ------------------------------------------------------------
    def _ensure_ext_log(self, part: Path) -> None:
        """The ext log's existence marks a partition whose external ids
        are all recorded (get()'s generated-shape fast-path miss is then
        authoritative). Upgrading a legacy partition must BACKFILL
        entries for every frame living outside its id's prefix bucket
        before the marker appears — atomically (tmp + rename), so a
        crash mid-backfill doesn't leave a marker that hides data."""
        import fcntl
        path = part / "external_ids.log"
        if path.exists():      # cheap no-lock fast path: the marker is
            return             # never removed once present
        with self.c.lock:   # serialize vs concurrent inserts in THIS
            # process; the flock below extends the exclusion across
            # processes — journal appends are flock'd per-frame, so two
            # processes first-touching a legacy partition could
            # otherwise interleave check/backfill/rename and the loser's
            # rename would clobber frames the winner just appended.
            # The lock file lives OUTSIDE the partition dir: remove()
            # unlinks everything inside it, and an unlinked lock file
            # would let a later process flock a fresh inode concurrently
            # with a holder of the old one
            lockf = (part.parent / f"{part.name}.lock").open("a")
            try:
                fcntl.flock(lockf.fileno(), fcntl.LOCK_EX)
                if path.exists():
                    return
                frames = []
                for seg in self._segments(part):
                    seg_bucket = int(seg.name[4:20], 16)
                    for eid in self._replay_segment(seg):
                        if self._bucket_from_id(eid) != seg_bucket:
                            frames.append(json.dumps(
                                {"x": eid, "b": seg_bucket}).encode())
                tmp = part / "external_ids.log.tmp"
                if tmp.exists():
                    tmp.unlink()
                if frames:
                    EventLog(str(tmp)).append_many(frames)
                else:
                    tmp.touch()
                tmp.replace(path)
                # file identity changed: any cached scan state is stale
                self.c.replay_cache.pop(str(path), None)
            finally:
                lockf.close()   # releases the flock

    def init(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        part = self._part_dir(app_id, channel_id)
        part.mkdir(parents=True, exist_ok=True)
        self._ensure_ext_log(part)
        return True

    def remove(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        part = self._part_dir(app_id, channel_id)
        with self.c.lock:
            if part.exists():
                for p in part.iterdir():
                    self.c.replay_cache.pop(str(p), None)
                    self.c.index_cache.pop(str(p), None)
                    if p.is_dir():       # _prepared ingest cache
                        import shutil
                        shutil.rmtree(p, ignore_errors=True)
                    else:
                        p.unlink()
                part.rmdir()
        return True

    def close(self) -> None:
        self.c.close()

    def fsck(self, repair: bool = False) -> List[dict]:
        """Partition-wide consistency sweep: (1) torn tails on every
        CRC-framed journal (segments, tombstones, external ids) — scans
        already ignore them but they hide future appends; (2) stale or
        missing segment sidecar indexes (crash between append and index
        flush). Repair truncates tails and rebuilds indexes from the
        journal (source of truth)."""
        # flush this process's own batched index state first: on a LIVE
        # store, dirty in-memory indexes make sidecars look stale when
        # nothing is actually wrong
        self.c.close()
        findings: List[dict] = []
        for part in sorted(self.c.base_dir.glob("app_*")):
            if not part.is_dir():
                continue
            for jpath in sorted(part.glob("*.log")):
                valid_end = 0
                for _payload, end in EventLog(str(jpath)).scan_from(0):
                    valid_end = end
                try:
                    size = jpath.stat().st_size
                except OSError:
                    continue
                if size > valid_end:
                    finding = {
                        "kind": "torn_tail", "path": str(jpath),
                        "reason": (f"{size - valid_end} trailing bytes "
                                   "fail frame CRC"),
                        "action": "none"}
                    if repair:
                        with self.c.lock:
                            os.truncate(jpath, valid_end)
                            self.c.replay_cache.pop(str(jpath), None)
                            self.c.index_cache.pop(str(jpath), None)
                        finding["action"] = f"truncated to {valid_end}"
                    findings.append(finding)
            for seg in self._segments(part):
                idx_path = seg.with_suffix(".idx")
                size = seg.stat().st_size if seg.exists() else 0
                synced = -1
                if idx_path.exists():
                    try:
                        synced = _SegmentIndex.load(
                            json.loads(idx_path.read_text())).synced
                    except (ValueError, KeyError):
                        synced = -1
                if synced == size:
                    continue
                finding = {
                    "kind": "stale_index", "path": str(idx_path),
                    "reason": (f"sidecar covers {max(synced, 0)} of "
                               f"{size} journal bytes"),
                    "action": "none"}
                if repair:
                    with self.c.lock:
                        self.c.index_cache.pop(str(seg), None)
                        self._index(seg)   # rebuild/extend + persist
                    finding["action"] = "rebuilt"
                findings.append(finding)
        return findings

    def _insert(self, event: Event, app_id: int,
                channel_id: Optional[int] = None) -> str:
        return self._insert_many([event], app_id, channel_id)[0]

    def _insert_many(self, events, app_id, channel_id=None) -> List[str]:
        """Bulk path: group by segment, one blob append + one index
        update per touched segment. The generated-id fast path never
        clones the Event (dataclass replace + re-validation was a
        measured ~20% of bulk-ingest wall-clock), converts each event
        time to microseconds exactly once, and draws ids from
        os.urandom instead of the slower uuid4 wrapper (same 128 random
        bits)."""
        import os as _os

        part = self._part_dir(app_id, channel_id)
        part.mkdir(parents=True, exist_ok=True)
        self._ensure_ext_log(part)
        bucket_us = self.c.bucket_us
        out_ids: List[str] = []
        # bucket -> list of (event, id, t_us): the event object is the
        # caller's, never cloned; the id travels alongside
        by_seg: Dict[int, List[tuple]] = {}
        batch_ids: Set[str] = set()
        ext_frames: List[bytes] = []
        # one urandom draw for the whole batch (the per-event syscall
        # was measurable at 10M-event scale); 32 hex chars per id
        rand_hex = _os.urandom(16 * len(events)).hex() if events else ""
        rand_pos = 0
        with self.c.lock:
            dead = self._tombstones(part)
            ext = self._ext_index(part)
            for e in events:
                t = e.event_time
                if t.tzinfo is None:     # _us inlined: ingest hot path
                    t = t.replace(tzinfo=timezone.utc)
                t_us = int(t.timestamp() * 1_000_000)
                bucket = (t_us // bucket_us) * bucket_us
                if e.event_id:
                    # only externally supplied ids can collide; generated
                    # ids are 128 random bits (checking them would force
                    # a replay of the segment per batch — O(N^2)
                    # ingest). The ext index pins down every segment an
                    # external id ever landed in, so cross-bucket dups
                    # are caught too.
                    if e.event_id in batch_ids:
                        raise base.StorageWriteError(
                            f"Duplicate event id {e.event_id}")
                    for b in {bucket, *ext.get(e.event_id, ())}:
                        seg = self._segment_path(part, b)
                        prev = self._replay_segment(seg).get(e.event_id)
                        if prev is not None and self._live(prev, dead):
                            raise base.StorageWriteError(
                                f"Duplicate event id {e.event_id}")
                    # delete-then-reinsert: if a tombstone would also
                    # cover the NEW frame (clock tie or skew), nudge its
                    # creation time past the tombstone so it is live
                    tomb = dead.get(e.event_id, -1)
                    if tomb >= _LEGACY_TOMB_US:
                        # an untimed (pre-upgrade) tombstone covers ALL
                        # frames of this id forever; a reinsert would be
                        # silently invisible — refuse instead
                        raise base.StorageWriteError(
                            f"Event id {e.event_id} was deleted by a "
                            "legacy untimed tombstone and cannot be "
                            "reinserted")
                    if tomb >= _us(e.creation_time):
                        e = replace(e, creation_time=_from_us(tomb + 1))
                    batch_ids.add(e.event_id)
                    ext_frames.append(json.dumps(
                        {"x": e.event_id, "b": bucket}).encode())
                    eid = e.event_id
                else:
                    # routing is ALWAYS by event time; an id prefix does
                    # not redirect the event
                    eid = f"{bucket:016x}-{rand_hex[rand_pos:rand_pos + 32]}"
                    rand_pos += 32
                group = by_seg.get(bucket)
                if group is None:
                    group = by_seg[bucket] = []
                group.append((e, eid, t_us))
                out_ids.append(eid)
            # ext records BEFORE the segment appends: a crash in between
            # leaves a harmless unreferenced ext entry, whereas the
            # reverse order would strand a generated-shape external id
            # beyond the reach of get()/delete() (whose targeted miss is
            # authoritative) and of cross-bucket duplicate detection
            if ext_frames:
                EventLog(str(part / "external_ids.log")).append_many(
                    ext_frames)
            for bucket, triples in by_seg.items():
                seg = self._segment_path(part, bucket)
                ix = self._index(seg)
                # pre-size a FRESH segment's Blooms: without this, bulk
                # ingest saturates the default filter repeatedly. The
                # batch is the scale hint (a caller inserting 100k
                # events will insert more), CAPPED at 8x this segment's
                # slice — a batch spread over many segments must not
                # give every segment a whole-batch-sized filter, whose
                # serialization then dominates the sidecar persists
                # (digest-tracked regrows make under-sizing cheap)
                need = _bloom_bits_for(
                    max(ix.count + len(triples),
                        min(len(events), 8 * len(triples))))
                if need > ix.bits and ix.count == 0 and ix.filled == 0 \
                        and ix.tfilled == 0 and ix.pfilled == 0:
                    grown = _SegmentIndex(bits=need)
                    grown.synced = ix.synced
                    grown.mem_size = ix.mem_size
                    grown.dirty = ix.dirty
                    grown.names_incomplete = ix.names_incomplete
                    grown.event_names = set(ix.event_names)
                    ix = grown
                    self.c.index_cache[str(seg)] = ix
                blobs = [_payload_for(e, eid, t_us,
                                      eid_safe=not e.event_id)
                         for e, eid, t_us in triples]
                off, end = EventLog(str(seg)).append_many(blobs)
                if off != ix.mem_size or end - off != framed_size(blobs):
                    # another process appended between our index snapshot
                    # and this append (or interleaved with the legacy
                    # looped fallback): the journal is the source of
                    # truth — rebuild (covers our frames too)
                    self.c.index_cache.pop(str(seg), None)
                    ix = self._index(seg)
                else:
                    add_parts = ix.add_parts
                    for e, eid, t_us in triples:
                        add_parts(t_us, e.entity_type, e.entity_id,
                                  e.event, e.target_entity_type,
                                  e.target_entity_id,
                                  None if e.properties.is_empty
                                  else e.properties.fields)
                    ix.mem_size = end
                    if ix.bloom_saturated:
                        grown = ix.regrow_from_digests()
                        if grown is None:
                            grown = ix.with_grown_bloom(
                                self._replay_segment(seg).values())
                        ix = grown
                        self.c.index_cache[str(seg)] = ix
                ix.dirty += len(triples)
                if ix.dirty >= _IDX_FLUSH_MIN and \
                        ix.dirty * _IDX_FLUSH_FRACTION >= ix.count:
                    _persist_index(seg, ix)
                    ix.dirty = 0
        return out_ids

    def _insert_batch(self, events, app_id, channel_id=None) -> List[str]:
        return self._insert_many(events, app_id, channel_id)

    def get(self, event_id: str, app_id: int,
            channel_id: Optional[int] = None) -> Optional[Event]:
        part = self._part_dir(app_id, channel_id)
        dead = self._tombstones(part)
        bucket = self._bucket_from_id(event_id)
        targets: List[int] = [] if bucket is None else [bucket]
        for b in self._ext_index(part).get(event_id, ()):
            if b not in targets:
                targets.append(b)
        for b in targets:
            ev = self._replay_segment(
                self._segment_path(part, b)).get(event_id)
            if ev is not None and self._live(ev, dead):
                return ev
        if bucket is not None and (part / "external_ids.log").exists():
            # generated-shape ids are either store-generated (live in
            # their prefix segment) or imported (recorded in the ext
            # index) — the targeted miss is authoritative, no full scan.
            # A partition WITHOUT an ext log predates external-id
            # recording: fall through to the scan
            return None
        for seg in self._segments(part):
            ev = self._replay_segment(seg).get(event_id)
            if ev is not None and self._live(ev, dead):
                return ev
        return None

    def delete(self, event_id: str, app_id: int,
               channel_id: Optional[int] = None) -> bool:
        with self.c.lock:
            ev = self.get(event_id, app_id, channel_id)
            if ev is None:
                return False
            part = self._part_dir(app_id, channel_id)
            # clamp to the frame's creation time so events stamped in
            # the future (imports) are still covered by the tombstone
            tus = max(_now_us(), _us(ev.creation_time))
            EventLog(str(part / "tombstones.log")).append(
                json.dumps({"$tombstone": event_id,
                            "tus": tus}).encode())
        return True

    @staticmethod
    def _segment_survives(ix: _SegmentIndex, *, start_us, until_us,
                          entity_type, entity_id, event_names,
                          target_entity_type, target_entity_id,
                          properties) -> bool:
        """Index pushdown shared by `find` and `scan_columns`: True iff
        the segment may hold a matching event and must be replayed."""
        if not ix.overlaps(start_us, until_us):
            return False
        if entity_type is not None and entity_id is not None \
                and not ix.may_contain(entity_type, entity_id):
            return False
        if event_names and not ix.may_contain_event(event_names):
            return False
        if isinstance(target_entity_type, str) \
                and isinstance(target_entity_id, str) \
                and not ix.may_contain_target(target_entity_type,
                                              target_entity_id):
            return False
        # a matching event must carry EVERY filter pair, so one pair
        # definitely absent from the segment prunes it (the ES
        # query-DSL pushdown role, at skip-index granularity)
        if properties and any(
                not ix.may_contain_property(k, v)
                for k, v in properties.items()):
            return False
        return True

    def find(self, app_id: int, channel_id: Optional[int] = None, *,
             start_time=None, until_time=None, entity_type=None,
             entity_id=None, event_names=None,
             target_entity_type=base._UNSET,
             target_entity_id=base._UNSET,
             properties=None,
             limit: Optional[int] = None,
             reversed: bool = False) -> Iterator[Event]:
        part = self._part_dir(app_id, channel_id)
        start_us = _us(start_time) if start_time is not None else None
        until_us = _us(until_time) if until_time is not None else None
        dead = self._tombstones(part)
        events: List[Event] = []
        for seg in self._segments(part):
            if not self._segment_survives(
                    self._index(seg), start_us=start_us, until_us=until_us,
                    entity_type=entity_type, entity_id=entity_id,
                    event_names=event_names,
                    target_entity_type=target_entity_type,
                    target_entity_id=target_entity_id,
                    properties=properties):
                self.c.stats["segments_pruned"] += 1
                continue
            self.c.stats["segments_scanned"] += 1
            for e in self._replay_segment(seg).values():
                if not self._live(e, dead):
                    continue
                if base.match_event(
                        e, start_time=start_time, until_time=until_time,
                        entity_type=entity_type, entity_id=entity_id,
                        event_names=event_names,
                        target_entity_type=target_entity_type,
                        target_entity_id=target_entity_id,
                        properties=properties):
                    events.append(e)
        events.sort(key=lambda e: e.event_time, reverse=reversed)
        if limit is not None and limit > 0:
            events = events[:limit]
        return iter(events)

    # -- columnar training scan ---------------------------------------------
    def scan_columns(self, app_id: int, channel_id: Optional[int] = None, *,
                     start_time=None, until_time=None, entity_type=None,
                     entity_id=None, event_names=None,
                     target_entity_type=base._UNSET,
                     target_entity_id=base._UNSET,
                     properties=None, value_spec=None,
                     require_target: bool = True,
                     workers: Optional[int] = None,
                     since: Optional[Dict[str, int]] = None,
                     upto: Optional[Dict[str, int]] = None
                     ) -> "columns.EventColumns":
        """`find()` semantics, columnar output: identical index pushdown
        and post-filters, but matching frames decode straight into numpy
        columns (no Event/datetime/DataMap per frame) on a chunked
        `PIO_INGEST_WORKERS` process pool. Segments whose Event replay
        is already cached at the current journal size reuse it instead
        of re-reading the journal; segments the raw path can't reproduce
        exactly (legacy frames, in-journal tombstones, external ids)
        fall back to the Event replay per segment. Output is invariant
        under worker count and byte-equivalent to
        `columns_from_events(self.find(...))`.

        With `since=<ingest_watermark snapshot>` only the journal bytes
        appended after that watermark are decoded (the streaming delta
        path, see `_scan_delta`); `upto` pins the exclusive upper bound
        to a second watermark the caller snapshotted before calling."""
        if since is not None:
            return self._scan_delta(
                app_id, channel_id, since=since, upto=upto,
                start_time=start_time, until_time=until_time,
                entity_type=entity_type, entity_id=entity_id,
                event_names=event_names,
                target_entity_type=target_entity_type,
                target_entity_id=target_entity_id,
                properties=properties, value_spec=value_spec,
                require_target=require_target)
        del upto
        procs = ingest_workers(workers)
        part = self._part_dir(app_id, channel_id)
        start_us = _us(start_time) if start_time is not None else None
        until_us = _us(until_time) if until_time is not None else None
        dead = self._tombstones(part)
        spec = columns.normalize_value_spec(value_spec)
        filters = dict(start_time=start_time, until_time=until_time,
                       entity_type=entity_type, entity_id=entity_id,
                       event_names=event_names,
                       target_entity_type=target_entity_type,
                       target_entity_id=target_entity_id,
                       properties=properties)
        if len(dead) > _DEAD_SHIP_MAX:
            # the worker cfg ships the tombstone map with every chunk; a
            # huge one makes the Event path the cheaper option
            return columns.columns_from_events(
                self.find(app_id, channel_id, **filters),
                value_spec, require_target)
        cfg_blob = pickle.dumps(
            {"start_us": start_us, "until_us": until_us,
             "entity_type": entity_type, "entity_id": entity_id,
             "event_names": frozenset(event_names) if event_names else None,
             "tet": columns.encode_target(target_entity_type, base._UNSET),
             "tei": columns.encode_target(target_entity_id, base._UNSET),
             "properties": dict(properties) if properties else None,
             "value_spec": spec, "require_target": require_target,
             "dead": dict(dead)},
            protocol=pickle.HIGHEST_PROTOCOL)
        pool = _scan_pool(procs) if procs > 1 else None
        plan: List[tuple] = []
        for seg in self._segments(part):
            if not self._segment_survives(
                    self._index(seg), start_us=start_us, until_us=until_us,
                    entity_type=entity_type, entity_id=entity_id,
                    event_names=event_names,
                    target_entity_type=target_entity_type,
                    target_entity_id=target_entity_id,
                    properties=properties):
                self.c.stats["segments_pruned"] += 1
                continue
            self.c.stats["segments_scanned"] += 1
            key = str(seg)
            try:
                size = seg.stat().st_size
            except OSError:
                continue
            cached = self.c.replay_cache.get(key)
            if cached is not None and cached[0] == size:
                plan.append(("block", self._event_block(
                    cached[2], dead, filters, spec, require_target)))
                continue
            chunks = (_frame_chunks(seg, size, procs) if pool is not None
                      else [(0, size)])
            futs = [(pool.submit(scan_chunk, key, s, e, cfg_blob)
                     if pool is not None else None, s, e)
                    for s, e in chunks]
            plan.append(("futs", futs, seg))
        blocks: List[tuple] = []
        for entry in plan:
            if entry[0] == "block":
                blocks.append(entry[1])
                continue
            _tag, futs, seg = entry
            seg_blocks: List[tuple] = []
            need_exact = truncated = False
            for fut, s, e in futs:
                if truncated:
                    break
                try:
                    res = (fut.result() if fut is not None
                           else scan_chunk(str(seg), s, e, cfg_blob))
                except Exception:
                    need_exact = True   # pool/worker failure: Event path
                    break
                if res[0] == "exact":
                    need_exact = True
                    break
                _ok, block, consumed = res
                seg_blocks.append(block)
                if consumed < e:
                    # CRC-invalid frame mid-journal: a serial scan stops
                    # there, so later chunks must be dropped too
                    truncated = True
            if need_exact:
                blocks.append(self._event_block(
                    self._replay_segment(seg), dead, filters, spec,
                    require_target))
            else:
                blocks.extend(seg_blocks)
        return columns.merge_blocks(blocks)

    def _scan_delta(self, app_id: int, channel_id: Optional[int], *,
                    since: Dict[str, int],
                    upto: Optional[Dict[str, int]],
                    start_time=None, until_time=None, entity_type=None,
                    entity_id=None, event_names=None,
                    target_entity_type=base._UNSET,
                    target_entity_id=base._UNSET,
                    properties=None, value_spec=None,
                    require_target: bool = True
                    ) -> "columns.EventColumns":
        """Decode ONLY the journal bytes in (since, upto]: per segment,
        frames from the `since` byte offset up to the `upto` size go
        through the exact `scan_chunk` filter/decode path the full scan
        uses, so delta rows are byte-equivalent to the tail of a full
        scan. The result is correct ONLY as an append-delta on top of
        the `since` snapshot, so anything that rewrites history between
        the watermarks raises `DeltaInvalidated` (callers fall back to
        the full scan):

          - tombstones.log grew: a delete may kill rows ALREADY FOLDED
            into the since snapshot;
          - external_ids.log grew: a caller-supplied id can overwrite an
            earlier frame (last-wins), which a pure append-delta would
            double-count;
          - a segment shrank, vanished, or was unreadable (-1): the
            journal was rewritten under us;
          - a delta frame is evlog-legacy / in-journal "$tombstone" /
            externally-identified ("exact" from `scan_chunk`), or a
            torn frame truncates the range;
          - the delta byte span exceeds `PIO_DELTA_MAX_BYTES` (the
            host-memory bound — a full scan is the better tool then).
        """
        part = self._part_dir(app_id, channel_id)
        wm = upto if upto is not None else self.ingest_watermark(
            app_id, channel_id)
        for name in ("tombstones.log", "external_ids.log"):
            if wm.get(name, 0) != since.get(name, 0):
                raise base.DeltaInvalidated(
                    f"{name} changed between watermarks "
                    f"({since.get(name, 0)} -> {wm.get(name, 0)})")
        spans: List[Tuple[str, int, int]] = []   # (seg name, lo, hi)
        for name, lo in since.items():
            if name in ("tombstones.log", "external_ids.log"):
                continue
            hi = wm.get(name)
            if hi is None or hi < lo or lo < 0 or hi < 0:
                raise base.DeltaInvalidated(
                    f"segment {name} rewritten between watermarks "
                    f"({lo} -> {hi})")
        for name, hi in wm.items():
            if name in ("tombstones.log", "external_ids.log"):
                continue
            if hi < 0:
                raise base.DeltaInvalidated(f"segment {name} unreadable")
            lo = since.get(name, 0)
            if hi > lo:
                spans.append((name, lo, hi))
        budget = int(os.environ.get("PIO_DELTA_MAX_BYTES", "")
                     or _DELTA_MAX_BYTES)
        if sum(hi - lo for _, lo, hi in spans) > budget:
            raise base.DeltaInvalidated(
                "delta span exceeds PIO_DELTA_MAX_BYTES "
                f"({sum(h - l for _, l, h in spans)} > {budget})")
        dead = self._tombstones(part)
        if len(dead) > _DEAD_SHIP_MAX:
            raise base.DeltaInvalidated("tombstone map too large for "
                                        "the raw-frame delta decode")
        spec = columns.normalize_value_spec(value_spec)
        start_us = _us(start_time) if start_time is not None else None
        until_us = _us(until_time) if until_time is not None else None
        cfg_blob = pickle.dumps(
            {"start_us": start_us, "until_us": until_us,
             "entity_type": entity_type, "entity_id": entity_id,
             "event_names": frozenset(event_names) if event_names else None,
             "tet": columns.encode_target(target_entity_type, base._UNSET),
             "tei": columns.encode_target(target_entity_id, base._UNSET),
             "properties": dict(properties) if properties else None,
             "value_spec": spec, "require_target": require_target,
             "dead": dict(dead)},
            protocol=pickle.HIGHEST_PROTOCOL)
        blocks: List[tuple] = []
        for name, lo, hi in spans:
            seg = part / name
            # no index pushdown here: the skip-index may not cover the
            # fresh tail yet, and delta spans are small by construction
            status, block, consumed = scan_chunk(str(seg), lo, hi,
                                                 cfg_blob)
            if status != "ok":
                raise base.DeltaInvalidated(
                    f"segment {name} delta needs dict semantics "
                    "(legacy/tombstone/external-id frame)")
            if consumed < hi:
                raise base.DeltaInvalidated(
                    f"segment {name} torn mid-delta at {consumed}")
            self.c.stats["segments_scanned"] += 1
            blocks.append(block)
        return columns.merge_blocks(blocks)

    def _event_block(self, table: Dict[str, Event], dead, filters,
                     spec, require_target: bool) -> tuple:
        """Event-object fallback block for one replayed segment."""
        evs = [e for e in table.values()
               if self._live(e, dead) and base.match_event(e, **filters)]
        return columns.block_from_events(evs, spec, require_target)

    # -- prepared-data cache support -----------------------------------------
    def ingest_watermark(self, app_id: int,
                         channel_id: Optional[int] = None) -> Dict[str, int]:
        """Byte watermarks of every journal feeding a scan. Any append
        grows a segment (or creates one), any delete grows
        tombstones.log, external ids grow external_ids.log — so an
        unchanged watermark proves an unchanged scan result."""
        part = self._part_dir(app_id, channel_id)
        wm: Dict[str, int] = {}
        for seg in self._segments(part):
            try:
                wm[seg.name] = seg.stat().st_size
            except OSError:
                wm[seg.name] = -1
        for name in ("tombstones.log", "external_ids.log"):
            p = part / name
            wm[name] = p.stat().st_size if p.exists() else 0
        return wm

    def ingest_cache_dir(self, app_id: int,
                         channel_id: Optional[int] = None) -> Path:
        return self._part_dir(app_id, channel_id) / "_prepared"

    # -- columnar property aggregation ---------------------------------------
    def aggregate_properties(self, app_id: int,
                             channel_id: Optional[int] = None, *,
                             entity_type: str,
                             start_time=None, until_time=None,
                             required=None):
        """$set/$unset/$delete replay through the pushdown + raw-frame
        scan: segments without property events prune via the name set,
        and surviving frames fold into EventOps without constructing
        Events (the base path decodes every frame into an Event plus
        two datetimes first). Byte-equivalent to the base
        implementation; journals the raw path can't reproduce exactly
        fall back to it."""
        from predictionio_tpu.data import aggregate as agg
        names = ("$set", "$unset", "$delete")
        name_set = frozenset(names)
        part = self._part_dir(app_id, channel_id)
        start_us = _us(start_time) if start_time is not None else None
        until_us = _us(until_time) if until_time is not None else None
        dead = self._tombstones(part)
        rows: List[tuple] = []   # (tus, seq, name, entity_id, props|None)
        seq = 0
        for seg in self._segments(part):
            if not self._segment_survives(
                    self._index(seg), start_us=start_us, until_us=until_us,
                    entity_type=entity_type, entity_id=None,
                    event_names=names, target_entity_type=base._UNSET,
                    target_entity_id=base._UNSET, properties=None):
                self.c.stats["segments_pruned"] += 1
                continue
            self.c.stats["segments_scanned"] += 1
            key = str(seg)
            try:
                size = seg.stat().st_size
            except OSError:
                continue
            cached = self.c.replay_cache.get(key)
            if cached is not None and cached[0] == size:
                for e in cached[2].values():
                    if e.event not in name_set \
                            or e.entity_type != entity_type \
                            or not self._live(e, dead) \
                            or not base.match_event(
                                e, start_time=start_time,
                                until_time=until_time):
                        continue
                    rows.append((columns._event_us(e), seq, e.event,
                                 e.entity_id, e.properties._fields))
                    seq += 1
                continue
            for payload, _end in EventLog(key).scan_from(0):
                obj = json.loads(payload.decode())
                if "$tombstone" in obj or "tus" not in obj \
                        or not _GEN_ID.match(obj["id"]):
                    # dict-replay semantics needed: base path instead
                    return super().aggregate_properties(
                        app_id, channel_id, entity_type=entity_type,
                        start_time=start_time, until_time=until_time,
                        required=required)
                if obj["e"] not in name_set or obj["et"] != entity_type:
                    continue
                tus = obj["tus"]
                if start_us is not None and tus < start_us:
                    continue
                if until_us is not None and tus >= until_us:
                    continue
                if dead and dead.get(obj["id"], -1) >= obj["cus"]:
                    continue
                rows.append((tus, seq, obj["e"], obj["ei"], obj.get("p")))
                seq += 1
        rows.sort(key=lambda r: (r[0], r[1]))   # find()'s stable time sort
        ops: Dict[str, agg.EventOp] = {}
        for tus, _seq, name, ei, p in rows:
            op = agg.op_from_parts(
                name, p, columns.t_millis_from_us_scalar(tus))
            prev = ops.get(ei)
            ops[ei] = op if prev is None else prev.combine(op)
        out = {}
        for ei, op in ops.items():
            pm = op.to_property_map()
            if pm is not None:
                out[ei] = pm
        if required:
            req = list(required)
            out = {k: v for k, v in out.items()
                   if all(r in v.fields for r in req)}
        return out


# -- ingest worker pool ------------------------------------------------------

_CHUNK_MIN_BYTES = 1 << 20      # don't chunk journals under 1 MiB
_DEAD_SHIP_MAX = 50_000         # tombstone-map size cap for worker cfg
_DELTA_MAX_BYTES = 64 * 1024 * 1024   # delta host-memory bound default
_SCAN_POOL = None
_SCAN_POOL_PROCS = 0            # -1 = pools unusable in this process
_SCAN_POOL_LOCK = threading.Lock()


def ingest_workers(override: Optional[int] = None) -> int:
    """Scan parallelism: explicit override, else PIO_INGEST_WORKERS,
    else 1 (serial in-process decode)."""
    if override is not None:
        return max(1, int(override))
    try:
        return max(1, int(os.environ.get("PIO_INGEST_WORKERS", "1") or "1"))
    except ValueError:
        return 1


def _scan_pool(procs: int):
    """Persistent spawn-start worker pool. Spawn, not fork: the parent
    may hold jax/XLA runtime threads that a fork would deadlock. The
    ~0.5 s startup is paid once per process and amortized across every
    scan. Returns None when pools can't start (sandboxes, missing
    semaphores) — callers then decode inline."""
    global _SCAN_POOL, _SCAN_POOL_PROCS
    with _SCAN_POOL_LOCK:
        if _SCAN_POOL_PROCS == -1:
            return None
        if _SCAN_POOL is not None and _SCAN_POOL_PROCS >= procs:
            return _SCAN_POOL
        try:
            import multiprocessing
            from concurrent.futures import ProcessPoolExecutor
            pool = ProcessPoolExecutor(
                max_workers=procs,
                mp_context=multiprocessing.get_context("spawn"))
            pool.submit(int, 0).result(timeout=120)   # fail fast, not mid-scan
            if _SCAN_POOL is not None:
                _SCAN_POOL.shutdown(wait=False)
            _SCAN_POOL, _SCAN_POOL_PROCS = pool, procs
            _count_pool_spawn()
            return pool
        except Exception:
            _SCAN_POOL_PROCS = -1
            return None


def _count_pool_spawn() -> None:
    """`pio_ingest_pool_spawns_total` is the steady-state proof that the
    spawn pool is REUSED across refresher ticks / cache invalidations:
    flat after warmup, climbing = something is tearing the pool down."""
    try:
        from predictionio_tpu.obs import metrics as obs_metrics
        obs_metrics.get_registry().counter(
            "pio_ingest_pool_spawns_total",
            "Spawn-start scan worker pools created (flat in steady "
            "state: the pool is shared across scans)").inc()
    except Exception:   # noqa: BLE001 — metrics must never break a scan
        pass


def _frame_chunks(path: Path, size: int, procs: int):
    """Frame-aligned byte ranges for chunked decode. Header-only walk
    (lengths, no CRC — workers verify payloads); stops at the first
    torn header exactly where a serial scan would."""
    target = max(size // max(procs, 1), _CHUNK_MIN_BYTES)
    try:
        with open(path, "rb") as f:
            data = f.read(size)
    except OSError:
        return []
    hsz = _HEADER.size
    unpack = _HEADER.unpack_from
    bounds = [0]
    pos = 0
    n = len(data)
    while pos + hsz <= n:
        magic, length, _crc = unpack(data, pos)
        if magic != MAGIC or length > (1 << 30):
            break
        nxt = pos + hsz + length
        if nxt > n:
            break
        pos = nxt
        if pos - bounds[-1] >= target:
            bounds.append(pos)
    if pos > bounds[-1]:
        bounds.append(pos)
    return [(bounds[i], bounds[i + 1]) for i in range(len(bounds) - 1)]

"""SQLite storage driver ("SQLITE" type) — the default persistent backend.

Plays the role of the reference's JDBC driver
(`storage/jdbc/src/main/scala/.../JDBC{LEvents,Models,...}.scala`): one SQL
backend implementing every DAO, with per-(app,channel) event tables named
`events_<appId>[_<channelId>]` (mirroring JDBCUtils.eventTableName).

A single serialized connection guarded by an RLock keeps this correct under
the threaded HTTP servers; SQLite WAL mode keeps readers unblocked.
"""

from __future__ import annotations

import json
import sqlite3
import threading
import uuid
from datetime import datetime
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Sequence

from predictionio_tpu.data.event import (
    DataMap, Event, from_millis, to_millis, utcnow,
)
from predictionio_tpu.data.storage import base, columns
from predictionio_tpu.data.storage.base import (
    AccessKey, App, Channel, EngineInstance, EvaluationInstance, Model,
    SLOObjective, TenantQuota, _UNSET,
    match_properties as _match_properties,
)


# Meta-table DDL in SQLite dialect; the Postgres driver reuses this list
# through its dialect translation (`postgres._translate`), so the two SQL
# backends can never drift apart structurally.
META_DDL = (
    """CREATE TABLE IF NOT EXISTS apps (
        id INTEGER PRIMARY KEY AUTOINCREMENT,
        name TEXT NOT NULL UNIQUE,
        description TEXT)""",
    """CREATE TABLE IF NOT EXISTS access_keys (
        accesskey TEXT PRIMARY KEY,
        appid INTEGER NOT NULL,
        events TEXT NOT NULL)""",
    """CREATE TABLE IF NOT EXISTS channels (
        id INTEGER PRIMARY KEY AUTOINCREMENT,
        name TEXT NOT NULL,
        appid INTEGER NOT NULL)""",
    """CREATE TABLE IF NOT EXISTS engine_instances (
        id TEXT PRIMARY KEY, status TEXT, starttime INTEGER,
        endtime INTEGER, engineid TEXT, engineversion TEXT,
        enginevariant TEXT, enginefactory TEXT, batch TEXT,
        env TEXT, runtimeconf TEXT, datasourceparams TEXT,
        preparatorparams TEXT, algorithmsparams TEXT,
        servingparams TEXT, heartbeat INTEGER)""",
    """CREATE TABLE IF NOT EXISTS evaluation_instances (
        id TEXT PRIMARY KEY, status TEXT, starttime INTEGER,
        endtime INTEGER, evaluationclass TEXT,
        engineparamsgeneratorclass TEXT, batch TEXT, env TEXT,
        runtimeconf TEXT, evaluatorresults TEXT,
        evaluatorresultshtml TEXT, evaluatorresultsjson TEXT)""",
    """CREATE TABLE IF NOT EXISTS models (
        id TEXT PRIMARY KEY, models BLOB)""",
    """CREATE TABLE IF NOT EXISTS models_quarantine (
        id TEXT PRIMARY KEY, models BLOB, reason TEXT,
        quarantined_at INTEGER)""",
    """CREATE TABLE IF NOT EXISTS leases (
        name TEXT PRIMARY KEY, holder TEXT NOT NULL,
        expires_ms INTEGER NOT NULL, journal TEXT NOT NULL)""",
    """CREATE TABLE IF NOT EXISTS tenant_quotas (
        appid INTEGER, rate REAL, burst REAL,
        concurrency INTEGER, queue_max INTEGER, weight REAL,
        channel TEXT NOT NULL DEFAULT '',
        PRIMARY KEY (appid, channel))""",
    """CREATE TABLE IF NOT EXISTS slo_objectives (
        appid INTEGER PRIMARY KEY, latency_ms REAL, target REAL)""",
    # ingest watermark: one generation counter per event table, bumped
    # inside every write transaction — the monotone content fingerprint
    # behind `ingest_watermark()` (prepared-data cache + refresher noop
    # detection for SQL stores)
    """CREATE TABLE IF NOT EXISTS events_ingest_gen (
        tbl TEXT PRIMARY KEY, gen INTEGER NOT NULL)""",
)

# Additive schema migrations for stores created before a column existed;
# each statement is applied best-effort (duplicate-column errors from
# already-migrated stores are swallowed). Postgres runs the same list
# through its dialect translation.
META_MIGRATIONS = (
    "ALTER TABLE engine_instances ADD COLUMN heartbeat INTEGER",
    "ALTER TABLE models_quarantine ADD COLUMN quarantined_at INTEGER",
    # per-channel quotas: add the column everywhere; on Postgres also
    # swap the single-column PK for a composite unique index (the
    # `ON CONFLICT (appid, channel)` upsert target). sqlite rejects
    # DROP CONSTRAINT (swallowed) and instead rebuilds the table in
    # `_rebuild_tenant_quotas` — it cannot ALTER a primary key.
    "ALTER TABLE tenant_quotas ADD COLUMN channel TEXT NOT NULL DEFAULT ''",
    "ALTER TABLE tenant_quotas DROP CONSTRAINT tenant_quotas_pkey",
    "CREATE UNIQUE INDEX IF NOT EXISTS tenant_quotas_app_channel "
    "ON tenant_quotas (appid, channel)",
)


class SQLiteStorageClient:
    """Owns the sqlite connection; all DAOs of a source share one client."""

    def __init__(self, config: Optional[dict] = None):
        self.config = dict(config or {})
        path = self.config.get("PATH", self.config.get("path", ":memory:"))
        if path != ":memory:":
            path = str(Path(path).expanduser())
        self.path = path
        self.lock = threading.RLock()
        self.conn = sqlite3.connect(self.path, check_same_thread=False)
        self.conn.execute("PRAGMA journal_mode=WAL")
        self.conn.execute("PRAGMA synchronous=NORMAL")
        self._init_meta_tables()

    def _init_meta_tables(self) -> None:
        with self.lock, self.conn:
            for ddl in META_DDL:
                self.conn.execute(ddl)
        for mig in META_MIGRATIONS:
            try:
                with self.lock, self.conn:
                    self.conn.execute(mig)
            except sqlite3.OperationalError:
                pass  # column already exists (fresh DDL or prior migration)
        self._rebuild_tenant_quotas()

    def _rebuild_tenant_quotas(self) -> None:
        """sqlite cannot ALTER a PRIMARY KEY: a store created before
        per-channel quotas keeps PK(appid), and a channel upsert would
        silently REPLACE the app-wide row instead of adding a sibling.
        Detect the stale key via PRAGMA and rebuild the table with the
        composite key, preserving every row."""
        with self.lock:
            cols = self.conn.execute(
                "PRAGMA table_info(tenant_quotas)").fetchall()
        pk = {row[1] for row in cols if row[5]}   # (cid, name, ..., pk)
        if pk == {"appid", "channel"}:
            return
        ddl = next(d for d in META_DDL
                   if "IF NOT EXISTS tenant_quotas" in d)
        with self.lock, self.conn:
            self.conn.execute(
                "ALTER TABLE tenant_quotas RENAME TO tenant_quotas_old")
            self.conn.execute(ddl)
            self.conn.execute(
                "INSERT INTO tenant_quotas (appid, rate, burst,"
                " concurrency, queue_max, weight, channel)"
                " SELECT appid, rate, burst, concurrency, queue_max,"
                " weight, '' FROM tenant_quotas_old")
            self.conn.execute("DROP TABLE tenant_quotas_old")

    def close(self) -> None:
        with self.lock:
            self.conn.close()


def event_table_name(app_id: int, channel_id: Optional[int]) -> str:
    """`events_<appId>[_<channelId>]` (JDBCUtils.eventTableName)."""
    return f"events_{app_id}" + (f"_{channel_id}" if channel_id is not None else "")


class SQLiteApps(base.Apps):
    def __init__(self, client: SQLiteStorageClient):
        self.c = client

    def insert(self, app: App) -> Optional[int]:
        try:
            with self.c.lock, self.c.conn:
                if app.id:
                    self.c.conn.execute(
                        "INSERT INTO apps (id, name, description) VALUES (?,?,?)",
                        (app.id, app.name, app.description))
                    return app.id
                cur = self.c.conn.execute(
                    "INSERT INTO apps (name, description) VALUES (?,?)",
                    (app.name, app.description))
                return cur.lastrowid
        except sqlite3.IntegrityError as ex:
            raise base.StorageWriteError(
                f"App id or name already exists ({ex})") from ex

    def get(self, app_id: int) -> Optional[App]:
        with self.c.lock:
            row = self.c.conn.execute(
                "SELECT id, name, description FROM apps WHERE id=?",
                (app_id,)).fetchone()
        return App(*row) if row else None

    def get_by_name(self, name: str) -> Optional[App]:
        with self.c.lock:
            row = self.c.conn.execute(
                "SELECT id, name, description FROM apps WHERE name=?",
                (name,)).fetchone()
        return App(*row) if row else None

    def get_all(self) -> List[App]:
        with self.c.lock:
            rows = self.c.conn.execute(
                "SELECT id, name, description FROM apps ORDER BY id").fetchall()
        return [App(*r) for r in rows]

    def update(self, app: App) -> None:
        with self.c.lock, self.c.conn:
            self.c.conn.execute(
                "UPDATE apps SET name=?, description=? WHERE id=?",
                (app.name, app.description, app.id))

    def delete(self, app_id: int) -> None:
        with self.c.lock, self.c.conn:
            self.c.conn.execute("DELETE FROM apps WHERE id=?", (app_id,))


class SQLiteAccessKeys(base.AccessKeys):
    def __init__(self, client: SQLiteStorageClient):
        self.c = client

    def insert(self, k: AccessKey) -> Optional[str]:
        key = k.key or self.generate_key()
        try:
            with self.c.lock, self.c.conn:
                self.c.conn.execute(
                    "INSERT INTO access_keys (accesskey, appid, events) VALUES (?,?,?)",
                    (key, k.appid, json.dumps(list(k.events))))
        except sqlite3.IntegrityError as ex:
            raise base.StorageWriteError(
                f"Access key {key!r} already exists") from ex
        return key

    def get(self, key: str) -> Optional[AccessKey]:
        with self.c.lock:
            row = self.c.conn.execute(
                "SELECT accesskey, appid, events FROM access_keys "
                "WHERE accesskey=?", (key,)).fetchone()
        return AccessKey(row[0], row[1], tuple(json.loads(row[2]))) if row else None

    def get_all(self) -> List[AccessKey]:
        with self.c.lock:
            rows = self.c.conn.execute(
                "SELECT accesskey, appid, events FROM access_keys").fetchall()
        return [AccessKey(r[0], r[1], tuple(json.loads(r[2]))) for r in rows]

    def get_by_appid(self, appid: int) -> List[AccessKey]:
        with self.c.lock:
            rows = self.c.conn.execute(
                "SELECT accesskey, appid, events FROM access_keys WHERE appid=?",
                (appid,)).fetchall()
        return [AccessKey(r[0], r[1], tuple(json.loads(r[2]))) for r in rows]

    def update(self, k: AccessKey) -> None:
        with self.c.lock, self.c.conn:
            self.c.conn.execute(
                "UPDATE access_keys SET appid=?, events=? WHERE accesskey=?",
                (k.appid, json.dumps(list(k.events)), k.key))

    def delete(self, key: str) -> None:
        with self.c.lock, self.c.conn:
            self.c.conn.execute(
                "DELETE FROM access_keys WHERE accesskey=?", (key,))


class SQLiteChannels(base.Channels):
    def __init__(self, client: SQLiteStorageClient):
        self.c = client

    def insert(self, channel: Channel) -> Optional[int]:
        try:
            with self.c.lock, self.c.conn:
                if channel.id:
                    self.c.conn.execute(
                        "INSERT INTO channels (id, name, appid) VALUES (?,?,?)",
                        (channel.id, channel.name, channel.appid))
                    return channel.id
                cur = self.c.conn.execute(
                    "INSERT INTO channels (name, appid) VALUES (?,?)",
                    (channel.name, channel.appid))
                return cur.lastrowid
        except sqlite3.IntegrityError as ex:
            raise base.StorageWriteError(
                f"Channel id {channel.id} already exists") from ex

    def get(self, channel_id: int) -> Optional[Channel]:
        with self.c.lock:
            row = self.c.conn.execute(
                "SELECT id, name, appid FROM channels WHERE id=?",
                (channel_id,)).fetchone()
        return Channel(*row) if row else None

    def get_by_appid(self, appid: int) -> List[Channel]:
        with self.c.lock:
            rows = self.c.conn.execute(
                "SELECT id, name, appid FROM channels WHERE appid=? ORDER BY id",
                (appid,)).fetchall()
        return [Channel(*r) for r in rows]

    def delete(self, channel_id: int) -> None:
        with self.c.lock, self.c.conn:
            self.c.conn.execute("DELETE FROM channels WHERE id=?", (channel_id,))


class SQLiteEngineInstances(base.EngineInstances):
    COLS = ("id, status, starttime, endtime, engineid, engineversion, "
            "enginevariant, enginefactory, batch, env, runtimeconf, "
            "datasourceparams, preparatorparams, algorithmsparams, "
            "servingparams, heartbeat")

    def __init__(self, client: SQLiteStorageClient):
        self.c = client

    def _to_row(self, i: EngineInstance):
        return (i.id, i.status, to_millis(i.start_time), to_millis(i.end_time),
                i.engine_id, i.engine_version, i.engine_variant,
                i.engine_factory, i.batch, json.dumps(dict(i.env)),
                json.dumps(dict(i.runtime_conf)), i.data_source_params,
                i.preparator_params, i.algorithms_params, i.serving_params,
                to_millis(i.heartbeat) if i.heartbeat is not None else None)

    @staticmethod
    def _from_row(r) -> EngineInstance:
        return EngineInstance(
            id=r[0], status=r[1], start_time=from_millis(r[2]),
            end_time=from_millis(r[3]), engine_id=r[4], engine_version=r[5],
            engine_variant=r[6], engine_factory=r[7], batch=r[8],
            env=json.loads(r[9]), runtime_conf=json.loads(r[10]),
            data_source_params=r[11], preparator_params=r[12],
            algorithms_params=r[13], serving_params=r[14],
            heartbeat=from_millis(r[15]) if r[15] is not None else None)

    def insert(self, i: EngineInstance) -> str:
        iid = i.id or uuid.uuid4().hex
        i = i.with_(id=iid)
        with self.c.lock, self.c.conn:
            self.c.conn.execute(
                f"INSERT INTO engine_instances ({self.COLS}) VALUES "
                "(?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?)", self._to_row(i))
        return iid

    def get(self, iid: str) -> Optional[EngineInstance]:
        with self.c.lock:
            row = self.c.conn.execute(
                f"SELECT {self.COLS} FROM engine_instances WHERE id=?",
                (iid,)).fetchone()
        return self._from_row(row) if row else None

    def get_all(self) -> List[EngineInstance]:
        with self.c.lock:
            rows = self.c.conn.execute(
                f"SELECT {self.COLS} FROM engine_instances").fetchall()
        return [self._from_row(r) for r in rows]

    def get_completed(self, engine_id, engine_version, engine_variant):
        with self.c.lock:
            rows = self.c.conn.execute(
                f"SELECT {self.COLS} FROM engine_instances WHERE status=? AND "
                "engineid=? AND engineversion=? AND enginevariant=? "
                "ORDER BY starttime DESC",
                (base.EngineInstanceStatus.COMPLETED, engine_id,
                 engine_version, engine_variant)).fetchall()
        return [self._from_row(r) for r in rows]

    def get_latest_completed(self, engine_id, engine_version, engine_variant):
        rows = self.get_completed(engine_id, engine_version, engine_variant)
        return rows[0] if rows else None

    def update(self, i: EngineInstance) -> None:
        with self.c.lock, self.c.conn:
            self.c.conn.execute(
                "UPDATE engine_instances SET status=?, starttime=?, endtime=?, "
                "engineid=?, engineversion=?, enginevariant=?, enginefactory=?, "
                "batch=?, env=?, runtimeconf=?, datasourceparams=?, "
                "preparatorparams=?, algorithmsparams=?, servingparams=?, "
                "heartbeat=? WHERE id=?", self._to_row(i)[1:] + (i.id,))

    def delete(self, iid: str) -> None:
        with self.c.lock, self.c.conn:
            self.c.conn.execute("DELETE FROM engine_instances WHERE id=?", (iid,))


class SQLiteEvaluationInstances(base.EvaluationInstances):
    COLS = ("id, status, starttime, endtime, evaluationclass, "
            "engineparamsgeneratorclass, batch, env, runtimeconf, "
            "evaluatorresults, evaluatorresultshtml, evaluatorresultsjson")

    def __init__(self, client: SQLiteStorageClient):
        self.c = client

    def _to_row(self, i: EvaluationInstance):
        return (i.id, i.status, to_millis(i.start_time), to_millis(i.end_time),
                i.evaluation_class, i.engine_params_generator_class, i.batch,
                json.dumps(dict(i.env)), json.dumps(dict(i.runtime_conf)),
                i.evaluator_results, i.evaluator_results_html,
                i.evaluator_results_json)

    @staticmethod
    def _from_row(r) -> EvaluationInstance:
        return EvaluationInstance(
            id=r[0], status=r[1], start_time=from_millis(r[2]),
            end_time=from_millis(r[3]), evaluation_class=r[4],
            engine_params_generator_class=r[5], batch=r[6],
            env=json.loads(r[7]), runtime_conf=json.loads(r[8]),
            evaluator_results=r[9], evaluator_results_html=r[10],
            evaluator_results_json=r[11])

    def insert(self, i: EvaluationInstance) -> str:
        iid = i.id or uuid.uuid4().hex
        i = i.with_(id=iid)
        with self.c.lock, self.c.conn:
            self.c.conn.execute(
                f"INSERT INTO evaluation_instances ({self.COLS}) VALUES "
                "(?,?,?,?,?,?,?,?,?,?,?,?)", self._to_row(i))
        return iid

    def get(self, iid: str) -> Optional[EvaluationInstance]:
        with self.c.lock:
            row = self.c.conn.execute(
                f"SELECT {self.COLS} FROM evaluation_instances WHERE id=?",
                (iid,)).fetchone()
        return self._from_row(row) if row else None

    def get_all(self) -> List[EvaluationInstance]:
        with self.c.lock:
            rows = self.c.conn.execute(
                f"SELECT {self.COLS} FROM evaluation_instances").fetchall()
        return [self._from_row(r) for r in rows]

    def get_completed(self) -> List[EvaluationInstance]:
        with self.c.lock:
            rows = self.c.conn.execute(
                f"SELECT {self.COLS} FROM evaluation_instances WHERE status=? "
                "ORDER BY starttime DESC",
                (base.EvaluationInstanceStatus.COMPLETED,)).fetchall()
        return [self._from_row(r) for r in rows]

    def update(self, i: EvaluationInstance) -> None:
        with self.c.lock, self.c.conn:
            self.c.conn.execute(
                "UPDATE evaluation_instances SET status=?, starttime=?, "
                "endtime=?, evaluationclass=?, engineparamsgeneratorclass=?, "
                "batch=?, env=?, runtimeconf=?, evaluatorresults=?, "
                "evaluatorresultshtml=?, evaluatorresultsjson=? WHERE id=?",
                self._to_row(i)[1:] + (i.id,))

    def delete(self, iid: str) -> None:
        with self.c.lock, self.c.conn:
            self.c.conn.execute(
                "DELETE FROM evaluation_instances WHERE id=?", (iid,))


class SQLiteModels(base.Models):
    """Model blobs are stored wrapped in the integrity envelope; `get`
    verifies the checksum (CorruptBlobError on mismatch), `fsck` moves
    corrupt rows into the `models_quarantine` table with a reason."""

    def __init__(self, client: SQLiteStorageClient):
        self.c = client

    def insert(self, m: Model) -> None:
        from predictionio_tpu.data import integrity
        with self.c.lock, self.c.conn:
            self.c.conn.execute(
                "INSERT OR REPLACE INTO models (id, models) VALUES (?,?)",
                (m.id, integrity.wrap(m.models)))

    def get(self, mid: str) -> Optional[Model]:
        from predictionio_tpu.data import integrity
        with self.c.lock:
            row = self.c.conn.execute(
                "SELECT id, models FROM models WHERE id=?", (mid,)).fetchone()
        return Model(row[0], integrity.unwrap(bytes(row[1]))) if row else None

    def delete(self, mid: str) -> None:
        with self.c.lock, self.c.conn:
            self.c.conn.execute("DELETE FROM models WHERE id=?", (mid,))

    def list_model_ids(self) -> List[str]:
        with self.c.lock:
            rows = self.c.conn.execute(
                "SELECT id FROM models ORDER BY id").fetchall()
        return [r[0] for r in rows]

    def fsck(self, repair: bool = False) -> List[dict]:
        from predictionio_tpu.data import integrity
        findings: List[dict] = []
        with self.c.lock:
            rows = self.c.conn.execute(
                "SELECT id, models FROM models ORDER BY id").fetchall()
        for mid, blob in rows:
            ok, reason = integrity.verify(bytes(blob))
            if ok:
                continue
            finding = {"kind": "corrupt_blob", "id": mid,
                       "reason": reason, "action": "none"}
            if repair:
                now_ms = int(utcnow().timestamp() * 1000)
                with self.c.lock, self.c.conn:
                    self.c.conn.execute(
                        "INSERT OR REPLACE INTO models_quarantine "
                        "(id, models, reason, quarantined_at) "
                        "VALUES (?,?,?,?)",
                        (mid, blob, reason, now_ms))
                    self.c.conn.execute(
                        "DELETE FROM models WHERE id=?", (mid,))
                finding["action"] = "quarantined -> models_quarantine"
            findings.append(finding)
        return findings

    def quarantine_stats(self) -> dict:
        """Footprint of models_quarantine (feeds pio_quarantine_bytes)."""
        with self.c.lock:
            row = self.c.conn.execute(
                "SELECT COUNT(*), COALESCE(SUM(LENGTH(models)), 0) "
                "FROM models_quarantine").fetchone()
        return {"bytes": float(row[1]), "count": float(row[0])}

    def quarantine_gc(self, retention_s: float) -> List[dict]:
        """Drop quarantined rows past the retention window. Rows from
        before the quarantined_at column existed (NULL) are treated as
        expired — they predate any plausible retention window."""
        cutoff_ms = int((utcnow().timestamp() - retention_s) * 1000)
        with self.c.lock:
            rows = self.c.conn.execute(
                "SELECT id, LENGTH(models), quarantined_at "
                "FROM models_quarantine WHERE quarantined_at IS NULL "
                "OR quarantined_at <= ?", (cutoff_ms,)).fetchall()
        findings: List[dict] = []
        for mid, size, qat in rows:
            with self.c.lock, self.c.conn:
                self.c.conn.execute(
                    "DELETE FROM models_quarantine WHERE id=?", (mid,))
            findings.append({
                "kind": "quarantine_expired", "id": mid,
                "reason": f"quarantined row ({size or 0}B) past "
                          f"{retention_s:.0f}s retention",
                "action": "deleted"})
        return findings


class SQLiteTenantQuotas(base.TenantQuotas):
    """Per-app admission overrides; NULL columns inherit the server
    defaults, so an operator can pin one knob per app."""

    def __init__(self, client: SQLiteStorageClient):
        self.c = client

    _COLS = "appid, rate, burst, concurrency, queue_max, weight, channel"

    def upsert(self, quota: TenantQuota) -> None:
        with self.c.lock, self.c.conn:
            self.c.conn.execute(
                f"INSERT OR REPLACE INTO tenant_quotas ({self._COLS}) "
                "VALUES (?,?,?,?,?,?,?)",
                (quota.appid, quota.rate, quota.burst, quota.concurrency,
                 quota.queue_max, quota.weight, quota.channel))

    def get(self, appid: int, channel: str = "") -> Optional[TenantQuota]:
        with self.c.lock:
            row = self.c.conn.execute(
                f"SELECT {self._COLS} FROM tenant_quotas "
                "WHERE appid=? AND channel=?",
                (appid, channel)).fetchone()
        return TenantQuota(*row) if row else None

    def get_all(self) -> List[TenantQuota]:
        with self.c.lock:
            rows = self.c.conn.execute(
                f"SELECT {self._COLS} FROM tenant_quotas "
                "ORDER BY appid, channel").fetchall()
        return [TenantQuota(*r) for r in rows]

    def delete(self, appid: int, channel: str = "") -> None:
        with self.c.lock, self.c.conn:
            self.c.conn.execute(
                "DELETE FROM tenant_quotas WHERE appid=? AND channel=?",
                (appid, channel))


class SQLiteSLOObjectives(base.SLOObjectives):
    """Per-app SLO overrides; NULL columns inherit the server-wide
    objective, so an operator can tighten only one app's latency."""

    def __init__(self, client: SQLiteStorageClient):
        self.c = client

    _COLS = "appid, latency_ms, target"

    def upsert(self, slo: SLOObjective) -> None:
        with self.c.lock, self.c.conn:
            self.c.conn.execute(
                f"INSERT OR REPLACE INTO slo_objectives ({self._COLS}) "
                "VALUES (?,?,?)",
                (slo.appid, slo.latency_ms, slo.target))

    def get(self, appid: int) -> Optional[SLOObjective]:
        with self.c.lock:
            row = self.c.conn.execute(
                f"SELECT {self._COLS} FROM slo_objectives WHERE appid=?",
                (appid,)).fetchone()
        return SLOObjective(*row) if row else None

    def get_all(self) -> List[SLOObjective]:
        with self.c.lock:
            rows = self.c.conn.execute(
                f"SELECT {self._COLS} FROM slo_objectives "
                "ORDER BY appid").fetchall()
        return [SLOObjective(*r) for r in rows]

    def delete(self, appid: int) -> None:
        with self.c.lock, self.c.conn:
            self.c.conn.execute(
                "DELETE FROM slo_objectives WHERE appid=?", (appid,))


class SQLiteLeases(base.Leases):
    """CAS lease over a single row; the connection lock + transaction
    make the read-check-write atomic within this process, and WAL's
    writer exclusivity makes it atomic across processes sharing the
    db file (the cross-host deployment runs all routers against one
    shared metadata store)."""

    def __init__(self, client: SQLiteStorageClient):
        self.c = client

    @staticmethod
    def _from_row(r) -> base.Lease:
        return base.Lease(r[0], r[1], from_millis(r[2]), r[3] or "")

    def acquire(self, name: str, holder: str, ttl_s: float,
                journal: Optional[str] = None) -> Optional[base.Lease]:
        now = utcnow()
        now_ms = to_millis(now)
        exp_ms = now_ms + int(ttl_s * 1000)
        with self.c.lock, self.c.conn:
            self.c.conn.execute("BEGIN IMMEDIATE")
            row = self.c.conn.execute(
                "SELECT name, holder, expires_ms, journal FROM leases "
                "WHERE name=?", (name,)).fetchone()
            if row is not None and row[1] != holder and row[2] > now_ms:
                return None
            keep = (row[3] if row is not None else "") \
                if journal is None else journal
            self.c.conn.execute(
                "INSERT OR REPLACE INTO leases (name, holder, expires_ms, "
                "journal) VALUES (?,?,?,?)", (name, holder, exp_ms, keep))
        return base.Lease(name, holder, from_millis(exp_ms), keep or "")

    def get(self, name: str) -> Optional[base.Lease]:
        with self.c.lock:
            row = self.c.conn.execute(
                "SELECT name, holder, expires_ms, journal FROM leases "
                "WHERE name=?", (name,)).fetchone()
        return self._from_row(row) if row else None

    def release(self, name: str, holder: str) -> bool:
        with self.c.lock, self.c.conn:
            cur = self.c.conn.execute(
                "DELETE FROM leases WHERE name=? AND holder=?",
                (name, holder))
            return cur.rowcount > 0


class SQLiteEvents(base.EventStore):
    """Event store over per-(app,channel) tables (JDBCLEvents.scala:37-120).

    Tables are created lazily on first access so behavior matches the MEM
    driver on the uninitialized path.
    """

    def __init__(self, client: SQLiteStorageClient):
        self.c = client
        self._known: set = set()

    def _ensure(self, app_id: int, channel_id: Optional[int]) -> None:
        if (app_id, channel_id) not in self._known:
            self.init(app_id, channel_id)

    def init(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        t = event_table_name(app_id, channel_id)
        self._known.add((app_id, channel_id))
        with self.c.lock, self.c.conn:
            self.c.conn.execute(f"""CREATE TABLE IF NOT EXISTS {t} (
                id TEXT PRIMARY KEY,
                event TEXT NOT NULL,
                entitytype TEXT NOT NULL,
                entityid TEXT NOT NULL,
                targetentitytype TEXT,
                targetentityid TEXT,
                properties TEXT,
                eventtime INTEGER NOT NULL,
                tags TEXT,
                prid TEXT,
                creationtime INTEGER NOT NULL)""")
            self.c.conn.execute(
                f"CREATE INDEX IF NOT EXISTS {t}_entity ON {t} "
                "(entitytype, entityid)")
            self.c.conn.execute(
                f"CREATE INDEX IF NOT EXISTS {t}_time ON {t} (eventtime)")
        return True

    def remove(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        t = event_table_name(app_id, channel_id)
        with self.c.lock, self.c.conn:
            self.c.conn.execute(f"DROP TABLE IF EXISTS {t}")
            self._bump_gen(t)
        self._known.discard((app_id, channel_id))
        return True

    def close(self) -> None:
        pass

    def _insert(self, event: Event, app_id: int,
                channel_id: Optional[int] = None) -> str:
        t = event_table_name(app_id, channel_id)
        self._ensure(app_id, channel_id)
        e = event if event.event_id else event.with_id()
        try:
            with self.c.lock, self.c.conn:
                self.c.conn.execute(
                    f"INSERT INTO {t} VALUES (?,?,?,?,?,?,?,?,?,?,?)",
                    (e.event_id, e.event, e.entity_type, e.entity_id,
                     e.target_entity_type, e.target_entity_id,
                     e.properties.to_json(), to_millis(e.event_time),
                     json.dumps(list(e.tags)), e.pr_id,
                     to_millis(e.creation_time)))
                self._bump_gen(t)
        except sqlite3.IntegrityError as ex:
            raise base.StorageWriteError(str(ex)) from ex
        return e.event_id

    def _insert_batch(self, events: Sequence[Event], app_id: int,
                      channel_id: Optional[int] = None) -> List[str]:
        t = event_table_name(app_id, channel_id)
        self._ensure(app_id, channel_id)
        out, rows = [], []
        for event in events:
            e = event if event.event_id else event.with_id()
            out.append(e.event_id)
            rows.append((e.event_id, e.event, e.entity_type, e.entity_id,
                         e.target_entity_type, e.target_entity_id,
                         e.properties.to_json(), to_millis(e.event_time),
                         json.dumps(list(e.tags)), e.pr_id,
                         to_millis(e.creation_time)))
        try:
            with self.c.lock, self.c.conn:
                self.c.conn.executemany(
                    f"INSERT INTO {t} VALUES (?,?,?,?,?,?,?,?,?,?,?)", rows)
                self._bump_gen(t)
        except sqlite3.IntegrityError as ex:
            raise base.StorageWriteError(str(ex)) from ex
        return out

    @staticmethod
    def _row_to_event(r) -> Event:
        return Event(
            event_id=r[0], event=r[1], entity_type=r[2], entity_id=r[3],
            target_entity_type=r[4], target_entity_id=r[5],
            properties=DataMap.from_json(r[6] or "{}"),
            event_time=from_millis(r[7]),
            tags=tuple(json.loads(r[8] or "[]")), pr_id=r[9],
            creation_time=from_millis(r[10]))

    def get(self, event_id: str, app_id: int,
            channel_id: Optional[int] = None) -> Optional[Event]:
        t = event_table_name(app_id, channel_id)
        self._ensure(app_id, channel_id)
        with self.c.lock:
            row = self.c.conn.execute(
                f"SELECT * FROM {t} WHERE id=?", (event_id,)).fetchone()
        return self._row_to_event(row) if row else None

    def delete(self, event_id: str, app_id: int,
               channel_id: Optional[int] = None) -> bool:
        t = event_table_name(app_id, channel_id)
        self._ensure(app_id, channel_id)
        with self.c.lock, self.c.conn:
            cur = self.c.conn.execute(
                f"DELETE FROM {t} WHERE id=?", (event_id,))
            if cur.rowcount > 0:
                self._bump_gen(t)
            return cur.rowcount > 0

    def find(self, app_id: int, channel_id: Optional[int] = None, *,
             start_time: Optional[datetime] = None,
             until_time: Optional[datetime] = None,
             entity_type: Optional[str] = None,
             entity_id: Optional[str] = None,
             event_names: Optional[Sequence[str]] = None,
             target_entity_type: object = _UNSET,
             target_entity_id: object = _UNSET,
             properties=None,
             limit: Optional[int] = None,
             reversed: bool = False) -> Iterator[Event]:
        t = event_table_name(app_id, channel_id)
        self._ensure(app_id, channel_id)
        clauses, params = [], []
        if start_time is not None:
            clauses.append("eventtime >= ?")
            params.append(to_millis(start_time))
        if until_time is not None:
            clauses.append("eventtime < ?")
            params.append(to_millis(until_time))
        if entity_type is not None:
            clauses.append("entitytype = ?")
            params.append(entity_type)
        if entity_id is not None:
            clauses.append("entityid = ?")
            params.append(entity_id)
        if event_names is not None:
            names = list(event_names)
            clauses.append(
                "event IN (" + ",".join("?" * len(names)) + ")")
            params.extend(names)
        if target_entity_type is not _UNSET:
            if target_entity_type is None:
                clauses.append("targetentitytype IS NULL")
            else:
                clauses.append("targetentitytype = ?")
                params.append(target_entity_type)
        if target_entity_id is not _UNSET:
            if target_entity_id is None:
                clauses.append("targetentityid IS NULL")
            else:
                clauses.append("targetentityid = ?")
                params.append(target_entity_id)
        where = (" WHERE " + " AND ".join(clauses)) if clauses else ""
        order = " ORDER BY eventtime DESC, id DESC" if reversed \
            else " ORDER BY eventtime ASC, id ASC"
        # a property filter is applied post-SQL (the properties column is
        # a JSON blob), so the LIMIT moves after it — streaming the
        # cursor and stopping at `limit` matches, never materializing
        # the unfiltered table
        lim = f" LIMIT {int(limit)}" \
            if limit is not None and limit > 0 and not properties else ""
        with self.c.lock:
            cur = self.c.conn.execute(
                f"SELECT * FROM {t}{where}{order}{lim}", params)
            if not properties:
                events = [self._row_to_event(r) for r in cur.fetchall()]
            else:
                events = []
                for r in cur:
                    e = self._row_to_event(r)
                    if _match_properties(e, properties):
                        events.append(e)
                        if limit is not None and 0 < limit <= len(events):
                            break
        return iter(events)

    # -- columnar scan + ingest watermark ------------------------------------

    def _bump_gen(self, table: str) -> None:
        # caller holds the lock + transaction of the triggering write
        self.c.conn.execute(
            "INSERT INTO events_ingest_gen (tbl, gen) VALUES (?, 1) "
            "ON CONFLICT(tbl) DO UPDATE SET gen = gen + 1", (table,))

    def ingest_watermark(self, app_id: int,
                         channel_id: Optional[int] = None
                         ) -> Optional[Dict[str, int]]:
        t = event_table_name(app_id, channel_id)
        with self.c.lock:
            row = self.c.conn.execute(
                "SELECT gen FROM events_ingest_gen WHERE tbl=?",
                (t,)).fetchone()
        return {"gen": int(row[0]) if row else 0}

    def ingest_cache_dir(self, app_id: int,
                         channel_id: Optional[int] = None):
        # file-backed sqlite only: :memory: stores and the Postgres
        # subclass (no local db file) have no natural on-disk home
        path = getattr(self.c, "path", None)
        if not path or path == ":memory:":
            return None
        d = Path(path).parent / "ingest_cache" / \
            event_table_name(app_id, channel_id)
        return str(d)

    def scan_columns(self, app_id: int, channel_id: Optional[int] = None, *,
                     start_time: Optional[datetime] = None,
                     until_time: Optional[datetime] = None,
                     entity_type: Optional[str] = None,
                     entity_id: Optional[str] = None,
                     event_names: Optional[Sequence[str]] = None,
                     target_entity_type: object = _UNSET,
                     target_entity_id: object = _UNSET,
                     properties=None,
                     value_spec=None, require_target: bool = True,
                     workers: Optional[int] = None,
                     since: Optional[Dict[str, int]] = None,
                     upto: Optional[Dict[str, int]] = None):
        """Native columnar scan: SQL projection of exactly the five
        columns the row stream needs, with the same index pushdown as
        `find()` — no Event objects, no full-row decode. Rows arrive in
        find()'s exact order (eventtime ASC, id ASC), so the
        BlockBuilder's first-seen interning reproduces the Event-oracle
        tables bit-for-bit.

        The gen-counter watermark carries no byte offsets: a `since`
        delta cannot be sliced out of a mutable SQL table, so the
        streaming path gets `DeltaInvalidated` and full-rebuilds."""
        if since is not None:
            raise base.DeltaInvalidated(
                "sqlite watermark has no delta offsets")
        del upto, workers   # no delta slicing; scan is single-cursor
        t = event_table_name(app_id, channel_id)
        self._ensure(app_id, channel_id)
        clauses, params = [], []
        if start_time is not None:
            clauses.append("eventtime >= ?")
            params.append(to_millis(start_time))
        if until_time is not None:
            clauses.append("eventtime < ?")
            params.append(to_millis(until_time))
        if entity_type is not None:
            clauses.append("entitytype = ?")
            params.append(entity_type)
        if entity_id is not None:
            clauses.append("entityid = ?")
            params.append(entity_id)
        if event_names is not None:
            names = list(event_names)
            clauses.append(
                "event IN (" + ",".join("?" * len(names)) + ")")
            params.extend(names)
        if target_entity_type is not _UNSET:
            if target_entity_type is None:
                clauses.append("targetentitytype IS NULL")
            else:
                clauses.append("targetentitytype = ?")
                params.append(target_entity_type)
        if target_entity_id is not _UNSET:
            if target_entity_id is None:
                clauses.append("targetentityid IS NULL")
            else:
                clauses.append("targetentityid = ?")
                params.append(target_entity_id)
        if require_target:
            # pushdown of the require_target row drop: the builder
            # would skip NULL-target rows anyway, the index shouldn't
            # have to surface them first
            clauses.append("targetentityid IS NOT NULL")
        where = (" WHERE " + " AND ".join(clauses)) if clauses else ""
        spec = columns.normalize_value_spec(value_spec)
        # properties JSON only needs parsing when a value rule reads a
        # prop or a property post-filter is present
        need_props = bool(properties) or any(
            ent[0] != "const" for ent in spec.values())
        b = columns.BlockBuilder()
        with self.c.lock:
            cur = self.c.conn.execute(
                f"SELECT event, entityid, targetentityid, properties, "
                f"eventtime FROM {t}{where} ORDER BY eventtime ASC, id ASC",
                params)
            for name, eid, tei, props_json, ms in cur:
                props = json.loads(props_json) if (
                    need_props and props_json) else None
                if properties:
                    if props is None:
                        break_row = True
                    else:
                        break_row = any(
                            k not in props or props[k] != v
                            for k, v in properties.items())
                    if break_row:
                        continue
                v = columns.eval_value(spec, name, props)
                if v is None:
                    continue
                if require_target and tei is None:
                    continue
                b.add(eid, tei, float(v), ms * 1000)
        return columns.merge_blocks([b.block()])

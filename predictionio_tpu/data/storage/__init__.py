"""Storage SPI + drivers (reference: `data/.../storage/`).

`registry.storage()` is the process-wide entry point, the analog of the
reference's `Storage` object.
"""

from predictionio_tpu.data.storage.base import (
    AccessKey, AccessKeys, App, Apps, Channel, Channels, EngineInstance,
    EngineInstanceStatus, EngineInstances, EvaluationInstance,
    EvaluationInstanceStatus, EvaluationInstances, EventStore, Lease, Leases,
    Model, Models, SLOObjective, SLOObjectives, StorageError,
    StorageWriteError, TenantQuota, TenantQuotas,
)
from predictionio_tpu.data.storage.registry import (
    StorageRegistry, register_driver, set_default, storage,
)

__all__ = [
    "AccessKey", "AccessKeys", "App", "Apps", "Channel", "Channels",
    "EngineInstance", "EngineInstanceStatus", "EngineInstances",
    "EvaluationInstance", "EvaluationInstanceStatus", "EvaluationInstances",
    "EventStore", "Lease", "Leases", "Model", "Models", "SLOObjective",
    "SLOObjectives", "StorageError",
    "StorageWriteError", "TenantQuota", "TenantQuotas",
    "StorageRegistry", "register_driver", "set_default", "storage",
]

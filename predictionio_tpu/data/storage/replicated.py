"""Replicated model store ("REPLICATED" type): quorum writes + read-repair.

One torn blob or one lost disk must never cost a deploy (the fleet-ops
posture of the ROADMAP north star). A REPLICATED source is a virtual
Models store fanning out over N *other* configured sources:

  PIO_STORAGE_SOURCES_<N>_TYPE=REPLICATED
  PIO_STORAGE_SOURCES_<N>_REPLICAS=R1,R2,R3    (names of other sources)
  PIO_STORAGE_SOURCES_<N>_QUORUM=2             (optional; default majority)

Semantics:

  - `insert`/`delete` fan out to every target CONCURRENTLY (one worker
    per target) and ack once a QUORUM of targets succeeded — write
    latency tracks the quorum-th fastest target, not the sum of all
    targets; stragglers finish in the background so healthy-but-slow
    replicas still converge. Each target is independently wrapped in
    the registry's resilience proxy, so per-target retry schedules,
    retry budgets, and circuit breakers from PR-2/PR-3 apply before a
    target counts as failed. Fewer acks than quorum raises
    StorageError.
  - `get` reads targets in configured order and returns the first
    INTACT copy (the PR-3 envelope checksum is the arbiter). A replica
    that was corrupt (`CorruptBlobError`) or missing the blob is
    READ-REPAIRED in place: the verified payload is rewritten through
    the target's own atomic-write path, counted in
    `pio_model_repair_total{target}`. Unreachable targets are skipped,
    never written.
  - `fsck` aggregates each target's own fsck pass (quarantine etc. per
    driver) and `check_divergence(ids)` compares payload digests across
    replicas for the given instance ids — same id, differing checksum
    is the silent failure mode quorum writes leave behind; with
    `repair` the majority (first-target tie-break) copy is rewritten
    everywhere (`pio doctor --repair`).

The registry hands each target DAO out through its normal construction
path, so chaos seams (`storage.<target>.Models.*`,
`storage.<target>.models.insert.torn`) and metrics keep their
per-target identity — a partition of one target mid-quorum-write is
one armed fault rule away.
"""

from __future__ import annotations

import hashlib
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence, Tuple

# module (not name) import: integrity itself imports storage.base, so
# when integrity is the interpreter's FIRST import this module loads
# while integrity is mid-initialization — the module object is already
# in sys.modules (usable at call time), its names are not yet
from predictionio_tpu.data import integrity
from predictionio_tpu.data.storage import base
from predictionio_tpu.data.storage.base import Lease, Model, StorageError
from predictionio_tpu.obs import get_logger, get_registry

_log = get_logger("storage.replicated")


def _metrics():
    reg = get_registry()
    return {
        "repair": reg.counter(
            "pio_model_repair_total",
            "Model blobs rewritten on a replica by read-repair or "
            "divergence repair", labels=("target",)),
        "writes": reg.counter(
            "pio_replica_writes_total",
            "Per-target replica write outcomes", labels=("target",
                                                         "outcome")),
        "quorum": reg.counter(
            "pio_replica_quorum_total",
            "Quorum-acked operations by outcome", labels=("op", "outcome")),
        "divergence": reg.counter(
            "pio_replica_divergence_total",
            "Instance ids found with diverging replica checksums"),
    }


class ReplicatedStorageClient:
    """Holds the target-source names; DAOs are resolved lazily through
    the owning registry so each target keeps its own resilience proxy."""

    # the registry passes itself to factories advertising this flag
    needs_registry = True

    def __init__(self, config: Optional[dict] = None, registry=None):
        self.config = dict(config or {})
        self.registry = registry
        self.source_name = self.config.get("SOURCE_NAME", "REPLICATED")
        raw = self.config.get("REPLICAS", self.config.get("replicas", ""))
        self.targets: List[str] = [t.strip() for t in raw.split(",")
                                   if t.strip()]
        if len(self.targets) < 2:
            raise StorageError(
                f"REPLICATED source {self.source_name} needs >= 2 target "
                "sources (PIO_STORAGE_SOURCES_<N>_REPLICAS=A,B[,C...])")
        if registry is None:
            raise StorageError(
                "REPLICATED source requires registry-driven construction")
        for t in self.targets:
            if t == self.source_name:
                raise StorageError(
                    f"REPLICATED source {self.source_name} lists itself "
                    "as a replica target")
            scfg = registry.sources.get(t)
            if scfg is None:
                raise StorageError(
                    f"REPLICATED source {self.source_name}: unknown "
                    f"target source {t!r}")
            if scfg.get("TYPE", "").upper() == "REPLICATED":
                raise StorageError(
                    f"REPLICATED source {self.source_name}: target {t!r} "
                    "is itself REPLICATED (nesting not supported)")
        q = self.config.get("QUORUM", self.config.get("quorum"))
        self.quorum = int(q) if q else len(self.targets) // 2 + 1
        if not (1 <= self.quorum <= len(self.targets)):
            raise StorageError(
                f"REPLICATED source {self.source_name}: QUORUM "
                f"{self.quorum} outside 1..{len(self.targets)}")


class ReplicatedModels(base.Models):
    """Quorum-write / read-repair Models DAO over the client's targets."""

    def __init__(self, client: ReplicatedStorageClient):
        self.c = client
        self._lock = threading.Lock()
        self._daos: Optional[List[Tuple[str, base.Models]]] = None
        self._inflight: List = []    # straggler writes past quorum ack
        self._m = _metrics()

    def _targets(self) -> List[Tuple[str, base.Models]]:
        """(name, DAO) per target, resolved once through the registry
        (each comes back wrapped in its own resilience proxy)."""
        with self._lock:
            if self._daos is None:
                self._daos = [
                    (t, self.c.registry.get_data_object(t, "Models"))
                    for t in self.c.targets]
            return self._daos

    # -- writes -------------------------------------------------------------
    def _fan_out(self, op: str, fn) -> None:
        """Fan the write out to every target CONCURRENTLY and ack as
        soon as a QUORUM succeeded — write latency is the quorum-th
        fastest target (bounded by max(target)), not sum(target) as the
        old serial loop was. Stragglers keep running after the ack so
        slow-but-healthy replicas still converge; their per-target
        metrics and failure logs land when they finish. Each worker
        calls the target through its own resilience proxy, so
        per-target retry schedules, budgets, and breakers are exactly
        what they were under the serial loop."""
        targets = self._targets()
        n = len(targets)
        cond = threading.Condition()
        state = {"acks": 0, "done": 0}
        failures: List[Tuple[str, Exception]] = []

        def run(name: str, dao: base.Models) -> None:
            try:
                fn(dao)
            except Exception as e:
                self._m["writes"].labels(target=name,
                                         outcome="failed").inc()
                _log.warning("replica_write_failed", op=op, target=name,
                             error=f"{type(e).__name__}: {e}")
                with cond:
                    failures.append((name, e))
                    state["done"] += 1
                    cond.notify_all()
                return
            self._m["writes"].labels(target=name, outcome="ok").inc()
            with cond:
                state["acks"] += 1
                state["done"] += 1
                cond.notify_all()

        pool = ThreadPoolExecutor(max_workers=n,
                                  thread_name_prefix=f"replica-{op}")
        try:
            futs = [pool.submit(run, name, dao) for name, dao in targets]
            with self._lock:
                self._inflight = [f for f in self._inflight
                                  if not f.done()] + futs
            with cond:
                while state["acks"] < self.c.quorum and state["done"] < n:
                    cond.wait(timeout=0.5)
                acks = state["acks"]
                detail = "; ".join(f"{name}: {type(e).__name__}: {e}"
                                   for name, e in failures)
        finally:
            # no wait: an early quorum ack must not join stragglers
            pool.shutdown(wait=False)
        if acks < self.c.quorum:
            self._m["quorum"].labels(op=op, outcome="failed").inc()
            raise StorageError(
                f"replicated {op}: quorum not met "
                f"({acks}/{self.c.quorum} of {len(self.c.targets)} "
                f"targets acked; failures: {detail})")
        self._m["quorum"].labels(op=op, outcome="ok").inc()

    def _drain(self, timeout_s: float = 30.0) -> None:
        """Join straggler replica writes from earlier quorum-acked
        fan-outs (deterministic sequencing for tests and shutdown)."""
        with self._lock:
            pending, self._inflight = self._inflight, []
        for f in pending:
            try:
                f.result(timeout=timeout_s)
            except Exception:
                pass    # the worker already logged and counted it

    def insert(self, m: Model) -> None:
        self._fan_out("insert", lambda dao: dao.insert(m))

    def delete(self, mid: str) -> None:
        self._fan_out("delete", lambda dao: dao.delete(mid))

    # -- reads + read-repair ------------------------------------------------
    def get(self, mid: str) -> Optional[Model]:
        """First intact copy wins; earlier replicas that were corrupt or
        missing the blob are repaired from it (envelope-level
        read-repair). Targets that ERRORED (unreachable/breaker-open)
        are skipped and never written — repair needs positive evidence
        the replica is alive but wrong, not merely silent."""
        stale: List[Tuple[str, base.Models, str]] = []   # needs rewrite
        errors: List[Exception] = []
        saw_target = False
        for name, dao in self._targets():
            try:
                model = dao.get(mid)
            except integrity.CorruptBlobError as e:
                # the replica answered — positive evidence it is alive
                # but wrong, which is exactly what repair needs
                saw_target = True
                stale.append((name, dao, f"corrupt: {e}"))
                continue
            except (StorageError, OSError) as e:
                errors.append(e)
                continue
            saw_target = True
            if model is None:
                stale.append((name, dao, "missing"))
                continue
            self._repair(mid, model, stale)
            return model
        if saw_target:
            # every reachable replica agreed the blob does not exist
            if any(reason.startswith("corrupt") for _, _, reason in stale):
                raise integrity.CorruptBlobError(
                    f"model {mid}: every replica holding the blob is "
                    "corrupt; no intact copy to repair from")
            return None
        if errors:
            raise errors[-1]
        return None

    def _repair(self, mid: str, model: Model,
                stale: Sequence[Tuple[str, base.Models, str]]) -> None:
        for name, dao, reason in stale:
            try:
                dao.insert(model)
            except Exception as e:
                _log.warning("read_repair_failed", id=mid, target=name,
                             error=f"{type(e).__name__}: {e}")
                continue
            self._m["repair"].labels(target=name).inc()
            _log.warning("read_repair", id=mid, target=name, was=reason)

    def list_model_ids(self) -> List[str]:
        """Union of every reachable target's enumerable ids — a blob a
        quorum write missed on some replica still shows up as long as
        ONE replica holds it (that asymmetry is exactly what the
        divergence sweep wants to examine)."""
        ids: set = set()
        for name, dao in self._targets():
            lister = getattr(dao, "list_model_ids", None)
            if lister is None:
                continue
            try:
                ids.update(lister())
            except (StorageError, OSError) as e:
                _log.warning("list_model_ids_failed", target=name,
                             error=f"{type(e).__name__}: {e}")
        return sorted(ids)

    # -- fsck / divergence ---------------------------------------------------
    def fsck(self, repair: bool = False) -> List[dict]:
        """Each target's own fsck pass, findings tagged with the target
        name. A target whose fsck itself fails contributes one
        `fsck_error` finding instead of aborting the sweep."""
        findings: List[dict] = []
        for name, dao in self._targets():
            run = getattr(dao, "fsck", None)
            if run is None:
                continue
            try:
                found = run(repair=repair)
            except (StorageError, OSError) as e:
                found = [{"kind": "fsck_error", "reason": str(e),
                          "action": "none"}]
            for f in found:
                f.setdefault("target", name)
            findings.extend(found)
        return findings

    def check_divergence(self, ids: Sequence[str],
                         repair: bool = False) -> List[dict]:
        """Compare payload digests for each instance id across replicas.

        Divergence = same id, differing checksums (or a copy missing /
        corrupt on some replicas) — what a partitioned target misses
        during a quorum write, or silent rot fsck alone can't arbitrate.
        With `repair`, the majority digest (first-target order breaks
        ties) is rewritten to every disagreeing replica."""
        findings: List[dict] = []
        targets = self._targets()
        for mid in ids:
            copies: Dict[str, Optional[Model]] = {}
            states: Dict[str, str] = {}
            for name, dao in targets:
                try:
                    m = dao.get(mid)
                except integrity.CorruptBlobError:
                    states[name] = "corrupt"
                    copies[name] = None
                    continue
                except (StorageError, OSError) as e:
                    states[name] = f"unreachable: {type(e).__name__}"
                    copies[name] = None
                    continue
                if m is None:
                    states[name] = "missing"
                    copies[name] = None
                else:
                    states[name] = "sha256:" + hashlib.sha256(
                        m.models).hexdigest()[:16]
                    copies[name] = m
            digests = [s for s in states.values() if s.startswith("sha256:")]
            if not digests:
                continue   # nowhere intact: nothing to arbitrate
            if len(set(states.values())) == 1:
                continue   # all replicas agree
            self._m["divergence"].inc()
            finding = {"kind": "replica_divergence", "id": mid,
                       "replicas": dict(states),
                       "reason": "replica checksums disagree",
                       "action": "none"}
            if repair:
                finding["action"] = self._repair_divergence(
                    mid, targets, states, copies)
            findings.append(finding)
        return findings

    def _repair_divergence(self, mid, targets, states, copies) -> str:
        # majority digest wins; ties break in configured target order
        counts: Dict[str, int] = {}
        for s in states.values():
            if s.startswith("sha256:"):
                counts[s] = counts.get(s, 0) + 1
        best = max(counts.values())
        winner = next(s for n, _ in targets
                      if (s := states[n]).startswith("sha256:")
                      and counts[s] == best)
        source = next(copies[n] for n, _ in targets if states[n] == winner)
        repaired = []
        for name, dao in targets:
            if states[name] == winner \
                    or states[name].startswith("unreachable"):
                continue
            try:
                dao.insert(source)
            except Exception as e:
                _log.warning("divergence_repair_failed", id=mid,
                             target=name,
                             error=f"{type(e).__name__}: {e}")
                continue
            self._m["repair"].labels(target=name).inc()
            repaired.append(name)
        return (f"rewrote {','.join(repaired)} from {winner}"
                if repaired else "repair failed on every replica")

    # -- quarantine delegation ----------------------------------------------
    def quarantine_stats(self) -> Dict[str, float]:
        """Aggregate quarantine footprint across targets (for the
        `pio_quarantine_bytes` gauge)."""
        total = {"bytes": 0.0, "count": 0.0}
        for _, dao in self._targets():
            stats = getattr(dao, "quarantine_stats", None)
            if stats is None:
                continue
            try:
                s = stats()
            except (StorageError, OSError):
                continue
            total["bytes"] += s.get("bytes", 0.0)
            total["count"] += s.get("count", 0.0)
        return total

    def quarantine_gc(self, retention_s: float) -> List[dict]:
        """Chain each target's quarantine GC (scheduled-fsck retention)."""
        findings: List[dict] = []
        for name, dao in self._targets():
            gc = getattr(dao, "quarantine_gc", None)
            if gc is None:
                continue
            try:
                found = gc(retention_s)
            except (StorageError, OSError) as e:
                found = [{"kind": "quarantine_gc_error", "reason": str(e),
                          "action": "none"}]
            for f in found:
                f.setdefault("target", name)
            findings.extend(found)
        return findings


class ReplicatedLeases(base.Leases):
    """Quorum lease over the targets: a holder is the leader only while
    a majority of target stores agree. Two routers racing through a
    partition can each win a minority of targets, but never two
    overlapping majorities — the same overlap argument as the quorum
    writes above. A failed (sub-quorum) acquire releases the targets it
    did win, so a partial grab never starves the actual winner."""

    def __init__(self, client: ReplicatedStorageClient):
        self.c = client
        self._lock = threading.Lock()
        self._daos: Optional[List[Tuple[str, base.Leases]]] = None

    def _targets(self) -> List[Tuple[str, base.Leases]]:
        with self._lock:
            if self._daos is None:
                daos = []
                for t in self.c.targets:
                    try:
                        daos.append(
                            (t, self.c.registry.get_data_object(t, "Leases")))
                    except StorageError as e:
                        _log.warning("lease_target_unsupported", target=t,
                                     error=str(e))
                self._daos = daos
            return self._daos

    def acquire(self, name: str, holder: str, ttl_s: float,
                journal: Optional[str] = None) -> Optional[Lease]:
        targets = self._targets()
        won: List[Tuple[str, base.Leases]] = []
        lease: Optional[Lease] = None
        for tname, dao in targets:
            try:
                got = dao.acquire(name, holder, ttl_s, journal)
            except (StorageError, OSError) as e:
                _log.warning("lease_acquire_failed", target=tname,
                             error=f"{type(e).__name__}: {e}")
                continue
            if got is not None:
                won.append((tname, dao))
                lease = got
        if len(won) >= self.c.quorum:
            return lease
        for tname, dao in won:
            try:
                dao.release(name, holder)
            except (StorageError, OSError):
                pass    # its TTL will expire it
        return None

    def get(self, name: str) -> Optional[Lease]:
        """The majority holder's freshest row; with no majority, the
        freshest row seen (conservative: callers treat any row as a
        possibly-live lease)."""
        rows: List[Lease] = []
        for tname, dao in self._targets():
            try:
                row = dao.get(name)
            except (StorageError, OSError):
                continue
            if row is not None:
                rows.append(row)
        if not rows:
            return None
        by_holder: Dict[str, List[Lease]] = {}
        for row in rows:
            by_holder.setdefault(row.holder, []).append(row)
        majority = [ls for ls in by_holder.values()
                    if len(ls) >= self.c.quorum]
        pool = majority[0] if majority else rows
        return max(pool, key=lambda l: l.expires_at)

    def release(self, name: str, holder: str) -> bool:
        released = False
        for tname, dao in self._targets():
            try:
                released = dao.release(name, holder) or released
            except (StorageError, OSError):
                pass
        return released

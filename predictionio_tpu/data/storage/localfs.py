"""Local-filesystem model store ("LOCALFS" type).

Parity: reference `storage/localfs/.../LocalFSModels.scala:62` — model blobs
as files `pio_model_<id>` under a configured directory.

Durability: every blob is wrapped in the integrity envelope
(`data/integrity.py`) and written atomically (tmp → fsync → rename), so
a crash mid-insert can never leave a torn file under the final name.
`get` verifies the checksum and raises `CorruptBlobError` on mismatch;
`fsck` sweeps the directory, quarantining corrupt blobs into
`.quarantine/` (with a `.reason` sidecar) and clearing orphaned `*.tmp`
files from interrupted writes.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
from pathlib import Path
from typing import List, Optional

from predictionio_tpu.data import integrity
from predictionio_tpu.data.event import from_millis, to_millis, utcnow
from predictionio_tpu.data.storage import base
from predictionio_tpu.data.storage.base import Lease, Model
from predictionio_tpu.resilience import FaultError, faults


class LocalFSStorageClient:
    def __init__(self, config: Optional[dict] = None):
        self.config = dict(config or {})
        path = self.config.get("PATH", self.config.get("path", "~/.pio_store/models"))
        self.path = Path(os.path.expanduser(path))
        self.path.mkdir(parents=True, exist_ok=True)
        self.source_name = self.config.get("SOURCE_NAME", "LOCALFS")


class LocalFSLeases(base.Leases):
    """Lease row as a JSON file (`pio_lease_<name>`), CAS'd under an
    O_EXCL lockfile — the only cross-process mutual exclusion a plain
    filesystem offers. A lockfile left behind by a crashed holder is
    broken after `_STALE_LOCK_S` (the CAS critical section is a few
    syscalls; anything holding it for seconds is dead)."""

    _STALE_LOCK_S = 5.0

    def __init__(self, client: LocalFSStorageClient):
        self.c = client

    def _file(self, name: str) -> Path:
        safe = "".join(ch if ch.isalnum() or ch in "-_" else "_"
                       for ch in name)
        return self.c.path / f"pio_lease_{safe}"

    @contextlib.contextmanager
    def _cas_lock(self, name: str, timeout_s: float = 2.0):
        lock = self._file(name).with_name(self._file(name).name + ".lock")
        pause = threading.Event()
        waited = 0.0
        while True:
            try:
                fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                os.close(fd)
                break
            except FileExistsError:
                try:
                    age = utcnow().timestamp() - lock.stat().st_mtime
                    if age > self._STALE_LOCK_S:
                        lock.unlink(missing_ok=True)
                        continue
                except OSError:
                    continue    # lock vanished between open and stat
                if waited >= timeout_s:
                    raise base.StorageUnavailableError(
                        f"lease lockfile {lock} held for {waited:.1f}s")
                pause.wait(0.005)
                waited += 0.005
        try:
            yield
        finally:
            lock.unlink(missing_ok=True)

    def _read(self, name: str) -> Optional[Lease]:
        try:
            data = json.loads(self._file(name).read_bytes())
        except (OSError, ValueError):
            return None
        return Lease(data["name"], data["holder"],
                     from_millis(data["expires_ms"]),
                     data.get("journal", ""))

    def acquire(self, name: str, holder: str, ttl_s: float,
                journal: Optional[str] = None) -> Optional[Lease]:
        with self._cas_lock(name):
            cur = self._read(name)
            now = utcnow()
            if cur is not None and cur.holder != holder \
                    and not cur.expired(now):
                return None
            keep = (cur.journal if cur is not None else "") \
                if journal is None else journal
            exp_ms = to_millis(now) + int(ttl_s * 1000)
            lease = Lease(name, holder, from_millis(exp_ms), keep)
            integrity.atomic_write_bytes(self._file(name), json.dumps({
                "name": name, "holder": holder, "expires_ms": exp_ms,
                "journal": keep}).encode())
            return lease

    def get(self, name: str) -> Optional[Lease]:
        # atomic rename on write: an unlocked read never sees a torn row
        return self._read(name)

    def release(self, name: str, holder: str) -> bool:
        with self._cas_lock(name):
            cur = self._read(name)
            if cur is None or cur.holder != holder:
                return False
            self._file(name).unlink(missing_ok=True)
            return True


class LocalFSModels(base.Models):
    def __init__(self, client: LocalFSStorageClient):
        self.c = client

    def _file(self, mid: str) -> Path:
        safe = "".join(ch if ch.isalnum() or ch in "-_" else "_" for ch in mid)
        return self.c.path / f"pio_model_{safe}"

    def insert(self, m: Model) -> None:
        wrapped = integrity.wrap(m.models)
        path = self._file(m.id)
        # crash-consistency seam: when a torn-write fault is armed, only
        # a fraction of the bytes reach the final path (simulating a
        # crash mid-write on a non-atomic store) and the "process dies"
        seam = f"storage.{self.c.source_name}.models.insert.torn"
        frac = faults().torn_fraction(seam)
        if frac is not None:
            path.write_bytes(wrapped[:int(len(wrapped) * frac)])  # lint: ok
            raise FaultError(f"injected torn write at {seam}")
        integrity.atomic_write_bytes(path, wrapped)

    def get(self, mid: str) -> Optional[Model]:
        f = self._file(mid)
        if not f.exists():
            return None
        return Model(mid, integrity.unwrap(f.read_bytes()))

    def delete(self, mid: str) -> None:
        f = self._file(mid)
        if f.exists():
            f.unlink()
        integrity.purge_tmp_siblings(f)

    def list_model_ids(self) -> List[str]:
        """Ids derived from the `pio_model_*` filenames (the escape in
        `_file` is lossy for non-alnum ids — see base.Models)."""
        return sorted(
            f.name[len("pio_model_"):] for f in self.c.path.glob("pio_model_*")
            if not f.name.endswith(".tmp"))

    def fsck(self, repair: bool = False) -> List[dict]:
        """Scan all blobs; quarantine corrupt ones and purge orphaned
        tmp files when `repair` is set. Returns finding dicts."""
        findings: List[dict] = []
        for f in sorted(self.c.path.glob("pio_model_*")):
            if f.name.endswith(".tmp"):
                finding = {"kind": "tmp_orphan", "path": str(f),
                           "reason": "leftover tmp from interrupted write",
                           "action": "none"}
                if repair:
                    try:
                        f.unlink()
                        finding["action"] = "removed"
                    except OSError as exc:
                        finding["action"] = f"remove failed: {exc}"
                findings.append(finding)
                continue
            try:
                ok, reason = integrity.verify(f.read_bytes())
            except OSError as exc:
                ok, reason = False, f"unreadable: {exc}"
            if ok:
                continue
            finding = {"kind": "corrupt_blob", "path": str(f),
                       "reason": reason, "action": "none"}
            if repair:
                dest = integrity.quarantine_file(f, reason)
                finding["action"] = f"quarantined -> {dest}"
            findings.append(finding)
        return findings

    def quarantine_stats(self) -> dict:
        """Footprint of `.quarantine/` (feeds pio_quarantine_bytes)."""
        qdir = self.c.path / ".quarantine"
        total, count = 0, 0
        if qdir.is_dir():
            for f in qdir.iterdir():
                if f.name.endswith(".reason") or not f.is_file():
                    continue
                total += f.stat().st_size
                count += 1
        return {"bytes": float(total), "count": float(count)}

    def quarantine_gc(self, retention_s: float) -> List[dict]:
        """Delete quarantined blobs (and their reason sidecars) older
        than the retention window — quarantine is a forensic holding
        area, not an archive. Age is measured from the `.reason`
        sidecar's mtime (stamped at quarantine time; os.replace
        preserves the blob's own, possibly ancient, mtime), falling
        back to the blob's mtime when the sidecar is gone."""
        qdir = self.c.path / ".quarantine"
        if not qdir.is_dir():
            return []
        now = utcnow().timestamp()
        cutoff = now - retention_s
        findings: List[dict] = []
        for f in sorted(qdir.iterdir()):
            if f.name.endswith(".reason") or not f.is_file():
                continue
            try:
                sidecar = f.with_name(f.name + ".reason")
                mtime = (sidecar.stat().st_mtime if sidecar.exists()
                         else f.stat().st_mtime)
            except OSError:
                continue
            if mtime > cutoff:
                continue
            age = now - mtime
            finding = {"kind": "quarantine_expired", "path": str(f),
                       "reason": f"quarantined {age:.0f}s ago "
                                 f"(retention {retention_s:.0f}s)",
                       "action": "none"}
            try:
                f.unlink()
                f.with_name(f.name + ".reason").unlink(missing_ok=True)
                finding["action"] = "deleted"
            except OSError as exc:
                finding["action"] = f"delete failed: {exc}"
            findings.append(finding)
        return findings

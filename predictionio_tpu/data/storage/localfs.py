"""Local-filesystem model store ("LOCALFS" type).

Parity: reference `storage/localfs/.../LocalFSModels.scala:62` — model blobs
as files `pio_model_<id>` under a configured directory.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Optional

from predictionio_tpu.data.storage import base
from predictionio_tpu.data.storage.base import Model


class LocalFSStorageClient:
    def __init__(self, config: Optional[dict] = None):
        self.config = dict(config or {})
        path = self.config.get("PATH", self.config.get("path", "~/.pio_store/models"))
        self.path = Path(os.path.expanduser(path))
        self.path.mkdir(parents=True, exist_ok=True)


class LocalFSModels(base.Models):
    def __init__(self, client: LocalFSStorageClient):
        self.c = client

    def _file(self, mid: str) -> Path:
        safe = "".join(ch if ch.isalnum() or ch in "-_" else "_" for ch in mid)
        return self.c.path / f"pio_model_{safe}"

    def insert(self, m: Model) -> None:
        self._file(m.id).write_bytes(m.models)

    def get(self, mid: str) -> Optional[Model]:
        f = self._file(mid)
        if not f.exists():
            return None
        return Model(mid, f.read_bytes())

    def delete(self, mid: str) -> None:
        f = self._file(mid)
        if f.exists():
            f.unlink()

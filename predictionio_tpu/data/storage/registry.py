"""Storage registry: config-driven backend discovery and DAO construction.

Parity: reference `data/.../storage/Storage.scala:147-452` — sources are
declared via `PIO_STORAGE_SOURCES_<NAME>_TYPE` (+ driver-specific keys like
`_PATH`), repositories bind the three data roles to sources via
`PIO_STORAGE_REPOSITORIES_{METADATA,EVENTDATA,MODELDATA}_{NAME,SOURCE}`.
Configuration layers (highest wins): explicit dict > process env >
`pio-env` file (simple KEY=VALUE lines) named by `$PIO_ENV_FILE` or found
at `./pio-env` / `~/.pio_store/pio-env`.

Unlike the reference's classpath reflection, drivers register here in an
explicit table (`DRIVERS`), extensible via `register_driver`. When no
configuration is present at all, a zero-config default of a single SQLITE
source at `./.pio_store/pio.db` is used so quickstarts need no setup.
"""

from __future__ import annotations

import os
import re
import threading
from pathlib import Path
from typing import Callable, Dict, Mapping, Optional, Tuple

from predictionio_tpu.data.storage import base
from predictionio_tpu.data.storage.base import (
    StorageError, TRANSIENT_STORAGE_ERRORS,
)
from predictionio_tpu.data.storage.resilient import ResilientDAO
from predictionio_tpu.resilience import (
    CircuitBreaker, RetryBudget, RetryPolicy,
)


# type name -> (client factory, {dao role -> DAO class name on module})
DRIVERS: Dict[str, Dict[str, object]] = {}


def register_driver(type_name: str, client_factory: Callable,
                    daos: Mapping[str, Callable]) -> None:
    DRIVERS[type_name.upper()] = {"client": client_factory, "daos": dict(daos)}


def _register_builtin_drivers() -> None:
    from predictionio_tpu.data.storage import localfs, memory, sqlite

    register_driver("MEM", memory.MemStorageClient, {
        "Apps": memory.MemApps,
        "AccessKeys": memory.MemAccessKeys,
        "Channels": memory.MemChannels,
        "EngineInstances": memory.MemEngineInstances,
        "EvaluationInstances": memory.MemEvaluationInstances,
        "Models": memory.MemModels,
        "Leases": memory.MemLeases,
        "TenantQuotas": memory.MemTenantQuotas,
        "SLOObjectives": memory.MemSLOObjectives,
        "Events": memory.MemEvents,
    })
    register_driver("SQLITE", sqlite.SQLiteStorageClient, {
        "Apps": sqlite.SQLiteApps,
        "AccessKeys": sqlite.SQLiteAccessKeys,
        "Channels": sqlite.SQLiteChannels,
        "EngineInstances": sqlite.SQLiteEngineInstances,
        "EvaluationInstances": sqlite.SQLiteEvaluationInstances,
        "Models": sqlite.SQLiteModels,
        "Leases": sqlite.SQLiteLeases,
        "TenantQuotas": sqlite.SQLiteTenantQuotas,
        "SLOObjectives": sqlite.SQLiteSLOObjectives,
        "Events": sqlite.SQLiteEvents,
    })
    register_driver("LOCALFS", localfs.LocalFSStorageClient, {
        "Models": localfs.LocalFSModels,
        "Leases": localfs.LocalFSLeases,
    })
    from predictionio_tpu.data.storage import evlog, objectstore, postgres

    # event data on the native C++ append-only journal (the hbase-role
    # durable event store)
    register_driver("EVLOG", evlog.EvlogStorageClient, {
        "Events": evlog.EvlogEvents,
    })

    # the scalable INDEXED event store: time-bucketed segment journals
    # with minmax + entity-bloom sidecar indexes, so find() prunes
    # segments instead of scanning (the HBase rowkey-design role,
    # HBEventsUtil.scala:54-110)
    from predictionio_tpu.data.storage import pevlog
    register_driver("PEVLOG", pevlog.PevlogStorageClient, {
        "Events": pevlog.PevlogEvents,
    })

    # networked SQL backend (the reference's jdbc/PGSQL driver set);
    # the wire connection is only opened when the source is used
    for type_name in ("POSTGRES", "PGSQL"):
        register_driver(type_name, postgres.PostgresStorageClient, {
            "Apps": postgres.PostgresApps,
            "AccessKeys": postgres.PostgresAccessKeys,
            "Channels": postgres.PostgresChannels,
            "EngineInstances": postgres.PostgresEngineInstances,
            "EvaluationInstances": postgres.PostgresEvaluationInstances,
            "Models": postgres.PostgresModels,
            "TenantQuotas": postgres.PostgresTenantQuotas,
            "SLOObjectives": postgres.PostgresSLOObjectives,
            "Events": postgres.PostgresEvents,
        })

    # S3/HDFS are the reference's driver names (S3Models.scala,
    # HDFSModels.scala); OBJECTSTORE is the generic fsspec-URL form.
    # fsspec itself is imported lazily at client construction, so a
    # missing fsspec surfaces as a clear StorageError only when an
    # object-store source is actually used.
    for type_name in ("OBJECTSTORE", "S3", "HDFS"):
        register_driver(type_name, objectstore.ObjectStoreStorageClient,
                        {"Models": objectstore.ObjectStoreModels})

    # virtual Models source fanning out over other configured sources
    # (quorum writes + read-repair; see replicated.py)
    from predictionio_tpu.data.storage import replicated
    register_driver("REPLICATED", replicated.ReplicatedStorageClient,
                    {"Models": replicated.ReplicatedModels,
                     "Leases": replicated.ReplicatedLeases})


_register_builtin_drivers()

REPOSITORIES = ("METADATA", "EVENTDATA", "MODELDATA")
_SOURCE_RE = re.compile(r"^PIO_STORAGE_SOURCES_([^_]+)_(.+)$")
_REPO_RE = re.compile(r"^PIO_STORAGE_REPOSITORIES_([^_]+)_(NAME|SOURCE)$")


def load_env_file(path: Optional[str] = None) -> Dict[str, str]:
    """Load KEY=VALUE lines from a pio-env file (bin/load-pio-env.sh analog)."""
    candidates = [path] if path else [
        os.environ.get("PIO_ENV_FILE"),
        "./pio-env", os.path.expanduser("~/.pio_store/pio-env")]
    out: Dict[str, str] = {}
    for cand in candidates:
        if cand and Path(cand).is_file():
            for line in Path(cand).read_text().splitlines():
                line = line.strip()
                if not line or line.startswith("#") or "=" not in line:
                    continue
                k, v = line.split("=", 1)
                out[k.strip()] = v.strip().strip('"').strip("'")
            break
    return out


def effective_config(overrides: Optional[Mapping[str, str]] = None
                     ) -> Dict[str, str]:
    """Layered config: env file < process env < explicit overrides."""
    cfg = load_env_file()
    cfg.update({k: v for k, v in os.environ.items() if k.startswith("PIO_")})
    if overrides:
        cfg.update(overrides)
    return cfg


class StorageRegistry:
    """Holds sources (driver clients) and repository bindings; hands out DAOs.

    The accessor surface mirrors `Storage.scala:399-452`.
    """

    def __init__(self, config: Optional[Mapping[str, str]] = None):
        self.config = effective_config(config)
        self._lock = threading.RLock()
        self._clients: Dict[str, object] = {}
        self._daos: Dict[Tuple[str, str], object] = {}
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._budgets: Dict[str, Optional[RetryBudget]] = {}
        self.sources, self.repositories = self._parse(self.config)

    @staticmethod
    def _parse(cfg: Mapping[str, str]):
        sources: Dict[str, Dict[str, str]] = {}
        for k, v in cfg.items():
            m = _SOURCE_RE.match(k)
            if m:
                sources.setdefault(m.group(1), {})[m.group(2)] = v
        repos: Dict[str, Dict[str, str]] = {}
        for k, v in cfg.items():
            m = _REPO_RE.match(k)
            if m:
                repos.setdefault(m.group(1), {})[m.group(2)] = v
        if not sources:
            # zero-config default: one sqlite file source for everything
            sources = {"PIO": {"TYPE": "SQLITE",
                               "PATH": "./.pio_store/pio.db"}}
        # a repository without an explicit SOURCE binds to the first
        # source whose driver actually supports the DAOs that repo needs
        # (a Models-only object store must not become the METADATA repo)
        needs = {"METADATA": "Apps", "EVENTDATA": "Events",
                 "MODELDATA": "Models"}
        for repo in REPOSITORIES:
            repos.setdefault(repo, {})
            if "SOURCE" not in repos[repo]:
                candidates = [
                    name for name, scfg in sources.items()
                    if needs[repo] in DRIVERS.get(
                        scfg.get("TYPE", "").upper(), {}).get("daos", {})]
                repos[repo]["SOURCE"] = (candidates[0] if candidates
                                         else next(iter(sources)))
            repos[repo].setdefault("NAME", "pio_" + repo.lower())
        for name, scfg in sources.items():
            if "TYPE" not in scfg:
                raise StorageError(
                    f"Storage source {name} has no TYPE configured "
                    f"(PIO_STORAGE_SOURCES_{name}_TYPE)")
            if scfg["TYPE"].upper() not in DRIVERS:
                raise StorageError(
                    f"Storage source {name} has unknown TYPE "
                    f"{scfg['TYPE']!r}; known: {sorted(DRIVERS)}")
        return sources, repos

    # -- plumbing -----------------------------------------------------------
    def _client(self, source_name: str):
        with self._lock:
            if source_name not in self._clients:
                if source_name not in self.sources:
                    raise StorageError(f"Undefined storage source: {source_name}")
                scfg = dict(self.sources[source_name])
                # drivers see their own source name (chaos seams, fsck
                # reports, and quarantine metrics are labelled with it)
                scfg.setdefault("SOURCE_NAME", source_name)
                driver = DRIVERS[scfg["TYPE"].upper()]
                if scfg["TYPE"].upper() == "SQLITE" and "PATH" in scfg:
                    Path(scfg["PATH"]).expanduser().parent.mkdir(
                        parents=True, exist_ok=True)
                factory = driver["client"]
                if getattr(factory, "needs_registry", False):
                    # virtual sources (REPLICATED) resolve their target
                    # DAOs back through this registry
                    self._clients[source_name] = factory(
                        scfg, registry=self)
                else:
                    self._clients[source_name] = factory(scfg)
            return self._clients[source_name]

    def get_data_object(self, source_name: str, dao: str):
        """Parity: Storage.getDataObject (Storage.scala:308-357). The
        returned DAO is wrapped in the resilience proxy (retry + per-
        source circuit breaker + chaos seams) unless the source sets
        `PIO_STORAGE_SOURCES_<N>_RESILIENCE=off`."""
        with self._lock:
            key = (source_name, dao)
            if key not in self._daos:
                scfg = self.sources[source_name]
                driver = DRIVERS[scfg["TYPE"].upper()]
                if dao not in driver["daos"]:
                    raise StorageError(
                        f"Storage type {scfg['TYPE']} does not support "
                        f"data object {dao}")
                raw = driver["daos"][dao](self._client(source_name))
                self._daos[key] = self._wrap_resilient(
                    raw, source_name, dao, scfg)
            return self._daos[key]

    def _wrap_resilient(self, dao: object, source: str, dao_name: str,
                        scfg: Mapping[str, str]):
        """Per-source resilience knobs (all optional, via
        PIO_STORAGE_SOURCES_<N>_*): RESILIENCE=off disables wrapping;
        RETRY_ATTEMPTS / RETRY_BASE_DELAY tune the retry schedule;
        BREAKER_THRESHOLD / BREAKER_RECOVERY_S tune the breaker;
        RETRY_BUDGET caps aggregate retry amplification (tokens,
        0/off disables)."""
        # REPLICATED is a virtual source: each of its targets already
        # carries its own retry/breaker/budget wrapper, so double-
        # wrapping would retry a quorum failure that is by design final
        default_resilience = ("off" if scfg.get("TYPE", "").upper() ==
                              "REPLICATED" else "on")
        if str(scfg.get("RESILIENCE", default_resilience)).lower() in (
                "off", "0", "false", "no"):
            return dao
        policy = RetryPolicy(
            attempts=int(scfg.get("RETRY_ATTEMPTS", 3)),
            base_delay=float(scfg.get("RETRY_BASE_DELAY", 0.05)),
            retryable=TRANSIENT_STORAGE_ERRORS)
        return ResilientDAO(
            dao, seam=f"storage.{source}.{dao_name}", source=source,
            breaker=self._breaker(source, scfg), policy=policy,
            budget=self._budget(source, scfg))

    def _breaker(self, source: str, scfg: Mapping[str, str]) -> CircuitBreaker:
        breaker = self._breakers.get(source)
        if breaker is None:
            breaker = CircuitBreaker(
                f"storage.{source}",
                failure_threshold=int(scfg.get("BREAKER_THRESHOLD", 5)),
                recovery_time=float(scfg.get("BREAKER_RECOVERY_S", 30.0)))
            self._breakers[source] = breaker
        return breaker

    def _budget(self, source: str,
                scfg: Mapping[str, str]) -> Optional[RetryBudget]:
        """One shared retry budget per source (all its DAOs draw from
        the same bucket — amplification is a per-backend phenomenon)."""
        if source in self._budgets:
            return self._budgets[source]
        raw = str(scfg.get("RETRY_BUDGET", "50")).lower()
        budget: Optional[RetryBudget] = None
        if raw not in ("off", "0", "false", "no", "none", ""):
            budget = RetryBudget(capacity=float(raw))
        self._budgets[source] = budget
        return budget

    def breaker_states(self) -> Dict[str, str]:
        """Current breaker state per active source ('closed' / 'open' /
        'half-open'); feeds every server's /ready endpoint."""
        with self._lock:
            breakers = dict(self._breakers)
        return {name: b.state for name, b in breakers.items()}

    def _repo_dao(self, repo: str, dao: str):
        return self.get_data_object(self.repositories[repo]["SOURCE"], dao)

    # -- public accessors (Storage.scala:399-452) ---------------------------
    def get_meta_data_apps(self) -> base.Apps:
        return self._repo_dao("METADATA", "Apps")

    def get_meta_data_access_keys(self) -> base.AccessKeys:
        return self._repo_dao("METADATA", "AccessKeys")

    def get_meta_data_channels(self) -> base.Channels:
        return self._repo_dao("METADATA", "Channels")

    def get_meta_data_engine_instances(self) -> base.EngineInstances:
        return self._repo_dao("METADATA", "EngineInstances")

    def get_meta_data_evaluation_instances(self) -> base.EvaluationInstances:
        return self._repo_dao("METADATA", "EvaluationInstances")

    def get_model_data_models(self) -> base.Models:
        return self._repo_dao("MODELDATA", "Models")

    def get_leases(self) -> base.Leases:
        """Lease DAO on the MODELDATA repo's source (the store every
        router in a fleet shares). Sources whose driver has no Leases
        DAO (object stores) raise StorageError — the fleet degrades to
        always-leader with a warning."""
        return self._repo_dao("MODELDATA", "Leases")

    def get_meta_data_tenant_quotas(self) -> base.TenantQuotas:
        """Per-app admission-override DAO. Sources whose driver has no
        TenantQuotas DAO raise StorageError — the serving admission
        controller degrades to its env/CLI defaults with a warning."""
        return self._repo_dao("METADATA", "TenantQuotas")

    def get_meta_data_slo_objectives(self) -> base.SLOObjectives:
        """Per-app SLO-override DAO. Sources whose driver has no
        SLOObjectives DAO raise StorageError — the SLO tracker degrades
        to its env defaults."""
        return self._repo_dao("METADATA", "SLOObjectives")

    def get_events(self) -> base.EventStore:
        """The LEvents/PEvents analog (training reads go through ingest/)."""
        return self._repo_dao("EVENTDATA", "Events")

    def verify_all_data_objects(self) -> bool:
        """Smoke-test every repository (Storage.scala:370-392)."""
        self.get_meta_data_apps()
        self.get_meta_data_access_keys()
        self.get_meta_data_channels()
        self.get_meta_data_engine_instances()
        self.get_meta_data_evaluation_instances()
        self.get_model_data_models()
        events = self.get_events()
        events.init(0)
        events.remove(0)
        return True

    def close(self) -> None:
        with self._lock:
            for client in self._clients.values():
                close = getattr(client, "close", None)
                if close:
                    close()
            self._clients.clear()
            self._daos.clear()


_default: Optional[StorageRegistry] = None
_default_lock = threading.Lock()


def storage(refresh: bool = False) -> StorageRegistry:
    """The process-wide default registry, built from env on first use."""
    global _default
    with _default_lock:
        if _default is None or refresh:
            _default = StorageRegistry()
        return _default


def set_default(registry: Optional[StorageRegistry]) -> None:
    """Install (or clear) the process-default registry; used by tests/CLI."""
    global _default
    with _default_lock:
        _default = registry

"""Storage SPI: DAO interfaces and metadata records.

Parity targets in the reference:
  - meta records/DAOs: `data/.../storage/{Apps,AccessKeys,Channels,
    EngineInstances,EvaluationInstances,Models}.scala`
  - event DAO: `data/.../storage/LEvents.scala:40-520` (the non-Spark event
    access used by servers and the CLI). The reference's separate `PEvents`
    (Spark RDD access) has no direct analog here: its role — bulk reads for
    training — is played by `predictionio_tpu.ingest`, which streams
    `EventStore.find` results into dense sharded jax.Arrays.

Drivers implement these ABCs and are discovered by the registry in
`predictionio_tpu.data.storage` (see `registry.py`) from layered config, the
analog of `Storage.scala:159-357`'s env-driven reflection.
"""

from __future__ import annotations

import abc
import base64
import secrets
import re
from dataclasses import dataclass, field, replace
from datetime import datetime
from typing import Any, Dict, Iterator, List, Mapping, Optional, Sequence

from predictionio_tpu.data.aggregate import aggregate_properties
from predictionio_tpu.data.event import Event, PropertyMap, utcnow


class StorageError(Exception):
    """Parity: StorageException (Storage.scala:88)."""


class StorageWriteError(StorageError):
    """A write rejected by the backend (duplicate key, constraint violation)."""


class StorageUnavailableError(StorageError):
    """A transient backend failure (connection refused, timeout, flaky
    remote). Drivers raise this — or a plain OSError — for conditions a
    retry can cure; the resilience proxy (`resilient.py`) retries these
    and trips the source's circuit breaker when they persist. Client
    errors (StorageWriteError, bad params) must NOT use this type."""


# what the storage resilience layer treats as retryable / breaker-tripping
# (ConnectionError and TimeoutError are OSError subclasses)
TRANSIENT_STORAGE_ERRORS = (StorageUnavailableError, OSError)


# ---------------------------------------------------------------------------
# Meta data records
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class App:
    """An application namespace for events (Apps.scala:25-35)."""
    id: int
    name: str
    description: Optional[str] = None


@dataclass(frozen=True)
class AccessKey:
    """An API access key; empty `events` list = all events allowed
    (AccessKeys.scala:25-38)."""
    key: str
    appid: int
    events: Sequence[str] = ()


CHANNEL_NAME_RE = re.compile(r"^[a-zA-Z0-9-]{1,16}$")
CHANNEL_NAME_CONSTRAINT = (
    "Only alphanumeric and - characters are allowed and max length is 16.")


@dataclass(frozen=True)
class Channel:
    """A named event channel within an app (Channels.scala:25-62)."""
    id: int
    name: str
    appid: int

    def __post_init__(self):
        if not self.is_valid_name(self.name):
            raise ValueError(
                f"Invalid channel name: {self.name}. {CHANNEL_NAME_CONSTRAINT}")

    @staticmethod
    def is_valid_name(s: str) -> bool:
        return bool(CHANNEL_NAME_RE.match(s))


class EngineInstanceStatus:
    INIT = "INIT"
    TRAINING = "TRAINING"
    COMPLETED = "COMPLETED"
    FAILED = "FAILED"


class EvaluationInstanceStatus:
    INIT = "EVALINIT"
    RUNNING = "EVALRUNNING"
    COMPLETED = "EVALCOMPLETED"


@dataclass(frozen=True)
class EngineInstance:
    """Metadata row for one train run (EngineInstances.scala:25-60).

    `runtime_conf` replaces the reference's `sparkConf`: it carries the
    JAX runtime configuration (mesh shape, platform, precision flags).
    """
    id: str = ""
    status: str = ""
    start_time: datetime = field(default_factory=utcnow)
    end_time: datetime = field(default_factory=utcnow)
    engine_id: str = ""
    engine_version: str = ""
    engine_variant: str = ""
    engine_factory: str = ""
    batch: str = ""
    env: Mapping[str, str] = field(default_factory=dict)
    runtime_conf: Mapping[str, Any] = field(default_factory=dict)
    data_source_params: str = ""
    preparator_params: str = ""
    algorithms_params: str = ""
    serving_params: str = ""
    # last liveness beat from the training process; the stale-instance
    # janitor fails INIT/TRAINING rows whose heartbeat (or, if never
    # beaten, start_time) is older than the staleness threshold
    heartbeat: Optional[datetime] = None

    def with_(self, **kw) -> "EngineInstance":
        return replace(self, **kw)


@dataclass(frozen=True)
class EvaluationInstance:
    """Metadata row for one eval run (EvaluationInstances.scala:25-56)."""
    id: str = ""
    status: str = ""
    start_time: datetime = field(default_factory=utcnow)
    end_time: datetime = field(default_factory=utcnow)
    evaluation_class: str = ""
    engine_params_generator_class: str = ""
    batch: str = ""
    env: Mapping[str, str] = field(default_factory=dict)
    runtime_conf: Mapping[str, Any] = field(default_factory=dict)
    evaluator_results: str = ""
    evaluator_results_html: str = ""
    evaluator_results_json: str = ""

    def with_(self, **kw) -> "EvaluationInstance":
        return replace(self, **kw)


@dataclass(frozen=True)
class Model:
    """Serialized model blob keyed by engine instance ID (Models.scala:25-33)."""
    id: str
    models: bytes


@dataclass(frozen=True)
class Lease:
    """A TTL lease row: the fleet control plane's leader-election
    primitive (no reference analog — the reference's CreateServer is a
    single actor system; cross-host leader handoff needs shared state).

    `journal` is an opaque payload the holder may update while the
    lease is held — the fleet writes its rolling-reload progress there
    so a standby taking over can detect a half-rolled fleet and finish
    or abort it instead of leaving it silently mixed."""
    name: str
    holder: str
    expires_at: datetime
    journal: str = ""

    def expired(self, now: Optional[datetime] = None) -> bool:
        return (now or utcnow()) >= self.expires_at


@dataclass(frozen=True)
class TenantQuota:
    """Per-app admission-control override row (no reference analog —
    the reference is multi-app on ingest only; serve-side quotas are
    this port's million-user follow-on). Every field except `appid` is
    Optional: None means 'inherit the server-wide default' so an
    operator can raise one knob without freezing the rest.

    `channel` scopes the row WITHIN an app: "" (the default) is the
    app-wide row; a non-empty channel names a sub-quota that inherits
    every unset field from the app-wide row, which in turn inherits
    from the server default — a three-level resolution chain the
    admission controller walks (channel.merged_over(app).merged_over(
    default)). The field is LAST so `TenantQuota(*row)` positional
    construction from pre-channel readers keeps working."""
    appid: int
    rate: Optional[float] = None         # token-bucket refill, req/s
    burst: Optional[float] = None        # bucket capacity, requests
    concurrency: Optional[int] = None    # in-flight cap (0 = unlimited)
    queue_max: Optional[int] = None      # micro-batch pending cap
    weight: Optional[float] = None       # DRR drain weight
    channel: str = ""                    # "" = app-wide row

    def merged_over(self, other: "TenantQuota") -> "TenantQuota":
        """This row's explicit fields over `other`'s (defaults)."""
        return TenantQuota(
            appid=self.appid,
            rate=self.rate if self.rate is not None else other.rate,
            burst=self.burst if self.burst is not None else other.burst,
            concurrency=(self.concurrency if self.concurrency is not None
                         else other.concurrency),
            queue_max=(self.queue_max if self.queue_max is not None
                       else other.queue_max),
            weight=self.weight if self.weight is not None else other.weight,
            channel=self.channel)


@dataclass(frozen=True)
class SLOObjective:
    """Per-app service-level objective override row (read by
    `obs/slo.py`'s burn-rate tracker, the serving-side counterpart of
    `TenantQuota`). None means 'inherit the server-wide default' from
    PIO_SLO_LATENCY_MS / PIO_SLO_TARGET."""
    appid: int
    latency_ms: Optional[float] = None   # good-event latency threshold
    target: Optional[float] = None       # availability objective, e.g. 0.999


# ---------------------------------------------------------------------------
# DAO interfaces
# ---------------------------------------------------------------------------

class Apps(abc.ABC):
    """App CRUD (Apps.scala:43-61)."""

    @abc.abstractmethod
    def insert(self, app: App) -> Optional[int]:
        """Insert; a 0 id means 'generate one'. Returns the effective id."""

    @abc.abstractmethod
    def get(self, app_id: int) -> Optional[App]: ...

    @abc.abstractmethod
    def get_by_name(self, name: str) -> Optional[App]: ...

    @abc.abstractmethod
    def get_all(self) -> List[App]: ...

    @abc.abstractmethod
    def update(self, app: App) -> None: ...

    @abc.abstractmethod
    def delete(self, app_id: int) -> None: ...


class AccessKeys(abc.ABC):
    """Access key CRUD + generation (AccessKeys.scala:46-77)."""

    @abc.abstractmethod
    def insert(self, k: AccessKey) -> Optional[str]:
        """Insert; empty key means 'generate one'. Returns the effective key."""

    @abc.abstractmethod
    def get(self, key: str) -> Optional[AccessKey]: ...

    @abc.abstractmethod
    def get_all(self) -> List[AccessKey]: ...

    @abc.abstractmethod
    def get_by_appid(self, appid: int) -> List[AccessKey]: ...

    @abc.abstractmethod
    def update(self, k: AccessKey) -> None: ...

    @abc.abstractmethod
    def delete(self, key: str) -> None: ...

    def generate_key(self) -> str:
        """URL-safe 48-byte random key, never starting with '-'
        (AccessKeys.scala:68-77)."""
        while True:
            key = base64.urlsafe_b64encode(secrets.token_bytes(48)).decode().rstrip("=")
            if not key.startswith("-"):
                return key


class Channels(abc.ABC):
    """Channel CRUD (Channels.scala:64-81)."""

    @abc.abstractmethod
    def insert(self, channel: Channel) -> Optional[int]:
        """Insert; a 0 id means 'generate one'. Returns the effective id."""

    @abc.abstractmethod
    def get(self, channel_id: int) -> Optional[Channel]: ...

    @abc.abstractmethod
    def get_by_appid(self, appid: int) -> List[Channel]: ...

    @abc.abstractmethod
    def delete(self, channel_id: int) -> None: ...


class EngineInstances(abc.ABC):
    """Engine instance registry (EngineInstances.scala:62-100)."""

    @abc.abstractmethod
    def insert(self, i: EngineInstance) -> str: ...

    @abc.abstractmethod
    def get(self, iid: str) -> Optional[EngineInstance]: ...

    @abc.abstractmethod
    def get_all(self) -> List[EngineInstance]: ...

    @abc.abstractmethod
    def get_latest_completed(self, engine_id: str, engine_version: str,
                             engine_variant: str) -> Optional[EngineInstance]:
        """Most recent COMPLETED instance for (id, version, variant) — the
        row `deploy` resolves (EngineInstances.scala getLatestCompleted)."""

    @abc.abstractmethod
    def get_completed(self, engine_id: str, engine_version: str,
                      engine_variant: str) -> List[EngineInstance]: ...

    @abc.abstractmethod
    def update(self, i: EngineInstance) -> None: ...

    @abc.abstractmethod
    def delete(self, iid: str) -> None: ...

    def record_heartbeat(self, iid: str,
                         ts: Optional[datetime] = None) -> None:
        """Refresh the liveness beat on a row (default impl: get+update;
        drivers may override with a single-column write)."""
        row = self.get(iid)
        if row is not None:
            self.update(row.with_(heartbeat=ts or utcnow()))


class EvaluationInstances(abc.ABC):
    """Evaluation instance registry (EvaluationInstances.scala:58-84)."""

    @abc.abstractmethod
    def insert(self, i: EvaluationInstance) -> str: ...

    @abc.abstractmethod
    def get(self, iid: str) -> Optional[EvaluationInstance]: ...

    @abc.abstractmethod
    def get_all(self) -> List[EvaluationInstance]: ...

    @abc.abstractmethod
    def get_completed(self) -> List[EvaluationInstance]:
        """COMPLETED instances, reverse-sorted by start time."""

    @abc.abstractmethod
    def update(self, i: EvaluationInstance) -> None: ...

    @abc.abstractmethod
    def delete(self, iid: str) -> None: ...


class Models(abc.ABC):
    """Model blob store (Models.scala:36-45)."""

    @abc.abstractmethod
    def insert(self, m: Model) -> None: ...

    @abc.abstractmethod
    def get(self, mid: str) -> Optional[Model]: ...

    @abc.abstractmethod
    def delete(self, mid: str) -> None: ...

    def list_model_ids(self) -> List[str]:
        """Store-enumerable model ids, sorted. Default: the driver
        cannot enumerate (object stores without listing, etc.) — the
        fsck/doctor sweeps then fall back to metadata-derived ids
        alone. Drivers with lossy key escaping (localfs) return the
        ESCAPED names; instance ids are alphanumeric so the escape is
        the identity for every id the system itself writes."""
        return []


class Leases(abc.ABC):
    """TTL lease DAO — compare-and-swap leader election on shared
    storage. Acquire semantics (the only subtle part): `acquire`
    succeeds iff the row is absent, expired, or already held by the
    same holder (re-acquire == renew). Clocks are the metadata store's
    callers' — holders must pick TTLs that dominate their renewal
    jitter, not rely on sub-second fencing."""

    @abc.abstractmethod
    def acquire(self, name: str, holder: str, ttl_s: float,
                journal: Optional[str] = None) -> Optional[Lease]:
        """CAS-acquire/renew `name` for `holder` with a fresh TTL.
        Returns the new lease row on success, None when a different
        holder's unexpired lease exists. `journal=None` preserves the
        row's existing journal — even across a holder change, so a
        standby taking over an expired lease inherits the previous
        leader's roll journal atomically; a string replaces it (empty
        string clears it)."""

    @abc.abstractmethod
    def get(self, name: str) -> Optional[Lease]:
        """The current row, expired or not; None when absent. Callers
        decide what expiry means (`lease.expired()`)."""

    @abc.abstractmethod
    def release(self, name: str, holder: str) -> bool:
        """Delete the row iff `holder` still owns it. True when the
        row was deleted (a graceful step-down); False when someone
        else holds it or it is gone already."""


class TenantQuotas(abc.ABC):
    """Per-app quota-override CRUD on the metadata store, read by the
    serving admission controller (cached, so a write lands within its
    refresh interval, not instantly)."""

    @abc.abstractmethod
    def upsert(self, quota: TenantQuota) -> None:
        """Insert or fully replace the override row for
        `(quota.appid, quota.channel)`."""

    @abc.abstractmethod
    def get(self, appid: int,
            channel: str = "") -> Optional[TenantQuota]: ...

    @abc.abstractmethod
    def get_all(self) -> List[TenantQuota]: ...

    @abc.abstractmethod
    def delete(self, appid: int, channel: str = "") -> None: ...


class SLOObjectives(abc.ABC):
    """Per-app SLO-override CRUD on the metadata store, read by the
    serving SLO tracker (TTL-cached, like `TenantQuotas`)."""

    @abc.abstractmethod
    def upsert(self, slo: SLOObjective) -> None:
        """Insert or fully replace the override row for `slo.appid`."""

    @abc.abstractmethod
    def get(self, appid: int) -> Optional[SLOObjective]: ...

    @abc.abstractmethod
    def get_all(self) -> List[SLOObjective]: ...

    @abc.abstractmethod
    def delete(self, appid: int) -> None: ...


# ---------------------------------------------------------------------------
# Event store
# ---------------------------------------------------------------------------

_UNSET = object()  # sentinel distinguishing "no filter" from "filter == None"


class DeltaInvalidated(Exception):
    """A `scan_columns(since=...)` delta cannot be decoded safely — a
    delete/tombstone, external-id overwrite, journal rewrite, or
    over-budget delta landed between the two watermarks, or the driver
    has no delta path at all. Callers MUST fall back to a full scan
    (the watermark-keyed full `scan_columns`), which remains the ground
    truth. Deliberately NOT an OSError: the resilience layer must not
    retry it as a transient storage fault."""


def match_properties(e: Event, properties: Dict[str, object]) -> bool:
    """True iff every (name, value) filter pair appears verbatim in the
    event's properties (the ES field-value query role). Uses the
    PropertyMap's own `in`/`[]` — `.fields` copies the dict, which adds
    up on the per-event post-filter path."""
    pm = e.properties
    for k, v in properties.items():
        if k not in pm or pm[k] != v:
            return False
    return True


class EventStore(abc.ABC):
    """Event DAO, the analog of the reference's `LEvents` trait
    (LEvents.scala:40-520). All operations are scoped to an (app, channel);
    channel_id None = the app's default channel.

    Filter semantics of `find` match `LEvents.futureFind`:
      - start_time inclusive, until_time exclusive
      - event_names: any-of filter
      - target_entity_type/id use a three-state convention: leave the kwarg
        at its default for "no filter"; pass None to match events WITHOUT a
        target entity; pass a string to match it exactly (the reference's
        Option[Option[String]]).
    """

    @abc.abstractmethod
    def init(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        """Initialize storage for an (app, channel); idempotent."""

    @abc.abstractmethod
    def remove(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        """Drop all events of an (app, channel)."""

    @abc.abstractmethod
    def close(self) -> None: ...

    def insert(self, event: Event, app_id: int,
               channel_id: Optional[int] = None) -> str:
        """Insert one event (validated first); returns its id."""
        from predictionio_tpu.data.event import EventValidation
        EventValidation.validate(event)
        return self._insert(event, app_id, channel_id)

    def insert_batch(self, events: Sequence[Event], app_id: int,
                     channel_id: Optional[int] = None) -> List[str]:
        from predictionio_tpu.data.event import EventValidation
        for e in events:
            EventValidation.validate(e)
        return self._insert_batch(events, app_id, channel_id)

    @abc.abstractmethod
    def _insert(self, event: Event, app_id: int,
                channel_id: Optional[int] = None) -> str: ...

    def _insert_batch(self, events: Sequence[Event], app_id: int,
                      channel_id: Optional[int] = None) -> List[str]:
        return [self._insert(e, app_id, channel_id) for e in events]

    @abc.abstractmethod
    def get(self, event_id: str, app_id: int,
            channel_id: Optional[int] = None) -> Optional[Event]: ...

    @abc.abstractmethod
    def delete(self, event_id: str, app_id: int,
               channel_id: Optional[int] = None) -> bool: ...

    @abc.abstractmethod
    def find(self, app_id: int, channel_id: Optional[int] = None, *,
             start_time: Optional[datetime] = None,
             until_time: Optional[datetime] = None,
             entity_type: Optional[str] = None,
             entity_id: Optional[str] = None,
             event_names: Optional[Sequence[str]] = None,
             target_entity_type: object = _UNSET,
             target_entity_id: object = _UNSET,
             properties: Optional[Dict[str, object]] = None,
             limit: Optional[int] = None,
             reversed: bool = False) -> Iterator[Event]:
        """Find events; limit None = unlimited, limit <= 0 = unlimited
        (LEvents futureFind; the API layer applies its own default of 20).
        reversed=True requires entity_type+entity_id in the API layer; the
        store just sorts descending by event time.

        `properties` filters on exact property values: an event matches
        when every (name, value) pair appears in its properties — the
        arbitrary field-value query the reference delegates to
        Elasticsearch's query DSL (ESLEvents.scala:308). Every driver
        supports it (post-filter); PEVLOG additionally pushes it down to
        a per-segment property Bloom so non-matching segments are never
        replayed."""

    # -- derived operations --------------------------------------------------
    def scan_columns(self, app_id: int, channel_id: Optional[int] = None, *,
                     start_time: Optional[datetime] = None,
                     until_time: Optional[datetime] = None,
                     entity_type: Optional[str] = None,
                     entity_id: Optional[str] = None,
                     event_names: Optional[Sequence[str]] = None,
                     target_entity_type: object = _UNSET,
                     target_entity_id: object = _UNSET,
                     properties: Optional[Dict[str, object]] = None,
                     value_spec=None, require_target: bool = True,
                     workers: Optional[int] = None,
                     since: Optional[Dict[str, int]] = None,
                     upto: Optional[Dict[str, int]] = None):
        """Columnar training scan: `find` filter semantics, but the
        result is an `EventColumns` struct (interned int32 entity ids,
        float32 values per `value_spec`, int64 event times) instead of
        an Event iterator — the zero-object path template DataSources
        feed into `RatingColumns.from_store`/`PairColumns.from_store`.

        `since` is an `ingest_watermark()` snapshot: decode ONLY data
        appended after it (the streaming delta path), raising
        `DeltaInvalidated` whenever the delta cannot be produced exactly
        (deletes, rewrites, unsupported driver — this base
        implementation always raises, since `find` has no append-order
        lower bound). `upto` pins the delta's exclusive upper bound to a
        watermark the caller snapshotted BEFORE the scan, so the result
        provably corresponds to the `upto` fingerprint even while
        writers keep appending.

        This base implementation adapts `find()` (drivers keep their
        own pushdown); PEVLOG overrides it with a chunk-parallel
        raw-frame decode. `workers` is advisory — a driver without a
        parallel scan ignores it."""
        if since is not None:
            raise DeltaInvalidated(
                f"{type(self).__name__} has no delta scan path")
        del upto
        from predictionio_tpu.data.storage.columns import columns_from_events
        return columns_from_events(
            self.find(app_id, channel_id, start_time=start_time,
                      until_time=until_time, entity_type=entity_type,
                      entity_id=entity_id, event_names=event_names,
                      target_entity_type=target_entity_type,
                      target_entity_id=target_entity_id,
                      properties=properties),
            value_spec, require_target)

    def ingest_watermark(self, app_id: int,
                         channel_id: Optional[int] = None
                         ) -> Optional[Dict[str, int]]:
        """Monotone content fingerprint for the prepared-data cache:
        any insert/delete must change it. None (the default) disables
        caching for this driver."""
        return None

    def ingest_cache_dir(self, app_id: int,
                         channel_id: Optional[int] = None):
        """Directory for prepared-data cache blobs, or None when the
        driver has no natural on-disk home for them."""
        return None

    def aggregate_properties(self, app_id: int,
                             channel_id: Optional[int] = None, *,
                             entity_type: str,
                             start_time: Optional[datetime] = None,
                             until_time: Optional[datetime] = None,
                             required: Optional[Sequence[str]] = None,
                             ) -> Dict[str, PropertyMap]:
        """Replay $set/$unset/$delete into final per-entity properties
        (LEvents.futureAggregateProperties, LEvents.scala:393-440)."""
        events = self.find(
            app_id, channel_id,
            start_time=start_time, until_time=until_time,
            entity_type=entity_type,
            event_names=["$set", "$unset", "$delete"])
        result = aggregate_properties(events)
        if required:
            req = list(required)
            result = {k: v for k, v in result.items()
                      if all(r in v.fields for r in req)}
        return result

    def aggregate_properties_of_entity(
            self, app_id: int, channel_id: Optional[int] = None, *,
            entity_type: str, entity_id: str,
            start_time: Optional[datetime] = None,
            until_time: Optional[datetime] = None) -> Optional[PropertyMap]:
        from predictionio_tpu.data.aggregate import aggregate_properties_single
        events = self.find(
            app_id, channel_id,
            start_time=start_time, until_time=until_time,
            entity_type=entity_type, entity_id=entity_id,
            event_names=["$set", "$unset", "$delete"])
        return aggregate_properties_single(events)


def match_event(e: Event, *,
                start_time: Optional[datetime] = None,
                until_time: Optional[datetime] = None,
                entity_type: Optional[str] = None,
                entity_id: Optional[str] = None,
                event_names: Optional[Sequence[str]] = None,
                target_entity_type: object = _UNSET,
                target_entity_id: object = _UNSET,
                properties: Optional[Dict[str, object]] = None) -> bool:
    """Shared in-memory filter predicate implementing `find` semantics."""
    if properties and not match_properties(e, properties):
        return False
    if start_time is not None and e.event_time < _aware(start_time):
        return False
    if until_time is not None and e.event_time >= _aware(until_time):
        return False
    if entity_type is not None and e.entity_type != entity_type:
        return False
    if entity_id is not None and e.entity_id != entity_id:
        return False
    if event_names is not None and e.event not in set(event_names):
        return False
    if target_entity_type is not _UNSET and e.target_entity_type != target_entity_type:
        return False
    if target_entity_id is not _UNSET and e.target_entity_id != target_entity_id:
        return False
    return True


def _aware(t: datetime) -> datetime:
    from datetime import timezone
    return t if t.tzinfo else t.replace(tzinfo=timezone.utc)

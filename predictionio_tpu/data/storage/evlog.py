"""EVLOG storage driver: event data on the native C++ append-only journal.

The IO-plane analog of the reference's HBase event store
(`storage/hbase/HBEventsUtil.scala` — one table per app/channel; here one
CRC-framed journal file per app/channel, appended via
`native/eventlog.cpp` with flock-safe multi-process writes). Deletes are
tombstone frames; readers replay the journal (cached per file, refreshed
on size change).

Config: PIO_STORAGE_SOURCES_<NAME>_TYPE=EVLOG, ..._PATH=<dir>.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import zlib
from datetime import timezone
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Tuple

from predictionio_tpu.data.event import Event, datetime
from predictionio_tpu.data.storage import base
from predictionio_tpu.native.eventlog import MAGIC, EventLog
from predictionio_tpu.resilience import FaultError, faults


class EvlogStorageClient:
    def __init__(self, config):
        self.base_dir = Path(config.get("PATH", "./.pio_store/evlog"))
        self.base_dir.mkdir(parents=True, exist_ok=True)
        self.lock = threading.RLock()
        # path -> (bytes consumed snapshot, {event_id: Event})
        self.cache: Dict[str, Tuple[int, Dict[str, Event]]] = {}

    def close(self) -> None:
        pass


def _event_to_payload(e: Event) -> bytes:
    obj = e.to_api_json()
    # microsecond-precision times survive the journal exactly
    obj["eventTimeUs"] = _us(e.event_time)
    obj["creationTimeUs"] = _us(e.creation_time)
    return json.dumps(obj, separators=(",", ":")).encode()


def _us(t: datetime) -> int:
    if t.tzinfo is None:
        t = t.replace(tzinfo=timezone.utc)
    return int(t.timestamp() * 1_000_000)


def _from_us(us: int) -> datetime:
    return datetime.fromtimestamp(us / 1_000_000, tz=timezone.utc)


def _payload_to_event(obj: dict) -> Event:
    e = Event.from_api_json(obj)
    if "eventTimeUs" in obj:
        from dataclasses import replace
        e = replace(e, event_time=_from_us(obj["eventTimeUs"]),
                    creation_time=_from_us(obj["creationTimeUs"]))
    return e


class EvlogEvents(base.EventStore):
    def __init__(self, client: EvlogStorageClient):
        self.c = client

    def _path(self, app_id: int, channel_id: Optional[int]) -> Path:
        suffix = f"_{channel_id}" if channel_id is not None else ""
        return self.c.base_dir / f"events_{app_id}{suffix}.log"

    def _replay(self, app_id: int,
                channel_id: Optional[int]) -> Dict[str, Event]:
        """Journal -> {event_id: Event}, cached until the file grows."""
        path = self._path(app_id, channel_id)
        size = path.stat().st_size if path.exists() else 0
        with self.c.lock:
            cached = self.c.cache.get(str(path))
            if cached is not None and cached[0] == size:
                return cached[1]
            table: Dict[str, Event] = {}
            for payload in EventLog(str(path)).payloads():
                obj = json.loads(payload)
                if "$tombstone" in obj:
                    table.pop(obj["$tombstone"], None)
                else:
                    e = _payload_to_event(obj)
                    table[e.event_id] = e
            self.c.cache[str(path)] = (size, table)
            return table

    def init(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        path = self._path(app_id, channel_id)
        if not path.exists():
            path.touch()
        return True

    def remove(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        path = self._path(app_id, channel_id)
        with self.c.lock:
            if path.exists():
                EventLog(str(path)).truncate()
            self.c.cache.pop(str(path), None)
        return True

    def close(self) -> None:
        pass

    def _insert(self, event: Event, app_id: int,
                channel_id: Optional[int] = None) -> str:
        e = event if event.event_id else event.with_id()
        with self.c.lock:
            if e.event_id in self._replay(app_id, channel_id):
                raise base.StorageWriteError(
                    f"Duplicate event id {e.event_id}")
            path = self._path(app_id, channel_id)
            payload = _event_to_payload(e)
            # crash-consistency seam: append only part of the frame (a
            # mid-write crash on the journal) — fsck must truncate it
            frac = faults().torn_fraction("evlog.append.partial")
            if frac is not None:
                frame = struct.pack(
                    "<III", MAGIC, len(payload),
                    zlib.crc32(payload) & 0xFFFFFFFF) + payload
                with open(path, "ab") as f:
                    f.write(frame[:int(len(frame) * frac)])
                raise FaultError("injected torn append at "
                                 "evlog.append.partial")
            EventLog(str(path)).append(payload)
            # the replay cache is size-keyed; next read picks up the append
        return e.event_id

    def get(self, event_id: str, app_id: int,
            channel_id: Optional[int] = None) -> Optional[Event]:
        return self._replay(app_id, channel_id).get(event_id)

    def delete(self, event_id: str, app_id: int,
               channel_id: Optional[int] = None) -> bool:
        with self.c.lock:
            if event_id not in self._replay(app_id, channel_id):
                return False
            EventLog(str(self._path(app_id, channel_id))).append(
                json.dumps({"$tombstone": event_id}).encode())
        return True

    def fsck(self, repair: bool = False) -> List[dict]:
        """Detect torn journal tails (trailing bytes past the last valid
        frame — scans already ignore them, but they hide every FUTURE
        append). `repair` truncates to the last valid frame boundary."""
        findings: List[dict] = []
        for path in sorted(self.c.base_dir.glob("events_*.log")):
            valid_end = 0
            for _payload, end in EventLog(str(path)).scan_from(0):
                valid_end = end
            try:
                size = path.stat().st_size
            except OSError:
                continue
            if size <= valid_end:
                continue
            finding = {
                "kind": "torn_tail", "path": str(path),
                "reason": (f"{size - valid_end} trailing bytes fail "
                           "frame CRC"),
                "action": "none"}
            if repair:
                with self.c.lock:
                    os.truncate(path, valid_end)
                    self.c.cache.pop(str(path), None)
                finding["action"] = f"truncated to {valid_end}"
            findings.append(finding)
        return findings

    def find(self, app_id: int, channel_id: Optional[int] = None, *,
             start_time=None, until_time=None, entity_type=None,
             entity_id=None, event_names=None,
             target_entity_type=base._UNSET,
             target_entity_id=base._UNSET,
             properties=None,
             limit: Optional[int] = None,
             reversed: bool = False) -> Iterator[Event]:
        events = [
            e for e in self._replay(app_id, channel_id).values()
            if base.match_event(
                e, start_time=start_time, until_time=until_time,
                entity_type=entity_type, entity_id=entity_id,
                event_names=event_names,
                target_entity_type=target_entity_type,
                target_entity_id=target_entity_id,
                properties=properties)]
        events.sort(key=lambda e: e.event_time, reverse=reversed)
        if limit is not None and limit > 0:
            events = events[:limit]
        return iter(events)

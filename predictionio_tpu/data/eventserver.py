"""The Event Server: REST collection plane for events.

Parity: reference `data/.../api/EventServer.scala:54-663` — all routes,
status codes, auth and error messages:

  GET    /                      -> {"status": "alive"}
  GET    /health, /ready        -> liveness / readiness (utils.http base)
  GET    /plugins.json          -> plugin descriptions
  GET    /plugins/<type>/<name>/... -> plugin REST handler
  POST   /events.json           -> 201 {"eventId": id}
  GET    /events.json           -> filtered query (default limit 20)
  GET    /events/<id>.json      -> one event
  DELETE /events/<id>.json      -> {"message": "Found"/"Not Found"}
  POST   /batch/events.json     -> per-event statuses, max 50
  GET    /stats.json            -> hourly stats (requires stats=True)
  POST/GET /webhooks/<name>.json  -> JSON webhook connectors
  POST/GET /webhooks/<name>.form  -> form webhook connectors

Auth: `accessKey` query param, or HTTP Basic with the key as username
(EventServer.scala:92-130); optional `channel` query param resolves a
channel by name within the key's app.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence
from urllib.parse import parse_qs

from predictionio_tpu.data.event import Event, parse_time
from predictionio_tpu.data.plugins import (
    INPUT_BLOCKER, INPUT_SNIFFER, EventInfo, EventServerPlugin,
    EventServerPluginContext,
)
from predictionio_tpu.data.stats import Stats
from predictionio_tpu.data.storage import StorageRegistry, StorageWriteError, storage
from predictionio_tpu.obs import MetricsRegistry
from predictionio_tpu.data.webhooks import FORM_CONNECTORS, JSON_CONNECTORS
from predictionio_tpu.data.webhooks.connectors import (
    ConnectorException, connector_to_event,
)
from predictionio_tpu.utils.http import (
    HTTPError, HTTPServerBase, Request, Response, parse_basic_auth_user,
)

MAX_EVENTS_PER_BATCH_REQUEST = 50  # EventServer.scala:70
DEFAULT_QUERY_LIMIT = 20           # EventServer.scala:353
PAYLOAD_BUCKETS = (256.0, 1024.0, 4096.0, 16384.0, 65536.0, 262144.0,
                   1048576.0)


@dataclass
class EventServerConfig:
    ip: str = "0.0.0.0"
    port: int = 7070
    plugins: Sequence[EventServerPlugin] = ()
    stats: bool = False
    # resilience knobs: default per-request deadline (0 = unbounded) and
    # in-flight admission cap (0 = unlimited; excess sheds with 429)
    default_deadline_ms: int = 0
    max_inflight: int = 0


@dataclass(frozen=True)
class AuthData:
    app_id: int
    channel_id: Optional[int]
    events: Sequence[str]


class EventServer(HTTPServerBase):
    def __init__(self, config: Optional[EventServerConfig] = None,
                 registry: Optional[StorageRegistry] = None,
                 metrics: Optional[MetricsRegistry] = None):
        self.config = config or EventServerConfig()
        super().__init__(host=self.config.ip, port=self.config.port,
                         metrics=metrics,
                         default_deadline_ms=self.config.default_deadline_ms,
                         max_inflight=self.config.max_inflight)
        self.registry = registry or storage()
        self.event_client = self.registry.get_events()
        self.access_keys_client = self.registry.get_meta_data_access_keys()
        self.channels_client = self.registry.get_meta_data_channels()
        self.stats = Stats()
        self.plugin_context = EventServerPluginContext(self.config.plugins)
        self._ingest_counter = self.metrics.counter(
            "pio_events_ingested_total",
            "Events accepted into storage, by ingest surface",
            labels=("via",))
        self._payload_hist = self.metrics.histogram(
            "pio_ingest_payload_bytes",
            "Ingest request payload size in bytes",
            buckets=PAYLOAD_BUCKETS)
        # restart-recovery sweep (torn journal tails are an event-store
        # concern; report-only unless `pio doctor --repair`)
        from predictionio_tpu.data.fsck import startup_check
        from predictionio_tpu.obs import get_logger
        startup_check(self.registry, log=get_logger("eventserver").warning)
        self._install_routes()

    # -- readiness ----------------------------------------------------------
    def readiness(self):
        """Ready = no storage circuit breaker is open (an open breaker
        means ingests would fast-fail 503; tell the LB to back off)."""
        states = self.registry.breaker_states()
        open_breakers = sorted(
            n for n, s in states.items() if s == "open")
        return not open_breakers, {"storageBreakers": states}

    # -- auth ---------------------------------------------------------------
    def _auth(self, req: Request) -> AuthData:
        """EventServer.scala:92-130 withAccessKey."""
        key = req.query_get("accessKey")
        channel_name = req.query_get("channel")
        if key is None:
            key = parse_basic_auth_user(req.headers)
            if key is None:
                raise HTTPError(401, "Missing accessKey.")
        ak = self.access_keys_client.get(key)
        if ak is None:
            raise HTTPError(401, "Invalid accessKey.")
        channel_id = None
        if channel_name is not None:
            channel_map = {c.name: c.id
                           for c in self.channels_client.get_by_appid(ak.appid)}
            if channel_name not in channel_map:
                raise HTTPError(401, f"Invalid channel '{channel_name}'.")
            channel_id = channel_map[channel_name]
        return AuthData(ak.appid, channel_id, ak.events)

    # -- ingestion helper ---------------------------------------------------
    def _ingest(self, event: Event, auth: AuthData,
                via: str = "single") -> str:
        info = EventInfo(auth.app_id, auth.channel_id, event)
        self.plugin_context.run_blockers(info)
        try:
            event_id = self.event_client.insert(
                event, auth.app_id, auth.channel_id)
        except StorageWriteError as e:
            # a rejected write (e.g. duplicate explicit eventId) is a client
            # error on every ingest surface: single, batch, and webhooks
            raise HTTPError(400, str(e))
        self.plugin_context.notify_sniffers(info)
        self._ingest_counter.labels(via=via).inc()
        if self.config.stats:
            self.stats.bookkeeping(auth.app_id, 201, event)
        return event_id

    # -- routes -------------------------------------------------------------
    def _install_routes(self) -> None:
        r = self.router

        @r.get("/")
        def index(req: Request) -> Response:
            return Response.json({"status": "alive"})

        @r.get("/plugins.json")
        def plugins_json(req: Request) -> Response:
            return Response.json(self.plugin_context.describe())

        def _plugin_rest(req: Request) -> Response:
            auth = self._auth(req)
            ptype, pname = req.params["ptype"], req.params["pname"]
            args = [a for a in req.params.get("args", "").split("/") if a]
            table = {INPUT_BLOCKER: self.plugin_context.input_blockers,
                     INPUT_SNIFFER: self.plugin_context.input_sniffers}
            if ptype not in table or pname not in table[ptype]:
                raise HTTPError(404, f"Unknown plugin {ptype}/{pname}")
            return Response.json(table[ptype][pname].handle_rest(
                auth.app_id, auth.channel_id, args))

        r.get("/plugins/<ptype>/<pname>")(_plugin_rest)
        r.get("/plugins/<ptype>/<pname>/<args:path>")(_plugin_rest)

        @r.post("/events.json")
        def post_event(req: Request) -> Response:
            auth = self._auth(req)
            self._payload_hist.observe(float(len(req.body)))
            event = Event.from_api_json(req.json())
            if auth.events and event.event not in auth.events:
                return Response.json(
                    {"message": f"{event.event} events are not allowed"}, 403)
            event_id = self._ingest(event, auth)
            return Response.json({"eventId": event_id}, 201)

        @r.get("/events.json")
        def get_events(req: Request) -> Response:
            auth = self._auth(req)
            q = req.query
            reversed_flag = (q.get("reversed", "false").lower() == "true")
            if reversed_flag and not (q.get("entityType") and q.get("entityId")):
                raise HTTPError(
                    400, "the parameter reversed can only be used with both "
                         "entityType and entityId specified.")
            limit = int(q["limit"]) if "limit" in q else DEFAULT_QUERY_LIMIT
            kwargs = {}
            if "targetEntityType" in q:
                kwargs["target_entity_type"] = q["targetEntityType"]
            if "targetEntityId" in q:
                kwargs["target_entity_id"] = q["targetEntityId"]
            events = list(self.event_client.find(
                auth.app_id, auth.channel_id,
                start_time=parse_time(q["startTime"]) if "startTime" in q else None,
                until_time=parse_time(q["untilTime"]) if "untilTime" in q else None,
                entity_type=q.get("entityType"),
                entity_id=q.get("entityId"),
                event_names=[q["event"]] if "event" in q else None,
                limit=limit, reversed=reversed_flag, **kwargs))
            if not events:
                return Response.json({"message": "Not Found"}, 404)
            return Response.json([e.to_api_json() for e in events])

        @r.get("/events/<event_id>.json")
        def get_event(req: Request) -> Response:
            auth = self._auth(req)
            event = self.event_client.get(
                req.params["event_id"], auth.app_id, auth.channel_id)
            if event is None:
                return Response.json({"message": "Not Found"}, 404)
            return Response.json(event.to_api_json())

        @r.delete("/events/<event_id>.json")
        def delete_event(req: Request) -> Response:
            auth = self._auth(req)
            found = self.event_client.delete(
                req.params["event_id"], auth.app_id, auth.channel_id)
            if found:
                return Response.json({"message": "Found"})
            return Response.json({"message": "Not Found"}, 404)

        @r.post("/batch/events.json")
        def post_batch(req: Request) -> Response:
            auth = self._auth(req)
            self._payload_hist.observe(float(len(req.body)))
            payload = req.json()
            if not isinstance(payload, list):
                raise HTTPError(400, "Batch request body must be a JSON array")
            if len(payload) > MAX_EVENTS_PER_BATCH_REQUEST:
                raise HTTPError(
                    400, "Batch request must have less than or equal to "
                         f"{MAX_EVENTS_PER_BATCH_REQUEST} events")
            results = []
            for item in payload:
                try:
                    event = Event.from_api_json(item)
                except (ValueError, TypeError) as e:
                    results.append({"status": 400, "message": str(e)})
                    continue
                if auth.events and event.event not in auth.events:
                    results.append({
                        "status": 403,
                        "message": f"{event.event} events are not allowed"})
                    continue
                try:
                    event_id = self._ingest(event, auth, via="batch")
                    results.append({"status": 201, "eventId": event_id})
                except HTTPError as e:
                    results.append({"status": e.status, "message": e.message})
                except Exception as e:
                    results.append({"status": 500, "message": str(e)})
            return Response.json(results)

        @r.get("/stats.json")
        def stats_json(req: Request) -> Response:
            auth = self._auth(req)
            if not self.config.stats:
                return Response.json(
                    {"message": "To see stats, launch Event Server with "
                                "--stats argument."}, 404)
            return Response.json(self.stats.get_stats(auth.app_id))

        @r.post("/webhooks/<name>.json")
        def webhook_json(req: Request) -> Response:
            auth = self._auth(req)
            name = req.params["name"]
            connector = JSON_CONNECTORS.get(name)
            if connector is None:
                return Response.json(
                    {"message": f"webhooks connection for {name} is not "
                                "supported."}, 404)
            self._payload_hist.observe(float(len(req.body)))
            try:
                event = connector_to_event(connector, req.json())
            except ConnectorException as e:
                raise HTTPError(400, str(e))
            event_id = self._ingest(event, auth, via="webhook")
            return Response.json({"eventId": event_id}, 201)

        @r.get("/webhooks/<name>.json")
        def webhook_json_get(req: Request) -> Response:
            self._auth(req)
            if req.params["name"] in JSON_CONNECTORS:
                return Response.json({"message": "Ok"})
            return Response.json(
                {"message": f"webhooks connection for {req.params['name']} "
                            "is not supported."}, 404)

        @r.post("/webhooks/<name>.form")
        def webhook_form(req: Request) -> Response:
            auth = self._auth(req)
            name = req.params["name"]
            connector = FORM_CONNECTORS.get(name)
            if connector is None:
                return Response.json(
                    {"message": f"webhooks connection for {name} is not "
                                "supported."}, 404)
            self._payload_hist.observe(float(len(req.body)))
            fields = {k: v[0] for k, v in
                      parse_qs(req.body.decode("utf-8"),
                               keep_blank_values=True).items()}
            try:
                event = connector_to_event(connector, fields)
            except ConnectorException as e:
                raise HTTPError(400, str(e))
            event_id = self._ingest(event, auth, via="webhook")
            return Response.json({"eventId": event_id}, 201)

        @r.get("/webhooks/<name>.form")
        def webhook_form_get(req: Request) -> Response:
            self._auth(req)
            if req.params["name"] in FORM_CONNECTORS:
                return Response.json({"message": "Ok"})
            return Response.json(
                {"message": f"webhooks connection for {req.params['name']} "
                            "is not supported."}, 404)


def create_event_server(config: Optional[EventServerConfig] = None,
                        registry: Optional[StorageRegistry] = None,
                        background: bool = True) -> EventServer:
    """Parity: EventServer.createEventServer (EventServer.scala:632-654)."""
    server = EventServer(config, registry)
    server.start(background=background)
    return server

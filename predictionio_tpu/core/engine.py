"""Engine: concrete DASE pipeline with named component maps.

Parity target: `core/.../controller/Engine.scala` (832 LoC) — component
class maps, `train` (sequential per-algorithm loop, Engine.scala:692),
`eval` (folds × algorithms cartesian, Engine.scala:730-820), JSON variant ->
EngineParams extraction (`jValueToEngineParams:357-420`), and the
deploy-time model preparation split out into persistence.py.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Mapping, Sequence, Tuple, Type

from predictionio_tpu.core.base import (
    Algorithm, DataSource, Preparator, Serving,
    StopAfterPrepareInterruption, StopAfterReadInterruption, sanity_check,
)
from predictionio_tpu.core.params import (
    EngineParams, ParamsError, Params, extract_params,
)
from predictionio_tpu.core.runtime import RuntimeContext


class Engine:
    """An engine = named maps of DASE component classes
    (Engine.scala:101-155). Single-class convenience: pass the class itself
    instead of a one-entry map and it is registered under ''."""

    def __init__(self,
                 data_source: "Mapping[str, Type[DataSource]] | Type[DataSource]",
                 preparator: "Mapping[str, Type[Preparator]] | Type[Preparator]",
                 algorithms: "Mapping[str, Type[Algorithm]] | Type[Algorithm]",
                 serving: "Mapping[str, Type[Serving]] | Type[Serving]"):
        self.data_source_classes = self._as_map(data_source)
        self.preparator_classes = self._as_map(preparator)
        self.algorithm_classes = self._as_map(algorithms)
        self.serving_classes = self._as_map(serving)

    @staticmethod
    def _as_map(x) -> Dict[str, type]:
        if isinstance(x, Mapping):
            return dict(x)
        return {"": x}

    # -- component instantiation (the Doer analog) --------------------------
    def _doer(self, table: Mapping[str, type], kind: str,
              name_params: Tuple[str, Params]):
        name, params = name_params
        if name not in table:
            raise KeyError(
                f"{kind} '{name}' is not registered in this engine; "
                f"available: {sorted(table)}")
        return table[name](params)

    def make_components(self, engine_params: EngineParams):
        ds = self._doer(self.data_source_classes, "DataSource",
                        engine_params.data_source_params)
        prep = self._doer(self.preparator_classes, "Preparator",
                          engine_params.preparator_params)
        algos = [self._doer(self.algorithm_classes, "Algorithm", ap)
                 for ap in engine_params.algorithm_params_list]
        if not algos:
            raise ValueError("EngineParams specifies no algorithms")
        serving = self._doer(self.serving_classes, "Serving",
                             engine_params.serving_params)
        return ds, prep, algos, serving

    # -- train (Engine.scala:157-192 + 643-708) -----------------------------
    def train(self, ctx: RuntimeContext,
              engine_params: EngineParams) -> List[Any]:
        import time as _time

        ds, prep, algos, _ = self.make_components(engine_params)
        bind_serving_context(algos, ctx)
        wp = ctx.workflow_params
        tm = ctx.phase_timings
        tm.clear()   # a reused context must not leak a previous run's
        # phases into this instance's persisted record
        from predictionio_tpu.ingest.pipeline import take_phase_timings
        take_phase_timings()   # drop a previous run's ingest stages
        t0 = _time.perf_counter()
        td = ds.read_training(ctx)
        tm["read_s"] = round(_time.perf_counter() - t0, 4)
        # read_s subdivided: scan/build/transfer + cache hit counters from
        # the columnar ingest pipeline, when the data source used it
        tm.update({k: round(v, 4) for k, v in take_phase_timings().items()})
        if not wp.skip_sanity_check:
            sanity_check(td)
        if wp.stop_after_read:
            raise StopAfterReadInterruption()
        t0 = _time.perf_counter()
        pd = prep.prepare(ctx, td)
        tm["prepare_s"] = round(_time.perf_counter() - t0, 4)
        if not wp.skip_sanity_check:
            sanity_check(pd)
        if wp.stop_after_prepare:
            raise StopAfterPrepareInterruption()
        models = []
        for i, algo in enumerate(algos):
            # sequential per-algo loop (Engine.scala:692)
            t0 = _time.perf_counter()
            model = algo.train(ctx, pd)
            tm[f"train_algo{i}_s"] = round(_time.perf_counter() - t0, 4)
            if not wp.skip_sanity_check:
                sanity_check(model)
            models.append(model)
        return models

    # -- eval (Engine.scala:730-820) ----------------------------------------
    def eval(self, ctx: RuntimeContext, engine_params: EngineParams
             ) -> List[Tuple[Any, Sequence[Tuple[Any, Any, Any]]]]:
        """Returns [(evalInfo, [(query, prediction, actual)])] per fold."""
        ds, prep, algos, serving = self.make_components(engine_params)
        bind_serving_context(algos, ctx)
        folds = ds.read_eval(ctx)
        out = []
        for td, eval_info, qa_pairs in folds:
            pd = prep.prepare(ctx, td)
            models = [a.train(ctx, pd) for a in algos]
            queries = [(i, serving.supplement(q))
                       for i, (q, _) in enumerate(qa_pairs)]
            # per-algo batched inference, joined by query index
            # (union + groupByKey in the reference, Engine.scala:790-796)
            per_algo: List[Dict[int, Any]] = []
            for algo, model in zip(algos, models):
                per_algo.append(dict(algo.batch_predict(model, queries)))
            qpa = []
            for i, (q, a) in enumerate(qa_pairs):
                preds = [pa[i] for pa in per_algo]
                qpa.append((q, serving.serve(q, preds), a))
            out.append((eval_info, qpa))
        return out

    # -- JSON variant -> EngineParams (Engine.scala:357-420) ----------------
    def engine_params_from_variant(self, variant: "Mapping | str"
                                   ) -> EngineParams:
        if isinstance(variant, str):
            variant = json.loads(variant)
        known_top = {"id", "description", "engineFactory", "engine_factory",
                     "datasource", "preparator", "algorithms", "serving",
                     "sparkConf", "runtimeConf", "runtime_conf"}
        unknown_top = set(variant) - known_top
        if unknown_top:
            raise ParamsError(
                f"$: unknown engine variant key(s) {sorted(unknown_top)}; "
                f"known: {sorted(known_top)}")

        def one(table, kind, node) -> Tuple[str, Params]:
            if node is None:
                name = ""
                params_json: Any = {}
            else:
                bad = set(node) - {"name", "params"}
                if bad:
                    raise ParamsError(
                        f"$.{kind.lower()}: unknown key(s) {sorted(bad)}; "
                        "component nodes take only 'name' and 'params'")
                name = node.get("name", "")
                params_json = node.get("params", {})
            if name not in table:
                if len(table) == 1 and name == "":
                    name = next(iter(table))
                else:
                    raise ParamsError(
                        f"{kind} '{name}' not registered; "
                        f"available: {sorted(table)}")
            cls = table[name]
            pcls = getattr(cls, "params_class", None)
            if pcls is None:
                raise ParamsError(f"{kind} {cls.__name__} has no params_class")
            return name, extract_params(pcls, params_json, f"$.{kind.lower()}")

        algo_nodes = variant.get("algorithms") or []
        if not algo_nodes:
            # a single unnamed algorithm with default params
            algo_nodes = [{"name": "", "params": {}}]
        return EngineParams(
            data_source_params=one(self.data_source_classes, "Datasource",
                                   variant.get("datasource")),
            preparator_params=one(self.preparator_classes, "Preparator",
                                  variant.get("preparator")),
            algorithm_params_list=tuple(
                one(self.algorithm_classes, "Algorithm", n)
                for n in algo_nodes),
            serving_params=one(self.serving_classes, "Serving",
                               variant.get("serving")),
        )


def bind_serving_context(algos, ctx: RuntimeContext) -> None:
    """Give algorithms that read the event store at serve time (e-comm
    constraint events, ECommAlgorithm.scala:331-430) the live context.
    Called on every path that runs predict: train (direct use), eval, and
    prepare_deploy."""
    for algo in algos:
        hook = getattr(algo, "with_serving_context", None)
        if callable(hook):
            hook(ctx)


class SimpleEngine(Engine):
    """DataSource + one Algorithm, identity prep, first serving
    (Engine.scala SimpleEngine:838-855)."""

    def __init__(self, data_source: Type[DataSource],
                 algorithm: Type[Algorithm]):
        from predictionio_tpu.core.base import FirstServing, IdentityPreparator
        super().__init__(data_source, IdentityPreparator, algorithm,
                         FirstServing)


class EngineFactory:
    """Subclass and override `apply()` to return an Engine; referenced by
    dotted name from engine.json's engineFactory
    (controller/EngineFactory.scala)."""

    @classmethod
    def apply(cls) -> Engine:
        raise NotImplementedError

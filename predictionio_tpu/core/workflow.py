"""Train/eval orchestration around the storage registries.

Parity targets:
  - `CoreWorkflow.runTrain` / `runEvaluation`
    (`core/.../workflow/CoreWorkflow.scala:45-160`)
  - engine factory reflection (`CreateWorkflow.scala:195-203`,
    `WorkflowUtils.getEngine`)
  - deploy-time model preparation (`Engine.prepareDeploy`,
    `controller/Engine.scala:199-269`)
"""

from __future__ import annotations

import importlib
import threading
from typing import Any, Dict, List, Optional, Tuple

from predictionio_tpu.core.engine import Engine, EngineFactory
from predictionio_tpu.core.params import EngineParams
from predictionio_tpu.core.persistence import (
    deserialize_models, serialize_models,
)
from predictionio_tpu.core.runtime import RuntimeContext
from predictionio_tpu.data.event import utcnow
from predictionio_tpu.data.storage.base import (
    EngineInstance, EngineInstanceStatus, Model,
)
from predictionio_tpu.obs import (
    get_logger, install_compile_probe, record_train_phases,
)

_log = get_logger("workflow")

# explicit registry complementing dotted-path import, so quickstart factories
# can register under short names (the classpath-reflection analog)
_ENGINE_FACTORIES = {}


def register_engine(name: str, factory) -> None:
    _ENGINE_FACTORIES[name] = factory


def resolve_engine(factory_name: str) -> Engine:
    """Resolve an engine factory by registered short name or dotted path
    'package.module.FactoryClass' (WorkflowUtils.getEngine analog)."""
    target = _ENGINE_FACTORIES.get(factory_name)
    if target is None and "." not in factory_name:
        # short names self-register on import: try the bundled templates
        mod_name = f"predictionio_tpu.models.{factory_name}"
        try:
            importlib.import_module(mod_name)
            target = _ENGINE_FACTORIES.get(factory_name)
        except ModuleNotFoundError as e:
            if e.name != mod_name:
                raise   # a real dependency failure inside the template

    if target is None:
        module_name, _, attr = factory_name.rpartition(".")
        if not module_name:
            raise ValueError(
                f"Unknown engine factory {factory_name!r}; registered: "
                f"{sorted(_ENGINE_FACTORIES)} (or use a dotted path)")
        mod = importlib.import_module(module_name)
        target = getattr(mod, attr)
    if isinstance(target, Engine):
        return target
    if isinstance(target, type) and issubclass(target, EngineFactory):
        return target.apply()
    if callable(target):
        result = target()
        if isinstance(result, Engine):
            return result
    raise TypeError(f"{factory_name!r} did not produce an Engine")


def _heartbeat_interval(registry) -> float:
    """`PIO_TRAIN_HEARTBEAT_S` (default 5s); <= 0 disables the beat."""
    cfg = getattr(registry, "config", {}) or {}
    try:
        return float(cfg.get("PIO_TRAIN_HEARTBEAT_S", 5.0))
    except (TypeError, ValueError):
        return 5.0


def _start_heartbeat(instances, instance_id: str, stop: threading.Event,
                     interval_s: float) -> Optional[threading.Thread]:
    if interval_s <= 0:
        return None

    def beat():
        while not stop.wait(interval_s):
            try:
                instances.record_heartbeat(instance_id)
            except Exception as e:
                # a failed beat must never kill the train; the janitor
                # threshold absorbs gaps far longer than one interval
                _log.warning("heartbeat_failed", instance_id=instance_id,
                             error=f"{type(e).__name__}: {e}")

    t = threading.Thread(target=beat, name=f"pio-heartbeat-{instance_id}",
                         daemon=True)
    t.start()
    return t


def _stop_heartbeat(stop: threading.Event,
                    thread: Optional[threading.Thread]) -> None:
    stop.set()
    if thread is not None and thread.is_alive():
        thread.join(timeout=10.0)


class CoreWorkflow:
    """Training orchestration with engine-instance lifecycle."""

    @staticmethod
    def run_train(engine: Engine, engine_params: EngineParams,
                  ctx: RuntimeContext, *,
                  engine_factory: str = "",
                  engine_variant: str = "",
                  verbose_save: bool = True,
                  persist: bool = True) -> EngineInstance:
        """Train, persist models, record the instance
        (CoreWorkflow.scala:45-101): insert INIT row, train, serialize
        models into the model repo, update status to COMPLETED; any failure
        leaves the row non-COMPLETED so deploy refuses it
        (commands/Engine.scala:235-236).

        `persist=False` runs the training computation but touches no
        storage — the non-coordinator processes of a multi-host run use
        it: they must participate in every collective, while only
        process 0 owns the metadata/model writes (the analog of Spark
        executors computing while the driver alone talks to storage)."""
        # per-phase wall times and XLA compile counts land in the
        # process-default metrics registry; the CLI renders its timing
        # report from there (obs.train_report)
        install_compile_probe()
        if not persist:
            engine.train(ctx, engine_params)
            record_train_phases(ctx.phase_timings)
            return EngineInstance(
                id="", status=EngineInstanceStatus.COMPLETED,
                start_time=utcnow(), end_time=utcnow(),
                engine_id="default", engine_version="default",
                engine_variant=engine_variant or "default",
                engine_factory=engine_factory)
        registry = ctx.registry
        instances = registry.get_meta_data_engine_instances()
        row = EngineInstance(
            id="", status=EngineInstanceStatus.INIT,
            start_time=utcnow(), end_time=utcnow(),
            engine_id="default", engine_version="default",
            engine_variant=engine_variant or "default",
            engine_factory=engine_factory,
            batch=ctx.workflow_params.batch,
            env={}, runtime_conf=dict(ctx.workflow_params.runtime_conf),
            data_source_params=_named_params_json(
                engine_params.data_source_params),
            preparator_params=_named_params_json(
                engine_params.preparator_params),
            algorithms_params=_algo_params_json(engine_params),
            serving_params=_named_params_json(engine_params.serving_params),
        )
        instance_id = instances.insert(row)
        row = row.with_(id=instance_id,
                        status=EngineInstanceStatus.TRAINING,
                        heartbeat=utcnow())
        instances.update(row)
        # liveness beats let the stale-instance janitor distinguish a
        # long-running train from one whose process died mid-run
        stop_beat = threading.Event()
        beat_thread = _start_heartbeat(
            instances, instance_id, stop_beat,
            interval_s=_heartbeat_interval(registry))
        try:
            models = engine.train(ctx, engine_params)
            record_train_phases(ctx.phase_timings)
            _, _, algos, _ = engine.make_components(engine_params)
            blob = serialize_models(instance_id, algos, models, ctx)
            registry.get_model_data_models().insert(Model(instance_id, blob))
            # the beat thread must be down BEFORE the terminal status
            # write: a concurrent get+update beat could resurrect the
            # TRAINING row after COMPLETED landed
            _stop_heartbeat(stop_beat, beat_thread)
            row = row.with_(
                status=EngineInstanceStatus.COMPLETED, end_time=utcnow(),
                # per-phase timings travel with the instance: `pio
                # status`/dashboard can show WHERE a train spent its
                # time, not just start/end
                runtime_conf={**row.runtime_conf,
                              "phase_timings": dict(ctx.phase_timings)})
            instances.update(row)
            return row
        except Exception as e:
            _stop_heartbeat(stop_beat, beat_thread)
            _log.exception("train_failed", instance_id=instance_id,
                           error=f"{type(e).__name__}: {e}")
            row = row.with_(status=EngineInstanceStatus.FAILED,
                            end_time=utcnow())
            instances.update(row)
            raise
        finally:
            _stop_heartbeat(stop_beat, beat_thread)

    @staticmethod
    def prepare_deploy(engine: Engine, instance: EngineInstance,
                       ctx: RuntimeContext,
                       engine_params: Optional[EngineParams] = None,
                       *, warm_batch_max: Optional[int] = None,
                       observed_sizes: Optional[Dict[int, int]] = None
                       ) -> Tuple[List[Any], List[Any], Any]:
        """Load (or retrain) the instance's models for serving; returns
        (algorithms, models, serving). (Engine.prepareDeploy +
        CreateServer.createServerActorWithEngine:186-244).

        `warm_batch_max` caps the batch buckets AOT-warmed through each
        algorithm's `warm_serving` hook (the server passes its
        micro-batcher `batch_max`); None skips warmup entirely.
        `observed_sizes` (pow2 batch size -> drain count, the
        micro-batcher's persisted histogram) narrows warmup to the
        shapes real traffic actually formed."""
        if engine_params is None:
            engine_params = engine_params_from_instance(engine, instance)
        from predictionio_tpu.core.engine import bind_serving_context
        from predictionio_tpu.resilience import faults
        faults().check("deploy.prepare")  # chaos seam: /reload rollback
        ds, prep, algos, serving = engine.make_components(engine_params)
        bind_serving_context(algos, ctx)
        blob_row = ctx.registry.get_model_data_models().get(instance.id)
        if blob_row is None:
            raise ValueError(f"No model blob for instance {instance.id}")

        def retrain(indices):
            # read/prepare once; train only the marker algorithms
            # (Engine.prepareDeploy retrains Unit models, Engine.scala:211-233)
            td = ds.read_training(ctx)
            pd = prep.prepare(ctx, td)
            return {i: algos[i].train(ctx, pd) for i in indices}

        models = deserialize_models(blob_row.models, instance.id, algos,
                                    ctx, retrain)
        if warm_batch_max is not None:
            # the serving mesh candidate: the engine-instance's recorded
            # runtime_conf (training's device layout) merged with the
            # server's own runtime_conf — a configured mesh in either
            # forces the sharded serve path; otherwise plans shard only
            # when the catalog exceeds one device's capacity
            from predictionio_tpu.ops.topk_sharded import (
                serve_mesh_from_conf,
            )
            conf = {**dict(getattr(instance, "runtime_conf", None) or {}),
                    **dict(ctx.workflow_params.runtime_conf or {})}
            warm_deploy(algos, models, warm_batch_max,
                        mesh=serve_mesh_from_conf(conf),
                        observed_sizes=observed_sizes)
        return algos, models, serving


def derive_warm_buckets(warm_batch_max: int,
                        observed_sizes: Optional[Dict[int, int]] = None
                        ) -> List[int]:
    """The batch shapes a deploy should AOT-compile.

    No observation history -> the full pow2 ladder 1..warm_batch_max
    (cold start must handle anything). With a recorded batch-size
    histogram, only the observed pow2 shapes (clamped to the ladder)
    plus bucket 1 — the single-query shape every dispatch can fall back
    to — get compiled, cutting deploy warmup time on workloads that
    never form the big batches."""
    cap = max(1, int(warm_batch_max))
    ladder: List[int] = []
    b = 1
    while b <= cap:
        ladder.append(b)
        b *= 2
    if not observed_sizes:
        return ladder
    wanted = {1}
    for size, count in observed_sizes.items():
        try:
            size, count = int(size), int(count)
        except (TypeError, ValueError):
            continue
        if count <= 0 or size < 1:
            continue
        # clamp outsized observations (batch_max shrank between runs)
        # onto the largest ladder shape
        wanted.add(max(s for s in ladder if s <= size))
    return [s for s in ladder if s in wanted]


def warm_deploy(algos: List[Any], models: List[Any],
                warm_batch_max: int, mesh=None,
                observed_sizes: Optional[Dict[int, int]] = None) -> int:
    """AOT-warm every algorithm's serve executables for the power-of-two
    batch buckets up to `warm_batch_max`, pinning model state device
    resident, so steady-state serving never recompiles. `mesh` (a
    `topk_sharded.ServeMesh` or None) is forwarded to every
    `warm_serving` override that accepts it, so plans can shard model
    state across the device mesh; legacy two-argument overrides keep
    working. Warmup cost/count land in the default metrics registry
    (`pio_serve_warmup_seconds`, `pio_serve_warmup_compiles_total`);
    `PIO_SERVE_WARMUP=off` disables. A warmup failure is logged, never
    fatal — the generic dispatch paths still serve correctly, just
    slower on first touch."""
    import inspect
    import os
    import time as _time
    if os.environ.get("PIO_SERVE_WARMUP", "on").lower() in (
            "off", "0", "false"):
        return 0
    # compiles during warmup must be attributed (and post-warmup drift
    # detectable), so the probe goes in before the first lowering
    install_compile_probe()
    buckets = derive_warm_buckets(warm_batch_max, observed_sizes)
    from predictionio_tpu.obs import get_registry
    reg = get_registry()
    t0 = _time.perf_counter()
    compiled = 0
    for algo, model in zip(algos, models):
        label = type(algo).__name__
        try:
            try:
                params = inspect.signature(algo.warm_serving).parameters
                takes_mesh = ("mesh" in params or any(
                    p.kind is inspect.Parameter.VAR_KEYWORD
                    for p in params.values()))
            except (TypeError, ValueError):
                takes_mesh = False
            n = (algo.warm_serving(model, buckets, mesh=mesh)
                 if takes_mesh else algo.warm_serving(model, buckets))
            compiled += int(n or 0)
        except Exception as e:
            _log.warning("serve_warmup_failed", algo=label,
                         error=f"{type(e).__name__}: {e}")
    reg.gauge("pio_serve_warmup_seconds",
              "Wall time of the last deploy serve warmup").set(
        _time.perf_counter() - t0)
    if compiled:
        reg.counter(
            "pio_serve_warmup_compiles_total",
            "Serve executables AOT-compiled at deploy warmup").inc(compiled)
    _log.info("serve_warmup", buckets=buckets, compiled=compiled,
              shards=(mesh.n_shards if mesh is not None else 0),
              seconds=round(_time.perf_counter() - t0, 3))
    return compiled


def engine_params_from_instance(engine: Engine,
                                instance: EngineInstance) -> EngineParams:
    """Rebuild EngineParams from the params JSON recorded on the instance
    (Engine.engineInstanceToEngineParams, Engine.scala:422-492)."""
    import json
    variant = {
        "datasource": json.loads(instance.data_source_params or "{}"),
        "preparator": json.loads(instance.preparator_params or "{}"),
        "algorithms": json.loads(instance.algorithms_params or "[]"),
        "serving": json.loads(instance.serving_params or "{}"),
    }
    return engine.engine_params_from_variant(variant)


def _named_params_json(name_params) -> str:
    import dataclasses
    import json
    name, p = name_params
    return json.dumps({"name": name, "params": dataclasses.asdict(p)})


def _algo_params_json(engine_params: EngineParams) -> str:
    import dataclasses
    import json
    return json.dumps([
        {"name": name, "params": dataclasses.asdict(p)}
        for name, p in engine_params.algorithm_params_list])

"""FakeWorkflow: run arbitrary code through the workflow machinery.

Parity: `core/.../workflow/FakeWorkflow.scala:33-120` — `FakeRun` wraps a
`SparkContext => Unit` function as a fake engine + evaluator so arbitrary
Spark code runs with pio's bookkeeping. Here the function takes a
`RuntimeContext` and runs under an EvaluationInstance record, giving it
the same observability as a real evaluation.
"""

from __future__ import annotations

import traceback
from typing import Any, Callable

from predictionio_tpu.core.runtime import RuntimeContext
from predictionio_tpu.data.event import utcnow
from predictionio_tpu.data.storage.base import (
    EvaluationInstance, EvaluationInstanceStatus,
)


def fake_run(fn: Callable[[RuntimeContext], Any],
             ctx: RuntimeContext, *, label: str = "FakeRun") -> Any:
    """Run `fn(ctx)`, recording an EvaluationInstance around it
    (FakeWorkflow.runEval + FakeEvalResult)."""
    instances = ctx.registry.get_meta_data_evaluation_instances()
    row = EvaluationInstance(
        id="", status=EvaluationInstanceStatus.RUNNING,
        start_time=utcnow(), end_time=utcnow(),
        evaluation_class=label, batch=ctx.workflow_params.batch)
    iid = instances.insert(row)
    row = row.with_(id=iid)
    try:
        result = fn(ctx)
        instances.update(row.with_(
            status=EvaluationInstanceStatus.COMPLETED, end_time=utcnow(),
            evaluator_results=repr(result)[:1000]))
        return result
    except Exception:
        traceback.print_exc()
        instances.update(row.with_(end_time=utcnow()))
        raise

"""Self-cleaning data source: event-store pruning at train time.

Parity: `core/.../core/SelfCleaningDataSource.scala:42-326` — a mixin that,
given an `EventWindow(duration, removeDuplicates, compressProperties)`,
  - drops non-`$set`/`$unset` events older than `duration`,
  - compresses each entity's `$set`/`$unset` chain into ONE `$set` event
    carrying the final aggregated properties,
  - removes duplicate events (identical up to eventId/creationTime),
and replaces the store's contents accordingly (`cleanPersistedPEvents`).
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from datetime import timedelta
from typing import Iterable, List, Optional, Tuple

from predictionio_tpu.data import store as store_facade
from predictionio_tpu.data.aggregate import aggregate_properties
from predictionio_tpu.data.event import Event, utcnow

_DURATION_RE = re.compile(
    r"^\s*(\d+)\s*(seconds?|minutes?|hours?|days?|weeks?|s|m|h|d|w)\s*$")

_UNIT_SECONDS = {"s": 1, "second": 1, "seconds": 1,
                 "m": 60, "minute": 60, "minutes": 60,
                 "h": 3600, "hour": 3600, "hours": 3600,
                 "d": 86400, "day": 86400, "days": 86400,
                 "w": 604800, "week": 604800, "weeks": 604800}


def parse_duration(s: "str | int | float") -> timedelta:
    """'3 days' / '12h' / seconds-as-number -> timedelta (the
    scala.concurrent.duration.Duration(...) analog)."""
    if isinstance(s, (int, float)):
        return timedelta(seconds=float(s))
    m = _DURATION_RE.match(s)
    if not m:
        raise ValueError(f"Cannot parse duration {s!r}")
    return timedelta(seconds=int(m.group(1)) * _UNIT_SECONDS[m.group(2)])


@dataclass(frozen=True)
class EventWindow:
    """(EventWindow, SelfCleaningDataSource.scala:322)"""
    duration: Optional[str] = None
    remove_duplicates: bool = False
    compress_properties: bool = False


def _is_set_event(e: Event) -> bool:
    return e.event in ("$set", "$unset")


def _dedup_key(e: Event) -> Tuple:
    props = tuple(sorted((k, repr(v)) for k, v in e.properties.items()))
    return (e.event, e.entity_type, e.entity_id, e.target_entity_type,
            e.target_entity_id, props, e.pr_id)


class SelfCleaningDataSource:
    """Mixin for DataSource subclasses; define `app_name` (property or
    attribute) and `event_window`."""

    app_name: str = ""
    event_window: Optional[EventWindow] = None

    def cleaned_events(self, events: Iterable[Event],
                       now=None) -> List[Event]:
        """Pure cleaning pass: window filter + compress + dedup
        (getCleanedLEvents + compressLProperties + removeLDuplicates)."""
        ew = self.event_window
        events = list(events)
        if ew is None:
            return events
        now = now or utcnow()
        if ew.duration is not None:
            cutoff = now - parse_duration(ew.duration)
            # property events are exempt from the window: dropping an old
            # $set would lose current entity state
            events = [e for e in events
                      if _is_set_event(e) or e.event_time >= cutoff]
        if ew.compress_properties:
            set_events = [e for e in events if _is_set_event(e)]
            others = [e for e in events if not _is_set_event(e)]
            compressed: List[Event] = []
            by_entity = {}
            for e in set_events:
                by_entity.setdefault((e.entity_type, e.entity_id),
                                     []).append(e)
            for (etype, eid), chain in by_entity.items():
                final = aggregate_properties(chain).get(eid)
                if final is None or final.fields.is_empty:
                    continue
                compressed.append(Event(
                    event="$set", entity_type=etype, entity_id=eid,
                    properties=final.fields,
                    event_time=max(e.event_time for e in chain)))
            events = compressed + others
        if ew.remove_duplicates:
            seen = {}
            for e in sorted(events, key=lambda e: e.event_time_millis):
                key = _dedup_key(e)
                if key not in seen:
                    seen[key] = e
            events = list(seen.values())
        return events

    def clean_persisted_events(self, ctx, channel: Optional[str] = None,
                               now=None) -> int:
        """Replace the store contents with the cleaned event set
        (cleanPersistedPEvents / wipe). Returns the number of events
        removed."""
        if self.event_window is None:
            return 0
        registry = ctx.registry
        app_id, channel_id = store_facade.app_name_to_id(
            registry, self.app_name, channel)
        events_dao = registry.get_events()
        original = list(events_dao.find(app_id, channel_id))
        cleaned = self.cleaned_events(original, now=now)
        kept_ids = {e.event_id for e in cleaned if e.event_id}
        removed = 0
        for e in original:
            if e.event_id and e.event_id not in kept_ids:
                events_dao.delete(e.event_id, app_id, channel_id)
                removed += 1
        for e in cleaned:
            if not e.event_id:   # newly compressed events
                events_dao.insert(e, app_id, channel_id)
        return removed

"""DASE core: base abstractions, Engine, workflow, persistence.

The analog of the reference's `core/` module (SURVEY.md §2.1): typed DASE
component contracts (`base.py` ≙ `core/.../core/Base*.scala`), the concrete
`Engine` with named component maps (`engine.py` ≙
`core/.../controller/Engine.scala`), typed JSON params extraction
(`params.py` ≙ `core/.../workflow/JsonExtractor.scala`), train/eval
orchestration (`workflow.py` ≙ `core/.../workflow/CoreWorkflow.scala`), and
model persistence (`persistence.py` ≙ Kryo + `PersistentModel`).

The structural difference from the reference: where every Base* method took
a `SparkContext`, components here receive a `RuntimeContext` carrying the
device mesh, the storage registry, and workflow params — the single-
controller JAX replacement for the Spark driver.
"""

from predictionio_tpu.core.params import (  # noqa: F401
    Params, EmptyParams, EngineParams, extract_params, params_to_json,
)
from predictionio_tpu.core.runtime import (  # noqa: F401
    RuntimeContext, WorkflowParams,
)
from predictionio_tpu.core.base import (  # noqa: F401
    DataSource, Preparator, IdentityPreparator, Algorithm, Serving,
    FirstServing, Evaluator, TrainingInterrupted, StopAfterReadInterruption,
    StopAfterPrepareInterruption,
)
from predictionio_tpu.core.persistence import (  # noqa: F401
    PersistentModel, PersistentModelManifest, serialize_models,
    deserialize_models,
)
from predictionio_tpu.core.engine import (  # noqa: F401
    Engine, EngineFactory, SimpleEngine,
)
from predictionio_tpu.core.workflow import (  # noqa: F401
    CoreWorkflow, register_engine, resolve_engine,
)
from predictionio_tpu.core.evaluation import (  # noqa: F401
    AverageMetric, EngineParamsGenerator, Evaluation, Metric,
    MetricEvaluator, MetricEvaluatorResult, OptionAverageMetric,
    StdevMetric, SumMetric, ZeroMetric, run_evaluation,
)

"""Offline bulk inference.

Parity: `core/.../workflow/BatchPredict.scala:145-229` — read one query
per line (JSON), run the supplement -> predict-all-algos -> serve chain,
write one JSON prediction per line, preserving input order.

TPU-first difference: the reference maps queries one at a time inside an
RDD; here queries are chunked into device batches through the algorithms'
`batch_predict` (one jit'd program per chunk shape).
"""

from __future__ import annotations

import json
from typing import Iterable, Iterator, List

from predictionio_tpu.core.engine import Engine
from predictionio_tpu.core.params import extract_params
from predictionio_tpu.core.runtime import RuntimeContext
from predictionio_tpu.core.workflow import CoreWorkflow
from predictionio_tpu.serving.server import _Deployment, to_jsonable


def batch_predict_lines(engine: Engine, instance, ctx: RuntimeContext,
                        lines: Iterable[str], *,
                        chunk_size: int = 1024) -> Iterator[str]:
    """Yield one JSON result line per input query line, in order."""
    algos, models, serving = CoreWorkflow.prepare_deploy(engine, instance, ctx)
    # the same serve chain the prediction server runs, one chunk at a time
    dep = _Deployment(engine, instance, algos, models, serving)

    def flush(payloads: List[dict]) -> Iterator[str]:
        queries = [extract_params(dep.query_class, p)
                   if dep.query_class is not None else p
                   for p in payloads]
        predictions = dep.predict_batch(queries)
        for payload, prediction in zip(payloads, predictions):
            yield json.dumps({"query": payload,
                              "prediction": to_jsonable(prediction)})

    chunk: List[dict] = []
    for line in lines:
        line = line.strip()
        if not line:
            continue
        chunk.append(json.loads(line))
        if len(chunk) >= chunk_size:
            yield from flush(chunk)
            chunk = []
    if chunk:
        yield from flush(chunk)


def run_batch_predict(engine: Engine, instance, ctx: RuntimeContext, *,
                      input_path: str, output_path: str,
                      chunk_size: int = 1024) -> int:
    """File-to-file driver (BatchPredict.scala main); returns the number
    of predictions written."""
    n = 0
    with open(input_path) as fin, open(output_path, "w") as fout:
        for out_line in batch_predict_lines(engine, instance, ctx,
                                            fin, chunk_size=chunk_size):
            fout.write(out_line + "\n")
            n += 1
    return n

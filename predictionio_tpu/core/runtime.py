"""RuntimeContext: the per-run execution context handed to DASE components.

The reference passed a `SparkContext` into every Base* method and built it
per workflow run (`core/.../workflow/WorkflowContext.scala:27-46`). The
TPU-native analog bundles:
  - the device `Mesh` all jit'd compute shards over,
  - the `StorageRegistry` (event/meta/model repositories),
  - `WorkflowParams` (verbosity, sanity-check and stop-after flags — parity
    with `core/.../workflow/WorkflowParams.scala`).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Mapping, Optional

from predictionio_tpu.parallel import MeshSpec, make_mesh


@dataclass(frozen=True)
class WorkflowParams:
    """(WorkflowParams.scala:25-40; sparkEnv -> runtime_conf)"""
    batch: str = ""
    verbose: int = 2
    skip_sanity_check: bool = False
    stop_after_read: bool = False
    stop_after_prepare: bool = False
    runtime_conf: Mapping[str, Any] = field(default_factory=dict)


class RuntimeContext:
    """Execution context for one train/eval/serve run."""

    def __init__(self, registry=None, mesh=None,
                 workflow_params: Optional[WorkflowParams] = None):
        self._registry = registry
        self._mesh = mesh
        self.workflow_params = workflow_params or WorkflowParams()
        # per-phase wall-clock filled by Engine.train (read/prepare/
        # per-algo), persisted into the EngineInstance runtime_conf —
        # the per-run tracing record the reference keeps only as
        # start/end times (CoreWorkflow.scala:45-101)
        self.phase_timings: dict = {}

    @property
    def registry(self):
        if self._registry is None:
            from predictionio_tpu.data.storage import storage
            self._registry = storage()
        return self._registry

    @property
    def mesh(self):
        """The device mesh, built lazily from runtime_conf's 'mesh' spec
        (the analog of WorkflowContext building the SparkContext)."""
        if self._mesh is None:
            spec = MeshSpec.from_conf(dict(self.workflow_params.runtime_conf))
            self._mesh = make_mesh(spec)
        return self._mesh

    def with_mesh(self, mesh) -> "RuntimeContext":
        ctx = RuntimeContext(self._registry, mesh, self.workflow_params)
        return ctx

    @property
    def event_store(self):
        return self.registry.get_events()

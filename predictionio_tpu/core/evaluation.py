"""Evaluation & hyperparameter tuning.

Parity targets:
  - `Metric` base + AverageMetric / OptionAverageMetric / StdevMetric /
    SumMetric / ZeroMetric (`core/.../controller/Metric.scala:39-268`)
  - `Evaluation` binding engine + metrics
    (`core/.../controller/Evaluation.scala:34-125`)
  - `EngineParamsGenerator` grid candidates
    (`core/.../controller/EngineParamsGenerator.scala`)
  - `MetricEvaluator` scoring every candidate and picking the best
    (`core/.../controller/MetricEvaluator.scala:185-245`)
  - prefix memoization across candidates (`FastEvalEngine.scala:46-346`):
    a param sweep re-reading/re-preparing/re-training only the stages
    whose params actually changed
  - `CoreWorkflow.runEvaluation` EvaluationInstance lifecycle
    (`core/.../workflow/CoreWorkflow.scala:103-160`)
"""

from __future__ import annotations

import json
import math
import traceback
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

from predictionio_tpu.core.base import Evaluator
from predictionio_tpu.core.engine import Engine
from predictionio_tpu.core.params import EngineParams, params_to_json
from predictionio_tpu.core.runtime import RuntimeContext
from predictionio_tpu.data.event import utcnow
from predictionio_tpu.data.storage.base import (
    EvaluationInstance, EvaluationInstanceStatus,
)

# eval data set shape: [(eval_info, [(query, prediction, actual)])]
EvalDataSet = List[Tuple[Any, List[Tuple[Any, Any, Any]]]]


# ---------------------------------------------------------------------------
# Metrics
# ---------------------------------------------------------------------------

class Metric:
    """Score an EvalDataSet; higher is better unless `comparator` flips it
    (Metric.scala:39-78)."""

    #: set False for error-style metrics where lower is better
    higher_is_better: bool = True

    def header(self) -> str:
        return type(self).__name__

    def calculate(self, ctx: RuntimeContext, eval_data: EvalDataSet) -> float:
        raise NotImplementedError

    def compare(self, a: float, b: float) -> int:
        key = (a > b) - (a < b)
        return key if self.higher_is_better else -key


class _BatchableMetric(Metric):
    """Shared machinery for the calculate_one family: a metric may
    override `calculate_batch` to score a whole fold's (Q,P,A) list as
    one array op (numpy / device arrays) instead of a Python loop per
    tuple — SURVEY.md §7.6 'Metric hierarchy over device arrays'. Large
    k-fold x param-grid sweeps are otherwise CPU-bound on tuple
    iteration. Returning None falls back to per-tuple calculate_one."""

    def calculate_batch(self, qpa: List[Tuple[Any, Any, Any]]):
        """Override: return an array-like of per-tuple scores for one
        fold (None entries allowed for OptionAverageMetric), or None to
        use the calculate_one fallback."""
        return None

    def _fold_scores(self, qpa) -> List:
        batch = self.calculate_batch(qpa)
        if batch is not None:
            return list(batch)
        return [self.calculate_one(q, p, a) for q, p, a in qpa]

    def calculate_one(self, q, p, a):
        raise NotImplementedError


class AverageMetric(_BatchableMetric):
    """Mean of calculate_one over every (Q,P,A) (Metric.scala:95-130)."""

    def calculate(self, ctx, eval_data):
        scores = [s for _, qpa in eval_data for s in self._fold_scores(qpa)]
        return float(sum(scores) / len(scores)) if scores else float("nan")


class OptionAverageMetric(_BatchableMetric):
    """Mean over non-None scores only (Metric.scala:132-170)."""

    def calculate(self, ctx, eval_data):
        scores = [s for _, qpa in eval_data for s in self._fold_scores(qpa)
                  if s is not None]
        return float(sum(scores) / len(scores)) if scores else float("nan")


class SumMetric(_BatchableMetric):
    """Sum of calculate_one (Metric.scala:217-250)."""

    def calculate(self, ctx, eval_data):
        return float(sum(s for _, qpa in eval_data
                         for s in self._fold_scores(qpa)))


class StdevMetric(_BatchableMetric):
    """Population stdev of calculate_one (Metric.scala:172-215)."""

    def calculate(self, ctx, eval_data):
        scores = [s for _, qpa in eval_data for s in self._fold_scores(qpa)]
        if not scores:
            return float("nan")
        mean = sum(scores) / len(scores)
        return float(math.sqrt(sum((s - mean) ** 2
                                   for s in scores) / len(scores)))


class ZeroMetric(Metric):
    """Always 0 — placeholder auxiliary metric (Metric.scala:252-268)."""

    def calculate(self, ctx, eval_data):
        return 0.0


# ---------------------------------------------------------------------------
# Evaluation binding + candidate generation
# ---------------------------------------------------------------------------

@dataclass
class Evaluation:
    """Engine + metrics (+ optional candidate generator)
    (controller/Evaluation.scala:34-125)."""
    engine: Engine
    metric: Metric
    other_metrics: Sequence[Metric] = ()
    engine_params_generator: Optional["EngineParamsGenerator"] = None


@dataclass
class EngineParamsGenerator:
    """A list of candidate EngineParams
    (controller/EngineParamsGenerator.scala)."""
    engine_params_list: Sequence[EngineParams]


# ---------------------------------------------------------------------------
# MetricEvaluator
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class MetricScores:
    score: float
    other_scores: Tuple[float, ...]
    engine_params: EngineParams


@dataclass(frozen=True)
class MetricEvaluatorResult:
    best_score: MetricScores
    best_index: int
    all_results: Tuple[MetricScores, ...]
    metric_header: str
    other_metric_headers: Tuple[str, ...]

    def one_liner(self) -> str:
        return (f"[{self.best_score.score:.4f}] "
                f"{self.metric_header} (best of "
                f"{len(self.all_results)} candidates)")

    def to_json(self) -> str:
        return json.dumps({
            "metricHeader": self.metric_header,
            "otherMetricHeaders": list(self.other_metric_headers),
            "bestIndex": self.best_index,
            "bestScore": self.best_score.score,
            "results": [
                {"score": r.score, "otherScores": list(r.other_scores)}
                for r in self.all_results],
        })

    def to_html(self) -> str:
        rows = "".join(
            f"<tr{' style=font-weight:bold' if i == self.best_index else ''}>"
            f"<td>{i}</td><td>{r.score}</td>"
            f"<td>{list(r.other_scores)}</td></tr>"
            for i, r in enumerate(self.all_results))
        return (f"<table><tr><th>#</th><th>{self.metric_header}</th>"
                f"<th>{list(self.other_metric_headers)}</th></tr>{rows}"
                "</table>")


class MetricEvaluator(Evaluator):
    """Evaluates every candidate EngineParams, returns the best
    (MetricEvaluator.scala:185-245). `output_path` dumps the full result
    JSON to a file."""

    def __init__(self, metric: Metric, other_metrics: Sequence[Metric] = (),
                 output_path: Optional[str] = None):
        super().__init__()
        self.metric = metric
        self.other_metrics = tuple(other_metrics)
        self.output_path = output_path

    def evaluate(self, ctx: RuntimeContext, engine: Engine,
                 engine_params_list: Sequence[EngineParams],
                 eval_data_set=None) -> MetricEvaluatorResult:
        cache = _PrefixCache()
        results: List[MetricScores] = []
        for params in engine_params_list:
            eval_data = _eval_with_cache(engine, ctx, params, cache)
            score = self.metric.calculate(ctx, eval_data)
            others = tuple(m.calculate(ctx, eval_data)
                           for m in self.other_metrics)
            results.append(MetricScores(score, others, params))
        best_index = 0
        for i, r in enumerate(results):
            if self.metric.compare(r.score,
                                   results[best_index].score) > 0:
                best_index = i
        result = MetricEvaluatorResult(
            best_score=results[best_index],
            best_index=best_index,
            all_results=tuple(results),
            metric_header=self.metric.header(),
            other_metric_headers=tuple(m.header()
                                       for m in self.other_metrics),
        )
        if self.output_path:
            with open(self.output_path, "w") as f:
                f.write(result.to_json())
        return result


# ---------------------------------------------------------------------------
# Prefix-memoized eval (the FastEvalEngine analog)
# ---------------------------------------------------------------------------

class _PrefixCache:
    """Caches per-candidate pipeline prefixes keyed by the params JSON of
    each stage (FastEvalEngine.scala:88-230): folds by DataSource params,
    prepared data by (DataSource, Preparator) params, trained models by
    (DataSource, Preparator, Algorithm) params and fold."""

    def __init__(self):
        self.folds: Dict[str, Any] = {}
        self.prepared: Dict[str, Any] = {}
        self.models: Dict[str, Any] = {}

    @staticmethod
    def key(*parts) -> str:
        return "|".join(
            f"{name}:{params_to_json(p)}" for name, p in parts)


def _eval_with_cache(engine: Engine, ctx: RuntimeContext,
                     engine_params: EngineParams,
                     cache: _PrefixCache) -> EvalDataSet:
    from predictionio_tpu.core.engine import bind_serving_context
    ds, prep, algos, serving = engine.make_components(engine_params)
    bind_serving_context(algos, ctx)
    ds_key = _PrefixCache.key(engine_params.data_source_params)
    if ds_key not in cache.folds:
        cache.folds[ds_key] = ds.read_eval(ctx)
    folds = cache.folds[ds_key]

    prep_key = ds_key + "||" + _PrefixCache.key(engine_params.preparator_params)
    if prep_key not in cache.prepared:
        cache.prepared[prep_key] = [prep.prepare(ctx, td)
                                    for td, _, _ in folds]
    prepared = cache.prepared[prep_key]

    out: EvalDataSet = []
    for fold_ix, ((td, eval_info, qa_pairs), pd) in enumerate(
            zip(folds, prepared)):
        models = []
        for algo, ap in zip(algos, engine_params.algorithm_params_list):
            m_key = (prep_key + f"||fold{fold_ix}||"
                     + _PrefixCache.key(ap))
            if m_key not in cache.models:
                cache.models[m_key] = algo.train(ctx, pd)
            models.append(cache.models[m_key])
        queries = [(i, serving.supplement(q))
                   for i, (q, _) in enumerate(qa_pairs)]
        per_algo = [dict(a.batch_predict(m, queries))
                    for a, m in zip(algos, models)]
        qpa = [(q, serving.serve(q, [pa[i] for pa in per_algo]), a)
               for i, (q, a) in enumerate(qa_pairs)]
        out.append((eval_info, qpa))
    return out


# ---------------------------------------------------------------------------
# Evaluation workflow (CoreWorkflow.runEvaluation)
# ---------------------------------------------------------------------------

def run_evaluation(evaluation: Evaluation, ctx: RuntimeContext, *,
                   evaluation_class: str = "",
                   engine_params_list: Optional[Sequence[EngineParams]] = None,
                   evaluator: Optional[MetricEvaluator] = None,
                   ) -> Tuple[EvaluationInstance, MetricEvaluatorResult]:
    """Run an evaluation end-to-end, recording an EvaluationInstance
    (CoreWorkflow.scala:103-160)."""
    registry = ctx.registry
    instances = registry.get_meta_data_evaluation_instances()
    row = EvaluationInstance(
        id="", status=EvaluationInstanceStatus.INIT,
        start_time=utcnow(), end_time=utcnow(),
        evaluation_class=evaluation_class,
        batch=ctx.workflow_params.batch,
        runtime_conf=dict(ctx.workflow_params.runtime_conf),
    )
    iid = instances.insert(row)
    row = row.with_(id=iid, status=EvaluationInstanceStatus.RUNNING)
    instances.update(row)
    try:
        if engine_params_list is None:
            gen = evaluation.engine_params_generator
            if gen is None:
                raise ValueError(
                    "No engine params: pass engine_params_list or set "
                    "Evaluation.engine_params_generator")
            engine_params_list = gen.engine_params_list
        evaluator = evaluator or MetricEvaluator(
            evaluation.metric, evaluation.other_metrics)
        result = evaluator.evaluate(ctx, evaluation.engine,
                                    engine_params_list)
        row = row.with_(
            status=EvaluationInstanceStatus.COMPLETED,
            end_time=utcnow(),
            evaluator_results=result.one_liner(),
            evaluator_results_html=result.to_html(),
            evaluator_results_json=result.to_json(),
        )
        instances.update(row)
        return row, result
    except Exception:
        traceback.print_exc()
        instances.update(row.with_(end_time=utcnow()))
        raise

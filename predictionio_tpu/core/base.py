"""DASE component contracts: DataSource, Preparator, Algorithm, Serving,
Evaluator.

Parity targets: `core/.../core/{BaseDataSource,BasePreparator,BaseAlgorithm,
BaseServing,BaseEvaluator}.scala` and the user-facing flavors in
`core/.../controller/`.

Design decision (TPU-first): the reference splits every component into
P(parallel)/L(local)/P2L flavors because Spark forces a distinction between
RDD-resident and driver-resident values. Single-controller JAX has no such
split — training data are host/device arrays owned by one Python process
and sharded over the mesh by annotation — so there is ONE flavor of each
component. What survives of the P/L distinction is the *persistence*
semantics, expressed per-algorithm (see `persist_model` and
`PersistentModel` in persistence.py):
  - persist_model=True  ≙ P2L/LAlgorithm (model auto-serialized; reference
    `P2LAlgorithm.makePersistentModel`)
  - persist_model=False ≙ PAlgorithm returning () (retrain on deploy;
    reference `Engine.prepareDeploy:211-233`)
  - implementing PersistentModel ≙ custom save/load (reference
    `controller/PersistentModel.scala:30-115`)

Every component is constructed with a single Params dataclass — the analog
of `Doer`'s reflective ctor-with-Params (`core/.../core/AbstractDoer.scala`).
"""

from __future__ import annotations

from typing import Any, Generic, List, Optional, Sequence, Tuple, Type, TypeVar

from predictionio_tpu.core.params import EmptyParams, Params
from predictionio_tpu.core.runtime import RuntimeContext

TD = TypeVar("TD")   # training data
EI = TypeVar("EI")   # evaluation info
PD = TypeVar("PD")   # prepared data
Q = TypeVar("Q")     # query
P = TypeVar("P")     # predicted result
A = TypeVar("A")     # actual result
M = TypeVar("M")     # model


class TrainingInterrupted(Exception):
    """Base for the stop-after-* control-flow interruptions
    (WorkflowUtils.scala:388-392)."""


class StopAfterReadInterruption(TrainingInterrupted):
    pass


class StopAfterPrepareInterruption(TrainingInterrupted):
    pass


class _Component:
    """Shared ctor: every DASE component takes one Params dataclass."""

    params_class: Type[Params] = EmptyParams

    def __init__(self, params: Optional[Params] = None):
        if params is None or (isinstance(params, EmptyParams)
                              and self.params_class is not EmptyParams):
            # an EmptyParams placeholder (EngineParams' default) means "use
            # this component's default params"
            params = self.params_class()
        self.params = params

    def __repr__(self):
        return f"{type(self).__name__}({self.params!r})"


class DataSource(_Component, Generic[TD, EI, Q, A]):
    """Reads training and evaluation data from the event store
    (BaseDataSource.scala:37-54; PDataSource/LDataSource collapse)."""

    def read_training(self, ctx: RuntimeContext) -> TD:
        raise NotImplementedError

    def read_eval(self, ctx: RuntimeContext
                  ) -> Sequence[Tuple[TD, EI, Sequence[Tuple[Q, A]]]]:
        """k folds of (trainingData, evalInfo, [(query, actual)])
        (readEval, BaseDataSource.scala:43)."""
        return []


class Preparator(_Component, Generic[TD, PD]):
    """TD -> PD (BasePreparator.scala:36)."""

    def prepare(self, ctx: RuntimeContext, td: TD) -> PD:
        raise NotImplementedError


class IdentityPreparator(Preparator):
    """PD = TD passthrough (controller/IdentityPreparator.scala:29-93)."""

    def prepare(self, ctx: RuntimeContext, td):
        return td


class Algorithm(_Component, Generic[PD, M, Q, P]):
    """Train a model; answer queries (BaseAlgorithm.scala:58-125).

    `query_class` plays the role of the reference's `queryClass` ClassTag
    (BaseAlgorithm.scala:104-113): the serving layer extracts incoming JSON
    into it via `extract_params`. None = raw dict passthrough.
    """

    query_class: Optional[type] = None
    persist_model: bool = True

    def train(self, ctx: RuntimeContext, pd: PD) -> M:
        raise NotImplementedError

    def predict(self, model: M, query: Q) -> P:
        raise NotImplementedError

    def batch_predict(self, model: M, queries: Sequence[Tuple[int, Q]]
                      ) -> List[Tuple[int, P]]:
        """Bulk inference for eval/batchpredict; default maps `predict`
        (P2LAlgorithm.batchPredict default, P2LAlgorithm.scala:26-45).
        Algorithms with device-batched inference override this to run one
        jit'd program over all queries."""
        return [(i, self.predict(model, q)) for i, q in queries]

    def warm_serving(self, model: M, buckets: Sequence[int],
                     mesh=None) -> int:
        """Deploy-time warmup hook: pin model state device-resident and
        AOT-compile the serve executables for the given batch-size
        `buckets`, so the first real request (and every one after) hits a
        precompiled static shape. `mesh` (a `topk_sharded.ServeMesh`, or
        None) is the candidate serving mesh: algorithms with sharding-
        capable plans pass it to `serve_plan`/`similar_plan`, which
        partition model state across the mesh when it is configured or
        the catalog exceeds one device's capacity. Overrides that predate
        the mesh parameter are still called (warm_deploy inspects the
        signature). Returns the number of executables compiled; the
        default is a no-op for host-only algorithms. Called by
        `CoreWorkflow.prepare_deploy` after models are loaded."""
        return 0


class Serving(_Component, Generic[Q, P]):
    """Query supplement + multi-algorithm result combination
    (BaseServing.scala:33-42, controller/LServing.scala)."""

    def supplement(self, query: Q) -> Q:
        return query

    def serve(self, query: Q, predictions: Sequence[P]) -> P:
        raise NotImplementedError


class FirstServing(Serving):
    """Serve the first algorithm's prediction (controller/LServing.scala
    LFirstServing)."""

    def serve(self, query, predictions):
        return predictions[0]


class Evaluator(_Component):
    """Scores the output of Engine.eval (BaseEvaluator.scala:37-48).
    Concrete implementation: MetricEvaluator in evaluation.py."""

    def evaluate(self, ctx: RuntimeContext, engine, engine_params_list,
                 eval_data_set) -> Any:
        raise NotImplementedError


def sanity_check(obj: Any) -> None:
    """Run an object's sanity_check hook if present (SanityCheck trait,
    `core/.../controller/SanityCheck.scala`; called from Engine.train,
    Engine.scala:652-690)."""
    hook = getattr(obj, "sanity_check", None)
    if callable(hook):
        hook()

"""Model persistence: the Kryo-blob + PersistentModel analog.

Parity targets:
  - Kryo serialization of the per-instance model list
    (`core/.../workflow/CoreWorkflow.scala:76-81`, `CreateServer.scala:58-72`)
  - `PersistentModel`/`PersistentModelLoader` custom save/load
    (`core/.../controller/PersistentModel.scala:30-115`)
  - `PersistentModelManifest` marker stored in place of bytes
    (`core/.../workflow/PersistentModelManifest.scala`)

Implementation: one pickle blob per engine instance containing the list of
per-algorithm entries. jax.Arrays are converted to numpy on save and live
as numpy until an algorithm moves them back to device (device placement is
a serving-time decision — the mesh at deploy time may differ from the mesh
at train time). Models implementing `PersistentModel` save themselves
(e.g. to a directory of .npz shards) and only their manifest enters the
blob; models of algorithms with `persist_model=False` store a retrain
marker, reproducing the reference's retrain-on-deploy semantics
(`Engine.scala:211-233`).
"""

from __future__ import annotations

import io
import pickle
from dataclasses import dataclass
from typing import Any, List, Sequence


@dataclass(frozen=True)
class PersistentModelManifest:
    """Marker stored instead of model bytes (PersistentModelManifest.scala)."""
    class_module: str
    class_name: str


@dataclass(frozen=True)
class RetrainMarker:
    """Stored for persist_model=False algorithms: deploy retrains
    (the reference's `Unit` model, Engine.scala:286-304)."""


class PersistentModel:
    """Custom save/load contract (PersistentModel.scala:30-115).

    Implementors define:
      save(instance_id, params, ctx) -> bool   (False = fall back to blob)
      @classmethod load(cls, instance_id, params, ctx) -> model
    """

    def save(self, instance_id: str, params, ctx) -> bool:
        raise NotImplementedError

    @classmethod
    def load(cls, instance_id: str, params, ctx):
        raise NotImplementedError


class _JaxAwarePickler(pickle.Pickler):
    """Pickle with jax.Array -> numpy conversion at save time."""

    def persistent_id(self, obj):
        return None

    def reducer_override(self, obj):
        try:
            import jax
            if isinstance(obj, jax.Array):
                import numpy as np
                return (np.asarray, (np.asarray(obj),))
        except ImportError:  # pragma: no cover
            pass
        return NotImplemented


def dumps(obj: Any) -> bytes:
    buf = io.BytesIO()
    _JaxAwarePickler(buf, protocol=pickle.HIGHEST_PROTOCOL).dump(obj)
    return buf.getvalue()


def loads(data: bytes) -> Any:
    return pickle.loads(data)


def serialize_models(instance_id: str, algorithms: Sequence, models: Sequence,
                     ctx) -> bytes:
    """Decide per-algorithm persistence and produce the instance blob
    (Engine.makeSerializableModels, Engine.scala:286-304)."""
    entries: List[Any] = []
    for algo, model in zip(algorithms, models):
        if isinstance(model, PersistentModel):
            if model.save(instance_id, algo.params, ctx):
                cls = type(model)
                entries.append(PersistentModelManifest(
                    cls.__module__, cls.__qualname__))
            else:
                entries.append(model)
        elif not getattr(algo, "persist_model", True):
            entries.append(RetrainMarker())
        else:
            entries.append(model)
    return dumps(entries)


def deserialize_models(blob: bytes, instance_id: str, algorithms: Sequence,
                       ctx, retrain) -> List[Any]:
    """Invert serialize_models at deploy time
    (Engine.prepareDeploy, Engine.scala:199-269).

    `retrain` is a callback (indices) -> {index: model} invoked only for
    the algorithm positions that stored a RetrainMarker — read/prepare run
    once, and only the marker algorithms pay a train."""
    entries = loads(blob)
    marker_ix = [i for i, e in enumerate(entries)
                 if isinstance(e, RetrainMarker)]
    fresh: dict = retrain(marker_ix) if marker_ix else {}
    out: List[Any] = []
    for i, (entry, algo) in enumerate(zip(entries, algorithms)):
        if isinstance(entry, PersistentModelManifest):
            import importlib
            mod = importlib.import_module(entry.class_module)
            cls = mod
            for part in entry.class_name.split("."):
                cls = getattr(cls, part)
            out.append(cls.load(instance_id, algo.params, ctx))
        elif isinstance(entry, RetrainMarker):
            out.append(fresh[i])
        else:
            out.append(entry)
    return out

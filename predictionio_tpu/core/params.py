"""Typed parameter classes and JSON extraction.

Parity targets:
  - `Params` marker + `EmptyParams` (`core/.../controller/Params.scala`)
  - `EngineParams` 4-tuple of named component params
    (`core/.../controller/EngineParams.scala:25-65`)
  - typed JSON -> params extraction with precise error messages
    (`core/.../workflow/JsonExtractor.scala:1-167`,
    `WorkflowUtils.extractParams:123-152`). The reference needed a dual
    Json4s/Gson extractor to cover Scala and Java params classes; here one
    dataclass-driven extractor covers everything, including nested
    dataclasses, Optionals, sequences and mappings.
"""

from __future__ import annotations

import dataclasses
import json
import typing
from typing import Any, Dict, Mapping, Optional, Sequence, Tuple, Type, TypeVar


class Params:
    """Marker base for component parameter classes; subclasses are
    `@dataclass`es. (Params.scala:25)"""


@dataclasses.dataclass(frozen=True)
class EmptyParams(Params):
    """(EmptyParams, Params.scala:30)"""


T = TypeVar("T")


class ParamsError(ValueError):
    """Extraction failure with a JSON-path-qualified message."""


def _type_name(tp) -> str:
    return getattr(tp, "__name__", str(tp))


# typing.get_type_hints resolves every annotation string through the
# defining module's globals on EVERY call — measured at ~0.4 ms per
# request on the serving hot path (each query extracts its Query
# dataclass). Hints are a pure function of the class: memoize.
_HINTS_CACHE: Dict[type, Dict[str, Any]] = {}


def _hints_for(cls: type) -> Dict[str, Any]:
    h = _HINTS_CACHE.get(cls)
    if h is None:
        h = _HINTS_CACHE[cls] = typing.get_type_hints(cls)
    return h


def extract_params(cls: Type[T], obj: Any, path: str = "$") -> T:
    """Build `cls` (a Params dataclass) from parsed JSON `obj`.

    Unknown keys are rejected (the reference's Json4sNative extractor
    silently ignored them, which the docs call out as a source of silent
    misconfiguration — strictness here is deliberate and tested)."""
    if isinstance(obj, str):
        obj = json.loads(obj) if obj.strip() else {}
    if obj is None:
        obj = {}
    if not isinstance(obj, Mapping):
        raise ParamsError(
            f"{path}: expected an object for {_type_name(cls)}, "
            f"got {type(obj).__name__}")
    if not dataclasses.is_dataclass(cls):
        raise ParamsError(f"{path}: {_type_name(cls)} is not a params dataclass")
    fields = {f.name: f for f in dataclasses.fields(cls)}
    unknown = set(obj) - set(fields)
    if unknown:
        raise ParamsError(
            f"{path}: unknown field(s) {sorted(unknown)} for "
            f"{_type_name(cls)}; known: {sorted(fields)}")
    hints = _hints_for(cls)
    kwargs: Dict[str, Any] = {}
    for name, f in fields.items():
        if name in obj:
            kwargs[name] = _coerce(hints.get(name, Any), obj[name],
                                   f"{path}.{name}")
        elif (f.default is dataclasses.MISSING
              and f.default_factory is dataclasses.MISSING):
            raise ParamsError(
                f"{path}: missing required field '{name}' "
                f"({_type_name(hints.get(name, Any))}) for {_type_name(cls)}")
    try:
        return cls(**kwargs)
    except (TypeError, ValueError) as e:
        raise ParamsError(f"{path}: cannot construct {_type_name(cls)}: {e}")


def _coerce(tp, value: Any, path: str) -> Any:
    origin = typing.get_origin(tp)
    args = typing.get_args(tp)
    if tp is Any or tp is None:
        return value
    if origin is typing.Union:
        if value is None:
            if type(None) in args:
                return None
            raise ParamsError(f"{path}: null not allowed for {tp}")
        errors = []
        for cand in (a for a in args if a is not type(None)):
            try:
                return _coerce(cand, value, path)
            except ParamsError as e:
                errors.append(str(e))
        raise ParamsError(f"{path}: no Union arm matched: {errors}")
    if dataclasses.is_dataclass(tp):
        return extract_params(tp, value, path)
    # typing.get_origin(Sequence[str]) is collections.abc.Sequence, and
    # get_origin(Mapping[...]) is collections.abc.Mapping — match the abc,
    # with Mapping checked first since dict-like abcs subclass Collection
    import collections.abc as cabc
    is_mapping_origin = (isinstance(origin, type)
                         and issubclass(origin, cabc.Mapping))
    is_seq_origin = (isinstance(origin, type) and not is_mapping_origin
                     and issubclass(origin, cabc.Sequence))
    if is_seq_origin or tp in (list, tuple):
        if not isinstance(value, (list, tuple)):
            raise ParamsError(
                f"{path}: expected array, got {type(value).__name__}")
        elem = args[0] if args else Any
        out = [_coerce(elem, v, f"{path}[{i}]") for i, v in enumerate(value)]
        return tuple(out) if origin is tuple or tp is tuple else out
    if is_mapping_origin or tp is dict:
        if not isinstance(value, Mapping):
            raise ParamsError(
                f"{path}: expected object, got {type(value).__name__}")
        vt = args[1] if len(args) == 2 else Any
        return {k: _coerce(vt, v, f"{path}.{k}") for k, v in value.items()}
    if tp is bool:
        if not isinstance(value, bool):
            raise ParamsError(
                f"{path}: expected bool, got {type(value).__name__}")
        return value
    if tp is int:
        if isinstance(value, bool) or not isinstance(value, int):
            if isinstance(value, float) and value.is_integer():
                return int(value)
            raise ParamsError(
                f"{path}: expected int, got {type(value).__name__}")
        return value
    if tp is float:
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            raise ParamsError(
                f"{path}: expected number, got {type(value).__name__}")
        return float(value)
    if tp is str:
        if not isinstance(value, str):
            raise ParamsError(
                f"{path}: expected string, got {type(value).__name__}")
        return value
    return value


def params_to_json(p: Optional[Params]) -> str:
    """Serialize a params dataclass back to JSON (for instance metadata)."""
    if p is None:
        return "{}"
    return json.dumps(dataclasses.asdict(p), sort_keys=True)


@dataclasses.dataclass(frozen=True)
class EngineParams:
    """Named component params for one engine variant
    (EngineParams.scala:25-65): (component name, params) pairs; algorithms
    is a list so one engine can run several algorithms at once."""
    data_source_params: Tuple[str, Params] = ("", EmptyParams())
    preparator_params: Tuple[str, Params] = ("", EmptyParams())
    algorithm_params_list: Sequence[Tuple[str, Params]] = ()
    serving_params: Tuple[str, Params] = ("", EmptyParams())

    def with_(self, **kw) -> "EngineParams":
        return dataclasses.replace(self, **kw)

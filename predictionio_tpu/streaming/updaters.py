"""Incremental model updaters: the shared fold-in machinery.

The model templates own their data semantics (what counts as a rating,
which events matter), so each template exposes a `fold_in(model, delta,
fctx)` hook; this module supplies what those hooks share — the
`FoldContext` (store access scoped to the delta window) and the
closed-form ALS fold helpers.

Fold-in semantics (the idempotence contract): a touched entity's FULL
history is refetched from the event store and its factor row re-solved
from scratch against fixed opposite-side factors (one exact ALS
half-step via `ops.als.fold_in_rows`). Re-applying the same delta is
therefore a no-op, and untouched rows are bit-identical by
construction. New USERS extend the BiMap (old indices stable — the
user side is not baked into any serve plan); new ITEMS invalidate the
delta, because the item-factor shape IS baked into the AOT serve
plans and a full rebuild is the correct response.

The periodic full retrain remains ground truth: folded models are
in-memory only and never persisted.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from predictionio_tpu.data.storage.base import DeltaInvalidated
from predictionio_tpu.ingest.bimap import BiMap
from predictionio_tpu.ops import als


@dataclass
class FoldContext:
    """Store access scoped to one refresh tick's delta window."""
    store: object                      # events DAO (registry.get_events())
    app_id: int
    channel_id: Optional[int]
    since: Dict[str, int]
    upto: Dict[str, int]
    mesh: object = None
    ds_params: Dict[str, object] = field(default_factory=dict)

    def delta_columns(self, **kw):
        """Template-spec re-scan of the SAME delta frames the generic
        change scan decoded (bytes-bounded by the storage contract)."""
        return self.store.scan_columns(
            self.app_id, self.channel_id, since=self.since,
            upto=self.upto, **kw)

    def user_history(self, user_id: str, event_names: Sequence[str]):
        """A touched user's full interaction history (the serving-time
        read idiom, LEventStore.findByEntity)."""
        return self.store.find(
            self.app_id, self.channel_id, entity_type="user",
            entity_id=user_id, event_names=list(event_names))

    def item_history(self, item_id: str, event_names: Sequence[str]):
        """All interactions TARGETING one item (reverse read for the
        item-side half-step)."""
        return self.store.find(
            self.app_id, self.channel_id, entity_type="user",
            target_entity_id=item_id, event_names=list(event_names))


def extend_bimap(base: BiMap, new_keys: Sequence[str]) -> BiMap:
    """Stable extension: existing ids unchanged, unseen keys appended
    in first-seen order."""
    fresh, seen = [], set()
    for k in new_keys:
        if base.get(k) is None and k not in seen:
            fresh.append(k)
            seen.add(k)
    if not fresh:
        return base
    return BiMap.from_keys(base.keys() + fresh)


def _history_arrays(events, key_of: Callable, value_of: Callable,
                    dedup_last_wins: bool):
    """(index, value) arrays from an event iterator. `key_of` maps an
    event to an opposite-side dense index (None = skip row, raise
    handled by caller), `value_of` to a float (None = skip)."""
    rows: List[Tuple[object, int, float]] = []
    for ev in events:
        v = value_of(ev)
        if v is None:
            continue
        ix = key_of(ev)
        rows.append((ev.event_time, ix, float(v)))
    if dedup_last_wins:
        last: Dict[int, float] = {}
        for _, ix, v in sorted(rows, key=lambda r: r[0]):
            last[ix] = v
        items = list(last.items())
        return (np.array([i for i, _ in items], np.int32),
                np.array([v for _, v in items], np.float32))
    return (np.array([ix for _, ix, _ in rows], np.int32),
            np.array([v for _, _, v in rows], np.float32))


def fold_als_users(fctx: FoldContext, users: BiMap, items: BiMap,
                   user_factors: np.ndarray, item_factors: np.ndarray,
                   touched: Sequence[str], *, event_names: Sequence[str],
                   value_of: Callable, dedup_last_wins: bool, reg: float,
                   implicit: bool = False, alpha: float = 1.0):
    """Re-solve the touched users' rows against FIXED item factors.
    Returns (new_user_factors, new_users_bimap, n_folded). New users
    are appended; a history touching an unknown item raises
    `DeltaInvalidated` (item shapes are baked into the serve plans)."""
    users2 = extend_bimap(users, touched)
    histories, rows = [], []
    for uid in touched:
        def item_ix(ev, _uid=uid):
            ii = items.get(ev.target_entity_id)
            if ii is None:
                raise DeltaInvalidated(
                    f"user {_uid!r} touched unknown item "
                    f"{ev.target_entity_id!r}: item shapes are baked "
                    "into the AOT serve plans")
            return ii
        ix, val = _history_arrays(
            fctx.user_history(uid, event_names), item_ix, value_of,
            dedup_last_wins)
        histories.append((ix, val))
        rows.append(users2.get(uid))
    new_rows = als.fold_in_rows(item_factors, histories, reg=reg,
                                implicit=implicit, alpha=alpha)
    uf = np.zeros((len(users2), user_factors.shape[1]), np.float32)
    uf[:len(user_factors)] = user_factors   # untouched rows bit-identical
    for r, row_ix in enumerate(rows):
        uf[row_ix] = new_rows[r]
    return uf, users2, len(rows)


def fold_als_items(fctx: FoldContext, users2: BiMap, items: BiMap,
                   user_factors: np.ndarray, item_factors: np.ndarray,
                   touched: Sequence[str], *, event_names: Sequence[str],
                   value_of: Callable, dedup_last_wins: bool, reg: float,
                   implicit: bool = False, alpha: float = 1.0):
    """Re-solve the touched items' rows against the (already folded)
    user factors — the second half of the fold sweep, and the part
    that actually flows into the device-resident serve plans. Returns
    (new_item_factors, n_folded). Unknown items or unknown users
    raise `DeltaInvalidated`."""
    histories, rows = [], []
    for iid in touched:
        ii = items.get(iid)
        if ii is None:
            raise DeltaInvalidated(
                f"new item {iid!r} in delta: item shapes are baked "
                "into the AOT serve plans; full rebuild required")
        def user_ix(ev, _iid=iid):
            ui = users2.get(ev.entity_id)
            if ui is None:
                raise DeltaInvalidated(
                    f"item {_iid!r} touched by unknown user "
                    f"{ev.entity_id!r}")
            return ui
        ix, val = _history_arrays(
            fctx.item_history(iid, event_names), user_ix, value_of,
            dedup_last_wins)
        histories.append((ix, val))
        rows.append(ii)
    new_rows = als.fold_in_rows(user_factors, histories, reg=reg,
                                implicit=implicit, alpha=alpha)
    yf = np.ascontiguousarray(item_factors, np.float32).copy()
    for r, row_ix in enumerate(rows):
        yf[row_ix] = new_rows[r]
    return yf, len(rows)

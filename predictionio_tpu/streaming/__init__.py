"""Streaming freshness: the layer between train and serve.

The reference system (PAPER.md) is a Lambda architecture — models go
stale between full `pio train` runs. This package closes the gap: the
pevlog journal + ingest watermark already know exactly *what changed*
since the last snapshot, so a deployed model can stay minutes-fresh
under a live event firehose without a retrain in the loop.

Three pieces:
  - `delta` — a generic change summary between two watermark snapshots,
    built on `EventStore.scan_columns(since=..., upto=...)` (bytes-
    bounded; raises `DeltaInvalidated` whenever a delete, journal
    rewrite, or over-budget span makes incremental decode unsafe).
  - `updaters` — `FoldContext` plus the shared closed-form ALS fold-in
    helpers the model templates' `fold_in` hooks build on.
  - `refresher` — the background thread in `PredictionServer` that
    ticks every `PIO_REFRESH_INTERVAL_S`: delta-scan -> fold-in ->
    hot-swap the updated factors into the device-resident serve plans
    (same shapes => the AOT executables keep serving, zero recompiles),
    with rollback-on-failure through the `streaming.refresh.swap` seam.

The periodic FULL retrain remains ground truth: fold-ins are in-memory
only and never persisted to the model store.
"""

from predictionio_tpu.streaming.delta import (  # noqa: F401
    Delta, scan_delta,
)
from predictionio_tpu.streaming.refresher import (  # noqa: F401
    Refresher, locate_event_store,
)
from predictionio_tpu.streaming.updaters import FoldContext  # noqa: F401

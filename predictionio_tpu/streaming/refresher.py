"""Background serve-path refresher: delta-scan -> fold-in -> hot swap.

A `Refresher` thread rides inside `PredictionServer` and ticks every
`refresh_interval_s` seconds: snapshot the ingest watermark, delta-scan
the journal tail, run each algorithm's `fold_in` hook, then COMMIT —
swap the updated item factors into the device-resident serve plans
(same shapes => the AOT executables keep serving, zero recompiles; only
the factor block crosses host->device) and publish a new deployment
object under the server's swap lock.

Failure policy (the PR-2 rollback discipline): all new models are
computed host-side BEFORE anything touches the serve path; the
`streaming.refresh.swap` fault seam fires between compute and commit;
any commit failure re-swaps the last-good factors and keeps the old
deployment — both factor sets are valid mid-swap, so in-flight client
requests never fail. `DeltaInvalidated` (deletes between snapshots,
new items, over-budget deltas, drivers with no delta path) falls back
to the full-scan path: an in-process retrain from the complete store
read, shape-matched plans hot-swapped, changed shapes re-warmed.

Freshness accounting: `pio_freshness_seconds` is the age of the newest
event reflected in the serving model, sampled at each successful tick
(0 when the store and model already agree). Events that landed between
the FULL train and the refresher's first watermark baseline ride the
next full retrain unless their user is touched again — fold-in
refetches a touched user's complete history, which heals most of that
gap for active users. Count-merge folds (cooccurrence, popularity) may
over-count events racing a full rebuild; the next full retrain is
ground truth.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, Optional, Tuple

from predictionio_tpu.data.storage.base import DeltaInvalidated
from predictionio_tpu.obs import MetricsRegistry, get_logger, get_registry
from predictionio_tpu.obs import trace
from predictionio_tpu.resilience import faults
from predictionio_tpu.streaming.delta import Delta, scan_delta
from predictionio_tpu.streaming.updaters import FoldContext

_log = get_logger(__name__)


def locate_event_store(dep, registry) -> Optional[
        Tuple[object, int, object, dict]]:
    """events DAO + app/channel ids from a live deployment's data
    source params (the `{"name":..., "params": {...}}` shape the
    workflow persists). Shared by the refresher and the quality
    feedback joiner; None when the deployment has no locatable app."""
    from predictionio_tpu.data.store import app_name_to_id
    try:
        raw = json.loads(dep.instance.data_source_params or "{}")
    except ValueError:
        return None
    params = raw.get("params", {}) if isinstance(raw, dict) else {}
    app_name = params.get("app_name")
    if not app_name:
        return None
    try:
        app_id, channel_id = app_name_to_id(
            registry, app_name, params.get("channel"))
    except ValueError:
        return None
    return registry.get_events(), app_id, channel_id, params


def _metrics(reg: MetricsRegistry) -> dict:
    return {
        "freshness": reg.gauge(
            "pio_freshness_seconds",
            "age of the newest event reflected in the serving model, "
            "sampled at the last successful refresh tick"),
        "ticks": reg.counter(
            "pio_streaming_refresh_total",
            "refresh ticks by outcome", labels=("outcome",)),
        "tick_s": reg.histogram(
            "pio_streaming_refresh_seconds", "refresh tick duration"),
        "folded": reg.counter(
            "pio_streaming_fold_rows_total",
            "factor rows re-solved by fold-in", labels=("side",)),
    }


class Refresher:
    """One background freshness loop per PredictionServer."""

    def __init__(self, server, interval_s: float, *,
                 stagger_s: float = 0.0,
                 metrics: Optional[MetricsRegistry] = None):
        self.server = server
        self.interval_s = float(interval_s)
        self.stagger_s = float(stagger_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._wm: Optional[Dict[str, int]] = None
        self._m = _metrics(metrics if metrics is not None
                           else get_registry())
        self.last_outcome = ""          # test/introspection surface
        self.beat = None                # watchdog liveness stamp

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        if self.beat is None:
            from predictionio_tpu.resilience.watchdog import watchdog
            # budget: a tick may legitimately take a full-rebuild, so
            # give several intervals of slack before a stall verdict
            self.beat = watchdog().register(
                "refresher", budget_s=self.interval_s * 3.0 + 5.0,
                restart=self._spawn)
        self._spawn()

    def _spawn(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, name="pio-refresher", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        beat, self.beat = self.beat, None
        if beat is not None:
            beat.close()
        t = self._thread
        if t is not None:
            t.join(min(10.0, self.interval_s + 5.0))

    def _loop(self) -> None:
        beat = self.beat
        if beat is not None:
            beat.guard(self._loop_body)
        else:
            self._loop_body()

    def _loop_body(self) -> None:
        # fleet rolling variant: replicas start offset by stagger so at
        # most one folds at a time and a poisoned swap (rolled back)
        # never hits the whole fleet in the same instant
        beat = self.beat
        if self.stagger_s > 0 and self._stop.wait(self.stagger_s):
            return
        while not self._stop.is_set():
            if beat is not None:
                beat.tick()
            try:
                self.tick()
            except Exception:
                self.last_outcome = "failed"
                self._m["ticks"].labels(outcome="failed").inc()
                _log.exception("refresh_tick_failed")
            if self._stop.wait(self.interval_s):
                return

    # -- one tick -----------------------------------------------------------
    def tick(self) -> str:
        """One refresh pass; returns the outcome label (also recorded
        in `pio_streaming_refresh_total`). Safe to call directly from
        tests — the loop is just pacing around this."""
        t0 = time.perf_counter()
        # background span: each tick (and the fold/rebuild inside it)
        # lands in the trace ring as kind="background" when tracing is on
        with trace.background("refresh_tick"):
            outcome = self._tick_inner()
        self.last_outcome = outcome
        self._m["ticks"].labels(outcome=outcome).inc()
        self._m["tick_s"].observe(time.perf_counter() - t0)
        return outcome

    def _tick_inner(self) -> str:
        server = self.server
        dep = server._dep
        if dep is None:
            return "no_deployment"
        located = self._locate(dep)
        if located is None:
            return "no_app"
        events, app_id, channel_id, ds_params = located
        # PIO_INGEST_SERVICE reroutes the delta scans below through the
        # shared ingest tier (watermark + find stay on the local store)
        from predictionio_tpu.ingest.client import maybe_remote
        events = maybe_remote(events)
        wm_now = events.ingest_watermark(app_id, channel_id)
        if wm_now is None:
            return "no_watermark"       # driver can't delta: stay passive
        if self._wm is None:
            # deploy-time baseline; pre-deploy stragglers ride the next
            # full retrain (module docstring, "Freshness accounting")
            self._wm = wm_now
            self._m["freshness"].set(0.0)
            return "baseline"
        if wm_now == self._wm:
            self._m["freshness"].set(0.0)
            return "noop"
        try:
            delta = scan_delta(events, app_id, channel_id, self._wm,
                               wm_now)
            fctx = FoldContext(
                store=events, app_id=app_id, channel_id=channel_id,
                since=self._wm, upto=wm_now,
                mesh=getattr(dep, "mesh", None), ds_params=ds_params)
            outcome = self._fold_and_swap(dep, delta, fctx)
        except DeltaInvalidated as e:
            _log.warning("delta_invalidated", reason=str(e))
            self._full_rebuild(dep)
            outcome = "full_rebuild"
            self._m["freshness"].set(0.0)
        except Exception:
            # commit failed and was rolled back (or fold itself blew
            # up): last-good keeps serving; do NOT advance the
            # watermark — the same delta retries next tick
            _log.exception("refresh_swap_rolled_back")
            return "rolled_back"
        self._wm = wm_now
        return outcome

    def _locate(self, dep) -> Optional[Tuple[object, int, object, dict]]:
        return locate_event_store(dep, self.server.ctx.registry)

    # -- fold + commit ------------------------------------------------------
    def _fold_and_swap(self, dep, delta: Delta,
                       fctx: FoldContext) -> str:
        if delta.empty:
            self._m["freshness"].set(0.0)
            return "noop"
        with trace.background("refresh_fold"):
            return self._fold_and_swap_inner(dep, delta, fctx)

    def _fold_and_swap_inner(self, dep, delta: Delta,
                             fctx: FoldContext) -> str:
        # phase 1 — compute ALL updated models host-side (no serving
        # impact; a crash here changes nothing the client sees)
        new_models = list(dep.models)
        swaps = []                      # (plan, new_item_factors)
        folded = False
        for i, (algo, model) in enumerate(zip(dep.algos, dep.models)):
            hook = getattr(algo, "fold_in", None)
            if hook is None or model is None:
                continue
            new_model = hook(model, delta, fctx)
            if new_model is None:
                continue
            new_models[i] = new_model
            folded = True
            plan = getattr(algo, "_serve_plan", None)
            factors = getattr(new_model, "item_factors", None)
            if plan is not None and factors is not None:
                swaps.append((plan, factors))
        if not folded:
            return "no_hooks"
        self._m["folded"].labels(side="user").inc(
            len(delta.touched_users))
        # phase 2 — commit: device swap + deployment publish, with
        # rollback to last-good on ANY failure (chaos seam included)
        done = []                       # (plan, previous_host_factors)
        try:
            faults().check("streaming.refresh.swap")
            for plan, factors in swaps:
                done.append((plan, plan.swap_factors(factors)))
            new_dep = self.server._refresh_deployment(dep, new_models)
            with self.server._dep_lock:
                self.server._dep = new_dep
        except Exception:
            for plan, old in done:
                plan.swap_factors(old)
            raise
        self._m["freshness"].set(
            max(0.0, time.time() - delta.newest_us / 1e6))  # lint: ok
        return "folded"

    # -- the full-scan fallback ---------------------------------------------
    def _full_rebuild(self, dep) -> None:
        """`DeltaInvalidated` => retrain in process from the complete
        store read (the watermark-keyed prepared cache keeps the scan
        cheap), hot-swap plans whose shapes survived, re-warm the rest,
        and publish. The serve path never sees a half-built state."""
        from predictionio_tpu.core.workflow import (
            engine_params_from_instance, warm_deploy,
        )
        from predictionio_tpu.ops.topk_sharded import serve_mesh_from_conf
        server = self.server
        ctx = server.ctx
        engine_params = engine_params_from_instance(dep.engine,
                                                    dep.instance)
        ds, prep, _, _ = dep.engine.make_components(engine_params)
        td = ds.read_training(ctx)
        pd = prep.prepare(ctx, td)
        new_models = [algo.train(ctx, pd) for algo in dep.algos]
        done, rewarm = [], []
        try:
            for algo, model in zip(dep.algos, new_models):
                plan = getattr(algo, "_serve_plan", None)
                factors = getattr(model, "item_factors", None)
                if plan is None or factors is None:
                    continue
                if factors.shape == (plan.n_items, plan.rank):
                    done.append((plan, plan.swap_factors(factors)))
                else:
                    rewarm.append((algo, model))
            if rewarm:
                # shape changed (catalog grew): recompile is unavoidable.
                # Same mesh derivation and batch buckets as deploy time
                # (CoreWorkflow.prepare_deploy).
                conf = {**dict(getattr(dep.instance, "runtime_conf",
                                       None) or {}),
                        **dict(ctx.workflow_params.runtime_conf or {})}
                wbm = (server.config.batch_max
                       if getattr(server, "_batcher", None) is not None
                       else 1)
                warm_deploy([a for a, _ in rewarm],
                            [m for _, m in rewarm], wbm,
                            mesh=serve_mesh_from_conf(conf))
            new_dep = server._refresh_deployment(dep, new_models)
            with server._dep_lock:
                server._dep = new_dep
        except Exception:
            for plan, old in done:
                plan.swap_factors(old)
            raise

"""Change summary between two ingest-watermark snapshots.

One generic, bytes-bounded delta scan answers three questions for the
refresher: which entities were touched, how many qualifying events
landed, and how old the newest one is (the freshness numerator). The
per-template `fold_in` hooks then re-scan with their OWN value
semantics through `FoldContext.delta_columns` — the storage layer
guarantees both scans decode the same journal frames.

Everything that makes incremental decode unsafe — a tombstone or
external-id overwrite between the snapshots, a rewritten/shrunk
segment, a span larger than `PIO_DELTA_MAX_BYTES`, or a driver with no
delta path at all — surfaces as `DeltaInvalidated`, and the caller
falls back to the full-scan path (which remains ground truth).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, Tuple

from predictionio_tpu.data.storage.base import DeltaInvalidated

# distinct touched entities per tick past which the closed-form fold-in
# stops being cheaper than a full rebuild (env: PIO_FOLD_MAX_TOUCHED)
_DEFAULT_MAX_TOUCHED = 512


def max_touched() -> int:
    try:
        return int(os.environ.get("PIO_FOLD_MAX_TOUCHED", "")
                   or _DEFAULT_MAX_TOUCHED)
    except ValueError:
        return _DEFAULT_MAX_TOUCHED


@dataclass
class Delta:
    """What changed between `since` and `upto` (both full
    `ingest_watermark` snapshots, `upto` taken BEFORE the scan so a
    concurrent appender can never slip events past the bookkeeping)."""
    since: Dict[str, int]
    upto: Dict[str, int]
    touched_users: Tuple[str, ...]     # distinct entity ids, scan order
    touched_items: Tuple[str, ...]     # distinct target ids, scan order
    n_events: int
    newest_us: int                     # max event time, epoch µs (0 = none)

    @property
    def empty(self) -> bool:
        return self.n_events == 0


def scan_delta(store, app_id: int, channel_id, since: Dict[str, int],
               upto: Dict[str, int]) -> Delta:
    """Generic change-detection scan: user-entity interaction events
    appended in (since, upto]. Raises `DeltaInvalidated` per the
    storage contract, and additionally when the touched-entity count
    exceeds `PIO_FOLD_MAX_TOUCHED` (a full rebuild is cheaper then)."""
    cols = store.scan_columns(
        app_id, channel_id, since=since, upto=upto,
        entity_type="user", value_spec={"*": 1.0}, require_target=True)
    if cols.n == 0:
        return Delta(since, upto, (), (), 0, 0)
    cap = max_touched()
    users = tuple(cols.entities)
    items = tuple(cols.targets)
    if len(users) > cap or len(items) > cap:
        raise DeltaInvalidated(
            f"{len(users)} users / {len(items)} items touched exceeds "
            f"PIO_FOLD_MAX_TOUCHED={cap}; full rebuild is cheaper")
    return Delta(since, upto, users, items, cols.n, int(cols.t_us.max()))

"""Server TLS + key authentication.

Parity: `common/.../configuration/SSLConfiguration.scala:32-74` (JKS
keystore -> sslContext for the spray servers; here PEM cert/key ->
`ssl.SSLContext`) and `common/.../authentication/KeyAuthentication.scala:
30-61` (optional server key checked as a query param for dashboard /
engine-server admin endpoints).

Config keys (from the layered config, `PIO_SERVER_*` — the server.conf
analog): PIO_SERVER_SSL_CERT, PIO_SERVER_SSL_KEY, PIO_SERVER_SSL_ENFORCED,
PIO_SERVER_ACCESS_KEY.
"""

from __future__ import annotations

import hmac
import ssl
from typing import Mapping, Optional

from predictionio_tpu.utils.http import HTTPError, Request, parse_basic_auth_user


def ssl_context_from_config(cfg: Mapping[str, str]) -> Optional[ssl.SSLContext]:
    """Build a server SSLContext from PEM cert/key paths; None when SSL is
    not configured. Raises when SSL is enforced but unconfigured
    (SSLConfiguration sslEnforced)."""
    cert = cfg.get("PIO_SERVER_SSL_CERT")
    key = cfg.get("PIO_SERVER_SSL_KEY")
    enforced = cfg.get("PIO_SERVER_SSL_ENFORCED", "").lower() in ("1", "true")
    if not cert or not key:
        if enforced:
            raise ValueError(
                "PIO_SERVER_SSL_ENFORCED is set but PIO_SERVER_SSL_CERT/"
                "PIO_SERVER_SSL_KEY are not configured")
        return None
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(certfile=cert, keyfile=key)
    return ctx


class KeyAuthentication:
    """Optional server key check (KeyAuthentication.scala:30-61): when a
    key is configured, requests must present it as ?accessKey= or as the
    Basic auth username."""

    def __init__(self, server_key: Optional[str] = None):
        self.server_key = server_key

    def check(self, req: Request) -> None:
        if not self.server_key:
            return
        supplied = req.query.get("accessKey") or parse_basic_auth_user(
            req.headers)
        # constant-time compare: the key gates /reload and /stop, so a
        # plain != would make it timing-probeable
        if not hmac.compare_digest(supplied or "", self.server_key):
            raise HTTPError(401, "Invalid accessKey.")

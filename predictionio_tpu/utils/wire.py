"""Selector readiness-loop HTTP/1.1 front end — the 10k-qps wire path.

Three bench rounds (BENCH_r03-r05) showed the device finishing a serve
batch in ~2 ms while microbatched throughput plateaued near 500-900 qps:
the ceiling was thread-per-connection handoffs and per-request header
dict construction in the stdlib `ThreadingHTTPServer` stack, not the
accelerator. This module replaces that stack for the serve plane:

  - a reactor thread multiplexes persistent keep-alive connections
    through a `selectors` readiness loop (accept + recv + incremental
    framing only — never a handler); `ShardedWire` scales that to N
    reactors (`PIO_WIRE_REACTORS`, default min(4, cpus)), each with its
    own `SO_REUSEPORT` listener on the same port so the kernel shards
    the accept stream, its own selector, connection table, idle sweep,
    and slice of the worker pool. Where SO_REUSEPORT is unavailable,
    reactor 0 keeps the single listener and hands accepted sockets to
    its siblings round-robin (`SelectorWire.adopt`).
  - a small fixed worker pool runs handlers, so 10k idle keep-alive
    connections cost one selector registration each instead of one
    blocked thread each (the documented starvation failure of the
    earlier worker-pool experiment in utils/http.py);
  - framing is incremental and allocation-lean: the header block is
    carried as one bytes slice and scanned in place for the few headers
    a route needs (`RawRequest.header`), with NO dict-of-headers built
    until a legacy route asks for one; the body is sliced out of the
    recv buffer exactly once;
  - egress coalesces pipelined bursts: responses land on a
    per-connection queue and are flushed with one gathered
    `socket.sendmsg` (writev-style iovecs, no `b"".join` copies) —
    while more pipelined requests are pending the flush is deferred so
    a 64-deep burst leaves in one syscall, strictly in request order
    (`PIO_WIRE_SENDMSG=0` restores one send per response). When the
    micro-batcher completes a drain it calls `flush_hint()` and the
    reactors opportunistically push any deferred responses without
    waiting for the owning worker.
  - a length-prefixed binary query framing for SDK clients
    (`Content-Type: application/x-pio-bin`): `decode_bin_query` reads
    a msgpack-subset map straight into the fast path's (user, num)
    shape, skipping JSON entirely; responses reuse the same
    pre-serialized splice as the JSON route.

The wire knows nothing about routes, JSON, metrics, or tenancy: it
calls one `handler(RawRequest) -> (response_bytes, close?)` supplied by
`utils/http.HTTPServerBase`, which layers routing + middleware on top
and picks this wire or the legacy threaded one via `PIO_SERVE_WIRE`.

Also here: `HTTPConnectionPool`, the persistent-upstream client side of
the same story — the fleet router proxies over reused
`http.client.HTTPConnection`s instead of dialing per request.

Deliberately stdlib-only and obs-free: the observability middleware
lives one layer up, and malformed-framing rejects (400/413/431/501) are
answered from a static table before any route exists. Two narrow
openings keep it that way without blinding the flight recorder:

  - `set_trace_hooks(stamp_new, on_sent)` installs two opaque
    callbacks (from `obs/trace.py`, via HTTPServerBase.start): one
    allocates preallocated stamp slots onto `RawRequest.trace` as a
    request is framed (the wire stamps `.reactor` onto whatever comes
    back so traces attribute accept-shard skew), the other fires after
    the response bytes hit the socket. Both are None by default and
    the hot path checks one global before paying anything — tracing
    off costs two loads.
  - `SelectorWire.stats` counts raw wire activity (accepts, framed
    requests, bytes, pipeline high-water, gathered flushes, busy
    workers) as plain ints; the obs layer scrapes `stats_snapshot()`
    into `pio_wire_*` families on /metrics, one `reactor` label per
    shard. No metrics objects live here.
"""

from __future__ import annotations

import http.client
import os
import select
import selectors
import socket
import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Tuple

# Framing limits: a head that never completes under the cap is 431, a
# declared body over the cap is 413 (both close the connection — the
# stream position is unrecoverable).
MAX_HEADER_BYTES = 16 << 10
MAX_BODY_BYTES = int(os.environ.get("PIO_WIRE_MAX_BODY", str(8 << 20)))
# idle keep-alive connections are swept after this long (mirrors the
# threaded wire's 60 s handler timeout)
KEEPALIVE_IDLE_S = float(os.environ.get("PIO_WIRE_IDLE_S", "65"))
# framed-but-unserved requests a pipelining client may stack up before
# the reactor stops parsing its buffer (bounds memory per connection)
PIPELINE_MAX = 64
_RECV_CHUNK = 1 << 18
_SEND_TIMEOUT_S = 30.0
# gathered-egress cap: a deferred pipelined burst is flushed once this
# many responses are queued even if more requests are still pending
_FLUSH_MAX_IOV = 64
SENDMSG_ON = os.environ.get(
    "PIO_WIRE_SENDMSG", "1").strip().lower() not in ("0", "off", "false")

RawHandler = Callable[["RawRequest"], Tuple[bytes, bool]]

# Tracing hooks (obs/trace.py), installed by the obs layer via
# set_trace_hooks(). None = tracing off; the wire never imports obs.
_STAMP_NEW: Optional[Callable[[float], object]] = None
_ON_SENT: Optional[Callable[["RawRequest"], None]] = None


def set_trace_hooks(stamp_new: Optional[Callable[[float], object]],
                    on_sent: Optional[Callable[["RawRequest"], None]]
                    ) -> None:
    """Install (or clear, with Nones) the flight-recorder hooks:
    `stamp_new(t_first_read) -> trace-or-None` runs as a request is
    framed (a non-None result gets `.reactor` set to the framing
    reactor's index), `on_sent(raw)` after its response bytes are on
    the socket."""
    global _STAMP_NEW, _ON_SENT
    _STAMP_NEW = stamp_new
    _ON_SENT = on_sent


def reactor_count() -> int:
    """`PIO_WIRE_REACTORS`, default min(4, cpu count): reactors are
    readiness loops, more of them than cores only adds contention."""
    raw = os.environ.get("PIO_WIRE_REACTORS", "").strip()
    if raw:
        try:
            return max(1, int(raw))
        except ValueError:
            pass
    return max(1, min(4, os.cpu_count() or 1))


def _default_workers() -> int:
    # Workers BLOCK in the handler (device step, store reads), they are
    # not CPU-bound — size the pool to cover the admission layer's
    # concurrency, not the core count, or overload queues invisibly at
    # the wire instead of shedding 429/503 with Retry-After at the app
    # layer.
    return int(os.environ.get(
        "PIO_WIRE_WORKERS",
        str(max(16, min(64, 4 * (os.cpu_count() or 4))))))


def _bind_listener(server_address: Tuple[str, int],
                   reuse_port: bool = False) -> socket.socket:
    """Bind + listen a nonblocking listener. With reuse_port=True the
    SO_REUSEPORT option must exist and stick — any failure raises
    OSError so ShardedWire can fall back to fd handoff."""
    ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        if reuse_port:
            opt = getattr(socket, "SO_REUSEPORT", None)
            if opt is None:
                raise OSError("SO_REUSEPORT unavailable")
            ls.setsockopt(socket.SOL_SOCKET, opt, 1)
        ls.bind(server_address)
    except OSError:
        ls.close()
        raise
    ls.listen(1024)
    ls.setblocking(False)
    return ls


_REASONS = http.client.responses
_STATUS_LINES: Dict[int, bytes] = {
    code: (f"HTTP/1.1 {code} {reason}\r\n".encode("ascii"))
    for code, reason in _REASONS.items()
}


def _status_line(code: int) -> bytes:
    line = _STATUS_LINES.get(code)
    if line is None:
        line = b"HTTP/1.1 %d Status\r\n" % code
    return line


class RawRequest:
    """One framed request: request-line fields plus the UNPARSED header
    block. Hot routes scan `header()` for the few names they need; the
    legacy path materializes a dict via `header_items()`."""

    __slots__ = ("method", "target", "path", "query_string", "head",
                 "body", "keep_alive", "client", "trace", "_lhead")

    def __init__(self, method: str, target: str, head: bytes,
                 client: str = ""):
        self.method = method
        self.target = target
        path, _, qs = target.partition("?")
        self.path = path
        self.query_string = qs
        self.head = head          # header block, no request line, no CRLFCRLF
        self.body = b""
        self.keep_alive = True
        self.client = client
        self.trace = None         # PendingTrace stamp slots (obs/trace.py)
        self._lhead: Optional[bytes] = None

    def header(self, name: str) -> Optional[str]:
        """Case-insensitive single-header scan over the raw block — no
        dict, one lazy lowercase copy per request shared by every
        lookup."""
        lh = self._lhead
        if lh is None:
            lh = self._lhead = b"\r\n" + self.head.lower()
        key = b"\r\n" + name.lower().encode("ascii") + b":"
        i = lh.find(key)
        if i < 0:
            return None
        start = i + len(key)
        end = lh.find(b"\r\n", start)
        if end < 0:
            end = len(lh)
        return self.head[start - 2:end - 2].decode("latin-1").strip()

    def header_items(self) -> List[Tuple[str, str]]:
        """All headers as (name, value) pairs — the legacy-route path
        that builds a Request with a dict of headers."""
        out = []
        for line in self.head.split(b"\r\n"):
            name, sep, value = line.partition(b":")
            if sep:
                out.append((name.decode("latin-1").strip(),
                            value.decode("latin-1").strip()))
        return out


class WireError(Exception):
    """Malformed framing; answered from a static table and the
    connection closes (the stream position is unrecoverable)."""

    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status
        self.message = message


def build_response(status: int, content_type: str, body: bytes,
                   rid: str = "", extra: Optional[Dict[str, str]] = None,
                   keep_alive: bool = True,
                   head_only: bool = False) -> bytes:
    """Assemble one HTTP/1.1 response as a single bytes object."""
    parts = [_status_line(status),
             b"Content-Type: ", content_type.encode("latin-1"), b"\r\n",
             b"Content-Length: %d\r\n" % len(body)]
    if rid:
        parts.append(b"X-Request-ID: " + rid.encode("latin-1") + b"\r\n")
    if extra:
        for k, v in extra.items():
            parts.append(k.encode("latin-1") + b": "
                         + v.encode("latin-1") + b"\r\n")
    if not keep_alive:
        parts.append(b"Connection: close\r\n")
    parts.append(b"\r\n")
    if not head_only:
        parts.append(body)
    return b"".join(parts)


def _error_bytes(e: WireError) -> bytes:
    # static messages only — no user input is ever echoed into this
    # JSON, so the manual quoting cannot be broken by it
    body = b'{"message": "%s"}' % e.message.encode("ascii", "replace")
    return build_response(e.status, "application/json", body,
                          keep_alive=False)


def frame_request(buf: bytearray, client: str = ""
                  ) -> Tuple[Optional[RawRequest], int]:
    """Try to frame one request at the head of `buf`.

    Returns (request, bytes_consumed) when a full request (head + body)
    is present, (None, 0) when more bytes are needed. Raises WireError
    on malformed input. Pure function of the buffer — the caller owns
    deleting the consumed prefix."""
    he = buf.find(b"\r\n\r\n")
    if he < 0:
        if len(buf) > MAX_HEADER_BYTES:
            raise WireError(431, "Request header block too large")
        return None, 0
    if he > MAX_HEADER_BYTES:
        raise WireError(431, "Request header block too large")
    head = bytes(buf[:he])
    eol = head.find(b"\r\n")
    line = head if eol < 0 else head[:eol]
    fields = line.split(b" ")
    if len(fields) != 3:
        raise WireError(400, "Malformed request line")
    method_b, target_b, version_b = fields
    if not version_b.startswith(b"HTTP/1."):
        raise WireError(400, "Unsupported HTTP version")
    raw = RawRequest(method_b.decode("latin-1"),
                     target_b.decode("latin-1"),
                     b"" if eol < 0 else head[eol + 2:], client)
    if raw.header("Transfer-Encoding") is not None:
        raise WireError(501, "Transfer-Encoding is not supported")
    length = 0
    cl = raw.header("Content-Length")
    if cl is not None:
        try:
            length = int(cl)
        except ValueError:
            raise WireError(400, "Invalid Content-Length header")
        if length < 0:
            raise WireError(400, "Invalid Content-Length header")
        if length > MAX_BODY_BYTES:
            raise WireError(413, "Request body over size limit")
    total = he + 4 + length
    if len(buf) < total:
        return None, 0
    if length:
        raw.body = bytes(memoryview(buf)[he + 4:total])
    conn_tok = raw.header("Connection")
    if version_b == b"HTTP/1.0":
        raw.keep_alive = (conn_tok is not None
                          and conn_tok.lower() == "keep-alive")
    else:
        raw.keep_alive = (conn_tok is None
                          or conn_tok.lower() != "close")
    return raw, total


# -- binary query framing ----------------------------------------------------
# The SDK fast lane: `Content-Type: application/x-pio-bin` carries the
# dominant serve query {"user": <str>, "num": <int>} as a msgpack-subset
# map, decoded by direct byte indexing straight into the same (user,
# num) pair the JSON fast-path regex produces. Strict by construction:
# exactly two fixstr keys in fixed order, nothing trailing, so the
# binary route accepts a SUBSET of what the JSON route serves
# (fuzz-gated accept containment in tests/test_wire.py). Responses are
# spliced from the same pre-serialized JSON fragments — only the
# request side changes representation.

BIN_CONTENT_TYPE = "application/x-pio-bin"
_BIN_PREFIX = b"\x82\xa4user"   # fixmap(2) + fixstr(4) "user"
_BIN_NUM_KEY = b"\xa3num"       # fixstr(3) "num"
_BIN_NUM_MAX = 999_999_999      # parity with the JSON fast-path regex


def encode_bin_query(user: str, num: int) -> bytes:
    """Encode the dominant serve query as the msgpack-subset frame
    `decode_bin_query` accepts (client/SDK side; the server only ever
    decodes). fixstr/str8/str16 user id, fixint/uint16/int32 num."""
    if num > _BIN_NUM_MAX or num < -_BIN_NUM_MAX:
        raise ValueError("num out of range for the binary query frame")
    ub = user.encode("utf-8")
    ul = len(ub)
    if ul <= 31:
        uhead = bytes((0xa0 | ul,))
    elif ul <= 0xff:
        uhead = b"\xd9" + bytes((ul,))
    elif ul <= 0xffff:
        uhead = b"\xda" + ul.to_bytes(2, "big")
    else:
        raise ValueError("user id too long for the binary query frame")
    if 0 <= num <= 0x7f:
        nb = bytes((num,))
    elif -32 <= num < 0:
        nb = bytes((num & 0xff,))
    elif 0 <= num <= 0xffff:
        nb = b"\xcd" + num.to_bytes(2, "big")
    else:
        nb = b"\xd2" + num.to_bytes(4, "big", signed=True)
    return b"".join((_BIN_PREFIX, uhead, ub, _BIN_NUM_KEY, nb))


def decode_bin_query(body: bytes) -> Optional[Tuple[str, int]]:
    """Decode one binary query frame to (user, num), or None when the
    body is not the exact shape `encode_bin_query` emits. Rejects
    trailing bytes, out-of-range nums, and invalid UTF-8 so every
    accepted frame maps onto a query the JSON route would also serve.

    The dominant shape (fixstr user <= 31 bytes, one-byte num) is
    decoded inline with the minimum of branches — it is ~95% of SDK
    traffic and the whole point of the frame; everything else takes
    `_decode_bin_slow`."""
    lb = len(body)
    if lb < 12 or body[:6] != _BIN_PREFIX:
        return None
    c = body[6]
    if 0xa0 <= c <= 0xbf:
        e = 7 + (c & 0x1f)
        p = e + 4
        if lb == p + 1 and body[e:p] == _BIN_NUM_KEY:
            c2 = body[p]
            if c2 <= 0x7f:
                try:
                    return body[7:e].decode("utf-8"), c2
                except UnicodeDecodeError:
                    return None
            if c2 >= 0xe0:
                try:
                    return body[7:e].decode("utf-8"), c2 - 256
                except UnicodeDecodeError:
                    return None
            return None      # one trailing byte that is no fixint
    return _decode_bin_slow(body, lb, c)


def _decode_bin_slow(body: bytes, lb: int, c: int
                     ) -> Optional[Tuple[str, int]]:
    # the off-dominant encodings: str8/str16 user ids, uint16/int32
    # nums, and every reject path the fast lane skipped
    if 0xa0 <= c <= 0xbf:
        s = 7
        e = s + (c & 0x1f)
    elif c == 0xd9:
        s = 8
        e = s + body[7]
    elif c == 0xda:
        s = 9
        e = s + ((body[7] << 8) | body[8])
    else:
        return None
    p = e + 4
    if lb <= p or body[e:p] != _BIN_NUM_KEY:
        return None
    c2 = body[p]
    if c2 <= 0x7f:
        num = c2
        q = p + 1
    elif c2 >= 0xe0:
        num = c2 - 256
        q = p + 1
    elif c2 == 0xcd:
        q = p + 3
        if lb < q:
            return None
        num = (body[p + 1] << 8) | body[p + 2]
    elif c2 == 0xd2:
        q = p + 5
        if lb < q:
            return None
        num = int.from_bytes(body[p + 1:q], "big", signed=True)
    else:
        return None
    if q != lb or num > _BIN_NUM_MAX or num < -_BIN_NUM_MAX:
        return None
    try:
        user = body[s:e].decode("utf-8")
    except UnicodeDecodeError:
        return None
    return user, num


class _Conn:
    __slots__ = ("sock", "fd", "client", "buf", "pending", "busy",
                 "closing", "last_active", "lock", "t_read", "outq",
                 "wlock")

    def __init__(self, sock: socket.socket, client: str):
        self.sock = sock
        self.fd = sock.fileno()
        self.client = client
        self.buf = bytearray()
        # entries: ("req", RawRequest) | ("err", response_bytes)
        self.pending: Deque[tuple] = deque()
        self.busy = False          # a worker currently owns this conn
        self.closing = False
        self.last_active = time.monotonic()
        self.lock = threading.Lock()
        self.t_read = 0.0          # first-read stamp for the next request
        # egress: (response bytes-or-memoryview, RawRequest-or-None),
        # appended under `lock`, drained under `wlock` (egress order)
        self.outq: Deque[tuple] = deque()
        self.wlock = threading.Lock()


class WireStats:
    """Raw wire activity counters: plain ints, no metrics objects, so
    the wire stays obs-free. Reactor-owned fields (accepted, requests,
    bytes_in, pipeline_hwm, errors) are written by the reactor thread
    only; `lock` guards the worker-side fields. `flushes` counts
    gathered egress syscalls — responses/flushes is the writev
    coalescing ratio the bench gates on."""

    __slots__ = ("accepted", "requests", "bytes_in", "pipeline_hwm",
                 "errors", "lock", "bytes_out", "responses",
                 "send_failures", "busy_workers", "flushes")

    def __init__(self):
        self.accepted = 0
        self.requests = 0
        self.bytes_in = 0
        self.pipeline_hwm = 0
        self.errors: Dict[int, int] = {}   # WireError status -> count
        self.lock = threading.Lock()
        self.bytes_out = 0
        self.responses = 0
        self.send_failures = 0
        self.busy_workers = 0
        self.flushes = 0


class SelectorWire:
    """One selector reactor. API mirrors ThreadingHTTPServer just
    enough (`server_address`, `serve_forever`, `shutdown`,
    `server_close`) that HTTPServerBase treats both wires uniformly.

    Sharding hooks (used by ShardedWire, inert standalone): `index`
    names the reactor in stats/traces; `listener` adopts a pre-bound
    socket (SO_REUSEPORT shard) instead of binding here; a reactor
    built with neither address nor listener accepts nothing and is fed
    via `adopt()` (the fd-handoff fallback)."""

    def __init__(self, server_address: Optional[Tuple[str, int]],
                 handler: RawHandler, workers: int = 0, *,
                 index: int = 0,
                 listener: Optional[socket.socket] = None,
                 sendmsg: Optional[bool] = None):
        self._handler = handler
        self._stop = False
        self._done = threading.Event()
        self._lifecycle = threading.Lock()
        self._conns: Dict[int, _Conn] = {}
        self._to_close: Deque[_Conn] = deque()
        self._adoptq: Deque[Tuple[socket.socket, str]] = deque()
        self._flush_req = False
        self._dispatch: Optional[Callable[[socket.socket, str], bool]] \
            = None
        self.index = index
        self._sendmsg_on = SENDMSG_ON if sendmsg is None else bool(sendmsg)
        self.stats = WireStats()
        self.beat = None                # watchdog stamp (serve_forever)
        if workers <= 0:
            workers = _default_workers()
        self._n_workers = max(1, workers)
        import queue as _queue
        self._workq: "_queue.Queue" = _queue.Queue()
        self._workers: List[threading.Thread] = []
        # bind in the constructor so the caller's EADDRINUSE retry loop
        # wraps construction, exactly as with ThreadingHTTPServer
        if listener is None and server_address is not None:
            listener = _bind_listener(server_address)
        self._listener = listener
        self.server_address = (listener.getsockname()
                               if listener is not None else ("", 0))
        # wake pipe: shutdown(), adopt() and worker close-requests
        # nudge select()
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._sel = selectors.DefaultSelector()

    # -- reactor -------------------------------------------------------------
    def serve_forever(self) -> None:
        for i in range(self._n_workers):
            t = threading.Thread(target=self._worker_loop, daemon=True,
                                 name=f"wire-{self.index}-worker-{i}")
            t.start()
            self._workers.append(t)
        sel = self._sel
        if self._listener is not None:
            sel.register(self._listener, selectors.EVENT_READ, "accept")
        sel.register(self._wake_r, selectors.EVENT_READ, "wake")
        last_sweep = time.monotonic()
        # watchdog liveness: the 1 s select timeout bounds the stamp
        # interval even when idle. beat() is ONE GIL-atomic store —
        # the only watchdog call allowed on the wire hot path. A wedged
        # reactor cannot be restarted (it owns live sockets), so a
        # stall degrades it for fleet ejection instead.
        from predictionio_tpu.resilience.watchdog import watchdog
        beat = self.beat = watchdog().register("reactor", budget_s=10.0)
        beat.attach()
        try:
            while not self._stop:
                beat.beat()
                for key, _ in sel.select(1.0):
                    data = key.data
                    if data == "accept":
                        self._accept()
                    elif data == "wake":
                        try:
                            while self._wake_r.recv(4096):
                                pass
                        except (BlockingIOError, OSError):
                            pass
                    else:
                        self._on_readable(data)
                if self._adoptq:
                    self._drain_adopted()
                if self._flush_req:
                    self._flush_req = False
                    self._flush_pass()
                self._drain_close_requests()
                now = time.monotonic()
                if now - last_sweep >= 5.0:
                    last_sweep = now
                    self._sweep_idle(now)
        finally:
            beat.close()
            self._done.set()

    def _accept(self) -> None:
        while True:
            try:
                sock, addr = self._listener.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            sock.setblocking(False)
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            client = addr[0] if addr else ""
            d = self._dispatch
            if d is not None and d(sock, client):
                continue               # handed to a sibling reactor
            self._register_conn(sock, client)

    def adopt(self, sock: socket.socket, client: str) -> None:
        """Hand an already-accepted socket to this reactor — the
        round-robin fallback path when SO_REUSEPORT cannot shard the
        accept stream at the kernel."""
        self._adoptq.append((sock, client))
        self._wake()

    def _drain_adopted(self) -> None:
        while self._adoptq:
            sock, client = self._adoptq.popleft()
            self._register_conn(sock, client)

    def _register_conn(self, sock: socket.socket, client: str) -> None:
        conn = _Conn(sock, client)
        self._conns[conn.fd] = conn
        self.stats.accepted += 1
        self._sel.register(sock, selectors.EVENT_READ, conn)

    def _on_readable(self, conn: _Conn) -> None:
        eof = False
        if not conn.buf and _STAMP_NEW is not None:
            # first bytes of the next request on this connection
            conn.t_read = time.perf_counter()
        n_in = 0
        try:
            while True:
                data = conn.sock.recv(_RECV_CHUNK)
                if not data:
                    eof = True
                    break
                conn.buf.extend(data)
                n_in += len(data)
                if len(data) < _RECV_CHUNK:
                    break
        except (BlockingIOError, InterruptedError):
            pass
        except OSError:
            eof = True
        self.stats.bytes_in += n_in
        conn.last_active = time.monotonic()
        if conn.buf:
            self._pump(conn)
        if eof:
            with conn.lock:
                busy_or_pending = conn.busy or bool(conn.pending)
                conn.closing = True
            self._unregister(conn)
            if not busy_or_pending:
                self._destroy(conn)

    def _pump(self, conn: _Conn) -> None:
        """Frame every complete request in the buffer (up to the
        pipeline cap) and hand the connection to a worker."""
        added = False
        st = self.stats
        while len(conn.pending) < PIPELINE_MAX:
            try:
                raw, consumed = frame_request(conn.buf, conn.client)
            except WireError as e:
                st.errors[e.status] = st.errors.get(e.status, 0) + 1
                with conn.lock:
                    conn.pending.append(("err", _error_bytes(e)))
                    conn.closing = True
                self._unregister(conn)
                added = True
                break
            if raw is None:
                break
            del conn.buf[:consumed]
            sn = _STAMP_NEW
            if sn is not None:
                raw.trace = sn(conn.t_read)
                if raw.trace is not None:
                    raw.trace.reactor = self.index
            st.requests += 1
            with conn.lock:
                conn.pending.append(("req", raw))
                depth = len(conn.pending)
            if depth > st.pipeline_hwm:
                st.pipeline_hwm = depth
            added = True
        if added:
            with conn.lock:
                if not conn.busy and conn.pending:
                    conn.busy = True
                    self._workq.put(conn)

    def _sweep_idle(self, now: float) -> None:
        for conn in list(self._conns.values()):
            with conn.lock:
                idle = (not conn.busy and not conn.pending
                        and not conn.buf and not conn.outq
                        and now - conn.last_active > KEEPALIVE_IDLE_S)
            if idle:
                self._unregister(conn)
                self._destroy(conn)

    def _drain_close_requests(self) -> None:
        while self._to_close:
            conn = self._to_close.popleft()
            self._unregister(conn)
            self._destroy(conn)

    def _unregister(self, conn: _Conn) -> None:
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            pass

    def _destroy(self, conn: _Conn) -> None:
        self._conns.pop(conn.fd, None)
        try:
            conn.sock.close()
        except OSError:
            pass

    def _wake(self) -> None:
        try:
            self._wake_w.send(b"x")
        except OSError:
            pass

    def flush_hint(self) -> None:
        """Cross-wakeup from the micro-batcher: a batch just drained,
        so deferred pipelined responses are likely complete — nudge the
        reactor to push them without waiting for the owning worker."""
        self._flush_req = True
        self._wake()

    def _flush_pass(self) -> None:
        for conn in list(self._conns.values()):
            if conn.outq:
                self._flush_out(conn, wait=False)

    # -- workers -------------------------------------------------------------
    def _worker_loop(self) -> None:
        st = self.stats
        while True:
            conn = self._workq.get()
            if conn is None:
                return
            with st.lock:
                st.busy_workers += 1
            try:
                self._service(conn)
            finally:
                with st.lock:
                    st.busy_workers -= 1

    def _service(self, conn: _Conn) -> None:
        """Serve this connection's framed requests in order; the busy
        flag guarantees one worker per connection, so pipelined
        responses cannot interleave. Responses land on conn.outq; the
        flush is deferred while more pipelined requests are pending so
        a whole burst leaves in one gathered sendmsg."""
        while True:
            with conn.lock:
                if not conn.pending:
                    conn.busy = False
                    close_now = conn.closing
                    break
                kind, item = conn.pending.popleft()
            if kind == "err":
                with conn.lock:
                    conn.outq.append((item, None))
                self._flush_out(conn)
                self._request_close(conn)
                return
            try:
                data, close = self._handler(item)
            except Exception:
                data, close = build_response(
                    500, "application/json",
                    b'{"message": "internal wire error"}',
                    keep_alive=False), True
            with conn.lock:
                conn.outq.append((data, item))
                defer = (self._sendmsg_on and bool(conn.pending)
                         and len(conn.outq) < _FLUSH_MAX_IOV
                         and not close and item.keep_alive)
            if not defer and not self._flush_out(conn):
                self._request_close(conn)
                return
            if close or not item.keep_alive:
                self._request_close(conn)
                return
            conn.last_active = time.monotonic()
        if close_now:
            self._flush_out(conn)
            self._request_close(conn)

    def _flush_out(self, conn: _Conn, wait: bool = True) -> bool:
        """Drain conn.outq to the socket: one gathered `sendmsg` per
        queued batch (writev — no join copies), one plain send per
        response when PIO_WIRE_SENDMSG is off. wait=False is the
        reactor's opportunistic path: it never blocks, requeueing any
        unsent tail in order for the owning worker."""
        if wait:
            conn.wlock.acquire()
        elif not conn.wlock.acquire(blocking=False):
            return True                # a worker owns egress right now
        try:
            return self._flush_locked(conn, wait)
        finally:
            conn.wlock.release()

    def _flush_locked(self, conn: _Conn, wait: bool) -> bool:
        st = self.stats
        sock = conn.sock
        end = time.monotonic() + _SEND_TIMEOUT_S
        while True:
            with conn.lock:
                if not conn.outq:
                    return True
                if self._sendmsg_on:
                    items = list(conn.outq)
                    conn.outq.clear()
                else:
                    items = [conn.outq.popleft()]
            bufs = [memoryview(d) for d, _ in items]
            idx = 0
            while bufs:
                try:
                    if self._sendmsg_on:
                        n = sock.sendmsg(bufs)
                    else:
                        n = sock.send(bufs[0])
                except (BlockingIOError, InterruptedError):
                    if not wait:
                        # requeue the unsent tail at the head, in order
                        with conn.lock:
                            conn.outq.extendleft(
                                (bufs[j], items[idx + j][1])
                                for j in range(len(bufs) - 1, -1, -1))
                        return True
                    remaining = end - time.monotonic()
                    if remaining <= 0:
                        return self._flush_fail()
                    try:
                        select.select([], [sock], [],
                                      min(remaining, 1.0))
                    except (OSError, ValueError):
                        return self._flush_fail()
                    continue
                except OSError:
                    return self._flush_fail()
                with st.lock:
                    st.flushes += 1
                    st.bytes_out += n
                while n:
                    head = bufs[0]
                    if n >= len(head):
                        n -= len(head)
                        bufs.pop(0)
                        self._mark_sent(items[idx])
                        idx += 1
                    else:
                        bufs[0] = head[n:]
                        break

    def _flush_fail(self) -> bool:
        with self.stats.lock:
            self.stats.send_failures += 1
        return False

    def _mark_sent(self, item: tuple) -> None:
        raw = item[1]
        with self.stats.lock:
            self.stats.responses += 1
        cb = _ON_SENT
        if cb is not None and raw is not None and raw.trace is not None:
            try:
                cb(raw)
            except Exception:
                pass               # tracing must never kill a worker

    def _request_close(self, conn: _Conn) -> None:
        """Workers never touch the selector: shut the socket down and
        let the reactor unregister + close it."""
        with conn.lock:
            conn.closing = True
        try:
            conn.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._to_close.append(conn)
        self._wake()

    def stats_snapshot(self) -> Dict[str, object]:
        """Point-in-time wire counters for the obs layer's pio_wire_*
        families. Reactor-owned fields are read without the lock —
        single int reads are atomic enough for monitoring."""
        st = self.stats
        with st.lock:
            out: Dict[str, object] = {
                "bytes_out": st.bytes_out,
                "responses": st.responses,
                "send_failures": st.send_failures,
                "busy_workers": st.busy_workers,
                "flushes": st.flushes,
            }
        out["reactor"] = self.index
        out["accepted"] = st.accepted
        out["requests"] = st.requests
        out["bytes_in"] = st.bytes_in
        out["pipeline_hwm"] = st.pipeline_hwm
        out["errors"] = dict(st.errors)
        out["open_conns"] = len(self._conns)
        out["queue_depth"] = self._workq.qsize()
        out["workers"] = self._n_workers
        busy = out["busy_workers"]
        out["utilization"] = (float(busy) / self._n_workers
                              if self._n_workers else 0.0)
        return out

    # -- lifecycle -----------------------------------------------------------
    def shutdown(self) -> None:
        self._stop = True
        self._wake()
        self._done.wait(timeout=5.0)

    def server_close(self) -> None:
        with self._lifecycle:
            workers, self._workers = self._workers, []
        for _ in workers:
            self._workq.put(None)
        for t in workers:
            t.join(timeout=2.0)
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        while self._adoptq:
            sock, _ = self._adoptq.popleft()
            try:
                sock.close()
            except OSError:
                pass
        for conn in list(self._conns.values()):
            self._unregister(conn)
            self._destroy(conn)
        try:
            self._sel.close()
        except Exception:
            pass
        for s in (self._wake_r, self._wake_w):
            try:
                s.close()
            except OSError:
                pass


class ShardedWire:
    """N SelectorWire reactors behind one serve port.

    With SO_REUSEPORT every reactor owns its own listener bound to the
    same (host, port) and the KERNEL shards the accept stream — no
    user-space handoff, no shared accept lock. Where SO_REUSEPORT is
    unavailable (or refused at bind), reactor 0 keeps the only
    listener and deals accepted sockets to its siblings round-robin
    via `SelectorWire.adopt`. Each reactor runs its own selector,
    connection table, idle sweep, and worker-pool slice; lifecycle and
    stats mirror SelectorWire so HTTPServerBase treats every wire the
    same. `stats_snapshot()` returns the aggregate plus a
    `"reactors"` list of per-shard snapshots."""

    def __init__(self, server_address: Tuple[str, int],
                 handler: RawHandler, reactors: int = 0,
                 workers: int = 0):
        n = max(1, reactors if reactors > 0 else reactor_count())
        if workers <= 0:
            workers = _default_workers()
        per = max(1, -(-workers // n))     # ceil-divided pool slice
        listeners: List[Optional[socket.socket]] = []
        self.reuse_port = False
        if n > 1:
            try:
                first = _bind_listener(server_address, reuse_port=True)
                listeners.append(first)
                host = server_address[0]
                port = first.getsockname()[1]
                for _ in range(n - 1):
                    listeners.append(
                        _bind_listener((host, port), reuse_port=True))
                self.reuse_port = True
            except OSError:
                for ls in listeners:
                    if ls is not None:
                        try:
                            ls.close()
                        except OSError:
                            pass
                listeners = []
        if not listeners:
            listeners = [_bind_listener(server_address)]
            listeners.extend([None] * (n - 1))
        self.reactors: List[SelectorWire] = [
            SelectorWire(None, handler, workers=per, index=i,
                         listener=listeners[i])
            for i in range(n)
        ]
        self.server_address = self.reactors[0].server_address
        for r in self.reactors[1:]:
            if r._listener is None:
                r.server_address = self.server_address
        self._rr = 0
        if not self.reuse_port and n > 1:
            self.reactors[0]._dispatch = self._dispatch_round_robin
        self._threads: List[threading.Thread] = []

    def _dispatch_round_robin(self, sock: socket.socket,
                              client: str) -> bool:
        i = self._rr = (self._rr + 1) % len(self.reactors)
        if i == 0:
            return False               # reactor 0 keeps its share
        self.reactors[i].adopt(sock, client)
        return True

    def serve_forever(self) -> None:
        for r in self.reactors[1:]:
            t = threading.Thread(target=r.serve_forever, daemon=True,
                                 name=f"wire-reactor-{r.index}")
            t.start()
            self._threads.append(t)
        self.reactors[0].serve_forever()

    def flush_hint(self) -> None:
        for r in self.reactors:
            r.flush_hint()

    def stats_snapshot(self) -> Dict[str, object]:
        """Aggregate counters plus per-reactor snapshots under
        "reactors" — the obs layer emits one `reactor` label per
        entry, the dashboard renders accept-shard balance from it."""
        per = [r.stats_snapshot() for r in self.reactors]
        agg: Dict[str, object] = {
            "reactor": -1,
            "reuse_port": self.reuse_port,
            "reactors": per,
        }
        for k in ("accepted", "requests", "bytes_in", "bytes_out",
                  "responses", "flushes", "send_failures",
                  "busy_workers", "open_conns", "queue_depth",
                  "workers"):
            agg[k] = sum(s[k] for s in per)
        agg["pipeline_hwm"] = max(s["pipeline_hwm"] for s in per)
        agg["utilization"] = (float(agg["busy_workers"]) / agg["workers"]
                              if agg["workers"] else 0.0)
        errors: Dict[int, int] = {}
        for s in per:
            for code, cnt in s["errors"].items():
                errors[code] = errors.get(code, 0) + cnt
        agg["errors"] = errors
        return agg

    # -- lifecycle -----------------------------------------------------------
    def shutdown(self) -> None:
        for r in self.reactors:
            r.shutdown()
        for t in self._threads:
            t.join(timeout=5.0)

    def server_close(self) -> None:
        for r in self.reactors:
            r.server_close()


class HTTPConnectionPool:
    """Persistent upstream connections for the fleet proxy.

    The router used to dial a fresh TCP connection per proxied request
    (urllib): at wire-path throughput the handshake dominates. This
    pool checks out a kept-alive `http.client.HTTPConnection` per
    (host, port), retries exactly once on a stale reuse (the upstream
    closed its keep-alive between our requests), and returns transport
    failures as OSError so the caller's retry-next-replica loop and
    ejection bookkeeping stay unchanged.

    Bodies are opaque bytes and Content-Type is forwarded verbatim, so
    binary-framed queries (`application/x-pio-bin`) proxy upstream
    unchanged — the router never re-encodes."""

    def __init__(self, max_idle_per_host: int = 4):
        self.max_idle = max_idle_per_host
        self._lock = threading.Lock()
        self._idle: Dict[Tuple[str, int], Deque] = {}

    def _checkout(self, host: str, port: int):
        with self._lock:
            q = self._idle.get((host, port))
            if q:
                return q.popleft(), True
        return None, False

    def _checkin(self, host: str, port: int, conn) -> None:
        with self._lock:
            q = self._idle.setdefault((host, port), deque())
            if len(q) < self.max_idle:
                q.append(conn)
                return
        conn.close()

    def request(self, host: str, port: int, method: str, path: str,
                body: Optional[bytes], headers: Dict[str, str],
                timeout: float) -> Tuple[int, Dict[str, str], bytes]:
        """One proxied request over a pooled connection. Returns
        (status, response headers, body). Transport-level failures
        raise OSError after at most one stale-connection retry."""
        attempts = 0
        while True:
            conn, reused = self._checkout(host, port)
            if conn is None:
                conn = http.client.HTTPConnection(host, port,
                                                  timeout=timeout)
            elif conn.sock is not None:
                conn.sock.settimeout(timeout)
            try:
                conn.request(method, path, body=body, headers=headers)
                resp = conn.getresponse()
                data = resp.read()
            except (http.client.HTTPException, OSError) as e:
                conn.close()
                # a reused connection the upstream already closed is
                # expected with keep-alive; retry ONCE on a fresh dial
                if reused and attempts == 0:
                    attempts += 1
                    continue
                if isinstance(e, OSError):
                    raise
                raise OSError(f"{type(e).__name__}: {e}") from e
            if resp.will_close:
                conn.close()
            else:
                self._checkin(host, port, conn)
            return resp.status, dict(resp.headers.items()), data

    def close(self) -> None:
        with self._lock:
            pools, self._idle = self._idle, {}
        for q in pools.values():
            for conn in q:
                try:
                    conn.close()
                except Exception:
                    pass

"""Selector readiness-loop HTTP/1.1 front end — the 10k-qps wire path.

Three bench rounds (BENCH_r03-r05) showed the device finishing a serve
batch in ~2 ms while microbatched throughput plateaued near 500-900 qps:
the ceiling was thread-per-connection handoffs and per-request header
dict construction in the stdlib `ThreadingHTTPServer` stack, not the
accelerator. This module replaces that stack for the serve plane:

  - ONE reactor thread multiplexes every persistent keep-alive
    connection through a `selectors` readiness loop (accept + recv +
    incremental framing only — never a handler);
  - a small fixed worker pool runs handlers, so 10k idle keep-alive
    connections cost one selector registration each instead of one
    blocked thread each (the documented starvation failure of the
    earlier worker-pool experiment in utils/http.py);
  - framing is incremental and allocation-lean: the header block is
    carried as one bytes slice and scanned in place for the few headers
    a route needs (`RawRequest.header`), with NO dict-of-headers built
    until a legacy route asks for one; the body is sliced out of the
    recv buffer exactly once;
  - responses are assembled as a single bytes join from pre-encoded
    status lines and written with one send loop.

The wire knows nothing about routes, JSON, metrics, or tenancy: it
calls one `handler(RawRequest) -> (response_bytes, close?)` supplied by
`utils/http.HTTPServerBase`, which layers routing + middleware on top
and picks this wire or the legacy threaded one via `PIO_SERVE_WIRE`.

Also here: `HTTPConnectionPool`, the persistent-upstream client side of
the same story — the fleet router proxies over reused
`http.client.HTTPConnection`s instead of dialing per request.

Deliberately stdlib-only and obs-free: the observability middleware
lives one layer up, and malformed-framing rejects (400/413/431/501) are
answered from a static table before any route exists. Two narrow
openings keep it that way without blinding the flight recorder:

  - `set_trace_hooks(stamp_new, on_sent)` installs two opaque
    callbacks (from `obs/trace.py`, via HTTPServerBase.start): one
    allocates preallocated stamp slots onto `RawRequest.trace` as a
    request is framed, the other fires after the response bytes hit
    the socket. Both are None by default and the hot path checks one
    global before paying anything — tracing off costs two loads.
  - `SelectorWire.stats` counts raw wire activity (accepts, framed
    requests, bytes, pipeline high-water, busy workers) as plain ints;
    the obs layer scrapes `stats_snapshot()` into `pio_wire_*`
    families on /metrics. No metrics objects live here.
"""

from __future__ import annotations

import http.client
import os
import select
import selectors
import socket
import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Tuple

# Framing limits: a head that never completes under the cap is 431, a
# declared body over the cap is 413 (both close the connection — the
# stream position is unrecoverable).
MAX_HEADER_BYTES = 16 << 10
MAX_BODY_BYTES = int(os.environ.get("PIO_WIRE_MAX_BODY", str(8 << 20)))
# idle keep-alive connections are swept after this long (mirrors the
# threaded wire's 60 s handler timeout)
KEEPALIVE_IDLE_S = float(os.environ.get("PIO_WIRE_IDLE_S", "65"))
# framed-but-unserved requests a pipelining client may stack up before
# the reactor stops parsing its buffer (bounds memory per connection)
PIPELINE_MAX = 64
_RECV_CHUNK = 1 << 18
_SEND_TIMEOUT_S = 30.0

RawHandler = Callable[["RawRequest"], Tuple[bytes, bool]]

# Tracing hooks (obs/trace.py), installed by the obs layer via
# set_trace_hooks(). None = tracing off; the wire never imports obs.
_STAMP_NEW: Optional[Callable[[float], object]] = None
_ON_SENT: Optional[Callable[["RawRequest"], None]] = None


def set_trace_hooks(stamp_new: Optional[Callable[[float], object]],
                    on_sent: Optional[Callable[["RawRequest"], None]]
                    ) -> None:
    """Install (or clear, with Nones) the flight-recorder hooks:
    `stamp_new(t_first_read) -> trace-or-None` runs as a request is
    framed, `on_sent(raw)` after its response bytes are on the
    socket."""
    global _STAMP_NEW, _ON_SENT
    _STAMP_NEW = stamp_new
    _ON_SENT = on_sent

_REASONS = http.client.responses
_STATUS_LINES: Dict[int, bytes] = {
    code: (f"HTTP/1.1 {code} {reason}\r\n".encode("ascii"))
    for code, reason in _REASONS.items()
}


def _status_line(code: int) -> bytes:
    line = _STATUS_LINES.get(code)
    if line is None:
        line = b"HTTP/1.1 %d Status\r\n" % code
    return line


class RawRequest:
    """One framed request: request-line fields plus the UNPARSED header
    block. Hot routes scan `header()` for the few names they need; the
    legacy path materializes a dict via `header_items()`."""

    __slots__ = ("method", "target", "path", "query_string", "head",
                 "body", "keep_alive", "client", "trace", "_lhead")

    def __init__(self, method: str, target: str, head: bytes,
                 client: str = ""):
        self.method = method
        self.target = target
        path, _, qs = target.partition("?")
        self.path = path
        self.query_string = qs
        self.head = head          # header block, no request line, no CRLFCRLF
        self.body = b""
        self.keep_alive = True
        self.client = client
        self.trace = None         # PendingTrace stamp slots (obs/trace.py)
        self._lhead: Optional[bytes] = None

    def header(self, name: str) -> Optional[str]:
        """Case-insensitive single-header scan over the raw block — no
        dict, one lazy lowercase copy per request shared by every
        lookup."""
        lh = self._lhead
        if lh is None:
            lh = self._lhead = b"\r\n" + self.head.lower()
        key = b"\r\n" + name.lower().encode("ascii") + b":"
        i = lh.find(key)
        if i < 0:
            return None
        start = i + len(key)
        end = lh.find(b"\r\n", start)
        if end < 0:
            end = len(lh)
        return self.head[start - 2:end - 2].decode("latin-1").strip()

    def header_items(self) -> List[Tuple[str, str]]:
        """All headers as (name, value) pairs — the legacy-route path
        that builds a Request with a dict of headers."""
        out = []
        for line in self.head.split(b"\r\n"):
            name, sep, value = line.partition(b":")
            if sep:
                out.append((name.decode("latin-1").strip(),
                            value.decode("latin-1").strip()))
        return out


class WireError(Exception):
    """Malformed framing; answered from a static table and the
    connection closes (the stream position is unrecoverable)."""

    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status
        self.message = message


def build_response(status: int, content_type: str, body: bytes,
                   rid: str = "", extra: Optional[Dict[str, str]] = None,
                   keep_alive: bool = True,
                   head_only: bool = False) -> bytes:
    """Assemble one HTTP/1.1 response as a single bytes object."""
    parts = [_status_line(status),
             b"Content-Type: ", content_type.encode("latin-1"), b"\r\n",
             b"Content-Length: %d\r\n" % len(body)]
    if rid:
        parts.append(b"X-Request-ID: " + rid.encode("latin-1") + b"\r\n")
    if extra:
        for k, v in extra.items():
            parts.append(k.encode("latin-1") + b": "
                         + v.encode("latin-1") + b"\r\n")
    if not keep_alive:
        parts.append(b"Connection: close\r\n")
    parts.append(b"\r\n")
    if not head_only:
        parts.append(body)
    return b"".join(parts)


def _error_bytes(e: WireError) -> bytes:
    # static messages only — no user input is ever echoed into this
    # JSON, so the manual quoting cannot be broken by it
    body = b'{"message": "%s"}' % e.message.encode("ascii", "replace")
    return build_response(e.status, "application/json", body,
                          keep_alive=False)


def frame_request(buf: bytearray, client: str = ""
                  ) -> Tuple[Optional[RawRequest], int]:
    """Try to frame one request at the head of `buf`.

    Returns (request, bytes_consumed) when a full request (head + body)
    is present, (None, 0) when more bytes are needed. Raises WireError
    on malformed input. Pure function of the buffer — the caller owns
    deleting the consumed prefix."""
    he = buf.find(b"\r\n\r\n")
    if he < 0:
        if len(buf) > MAX_HEADER_BYTES:
            raise WireError(431, "Request header block too large")
        return None, 0
    if he > MAX_HEADER_BYTES:
        raise WireError(431, "Request header block too large")
    head = bytes(buf[:he])
    eol = head.find(b"\r\n")
    line = head if eol < 0 else head[:eol]
    fields = line.split(b" ")
    if len(fields) != 3:
        raise WireError(400, "Malformed request line")
    method_b, target_b, version_b = fields
    if not version_b.startswith(b"HTTP/1."):
        raise WireError(400, "Unsupported HTTP version")
    raw = RawRequest(method_b.decode("latin-1"),
                     target_b.decode("latin-1"),
                     b"" if eol < 0 else head[eol + 2:], client)
    if raw.header("Transfer-Encoding") is not None:
        raise WireError(501, "Transfer-Encoding is not supported")
    length = 0
    cl = raw.header("Content-Length")
    if cl is not None:
        try:
            length = int(cl)
        except ValueError:
            raise WireError(400, "Invalid Content-Length header")
        if length < 0:
            raise WireError(400, "Invalid Content-Length header")
        if length > MAX_BODY_BYTES:
            raise WireError(413, "Request body over size limit")
    total = he + 4 + length
    if len(buf) < total:
        return None, 0
    if length:
        raw.body = bytes(memoryview(buf)[he + 4:total])
    conn_tok = raw.header("Connection")
    if version_b == b"HTTP/1.0":
        raw.keep_alive = (conn_tok is not None
                          and conn_tok.lower() == "keep-alive")
    else:
        raw.keep_alive = (conn_tok is None
                          or conn_tok.lower() != "close")
    return raw, total


class _Conn:
    __slots__ = ("sock", "fd", "client", "buf", "pending", "busy",
                 "closing", "last_active", "lock", "t_read")

    def __init__(self, sock: socket.socket, client: str):
        self.sock = sock
        self.fd = sock.fileno()
        self.client = client
        self.buf = bytearray()
        # entries: ("req", RawRequest) | ("err", response_bytes)
        self.pending: Deque[tuple] = deque()
        self.busy = False          # a worker currently owns this conn
        self.closing = False
        self.last_active = time.monotonic()
        self.lock = threading.Lock()
        self.t_read = 0.0          # first-read stamp for the next request


class WireStats:
    """Raw wire activity counters: plain ints, no metrics objects, so
    the wire stays obs-free. Reactor-owned fields (accepted, requests,
    bytes_in, pipeline_hwm, errors) are written by the reactor thread
    only; `lock` guards the worker-side fields."""

    __slots__ = ("accepted", "requests", "bytes_in", "pipeline_hwm",
                 "errors", "lock", "bytes_out", "responses",
                 "send_failures", "busy_workers")

    def __init__(self):
        self.accepted = 0
        self.requests = 0
        self.bytes_in = 0
        self.pipeline_hwm = 0
        self.errors: Dict[int, int] = {}   # WireError status -> count
        self.lock = threading.Lock()
        self.bytes_out = 0
        self.responses = 0
        self.send_failures = 0
        self.busy_workers = 0


class SelectorWire:
    """The selector front end. API mirrors ThreadingHTTPServer just
    enough (`server_address`, `serve_forever`, `shutdown`,
    `server_close`) that HTTPServerBase treats both wires uniformly."""

    def __init__(self, server_address: Tuple[str, int],
                 handler: RawHandler, workers: int = 0):
        self._handler = handler
        self._stop = False
        self._done = threading.Event()
        self._lifecycle = threading.Lock()
        self._conns: Dict[int, _Conn] = {}
        self._to_close: Deque[_Conn] = deque()
        self.stats = WireStats()
        if workers <= 0:
            # Workers BLOCK in the handler (device step, store reads),
            # they are not CPU-bound — size the pool to cover the
            # admission layer's concurrency, not the core count, or
            # overload queues invisibly at the wire instead of shedding
            # 429/503 with Retry-After at the app layer.
            workers = int(os.environ.get(
                "PIO_WIRE_WORKERS",
                str(max(16, min(64, 4 * (os.cpu_count() or 4))))))
        self._n_workers = max(1, workers)
        import queue as _queue
        self._workq: "_queue.Queue" = _queue.Queue()
        self._workers: List[threading.Thread] = []
        # bind in the constructor so the caller's EADDRINUSE retry loop
        # wraps construction, exactly as with ThreadingHTTPServer
        ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            ls.bind(server_address)
        except OSError:
            ls.close()
            raise
        ls.listen(1024)
        ls.setblocking(False)
        self._listener = ls
        self.server_address = ls.getsockname()
        # wake pipe: shutdown() and worker close-requests nudge select()
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._sel = selectors.DefaultSelector()

    # -- reactor -------------------------------------------------------------
    def serve_forever(self) -> None:
        for i in range(self._n_workers):
            t = threading.Thread(target=self._worker_loop, daemon=True,
                                 name=f"wire-worker-{i}")
            t.start()
            self._workers.append(t)
        sel = self._sel
        sel.register(self._listener, selectors.EVENT_READ, "accept")
        sel.register(self._wake_r, selectors.EVENT_READ, "wake")
        last_sweep = time.monotonic()
        try:
            while not self._stop:
                for key, _ in sel.select(1.0):
                    if key.data == "accept":
                        self._accept()
                    elif key.data == "wake":
                        try:
                            while self._wake_r.recv(4096):
                                pass
                        except (BlockingIOError, OSError):
                            pass
                    else:
                        self._on_readable(key.data)
                self._drain_close_requests()
                now = time.monotonic()
                if now - last_sweep >= 5.0:
                    last_sweep = now
                    self._sweep_idle(now)
        finally:
            self._done.set()

    def _accept(self) -> None:
        while True:
            try:
                sock, addr = self._listener.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            sock.setblocking(False)
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            conn = _Conn(sock, addr[0] if addr else "")
            self._conns[conn.fd] = conn
            self.stats.accepted += 1
            self._sel.register(sock, selectors.EVENT_READ, conn)

    def _on_readable(self, conn: _Conn) -> None:
        eof = False
        if not conn.buf and _STAMP_NEW is not None:
            # first bytes of the next request on this connection
            conn.t_read = time.perf_counter()
        n_in = 0
        try:
            while True:
                data = conn.sock.recv(_RECV_CHUNK)
                if not data:
                    eof = True
                    break
                conn.buf.extend(data)
                n_in += len(data)
                if len(data) < _RECV_CHUNK:
                    break
        except (BlockingIOError, InterruptedError):
            pass
        except OSError:
            eof = True
        self.stats.bytes_in += n_in
        conn.last_active = time.monotonic()
        if conn.buf:
            self._pump(conn)
        if eof:
            with conn.lock:
                busy_or_pending = conn.busy or bool(conn.pending)
                conn.closing = True
            self._unregister(conn)
            if not busy_or_pending:
                self._destroy(conn)

    def _pump(self, conn: _Conn) -> None:
        """Frame every complete request in the buffer (up to the
        pipeline cap) and hand the connection to a worker."""
        added = False
        st = self.stats
        while len(conn.pending) < PIPELINE_MAX:
            try:
                raw, consumed = frame_request(conn.buf, conn.client)
            except WireError as e:
                st.errors[e.status] = st.errors.get(e.status, 0) + 1
                with conn.lock:
                    conn.pending.append(("err", _error_bytes(e)))
                    conn.closing = True
                self._unregister(conn)
                added = True
                break
            if raw is None:
                break
            del conn.buf[:consumed]
            sn = _STAMP_NEW
            if sn is not None:
                raw.trace = sn(conn.t_read)
            st.requests += 1
            with conn.lock:
                conn.pending.append(("req", raw))
                depth = len(conn.pending)
            if depth > st.pipeline_hwm:
                st.pipeline_hwm = depth
            added = True
        if added:
            with conn.lock:
                if not conn.busy and conn.pending:
                    conn.busy = True
                    self._workq.put(conn)

    def _sweep_idle(self, now: float) -> None:
        for conn in list(self._conns.values()):
            with conn.lock:
                idle = (not conn.busy and not conn.pending
                        and not conn.buf
                        and now - conn.last_active > KEEPALIVE_IDLE_S)
            if idle:
                self._unregister(conn)
                self._destroy(conn)

    def _drain_close_requests(self) -> None:
        while self._to_close:
            conn = self._to_close.popleft()
            self._unregister(conn)
            self._destroy(conn)

    def _unregister(self, conn: _Conn) -> None:
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            pass

    def _destroy(self, conn: _Conn) -> None:
        self._conns.pop(conn.fd, None)
        try:
            conn.sock.close()
        except OSError:
            pass

    def _wake(self) -> None:
        try:
            self._wake_w.send(b"x")
        except OSError:
            pass

    # -- workers -------------------------------------------------------------
    def _worker_loop(self) -> None:
        st = self.stats
        while True:
            conn = self._workq.get()
            if conn is None:
                return
            with st.lock:
                st.busy_workers += 1
            try:
                self._service(conn)
            finally:
                with st.lock:
                    st.busy_workers -= 1

    def _service(self, conn: _Conn) -> None:
        """Serve this connection's framed requests in order; the busy
        flag guarantees one worker per connection, so pipelined
        responses cannot interleave."""
        while True:
            with conn.lock:
                if not conn.pending:
                    conn.busy = False
                    close_now = conn.closing
                    break
                kind, item = conn.pending.popleft()
            if kind == "err":
                self._send(conn, item)
                self._request_close(conn)
                return
            try:
                data, close = self._handler(item)
            except Exception:
                data, close = build_response(
                    500, "application/json",
                    b'{"message": "internal wire error"}',
                    keep_alive=False), True
            sent = self._send(conn, data)
            cb = _ON_SENT
            if sent and cb is not None and item.trace is not None:
                try:
                    cb(item)
                except Exception:
                    pass               # tracing must never kill a worker
            if not sent or close or not item.keep_alive:
                self._request_close(conn)
                return
            conn.last_active = time.monotonic()
        if close_now:
            self._request_close(conn)

    def _send(self, conn: _Conn, data: bytes) -> bool:
        """Blocking-with-timeout send on the nonblocking socket; small
        responses nearly always complete in one call."""
        mv = memoryview(data)
        end = time.monotonic() + _SEND_TIMEOUT_S
        sock = conn.sock
        st = self.stats
        while mv:
            try:
                n = sock.send(mv)
                mv = mv[n:]
            except (BlockingIOError, InterruptedError):
                remaining = end - time.monotonic()
                if remaining <= 0:
                    with st.lock:
                        st.send_failures += 1
                    return False
                try:
                    select.select([], [sock], [], min(remaining, 1.0))
                except (OSError, ValueError):
                    with st.lock:
                        st.send_failures += 1
                    return False
            except OSError:
                with st.lock:
                    st.send_failures += 1
                return False
        with st.lock:
            st.bytes_out += len(data)
            st.responses += 1
        return True

    def _request_close(self, conn: _Conn) -> None:
        """Workers never touch the selector: shut the socket down and
        let the reactor unregister + close it."""
        with conn.lock:
            conn.closing = True
        try:
            conn.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._to_close.append(conn)
        self._wake()

    def stats_snapshot(self) -> Dict[str, object]:
        """Point-in-time wire counters for the obs layer's pio_wire_*
        families. Reactor-owned fields are read without the lock —
        single int reads are atomic enough for monitoring."""
        st = self.stats
        with st.lock:
            out: Dict[str, object] = {
                "bytes_out": st.bytes_out,
                "responses": st.responses,
                "send_failures": st.send_failures,
                "busy_workers": st.busy_workers,
            }
        out["accepted"] = st.accepted
        out["requests"] = st.requests
        out["bytes_in"] = st.bytes_in
        out["pipeline_hwm"] = st.pipeline_hwm
        out["errors"] = dict(st.errors)
        out["open_conns"] = len(self._conns)
        out["queue_depth"] = self._workq.qsize()
        out["workers"] = self._n_workers
        return out

    # -- lifecycle -----------------------------------------------------------
    def shutdown(self) -> None:
        self._stop = True
        self._wake()
        self._done.wait(timeout=5.0)

    def server_close(self) -> None:
        with self._lifecycle:
            workers, self._workers = self._workers, []
        for _ in workers:
            self._workq.put(None)
        for t in workers:
            t.join(timeout=2.0)
        try:
            self._listener.close()
        except OSError:
            pass
        for conn in list(self._conns.values()):
            self._unregister(conn)
            self._destroy(conn)
        try:
            self._sel.close()
        except Exception:
            pass
        for s in (self._wake_r, self._wake_w):
            try:
                s.close()
            except OSError:
                pass


class HTTPConnectionPool:
    """Persistent upstream connections for the fleet proxy.

    The router used to dial a fresh TCP connection per proxied request
    (urllib): at wire-path throughput the handshake dominates. This
    pool checks out a kept-alive `http.client.HTTPConnection` per
    (host, port), retries exactly once on a stale reuse (the upstream
    closed its keep-alive between our requests), and returns transport
    failures as OSError so the caller's retry-next-replica loop and
    ejection bookkeeping stay unchanged."""

    def __init__(self, max_idle_per_host: int = 4):
        self.max_idle = max_idle_per_host
        self._lock = threading.Lock()
        self._idle: Dict[Tuple[str, int], Deque] = {}

    def _checkout(self, host: str, port: int):
        with self._lock:
            q = self._idle.get((host, port))
            if q:
                return q.popleft(), True
        return None, False

    def _checkin(self, host: str, port: int, conn) -> None:
        with self._lock:
            q = self._idle.setdefault((host, port), deque())
            if len(q) < self.max_idle:
                q.append(conn)
                return
        conn.close()

    def request(self, host: str, port: int, method: str, path: str,
                body: Optional[bytes], headers: Dict[str, str],
                timeout: float) -> Tuple[int, Dict[str, str], bytes]:
        """One proxied request over a pooled connection. Returns
        (status, response headers, body). Transport-level failures
        raise OSError after at most one stale-connection retry."""
        attempts = 0
        while True:
            conn, reused = self._checkout(host, port)
            if conn is None:
                conn = http.client.HTTPConnection(host, port,
                                                  timeout=timeout)
            elif conn.sock is not None:
                conn.sock.settimeout(timeout)
            try:
                conn.request(method, path, body=body, headers=headers)
                resp = conn.getresponse()
                data = resp.read()
            except (http.client.HTTPException, OSError) as e:
                conn.close()
                # a reused connection the upstream already closed is
                # expected with keep-alive; retry ONCE on a fresh dial
                if reused and attempts == 0:
                    attempts += 1
                    continue
                if isinstance(e, OSError):
                    raise
                raise OSError(f"{type(e).__name__}: {e}") from e
            if resp.will_close:
                conn.close()
            else:
                self._checkin(host, port, conn)
            return resp.status, dict(resp.headers.items()), data

    def close(self) -> None:
        with self._lock:
            pools, self._idle = self._idle, {}
        for q in pools.values():
            for conn in q:
                try:
                    conn.close()
                except Exception:
                    pass

"""Shared utilities: HTTP micro-framework, logging helpers."""

"""Minimal HTTP framework used by every host-side server.

The reference builds its REST planes on spray/akka actors
(`data/.../api/EventServer.scala`, `core/.../workflow/CreateServer.scala`,
`tools/.../dashboard/Dashboard.scala`). Here one stdlib-based router serves
all of them, over one of two interchangeable wires:

  - `selector` (default): the readiness-loop front end in
    `utils/wire.py` — persistent keep-alive connections multiplexed by
    one reactor thread over a small worker pool, incremental framing,
    and a `fast_route` hook that lets a server answer a hot route
    straight from the raw bytes (no header dict, no Request object) —
    the serve-plane wire overhaul behind the 10k-qps path;
  - `threaded`: the original `ThreadingHTTPServer` thread-per-connection
    stack, kept as the `PIO_SERVE_WIRE=threaded` escape hatch and used
    automatically when TLS is configured (the selector loop does not
    speak TLS).

Routing, middleware, and handler contracts are identical on both wires.

Features: method+path-pattern routing with `<name>` captures, JSON
request/response helpers, query params, per-request context, graceful
shutdown, optional TLS via an ssl context.

Observability middleware (predictionio_tpu.obs): every request gets a
request id (X-Request-ID in, generated otherwise; always echoed back),
one structured JSON log line (method, path, route, status, duration_ms,
request_id), a route/method/status counter and a per-route latency
histogram; every server serves its registry on `GET /metrics` in
Prometheus text format. Unhandled handler errors are logged structured
with the request id instead of a bare traceback print.

Resilience middleware (predictionio_tpu.resilience): `X-PIO-Deadline-Ms`
(or the server's `default_deadline_ms`) becomes a propagated Deadline —
expiry anywhere under the handler maps to 504; an open storage circuit
breaker maps to 503 + Retry-After; admission past `max_inflight` sheds
with 429 + Retry-After. Every server also answers `GET /health`
(liveness: the process responds) and `GET /ready` (readiness: the
subclass `readiness()` hook — model loaded, breakers closed).
"""

from __future__ import annotations

import json
import os
import re
import ssl as ssl_module
import threading
import time
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Mapping, Optional, Tuple
from urllib.parse import parse_qs, unquote, urlparse

from predictionio_tpu.obs import (
    MetricsRegistry, get_logger, get_registry, new_request_id,
)
from predictionio_tpu.obs import profiler as prof_mod
from predictionio_tpu.obs import trace
from predictionio_tpu.obs import tsdb as tsdb_mod
from predictionio_tpu.resilience import (
    DEADLINE_HEADER, Deadline, DeadlineExceeded, CircuitOpenError,
    InflightLimiter, OverloadedError, deadline_from_header, deadline_scope,
)
from predictionio_tpu.utils.wire import (
    RawRequest, SelectorWire, ShardedWire, build_response,
    reactor_count, set_trace_hooks,
)

_log = get_logger("http")


@dataclass
class Request:
    method: str
    path: str
    query: Mapping[str, str]
    headers: Mapping[str, str]
    body: bytes
    params: Mapping[str, str] = field(default_factory=dict)  # path captures
    client: str = ""
    request_id: str = ""       # assigned by the middleware, never empty there
    route: str = ""            # matched route pattern (metrics label)
    deadline: Optional[Deadline] = None   # from X-PIO-Deadline-Ms / default

    def json(self) -> Any:
        if not self.body:
            raise ValueError("Empty request body")
        try:
            return json.loads(self.body.decode("utf-8"))
        except json.JSONDecodeError as e:
            raise ValueError(f"Invalid JSON: {e}") from e

    def header(self, name: str, default: Optional[str] = None
               ) -> Optional[str]:
        """Case-insensitive header lookup (clients and proxies disagree
        on canonical casing; RFC 7230 says names are case-insensitive)."""
        v = self.headers.get(name)
        if v is not None:
            return v
        lname = name.lower()
        for k, val in self.headers.items():
            if k.lower() == lname:
                return val
        return default

    def query_get(self, name: str, default: Optional[str] = None) -> Optional[str]:
        return self.query.get(name, default)


@dataclass
class Response:
    status: int = 200
    body: Any = None              # JSON-serializable, or bytes, or str
    content_type: str = "application/json"
    headers: Mapping[str, str] = field(default_factory=dict)

    @staticmethod
    def json(obj: Any, status: int = 200, **headers) -> "Response":
        return Response(status=status, body=obj, headers=headers)

    @staticmethod
    def text(s: str, status: int = 200, content_type: str = "text/plain") -> "Response":
        return Response(status=status, body=s, content_type=content_type)

    @staticmethod
    def html(s: str, status: int = 200) -> "Response":
        return Response(status=status, body=s, content_type="text/html")


Handler = Callable[[Request], Response]


class HTTPError(Exception):
    """Raise from a handler to produce a JSON error response."""

    def __init__(self, status: int, message: str,
                 headers: Optional[Mapping[str, str]] = None):
        super().__init__(message)
        self.status = status
        self.message = message
        self.headers: Dict[str, str] = dict(headers or {})


def _compile(pattern: str) -> re.Pattern:
    """`<name>` captures one segment; `<name:path>` captures across slashes."""
    parts = []
    for piece in re.split(r"(<[a-zA-Z_]+(?::path)?>)", pattern):
        if piece.startswith("<") and piece.endswith(">"):
            inner = piece[1:-1]
            if inner.endswith(":path"):
                parts.append(f"(?P<{inner[:-5]}>.+)")
            else:
                parts.append(f"(?P<{inner}>[^/]+)")
        else:
            parts.append(re.escape(piece))
    return re.compile("^" + "".join(parts) + "$")


class Router:
    def __init__(self):
        self.routes: List[Tuple[str, str, re.Pattern, Handler]] = []

    def route(self, method: str, pattern: str):
        def deco(fn: Handler) -> Handler:
            self.routes.append(
                (method.upper(), pattern, _compile(pattern), fn))
            return fn
        return deco

    def get(self, pattern: str):
        return self.route("GET", pattern)

    def post(self, pattern: str):
        return self.route("POST", pattern)

    def delete(self, pattern: str):
        return self.route("DELETE", pattern)

    def dispatch(self, req: Request) -> Response:
        path_matched = False
        for method, pattern, regex, fn in self.routes:
            m = regex.match(req.path)
            if m:
                path_matched = True
                if method == req.method:
                    # captures are matched against the raw (still-encoded)
                    # path, then decoded individually — decoding first would
                    # let %2F alter routing and make such ids unreachable
                    req.route = pattern
                    req.params = {k: unquote(v)
                                  for k, v in m.groupdict().items()}
                    try:
                        return fn(req)
                    except HTTPError as e:
                        return Response.json({"message": e.message}, e.status,
                                             **e.headers)
                    except DeadlineExceeded as e:
                        return Response.json({"message": str(e)}, 504)
                    except CircuitOpenError as e:
                        return Response.json(
                            {"message": str(e)}, 503,
                            **{"Retry-After": str(max(1, round(
                                e.retry_after)))})
                    except OverloadedError as e:
                        return Response.json(
                            {"message": e.message}, e.status,
                            **{"Retry-After": str(max(1, round(
                                e.retry_after)))})
                    except ValueError as e:
                        return Response.json({"message": str(e)}, 400)
                    except Exception as e:
                        _log.exception(
                            "unhandled_error", request_id=req.request_id,
                            method=req.method, path=req.path,
                            error=f"{type(e).__name__}: {e}")
                        return Response.json({"message": f"{e}"}, 500)
        if path_matched:
            return Response.json({"message": "Method Not Allowed"}, 405)
        return Response.json({"message": "Not Found"}, 404)


class HTTPServerBase:
    """A threaded HTTP server wrapping a Router; start()/shutdown() API.

    Subclasses populate `self.router`. Parity note: plays the role of
    spray-can's `IO(Http) ! Http.Bind` + actor routing in the reference
    servers.
    """

    def __init__(self, host: str = "0.0.0.0", port: int = 0,
                 ssl_context: Optional[ssl_module.SSLContext] = None,
                 metrics: Optional[MetricsRegistry] = None,
                 default_deadline_ms: int = 0,
                 max_inflight: int = 0):
        self.host = host
        self.port = port
        self.router = Router()
        self._ssl_context = ssl_context
        # ThreadingHTTPServer or SelectorWire — same lifecycle surface
        self._httpd: Optional[Any] = None
        self._thread: Optional[threading.Thread] = None
        self._lifecycle_lock = threading.Lock()
        # one process-default registry unless a test passes its own, so a
        # single /metrics scrape sees every server in the process
        self.metrics = metrics if metrics is not None else get_registry()
        self.obs_log = get_logger(type(self).__name__)
        self._req_counter = self.metrics.counter(
            "pio_http_requests_total", "HTTP requests served",
            labels=("route", "method", "status"))
        self._req_hist = self.metrics.histogram(
            "pio_http_request_duration_seconds",
            "HTTP request wall time by matched route", labels=("route",))
        # resilience: per-request deadline default + HTTP-plane admission
        self.default_deadline_ms = default_deadline_ms
        self._limiter = InflightLimiter(
            max_inflight, surface=type(self).__name__)
        # `app` attributes the shed to a tenant where one is known; the
        # HTTP-plane inflight shed happens before auth, hence app=""
        self._shed_counter = self.metrics.counter(
            "pio_shed_total", "Requests shed by surface at admission",
            labels=("surface", "app"))
        self._deadline_counter = self.metrics.counter(
            "pio_deadline_expired_total",
            "Requests that exhausted their deadline", labels=("route",))
        self.router.get("/metrics")(self._metrics_endpoint)
        self.router.get("/health")(self._health_endpoint)
        self.router.get("/ready")(self._ready_endpoint)
        self.router.get("/traces.json")(self._traces_endpoint)
        self.router.get("/profile.json")(self._profile_json_endpoint)
        self.router.get("/profile.txt")(self._profile_txt_endpoint)
        self.router.get("/tsdb.json")(self._tsdb_endpoint)
        # continuous observatory: every server keeps its own bounded
        # time-series ring over its registry, scraped on a background
        # tick (PIO_TSDB_INTERVAL_S=0 disables the loop; the ring and
        # endpoint stay, just empty)
        self.tsdb = tsdb_mod.TSDB()
        self._scraper: Optional[tsdb_mod.Scraper] = None
        self._host_sampler = prof_mod.HostSampler(self.metrics)
        # last-seen absolute wire counters, so monotone pio_wire_*
        # counters can be advanced by delta on each /metrics scrape
        self._wire_last: Dict[str, float] = {}
        # hot-route hook (selector wire only): (method, path) -> a
        # handler taking the RAW framed request and returning complete
        # response bytes, or None to fall through to the Router path.
        # Only /queries.json rides this; every legacy route keeps the
        # full Request/middleware pipeline.
        self._fast_routes: Dict[Tuple[str, str],
                                Callable[[RawRequest], Optional[bytes]]] = {}
        self.wire = "unstarted"

    def fast_route(self, method: str, path: str,
                   fn: Callable[[RawRequest], Optional[bytes]]) -> None:
        """Register a raw-bytes handler for one exact (method, path).
        The handler returns a full HTTP response as bytes, or None to
        delegate to the normal Router dispatch (the fallback path MUST
        exist as a registered route)."""
        self._fast_routes[(method.upper(), path)] = fn

    def _metrics_endpoint(self, req: Request) -> Response:
        self._sync_wire_metrics()
        return Response.text(
            self.metrics.render(),
            content_type="text/plain; version=0.0.4; charset=utf-8")

    def _traces_endpoint(self, req: Request) -> Response:
        """The flight recorder's keep ring (filter: ?app= / ?min_ms= /
        ?trace_id= / ?limit=)."""
        return Response(status=200, body=trace.traces_json_body(
            req.query_get), content_type="application/json")

    # -- continuous observatory ----------------------------------------------
    def _profile_json_endpoint(self, req: Request) -> Response:
        """Sampling-profiler snapshot: per-role CPU shares + top
        frames by self and cumulative samples."""
        try:
            top = int(req.query_get("top") or 30)
        except ValueError:
            top = 30
        return Response.json(
            prof_mod.get_profiler().snapshot_json(top=max(1, top)))

    def _profile_txt_endpoint(self, req: Request) -> Response:
        """?fmt=collapsed (the default) serves flamegraph-ready
        collapsed stacks; ?fmt=top a terminal-friendly summary."""
        prof = prof_mod.get_profiler()
        if (req.query_get("fmt") or "collapsed") != "collapsed":
            snap = prof.snapshot_json(top=15)
            lines = [f"samples={snap['samples']} hz={snap['hz']}"]
            for role, st in snap["roles"].items():
                lines.append(f"role {role:<12} {st['share']:>7.2%}"
                             f"  ({st['samples']})")
            for row in snap["top_self"]:
                lines.append(f"self {row['share']:>7.2%}  {row['frame']}")
            return Response.text("\n".join(lines) + "\n")
        return Response.text(prof.collapsed())

    def _tsdb_endpoint(self, req: Request) -> Response:
        """The local time-series ring (?series=prefix,prefix &
        ?since=unix-ts filter)."""
        return Response.json(self.tsdb.to_json(
            req.query_get("series"), req.query_get("since")))

    def _obs_collectors(self) -> List[Callable[[], None]]:
        """Collectors the tsdb scraper runs before each snapshot —
        subclasses extend (fleet member scrape, device plan bytes)."""

        def _device_memory() -> None:
            prof_mod.sample_device_memory(self.metrics)

        return [self._sync_wire_metrics, self._host_sampler.sample,
                _device_memory]

    def _sync_wire_metrics(self) -> None:
        """Scrape the selector wire's raw counters into pio_wire_*
        families (called on /metrics; the wire itself stays obs-free).
        Monotone values advance their counter by delta since the last
        scrape; instantaneous ones land in gauges. Every family carries
        a `reactor` label — one series per accept shard under
        ShardedWire ("0" for the single-reactor wire), so shard skew is
        visible straight from /metrics."""
        httpd = self._httpd
        snap_fn = getattr(httpd, "stats_snapshot", None)
        if snap_fn is None:
            return
        snap = snap_fn()
        # ShardedWire returns the aggregate plus per-reactor snapshots;
        # a plain SelectorWire snapshot IS its own single shard
        shards = snap.get("reactors") or [snap]
        listen = f"{self.host}:{self.port}"
        m = self.metrics
        last = self._wire_last

        def _cdelta(name: str, help_text: str, key: str, value: float,
                    **extra) -> None:
            prev = last.get(name + key + str(sorted(extra.items())), 0.0)
            delta = value - prev
            if delta > 0:
                m.counter(name, help_text,
                          labels=("listen",) + tuple(sorted(extra))
                          ).labels(listen=listen, **extra).inc(delta)
            last[name + key + str(sorted(extra.items()))] = value

        for rs in shards:
            r = str(rs.get("reactor", 0))
            _cdelta("pio_wire_connections_accepted_total",
                    "Connections accepted by the selector wire",
                    f"accepted[{r}]", float(rs["accepted"]), reactor=r)
            _cdelta("pio_wire_requests_total",
                    "Requests framed off the selector wire",
                    f"requests[{r}]", float(rs["requests"]), reactor=r)
            _cdelta("pio_wire_responses_total",
                    "Responses fully written by the selector wire",
                    f"responses[{r}]", float(rs["responses"]), reactor=r)
            _cdelta("pio_wire_egress_flushes_total",
                    "Gathered egress syscalls (sendmsg batches); "
                    "responses/flushes is the writev coalescing ratio",
                    f"flushes[{r}]", float(rs.get("flushes", 0)),
                    reactor=r)
            _cdelta("pio_wire_send_failures_total",
                    "Response writes that failed or timed out",
                    f"send_failures[{r}]", float(rs["send_failures"]),
                    reactor=r)
            _cdelta("pio_wire_bytes_total", "Wire bytes by direction",
                    f"bytes_in[{r}]", float(rs["bytes_in"]),
                    dir="in", reactor=r)
            _cdelta("pio_wire_bytes_total", "Wire bytes by direction",
                    f"bytes_out[{r}]", float(rs["bytes_out"]),
                    dir="out", reactor=r)
            for status, count in dict(rs["errors"]).items():
                _cdelta("pio_wire_errors_total",
                        "Wire-level framing error responses by status",
                        f"err{status}[{r}]", float(count),
                        status=str(status), reactor=r)
            gauges = (
                ("pio_wire_connections_open",
                 "Connections currently registered with the reactor",
                 float(rs["open_conns"])),
                ("pio_wire_queue_depth",
                 "Connections waiting for a wire worker",
                 float(rs["queue_depth"])),
                ("pio_wire_workers_busy",
                 "Wire workers currently running a handler",
                 float(rs["busy_workers"])),
                ("pio_wire_workers", "Wire worker pool size",
                 float(rs["workers"])),
                ("pio_wire_pipeline_depth_hwm",
                 "High-water mark of framed-but-unserved pipelined "
                 "requests on one connection",
                 float(rs["pipeline_hwm"])),
                ("pio_wire_worker_utilization",
                 "Busy fraction of the wire worker pool "
                 "(busy_workers / workers)",
                 float(rs.get("utilization", 0.0))),
            )
            for name, help_text, value in gauges:
                m.gauge(name, help_text,
                        labels=("listen", "reactor")).labels(
                            listen=listen, reactor=r).set(value)
            reqs = float(rs["requests"])
            reuse = ((reqs - float(rs["accepted"])) / reqs
                     if reqs > 0 else 0.0)
            m.gauge("pio_wire_keepalive_reuse_ratio",
                    "Fraction of requests that reused a kept-alive "
                    "connection", labels=("listen", "reactor")).labels(
                        listen=listen, reactor=r).set(max(0.0, reuse))

    # -- health/readiness ---------------------------------------------------
    def readiness(self) -> Tuple[bool, Dict[str, Any]]:
        """Subclass hook: (ready?, detail). Default: serving = ready."""
        return True, {}

    def _health_endpoint(self, req: Request) -> Response:
        """Liveness: the process accepts connections and can respond."""
        return Response.json({"status": "ok"})

    def _ready_endpoint(self, req: Request) -> Response:
        """Readiness: fit to take traffic (model loaded, breakers
        closed); 503 tells the load balancer to route elsewhere."""
        ok, detail = self.readiness()
        body = {"ready": ok}
        body.update(detail)
        return Response.json(body, 200 if ok else 503)

    def _handle(self, req: Request) -> Response:
        """Resilience middleware around dispatch: deadline extraction +
        propagation (contextvar, for storage/batcher calls below the
        handler) and in-flight admission control."""
        try:
            req.deadline = deadline_from_header(
                req.header(DEADLINE_HEADER), self.default_deadline_ms)
        except ValueError as e:
            return Response.json({"message": str(e)}, 400)
        if req.deadline is not None and req.deadline.expired:
            return Response.json(
                {"message": "deadline expired before processing"}, 504)
        try:
            with self._limiter:
                with deadline_scope(req.deadline):
                    return self.router.dispatch(req)
        except OverloadedError as e:
            self._shed_counter.labels(surface=self._limiter.surface,
                                      app="").inc()
            return Response.json(
                {"message": e.message}, e.status,
                **{"Retry-After": str(max(1, round(e.retry_after)))})

    # -- selector-wire raw path ---------------------------------------------
    def _handle_raw(self, raw: RawRequest) -> Tuple[bytes, bool]:
        """The selector wire's single entry point: try the fast-route
        table on the raw frame, else materialize a full Request and run
        the identical middleware + Router pipeline the threaded wire
        uses. Returns (response bytes, close connection?)."""
        fast = self._fast_routes.get((raw.method, raw.path))
        if fast is not None:
            out = fast(raw)
            if out is not None:
                return out, not raw.keep_alive
        rid = raw.header("X-Request-ID") or new_request_id()
        raw_q = parse_qs(raw.query_string, keep_blank_values=True)
        req = Request(
            method=raw.method, path=raw.path,
            query={k: v[0] for k, v in raw_q.items()},
            headers=dict(raw.header_items()), body=raw.body,
            client=raw.client, request_id=rid)
        p = raw.trace
        tok = None
        if p is not None:
            trace.begin_raw(raw, raw.header(trace.TRACE_HEADER))
            p.rid = rid
            # expose the pending trace to handlers below (fleet router
            # spans, batcher submit on the legacy route)
            tok = trace.set_current(p)
        started = time.perf_counter()
        try:
            resp = self._handle(req)
        finally:
            if tok is not None:
                trace.reset_current(tok)
        if p is not None:
            trace.annotate_pending(p, status=resp.status,
                                   route=req.route or raw.path)
            trace.mark(p, trace.S_DONE)
        self._observe_request(req, resp, time.perf_counter() - started)
        payload = resp.body
        if isinstance(payload, bytes):
            data = payload
        elif isinstance(payload, str):
            data = payload.encode("utf-8")
        else:
            data = json.dumps(payload).encode("utf-8")
        out = build_response(
            resp.status, resp.content_type, data, rid,
            dict(resp.headers) if resp.headers else None,
            keep_alive=raw.keep_alive, head_only=raw.method == "HEAD")
        return out, not raw.keep_alive

    def _observe_request(self, req: Request, resp: Response,
                         duration: float) -> None:
        route = req.route or "(unmatched)"
        if resp.status == 504:
            self._deadline_counter.labels(route=route).inc()
        self._req_counter.labels(
            route=route, method=req.method, status=str(resp.status)).inc()
        self._req_hist.labels(route=route).observe(duration)
        self.obs_log.info(
            "request", request_id=req.request_id, method=req.method,
            path=req.path, route=route, status=resp.status,
            duration_ms=round(duration * 1000.0, 3))

    # -- lifecycle ----------------------------------------------------------
    def start(self, background: bool = True) -> int:
        router = self.router
        server_ref = self

        class _Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _respond(self):
                parsed = urlparse(self.path)
                raw_q = parse_qs(parsed.query, keep_blank_values=True)
                query = {k: v[0] for k, v in raw_q.items()}
                rid = self.headers.get("X-Request-ID") or new_request_id()
                try:
                    length = int(self.headers.get("Content-Length") or 0)
                    if length < 0:
                        raise ValueError("negative Content-Length")
                except ValueError:
                    # malformed framing: answer 400 instead of resetting
                    # the connection with no response at all; the body
                    # was never read, so the connection must close
                    self.close_connection = True
                    self._reply(Response.json(
                        {"message": "Invalid Content-Length header"},
                        400), rid)
                    return
                body = self.rfile.read(length) if length else b""
                req = Request(
                    method=self.command, path=parsed.path, query=query,
                    headers={k: v for k, v in self.headers.items()},
                    body=body, client=self.client_address[0],
                    request_id=rid)
                started = time.perf_counter()
                resp = server_ref._handle(req)
                server_ref._observe_request(
                    req, resp, time.perf_counter() - started)
                self._reply(resp, rid)

            def _reply(self, resp: Response, rid: str) -> None:
                payload = resp.body
                if isinstance(payload, bytes):
                    data = payload
                elif isinstance(payload, str):
                    data = payload.encode("utf-8")
                else:
                    data = json.dumps(payload).encode("utf-8")
                self.send_response(resp.status)
                self.send_header("Content-Type", resp.content_type)
                self.send_header("Content-Length", str(len(data)))
                self.send_header("X-Request-ID", rid)
                for k, v in resp.headers.items():
                    self.send_header(k, v)
                self.end_headers()
                if self.command != "HEAD":
                    self.wfile.write(data)

            do_GET = do_POST = do_DELETE = do_PUT = do_HEAD = _respond

            def log_message(self, fmt, *args):  # quiet by default
                server_ref.log_request_line(fmt % args)

        # Deep listen backlog: the stdlib default of 5 drops connections
        # (ECONNRESET) under concurrent client bursts. On the threaded
        # wire, daemon thread-per-connection stays (an earlier
        # worker-pool variant let idle keep-alive connections starve
        # every worker — the selector wire solves that with readiness
        # multiplexing instead); the handler timeout bounds how long an
        # idle keep-alive connection can pin its (daemon) thread.
        _Server = type("_Server", (ThreadingHTTPServer,),
                       {"request_queue_size": 128})
        _Handler.timeout = 60
        # wire selection: the selector readiness loop is the default;
        # PIO_SERVE_WIRE=threaded is the escape hatch, and TLS always
        # takes the threaded wire (the selector loop does not speak
        # ssl's WantRead/WantWrite dance)
        want = os.environ.get("PIO_SERVE_WIRE", "selector").lower()
        use_selector = want != "threaded" and self._ssl_context is None
        self.wire = "selector" if use_selector else "threaded"
        if use_selector:
            # flight-recorder hooks: process-global and idempotent; the
            # recorder reads PIO_TRACE_SAMPLE and returns None stamps
            # when tracing is off, so this costs ~nothing by default
            trace.get_recorder()
            set_trace_hooks(trace.new_stamps, trace.on_sent)

        def _bind():
            if use_selector:
                # PIO_WIRE_REACTORS > 1 shards the accept loop across
                # N reactors (SO_REUSEPORT, or fd handoff where that is
                # unavailable); at 1 the single-reactor wire is used
                # unchanged.
                n = reactor_count()
                if n > 1:
                    return ShardedWire((self.host, self.port),
                                       self._handle_raw, reactors=n)
                return SelectorWire((self.host, self.port),
                                    self._handle_raw)
            return _Server((self.host, self.port), _Handler)

        # 3-attempt bind with backoff (the reference retries Http.Bind
        # three times before giving up, CreateServer.scala:260-285) —
        # covers the port-release lag after stopping a previous server.
        # Only EADDRINUSE is transient; EACCES/EADDRNOTAVAIL etc. can
        # never succeed and raise immediately.
        import errno
        for attempt in range(3):
            try:
                self._httpd = _bind()
                break
            except OSError as e:
                if attempt == 2 or e.errno != errno.EADDRINUSE:
                    raise
                time.sleep(0.5 * (attempt + 1))
        if self._ssl_context is not None:
            self._httpd.socket = self._ssl_context.wrap_socket(
                self._httpd.socket, server_side=True)
        self.port = self._httpd.server_address[1]
        self._on_bound()
        # continuous observatory: process-global sampler (one thread
        # samples every thread once, however many servers run) + a GC
        # pause hook per registry + this server's tsdb scraper. Both
        # loops honor their =0 env escape inside start().
        prof_mod.ensure_started()
        prof_mod.install_gc_callbacks(self.metrics)
        if self._scraper is None:
            self._scraper = tsdb_mod.Scraper(
                self.tsdb, self.metrics,
                collectors=self._obs_collectors())
        self._scraper.start()
        if background:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, daemon=True,
                name=f"pio-http-serve-{self.port}")
            self._thread.start()
        else:
            self._httpd.serve_forever()
        return self.port

    def _on_bound(self) -> None:
        """Subclass hook: runs after the wire is bound (self._httpd
        set, self.port final) and before serve_forever — the place to
        connect wire-facing callbacks like the micro-batcher's
        flush_hint cross-wakeup."""

    def shutdown(self) -> None:
        # idempotent + thread-safe: the /stop handler thread and a caller
        # (test teardown, signal handler) may race into shutdown
        with self._lifecycle_lock:
            httpd, self._httpd = self._httpd, None
            thread, self._thread = self._thread, None
            scraper, self._scraper = self._scraper, None
        if scraper is not None:
            scraper.stop()
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if thread is not None:
            thread.join(timeout=5)

    def is_running(self) -> bool:
        return self._httpd is not None

    def log_request_line(self, line: str) -> None:
        pass


def parse_basic_auth_value(auth: Optional[str]) -> Optional[str]:
    """Username out of one raw `Authorization` header value — the
    header-lite form the wire fast path feeds straight from its scan."""
    import base64
    if not auth or not auth.startswith("Basic "):
        return None
    try:
        decoded = base64.b64decode(auth[len("Basic "):]).decode("utf-8")
    except Exception:
        return None
    return decoded.split(":")[0].strip() or None


def parse_basic_auth_user(headers: Mapping[str, str]) -> Optional[str]:
    """Extract the username of a Basic Authorization header (the reference
    accepts the access key as the Basic username, EventServer.scala:114-126)."""
    return parse_basic_auth_value(
        headers.get("Authorization") or headers.get("authorization"))

"""Cross-validation helpers.

Parity: `e2/.../evaluation/CrossValidation.scala:26-67` —
`CommonHelperFunctions.splitData`: k folds by index modulo; each fold
yields (training points, eval info, [(query, actual)]) matching the
`readEval` contract.
"""

from __future__ import annotations

from typing import Callable, List, Sequence, Tuple, TypeVar

T = TypeVar("T")
Q = TypeVar("Q")
A = TypeVar("A")


def split_data(k: int, data: Sequence[T],
               to_training: Callable[[Sequence[T]], object],
               to_qa: Callable[[T], Tuple[Q, A]]
               ) -> List[Tuple[object, str, List[Tuple[Q, A]]]]:
    """k folds by element-index modulo (zipWithIndex % k semantics)."""
    if k < 2:
        raise ValueError("k must be >= 2")
    folds = []
    for fold in range(k):
        train = [x for i, x in enumerate(data) if i % k != fold]
        test = [x for i, x in enumerate(data) if i % k == fold]
        folds.append((to_training(train), f"fold{fold}",
                      [to_qa(x) for x in test]))
    return folds

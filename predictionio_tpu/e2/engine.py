"""Reusable algorithm helpers over string-categorical data.

Parity targets:
  - `CategoricalNaiveBayes` — NB over string feature vectors with
    per-position likelihood maps and an unseen-feature default hook
    (`e2/.../engine/CategoricalNaiveBayes.scala:26-170`)
  - `MarkovChain` — row-normalized top-N sparse transition matrix
    (`e2/.../engine/MarkovChain.scala:28-88`)
  - `BinaryVectorizer` — (property, value) pair -> binary feature vector
    (`e2/.../engine/BinaryVectorizer.scala`)

These are host-side helpers for small categorical models; the dense
numerical kernels live in `predictionio_tpu.ops`.
"""

from __future__ import annotations

import math
from collections import Counter, defaultdict
from dataclasses import dataclass
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np


@dataclass(frozen=True)
class LabeledPoint:
    """(LabeledPoint, CategoricalNaiveBayes.scala:173)"""
    label: str
    features: Tuple[str, ...]


class CategoricalNaiveBayes:
    """NB over string-categorical features.

    `log_score` returns None when the point's label is unknown; unseen
    feature values fall back to `default_likelihood` (a function of the
    position's log-likelihood values), matching
    `CategoricalNaiveBayes.scala logScoreInternal`.
    """

    def __init__(self, priors: Dict[str, float],
                 likelihoods: Dict[str, List[Dict[str, float]]]):
        self.priors = priors            # label -> log prior
        self.likelihoods = likelihoods  # label -> per-position value->loglik

    @staticmethod
    def train(points: Iterable[LabeledPoint]) -> "CategoricalNaiveBayes":
        points = list(points)
        if not points:
            raise ValueError("no training points")
        n_features = len(points[0].features)
        label_counts = Counter(p.label for p in points)
        total = sum(label_counts.values())
        priors = {lb: math.log(c / total) for lb, c in label_counts.items()}
        likelihoods: Dict[str, List[Dict[str, float]]] = {}
        for lb, c in label_counts.items():
            per_pos = []
            for j in range(n_features):
                counts = Counter(p.features[j] for p in points
                                 if p.label == lb)
                per_pos.append({v: math.log(k / c)
                                for v, k in counts.items()})
            likelihoods[lb] = per_pos
        return CategoricalNaiveBayes(priors, likelihoods)

    def log_score(self, point: LabeledPoint,
                  default_likelihood: Callable[[List[float]], float]
                  = lambda lls: float("-inf")) -> Optional[float]:
        if point.label not in self.priors:
            return None
        lls = self.likelihoods[point.label]
        score = self.priors[point.label]
        for j, v in enumerate(point.features):
            if v in lls[j]:
                score += lls[j][v]
            else:
                score += default_likelihood(list(lls[j].values()))
        return score

    def predict(self, features: Sequence[str]) -> str:
        """argmax label (CategoricalNaiveBayes.scala predict); unseen
        feature values score strictly below every seen value of that
        position."""
        def unseen(lls: List[float]) -> float:
            return (min(lls) if lls else 0.0) - math.log(2.0)

        best, best_score = None, float("-inf")
        for lb in self.priors:
            s = self.log_score(LabeledPoint(lb, tuple(features)), unseen)
            if s is not None and s > best_score:
                best, best_score = lb, s
        return best


class MarkovChain:
    """Top-N row-normalized transition model (MarkovChain.scala:28-88)."""

    def __init__(self, transitions: Dict[int, List[Tuple[int, float]]],
                 n_states: int):
        self.transitions = transitions
        self.n_states = n_states

    @staticmethod
    def train(pairs: Iterable[Tuple[int, int]], n_states: int,
              top_n: int = 10) -> "MarkovChain":
        counts: Dict[int, Counter] = defaultdict(Counter)
        for a, b in pairs:
            counts[a][b] += 1
        transitions: Dict[int, List[Tuple[int, float]]] = {}
        for a, c in counts.items():
            total = sum(c.values())
            top = c.most_common(top_n)
            transitions[a] = [(b, k / total) for b, k in top]
        return MarkovChain(transitions, n_states)

    def predict(self, state: int) -> List[Tuple[int, float]]:
        """One transition step from `state` (MarkovChain predict)."""
        return self.transitions.get(state, [])


class BinaryVectorizer:
    """(property, value) pairs -> fixed binary vector
    (BinaryVectorizer.scala)."""

    def __init__(self, index: Dict[Tuple[str, str], int]):
        self.index = index
        self.num_features = len(index)

    @staticmethod
    def fit(maps: Iterable[Dict[str, str]],
            properties: Sequence[str]) -> "BinaryVectorizer":
        seen: Dict[Tuple[str, str], int] = {}
        for m in maps:
            for p in properties:
                if p in m and (p, m[p]) not in seen:
                    seen[(p, m[p])] = len(seen)
        return BinaryVectorizer(seen)

    def to_vector(self, m: Dict[str, str]) -> np.ndarray:
        out = np.zeros(self.num_features, np.float32)
        for key, ix in self.index.items():
            if m.get(key[0]) == key[1]:
                out[ix] = 1.0
        return out

"""e2: reusable engine/evaluation helpers.

Parity: the reference's standalone `e2/` module (SURVEY.md §2.5) —
`CategoricalNaiveBayes`, `MarkovChain`, `BinaryVectorizer`
(`e2/src/main/scala/.../engine/`) and `CommonHelperFunctions.splitData`
(`e2/.../evaluation/CrossValidation.scala:26-67`).
"""

from predictionio_tpu.e2.engine import (  # noqa: F401
    BinaryVectorizer, CategoricalNaiveBayes, LabeledPoint, MarkovChain,
)
from predictionio_tpu.e2.evaluation import split_data  # noqa: F401

"""JAX compile-cache-miss probe.

Every jit cache miss that reaches the XLA compiler emits the
`/jax/core/compile/backend_compile_duration` event on jax.monitoring's
duration stream (jax/_src/dispatch.py BACKEND_COMPILE_EVENT). Counting
those events counts real backend compilations — recompiles from shape
churn or cache invalidation show up here long before they show up as
mystery latency. `pio train` reports the per-run delta next to its phase
timings (the tf.data-service-style "where did the time go" telemetry).

jax.monitoring listeners are process-global and cannot be removed
individually, so installation is once-per-process into the
process-default registry; `install_compile_probe` is idempotent.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Optional

from predictionio_tpu.obs.metrics import MetricsRegistry, get_registry

BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
COMPILE_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                   30.0, 60.0, 120.0)

_install_lock = threading.Lock()
_installed = False


def _instruments(registry: MetricsRegistry):
    counter = registry.counter(
        "pio_jax_backend_compiles_total",
        "XLA backend compilations (jit compile-cache misses)")
    hist = registry.histogram(
        "pio_jax_backend_compile_seconds",
        "XLA backend compile wall time per compilation",
        buckets=COMPILE_BUCKETS)
    return counter, hist


def install_compile_probe(
        registry: Optional[MetricsRegistry] = None) -> None:
    """Register the jax.monitoring listener (once per process). Counts
    land in `registry` (default: the process-default registry)."""
    global _installed
    counter, hist = _instruments(registry or get_registry())
    with _install_lock:
        if _installed:
            return
        from jax import monitoring   # lazy: obs must import without jax

        def _on_duration(event: str, duration: float, **kwargs) -> None:
            if event == BACKEND_COMPILE_EVENT:
                counter.inc()
                hist.observe(duration)

        monitoring.register_event_duration_secs_listener(_on_duration)
        _installed = True


def compile_count(registry: Optional[MetricsRegistry] = None) -> int:
    """Current backend-compile count (0 before the probe ever fired)."""
    counter, _ = _instruments(registry or get_registry())
    return int(counter.value)


class _CompileWatch:
    """Result object of `compile_watch`; `.count` is live inside the
    block and frozen at exit."""

    def __init__(self, registry: Optional[MetricsRegistry]):
        self._registry = registry
        self._before = compile_count(registry)
        self._final: Optional[int] = None

    @property
    def count(self) -> int:
        if self._final is not None:
            return self._final
        return compile_count(self._registry) - self._before


@contextmanager
def compile_watch(registry: Optional[MetricsRegistry] = None):
    """Count backend compiles across a block::

        with compile_watch() as w:
            serve_a_lot()
        assert w.count == 0   # steady state must not recompile

    Installs the probe on entry (idempotent), so the first use in a
    process is also correct."""
    install_compile_probe(registry)
    watch = _CompileWatch(registry)
    try:
        yield watch
    finally:
        watch._final = compile_count(registry) - watch._before

"""Per-app SLO tracking: multi-window error-budget burn rates.

Each app gets a latency/availability objective — a request is *good*
when it succeeded (status < 500) AND finished under the app's latency
threshold. Defaults come from env (`PIO_SLO_LATENCY_MS`, default 250;
`PIO_SLO_TARGET`, default 0.999); per-app overrides live in the
metadata store (`SLOObjectives` DAO, the serving-side sibling of
`TenantQuotas`) and are picked up within the loader TTL.

The tracker keeps 60 one-minute (good, bad) buckets per app — O(1)
memory per app, LRU-bounded app map — and derives burn rates over a
fast (5 m) and a slow (1 h) window:

    burn = bad_fraction(window) / (1 - target)

Burn 1.0 means the error budget is being spent exactly at the rate
that exhausts it at the objective horizon; the classic multiwindow
page threshold is fast-window burn > 14.4 (2% of a 30-day budget in
one hour). Gauges: `pio_slo_burn_rate{app,window}`; `/ready` surfaces
`snapshot()` as a degradation detail without flipping readiness (an
SLO burn is a page, not a reason to pull a replica from rotation).
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, Optional, Tuple

from predictionio_tpu.obs.logs import get_logger
from predictionio_tpu.obs.metrics import MetricsRegistry, get_registry

_log = get_logger("slo")

# fast-window burn rate above which an app's SLO counts as degraded
FAST_BURN_ALERT = 14.4

_WINDOWS = (("5m", 5), ("1h", 60))       # (label, minutes)
_N_BUCKETS = 60


class _AppSLO:
    """One app's minute-bucket rings + resolved objective."""

    __slots__ = ("good", "bad", "minute", "latency_s", "target")

    def __init__(self, latency_s: float, target: float):
        self.good = [0] * _N_BUCKETS
        self.bad = [0] * _N_BUCKETS
        self.minute = 0                   # absolute minute of the cursor
        self.latency_s = latency_s
        self.target = target

    def _advance(self, now_min: int) -> None:
        gap = now_min - self.minute
        if gap <= 0:
            return
        if gap >= _N_BUCKETS:
            self.good = [0] * _N_BUCKETS
            self.bad = [0] * _N_BUCKETS
        else:
            for i in range(self.minute + 1, now_min + 1):
                self.good[i % _N_BUCKETS] = 0
                self.bad[i % _N_BUCKETS] = 0
        self.minute = now_min

    def record(self, now_min: int, ok: bool) -> None:
        self._advance(now_min)
        idx = now_min % _N_BUCKETS
        if ok:
            self.good[idx] += 1
        else:
            self.bad[idx] += 1

    def burn(self, now_min: int, minutes: int) -> float:
        """bad_fraction over the last `minutes` buckets, scaled by the
        error budget (1 - target). 0.0 when the window is empty."""
        self._advance(now_min)
        g = b = 0
        for i in range(minutes):
            idx = (now_min - i) % _N_BUCKETS
            g += self.good[idx]
            b += self.bad[idx]
        total = g + b
        if total <= 0:
            return 0.0
        budget = max(1.0 - self.target, 1e-9)
        return (b / total) / budget


class SLOTracker:
    """Process-wide per-app SLO state; thread-safe; bounded app map."""

    def __init__(self, metrics: Optional[MetricsRegistry] = None,
                 latency_ms: Optional[float] = None,
                 target: Optional[float] = None,
                 loader: Optional[Callable[
                     [], Dict[str, Tuple[Optional[float],
                                         Optional[float]]]]] = None,
                 loader_ttl_s: float = 10.0,
                 max_apps: int = 256):
        env = os.environ

        def _envf(name: str, default: float) -> float:
            try:
                return float(env.get(name, "") or default)
            except ValueError:
                return default

        self.latency_s = (latency_ms if latency_ms is not None
                          else _envf("PIO_SLO_LATENCY_MS", 250.0)) / 1000.0
        self.target = (target if target is not None
                       else _envf("PIO_SLO_TARGET", 0.999))
        self.target = min(max(self.target, 0.0), 0.999999)
        self._loader = loader
        self._loader_ttl_s = loader_ttl_s
        self._overrides: Dict[str, Tuple[Optional[float],
                                         Optional[float]]] = {}
        self._overrides_loaded = 0.0
        metrics = metrics if metrics is not None else get_registry()
        self._burn_gauge = metrics.gauge(
            "pio_slo_burn_rate",
            "Error-budget burn rate per app and window (1.0 = budget "
            "spent exactly at the objective horizon)",
            labels=("app", "window"))
        self._lock = threading.Lock()
        self._apps: "OrderedDict[str, _AppSLO]" = OrderedDict()
        self._max_apps = max(1, int(max_apps))
        self._gauge_synced = 0.0

    # -- objective resolution ------------------------------------------------
    def _refresh_overrides_locked(self, now: float) -> None:
        if self._loader is None:
            return
        if now - self._overrides_loaded < self._loader_ttl_s:
            return
        self._overrides_loaded = now
        try:
            loaded = self._loader()
        except Exception as e:
            _log.warning("slo_overrides_read_failed",
                         error=f"{type(e).__name__}: {e}")
            return
        if loaded is not None:
            self._overrides = dict(loaded)
            for label, (lat_ms, target) in self._overrides.items():
                st = self._apps.get(label)
                if st is not None:
                    st.latency_s = (lat_ms / 1000.0 if lat_ms is not None
                                    else self.latency_s)
                    st.target = (min(max(target, 0.0), 0.999999)
                                 if target is not None else self.target)

    def _app_locked(self, label: str) -> _AppSLO:
        st = self._apps.get(label)
        if st is not None:
            self._apps.move_to_end(label)
            return st
        lat_s, target = self.latency_s, self.target
        ov = self._overrides.get(label)
        if ov is not None:
            if ov[0] is not None:
                lat_s = ov[0] / 1000.0
            if ov[1] is not None:
                target = min(max(ov[1], 0.0), 0.999999)
        st = _AppSLO(lat_s, target)
        self._apps[label] = st
        while len(self._apps) > self._max_apps:
            self._apps.popitem(last=False)
        return st

    # -- recording -----------------------------------------------------------
    def record(self, app: str, duration_s: float, ok: bool,
               now: Optional[float] = None) -> None:
        """Count one request against `app`'s objective. `ok` is the
        availability verdict (False for 5xx/errors); the latency
        threshold is applied here on top."""
        now = time.time() if now is None else now
        now_min = int(now // 60)
        with self._lock:
            self._refresh_overrides_locked(now)
            st = self._app_locked(app or "")
            good = ok and duration_s <= st.latency_s
            st.record(now_min, good)
            sync = now - self._gauge_synced >= 5.0
            if sync:
                self._gauge_synced = now
                rows = [(label, s) for label, s in self._apps.items()]
            else:
                rows = []
        for label, s in rows:
            for wlabel, minutes in _WINDOWS:
                self._burn_gauge.labels(app=label, window=wlabel).set(
                    s.burn(now_min, minutes))

    # -- export --------------------------------------------------------------
    def snapshot(self, now: Optional[float] = None) -> Dict[str, Dict]:
        """Per-app objective + burn rates, for `/ready` detail and the
        dashboard."""
        now = time.time() if now is None else now
        now_min = int(now // 60)
        out: Dict[str, Dict] = {}
        with self._lock:
            items = list(self._apps.items())
        for label, st in items:
            b5 = st.burn(now_min, 5)
            b60 = st.burn(now_min, 60)
            out[label or "(default)"] = {
                "latency_ms": round(st.latency_s * 1000.0, 3),
                "target": st.target,
                "burn_5m": round(b5, 3),
                "burn_1h": round(b60, 3),
                "degraded": b5 > FAST_BURN_ALERT,
            }
        return out

    def degraded(self, now: Optional[float] = None) -> bool:
        """True when any app's fast-window burn is past the page
        threshold — surfaced in `/ready` detail, not in readiness."""
        snap = self.snapshot(now=now)
        return any(v["degraded"] for v in snap.values())


def dao_overrides_loader(registry) -> Optional[Callable[
        [], Dict[str, Tuple[Optional[float], Optional[float]]]]]:
    """Build an overrides loader reading the `SLOObjectives` DAO,
    mapping appid rows to app labels via the `Apps` DAO. None when the
    store exposes no SLO DAO (env defaults apply)."""
    if registry is None:
        return None
    try:
        dao = registry.get_meta_data_slo_objectives()
        apps = registry.get_meta_data_apps()
    except Exception as e:
        _log.warning("slo_dao_unavailable",
                     error=f"{type(e).__name__}: {e}",
                     fallback="env defaults")
        return None

    def _load() -> Dict[str, Tuple[Optional[float], Optional[float]]]:
        rows = dao.get_all()
        if not rows:
            return {}
        names = {a.id: a.name for a in apps.get_all()}
        out: Dict[str, Tuple[Optional[float], Optional[float]]] = {}
        for row in rows:
            label = names.get(row.appid) or f"app-{row.appid}"
            out[label] = (row.latency_ms, row.target)
        return out

    return _load

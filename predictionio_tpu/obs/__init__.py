"""Unified observability layer: metrics, structured logging, tracing.

The standard instrumentation surface for every layer of the stack
(`pio_*` metric families). Servers expose the process-default registry
on `GET /metrics` (Prometheus text format); the HTTP middleware in
`utils.http` emits one structured JSON log line per request with a
propagated request id; the serve chain, event ingestion, and the train
workflow all record into the same registry. Future perf PRs report
through this package instead of ad-hoc prints and time.time() — the
lint gate (`tools.lint`) enforces it in serving/, data/, and core/.
"""

from predictionio_tpu.obs.metrics import (  # noqa: F401
    DEFAULT_BUCKETS, Counter, Gauge, Histogram, MetricsRegistry,
    get_registry,
)
from predictionio_tpu.obs.logs import (  # noqa: F401
    StructuredLogger, get_logger, new_request_id,
)
from predictionio_tpu.obs.jaxprobe import (  # noqa: F401
    compile_count, compile_watch, install_compile_probe,
)
from predictionio_tpu.obs.report import (  # noqa: F401
    record_train_phases, train_report,
)
from predictionio_tpu.obs.trace import (  # noqa: F401
    TRACE_HEADER, PendingTrace, TraceRecorder, get_recorder,
)
from predictionio_tpu.obs.slo import (  # noqa: F401
    SLOTracker, dao_overrides_loader,
)
from predictionio_tpu.obs.quality import (  # noqa: F401
    CanaryGate, CanaryVeto, QualityJoiner, QualityStats,
    QuantileSketch, js_divergence, psi, quality_enabled,
)
from predictionio_tpu.obs.profiler import (  # noqa: F401
    HostSampler, SamplingProfiler, ensure_started, get_profiler,
    install_gc_callbacks, role_of, sample_device_memory,
)
from predictionio_tpu.obs.tsdb import (  # noqa: F401
    Scraper, TSDB, series_key,
)

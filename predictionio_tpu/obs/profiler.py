"""Always-on sampling profiler + runtime telemetry samplers.

A named background thread (`pio-prof-sampler`) wakes `PIO_PROF_HZ`
times per second (default 19 — a prime, so the sampler never phase-
locks with 10ms/100ms periodic work; `0` disables), walks
`sys._current_frames()`, and folds every thread's stack into a
bounded frame-trie. Threads are attributed to *roles* by their name
prefix (the wire names its reactors/workers, serving names its
drainers, the fleet names its heartbeat loops — the lint gate
enforces `name=` on every `threading.Thread` in the package), so
`/profile.json` can answer "what share of CPU samples land in wire
workers vs the batch drainer" without any per-call instrumentation.

Exports, via `HTTPServerBase` on every server:

  - ``GET /profile.json``  — per-role sample shares plus top frames by
    self and cumulative samples;
  - ``GET /profile.txt?fmt=collapsed`` — flamegraph-ready collapsed
    stacks (``role;frame;frame;... count`` per line; pipe into
    ``flamegraph.pl`` or speedscope).

The trie is bounded (`PIO_PROF_MAX_NODES`, default 4096): once the
node budget is spent, deeper frames fold into the deepest allocated
node, so memory stays O(budget) under pathological stack churn while
hot paths (allocated early, sampled often) keep full depth.

Alongside the sampler, this module owns the cheap runtime gauges:
GC pauses via `gc.callbacks` (`pio_gc_pause_seconds{generation}`),
host RSS/CPU/threads from `/proc/self`, and per-device memory from
`jax.Device.memory_stats()` — all sampled on the tsdb scrape tick,
not per-request.
"""

from __future__ import annotations

import gc
import os
import sys
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

from predictionio_tpu.obs.logs import get_logger
from predictionio_tpu.obs.metrics import MetricsRegistry, get_registry

_log = get_logger("profiler")

DEFAULT_HZ = 19.0
DEFAULT_MAX_NODES = 4096

# thread-name prefix -> role, first match wins (order matters:
# "wire-reactor-" before the generic "wire-" worker catch-all)
_ROLE_PREFIXES: Tuple[Tuple[str, str], ...] = (
    ("wire-reactor-", "reactor"),
    ("wire-", "worker"),
    ("pio-batch-drain", "drainer"),
    ("pio-feedback-drain", "drainer"),
    ("pio-plugin-drain", "drainer"),
    ("pio-refresher", "refresher"),
    ("pio-fleet-", "heartbeat"),
    ("pio-replica-agent", "heartbeat"),
    ("pio-heartbeat-", "heartbeat"),
    ("pio-fsck-sched", "heartbeat"),
    ("pio-quality-join", "joiner"),
    ("pio-prof", "obs"),
    ("pio-tsdb", "obs"),
    ("pio-watchdog", "obs"),
    ("pio-supervisor", "supervisor"),
    ("pio-http-serve", "http"),
    ("MainThread", "main"),
)


def role_of(thread_name: str) -> str:
    """Map a thread name to its serving role (see _ROLE_PREFIXES);
    unrecognized names — test harness threads, user code — are
    "other"."""
    for prefix, role in _ROLE_PREFIXES:
        if thread_name.startswith(prefix):
            return role
    return "other"


def format_thread_stack(ident: int, limit: int = 40) -> str:
    """One thread's current stack as a compact one-line string
    (`mod:func:line < mod:func:line < ...`, innermost first) from the
    same `sys._current_frames()` walk the sampler folds — the
    watchdog's stall dump. Empty string when the thread is gone."""
    frame = sys._current_frames().get(ident)
    if frame is None:
        return ""
    parts: List[str] = []
    f = frame
    while f is not None and len(parts) < limit:
        code = f.f_code
        mod = code.co_filename.rsplit("/", 1)[-1]
        parts.append(f"{mod}:{code.co_name}:{f.f_lineno}")
        f = f.f_back
    return " < ".join(parts)


def _envf(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class _Node:
    """One frame-trie node: children keyed by "module:function" and
    the count of samples whose stack ended exactly here."""

    __slots__ = ("children", "ended")

    def __init__(self):
        self.children: Dict[str, "_Node"] = {}
        self.ended = 0


class SamplingProfiler:
    """Bounded folded-stack sampler over `sys._current_frames()`.

    Directly instantiable for tests; the process-global instance
    (one sampler sees every thread, so per-server instances would
    multiply the overhead for identical data) comes from
    `ensure_started()`.
    """

    def __init__(self, hz: Optional[float] = None,
                 max_nodes: Optional[int] = None):
        self.hz = _envf("PIO_PROF_HZ", DEFAULT_HZ) if hz is None else hz
        self.max_nodes = int(
            _envf("PIO_PROF_MAX_NODES", DEFAULT_MAX_NODES)
            if max_nodes is None else max_nodes)
        self.max_nodes = max(16, self.max_nodes)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # per-role trie roots; role itself is the first collapsed segment
        self._roots: Dict[str, _Node] = {}
        self._nodes = 0              # allocated trie nodes across roles
        self._truncated = 0          # samples folded at the node budget
        self._self_counts: Dict[str, int] = {}   # innermost frame
        self._cum_counts: Dict[str, int] = {}    # anywhere on stack
        self._role_samples: Dict[str, int] = {}
        self._samples = 0            # thread-samples folded
        self._ticks = 0              # sampler wakeups
        self._started_at = 0.0

    # -- lifecycle -----------------------------------------------------------
    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def start(self) -> bool:
        """Spawn the sampler thread; False (and no thread) when hz<=0
        — hooks stay installed, the loop simply never exists, so
        `PIO_PROF_HZ=0` is zero-overhead."""
        if self.hz <= 0 or self.running:
            return False
        self._stop.clear()
        self._started_at = time.time()
        self._thread = threading.Thread(
            target=self._run, name="pio-prof-sampler", daemon=True)
        self._thread.start()
        return True

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2.0)

    def _run(self) -> None:
        interval = 1.0 / self.hz
        me = threading.get_ident()
        while not self._stop.wait(interval):
            try:
                self.sample_once(skip_ident=me)
            except Exception as e:     # never kill the sampler loop
                _log.warning("prof_sample_failed",
                             error=f"{type(e).__name__}: {e}")

    # -- sampling ------------------------------------------------------------
    def sample_once(self, skip_ident: Optional[int] = None) -> int:
        """Fold one sample of every live thread's stack; returns the
        number of threads folded. Public so tests can drive the fold
        deterministically without a live sampler thread."""
        names = {t.ident: t.name for t in threading.enumerate()}
        frames = sys._current_frames()
        folded = 0
        with self._lock:
            self._ticks += 1
            for ident, frame in frames.items():
                if ident == skip_ident:
                    continue
                role = role_of(names.get(ident, ""))
                stack: List[str] = []
                f = frame
                while f is not None:
                    code = f.f_code
                    mod = code.co_filename.rsplit("/", 1)[-1]
                    stack.append(f"{mod}:{code.co_name}")
                    f = f.f_back
                stack.reverse()        # outermost first, flamegraph order
                self._fold_locked(role, stack)
                folded += 1
            self._samples += folded
        return folded

    def _fold_locked(self, role: str, stack: List[str]) -> None:
        self._role_samples[role] = self._role_samples.get(role, 0) + 1
        if not stack:
            return
        node = self._roots.get(role)
        if node is None:
            if self._nodes >= self.max_nodes:   # budget covers roots too
                self._truncated += 1
                return
            node = self._roots[role] = _Node()
            self._nodes += 1
        truncated = False
        for key in stack:
            child = node.children.get(key)
            if child is None:
                if self._nodes >= self.max_nodes:
                    truncated = True
                    break
                child = node.children[key] = _Node()
                self._nodes += 1
            node = child
        if truncated:
            self._truncated += 1
        node.ended += 1
        innermost = stack[-1]
        self._self_counts[innermost] = self._self_counts.get(
            innermost, 0) + 1
        for key in set(stack):
            self._cum_counts[key] = self._cum_counts.get(key, 0) + 1

    # -- export --------------------------------------------------------------
    def snapshot_json(self, top: int = 30) -> Dict:
        """Shape served at /profile.json: role shares + top frames."""
        with self._lock:
            samples = self._samples
            roles = dict(self._role_samples)
            self_top = sorted(self._self_counts.items(),
                              key=lambda kv: -kv[1])[:top]
            cum_top = sorted(self._cum_counts.items(),
                             key=lambda kv: -kv[1])[:top]
            nodes, truncated = self._nodes, self._truncated
            ticks = self._ticks
        denom = float(samples) or 1.0

        def _frames(pairs: Iterable[Tuple[str, int]]) -> List[Dict]:
            return [{"frame": k, "samples": v,
                     "share": round(v / denom, 4)} for k, v in pairs]

        return {
            "hz": self.hz,
            "running": self.running,
            "ticks": ticks,
            "samples": samples,
            "since": self._started_at,
            "roles": {r: {"samples": n, "share": round(n / denom, 4)}
                      for r, n in sorted(roles.items(),
                                         key=lambda kv: -kv[1])},
            "top_self": _frames(self_top),
            "top_cumulative": _frames(cum_top),
            "trie": {"nodes": nodes, "max_nodes": self.max_nodes,
                     "truncated_samples": truncated},
        }

    def collapsed(self) -> str:
        """Flamegraph collapsed-stack format, one line per unique
        path: ``role;frame;frame;... count``."""
        lines: List[str] = []
        with self._lock:
            for role in sorted(self._roots):
                stack = [(self._roots[role], role)]
                while stack:
                    node, path = stack.pop()
                    if node.ended:
                        lines.append(f"{path} {node.ended}")
                    for key in sorted(node.children):
                        stack.append((node.children[key],
                                      f"{path};{key}"))
        return "\n".join(lines) + ("\n" if lines else "")

    def reset(self) -> None:
        with self._lock:
            self._roots.clear()
            self._nodes = 0
            self._truncated = 0
            self._self_counts.clear()
            self._cum_counts.clear()
            self._role_samples.clear()
            self._samples = 0
            self._ticks = 0


# -- process-global sampler ---------------------------------------------------
_global_lock = threading.Lock()
_global_profiler: Optional[SamplingProfiler] = None


def get_profiler() -> SamplingProfiler:
    """The process-global sampler (created from env knobs on first
    use; NOT started — see ensure_started)."""
    global _global_profiler
    with _global_lock:
        if _global_profiler is None:
            _global_profiler = SamplingProfiler()
        return _global_profiler


def ensure_started() -> SamplingProfiler:
    """Idempotently start the process-global sampler. With
    PIO_PROF_HZ=0 the instance exists (endpoints keep serving an
    empty profile) but no thread runs."""
    prof = get_profiler()
    if not prof.running:
        prof.start()
    return prof


def _reset_global_for_tests() -> None:
    global _global_profiler
    with _global_lock:
        prof, _global_profiler = _global_profiler, None
    if prof is not None:
        prof.stop()


# -- GC pause hook ------------------------------------------------------------
_gc_lock = threading.Lock()
_gc_registries: set = set()          # id() of registries already hooked
_gc_start_ns = 0


def install_gc_callbacks(metrics: Optional[MetricsRegistry] = None) -> bool:
    """Install a `gc.callbacks` hook observing every collection's
    wall time into `pio_gc_pause_seconds{generation}`. Idempotent per
    registry (one hook feeds one registry; a test registry gets its
    own). Returns True on install, False for already-installed."""
    metrics = metrics if metrics is not None else get_registry()
    hist = metrics.histogram(
        "pio_gc_pause_seconds",
        "Stop-the-world GC collection pauses by generation",
        buckets=(0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5),
        labels=("generation",))
    with _gc_lock:
        if id(metrics) in _gc_registries:
            return False
        _gc_registries.add(id(metrics))

    def _on_gc(phase: str, info: Dict) -> None:
        # CPython runs collections (and hence callbacks) under a
        # per-interpreter guard, so one start slot suffices
        global _gc_start_ns
        if phase == "start":
            _gc_start_ns = time.perf_counter_ns()
        elif phase == "stop" and _gc_start_ns:
            dt = (time.perf_counter_ns() - _gc_start_ns) / 1e9
            hist.labels(generation=str(info.get("generation", "?"))
                        ).observe(dt)

    gc.callbacks.append(_on_gc)
    return True


# -- host /proc sampler -------------------------------------------------------
class HostSampler:
    """RSS / CPU seconds / thread count from `/proc/self`, set on the
    tsdb tick. CPU is a monotone counter advanced by delta."""

    def __init__(self, metrics: Optional[MetricsRegistry] = None):
        m = metrics if metrics is not None else get_registry()
        self._rss = m.gauge("pio_host_rss_bytes",
                            "Resident set size of this process")
        self._threads = m.gauge("pio_host_threads",
                                "Live threads in this process")
        self._cpu = m.counter("pio_host_cpu_seconds_total",
                              "Process CPU time (user+system)")
        self._page = os.sysconf("SC_PAGE_SIZE")
        self._tick = float(os.sysconf("SC_CLK_TCK")) or 100.0
        self._last_cpu = 0.0

    def sample(self) -> None:
        try:
            with open("/proc/self/statm", "rb") as fh:
                self._rss.set(int(fh.read().split()[1]) * self._page)
            with open("/proc/self/stat", "rb") as fh:
                raw = fh.read()
            # field 2 is "(comm)" and may contain spaces: split after
            # the closing paren, stat fields 14/15 are utime/stime and
            # 20 is num_threads (1-indexed in proc(5))
            fields = raw[raw.rindex(b")") + 2:].split()
            cpu = (int(fields[11]) + int(fields[12])) / self._tick
            self._threads.set(int(fields[17]))
            if cpu > self._last_cpu:
                self._cpu.inc(cpu - self._last_cpu)
            self._last_cpu = cpu
        except (OSError, ValueError, IndexError):
            pass                      # non-procfs hosts: gauges stay 0


def sample_device_memory(metrics: Optional[MetricsRegistry] = None) -> int:
    """Per-device allocator stats into
    `pio_device_memory_bytes{device,kind}` (kind: in_use / peak).
    Returns the number of devices sampled; 0 when jax is unavailable
    or the backend exposes no memory_stats (CPU)."""
    m = metrics if metrics is not None else get_registry()
    try:
        import jax
        devices = jax.devices()
    except Exception:
        return 0
    gauge = m.gauge("pio_device_memory_bytes",
                    "Device allocator bytes by device and kind",
                    labels=("device", "kind"))
    sampled = 0
    for d in devices:
        try:
            stats = d.memory_stats() or {}
        except Exception:
            stats = {}
        if not stats:
            continue
        dev = f"{d.platform}:{d.id}"
        for kind, key in (("in_use", "bytes_in_use"),
                          ("peak", "peak_bytes_in_use")):
            if key in stats:
                gauge.labels(device=dev, kind=kind).set(
                    float(stats[key]))
        sampled += 1
    return sampled

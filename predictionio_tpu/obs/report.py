"""Train-phase metric recording and the `pio train` timing report.

`CoreWorkflow.run_train` records each phase wall time (read / prepare /
per-algorithm train) into the process-default metrics registry; the CLI
then prints a human-readable per-phase report SOURCED FROM that registry
— the same numbers a scraper would see on /metrics — alongside the JAX
backend-compile count from the compile probe ([[jaxprobe]]).
"""

from __future__ import annotations

from typing import Mapping, Optional

from predictionio_tpu.obs.metrics import MetricsRegistry, get_registry

TRAIN_BUCKETS = (0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 15.0, 60.0, 300.0,
                 1800.0, 7200.0)


def record_train_phases(phase_timings: Mapping[str, float],
                        registry: Optional[MetricsRegistry] = None) -> None:
    """Record a train run's per-phase wall seconds (keys like 'read_s',
    'prepare_s', 'train_algo0_s') into the registry."""
    reg = registry or get_registry()
    hist = reg.histogram(
        "pio_train_phase_seconds", "Training phase wall time per run",
        labels=("phase",), buckets=TRAIN_BUCKETS)
    for key, secs in phase_timings.items():
        phase = key[:-2] if key.endswith("_s") else key
        hist.labels(phase=phase).observe(float(secs))


def train_report(registry: Optional[MetricsRegistry] = None) -> str:
    """Per-phase timing report rendered from the metrics registry."""
    reg = registry or get_registry()
    snap = reg.snapshot()
    lines = ["Training phase report (from the metrics registry):"]
    fam = snap.get("pio_train_phase_seconds")
    if fam and fam["series"]:
        for s in fam["series"]:
            phase = s["labels"].get("phase", "?")
            lines.append(f"  {phase:<20} {s['sum']:9.3f}s"
                         f"  (runs: {s['count']})")
    else:
        lines.append("  (no training phases recorded)")
    compiles = snap.get("pio_jax_backend_compiles_total")
    if compiles and compiles["series"]:
        n = int(compiles["series"][0]["value"])
        secs = 0.0
        durations = snap.get("pio_jax_backend_compile_seconds")
        if durations and durations["series"]:
            secs = durations["series"][0]["sum"]
        lines.append(f"  jax_backend_compiles {n:9d}   ({secs:.3f}s "
                     "in the XLA compiler)")
    return "\n".join(lines)

"""Prediction-quality observatory: sketches, drift, reward, canary.

Three instruments that watch whether the *predictions* are any good —
the latency/saturation/burn side is PR-12/PR-14 territory:

- `QualityStats` rides the serve hot path and feeds per-app,
  allocation-light accumulators: bounded mergeable quantile sketches
  (a KLL-style compactor cascade) of the top-1 score and the top-k
  score margin, plus minute-ring empty-result and unknown-entity
  (cold-start) ratios. At every successful deploy/reload the live
  sketch is frozen into a fixed-bin reference histogram; subsequent
  traffic is binned against it and exported as multi-window drift
  gauges (`pio_pred_drift{app,metric,window}`, PSI and Jensen-Shannon
  vs the reference) shaped like the SLO burn windows.

- `QualityJoiner` is a background loop (same pacing discipline as the
  streaming refresher) that joins feedback events back to served
  predictions by the exact `prId` the server stamps onto posted
  feedback, within a configurable attribution window — yielding
  `pio_pred_reward_rate{app}`, join lag, and the unjoined ratio from
  the feedback loop that already writes events but that nothing read.

- `CanaryGate` replays a sample of recently-kept traced queries (the
  PR-12 trace ring) against the old and the new plans during a reload,
  reports top-k overlap and top-1 score delta
  (`pio_canary_overlap{app}`), and — when `PIO_CANARY_MIN_OVERLAP` is
  set — vetoes the swap through the existing load-failed abort path.

Everything exports through the process metrics registry, so the tsdb
ring, `/federate`, `/metrics.html`, and `/fleet.html` pick the new
families up with zero extra wiring; `/quality.json` serves the raw
snapshot. The hot-path entry point (`observe_result`) honours the
hot-route lint rules: stamp-only style, no dict churn, and the per-app
maps are LRU-bounded (enforced by the app-keyed lint rule).

Env knobs: `PIO_QUALITY` (default on), `PIO_QUALITY_SKETCH_K`
(compactor width, default 128), `PIO_ATTRIBUTION_S` (join window,
default 300), `PIO_CANARY_SAMPLE` (replayed queries per reload,
default 16), `PIO_CANARY_MIN_OVERLAP` (abort threshold, default 0 =
report-only).
"""

from __future__ import annotations

import bisect
import math
import os
import random
import threading
import time
from collections import OrderedDict
from datetime import datetime, timedelta, timezone
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from predictionio_tpu.obs import trace
from predictionio_tpu.obs.logs import get_logger
from predictionio_tpu.obs.metrics import MetricsRegistry, get_registry

_log = get_logger(__name__)

# drift reference histograms: deciles of the frozen sketch
_N_BINS = 10
# multi-window drift, shaped like the SLO burn windows (obs/slo.py)
_WINDOWS = (("5m", 5), ("1h", 60))
_N_BUCKETS = 60                 # minute ring depth == longest window
_REF_MIN_N = 50                 # auto-freeze once this many samples land
_BUF_MAX = 16384                # observation-buffer backstop before a
                                # hot-path fold (gauge sync folds every
                                # 5 s long before this at sane qps)
_DEFAULT_SKETCH_K = 128
_DEFAULT_ATTRIBUTION_S = 300.0
_DEFAULT_CANARY_SAMPLE = 16
_MAX_PENDING = 4096             # joiner's in-flight prId cap


# -- env knobs ----------------------------------------------------------------

def quality_enabled() -> bool:
    v = os.environ.get("PIO_QUALITY", "").strip().lower()
    return v not in ("off", "0", "false", "no")


def sketch_k() -> int:
    try:
        return max(8, int(os.environ.get("PIO_QUALITY_SKETCH_K", "")
                          or _DEFAULT_SKETCH_K))
    except ValueError:
        return _DEFAULT_SKETCH_K


def default_attribution_s() -> float:
    try:
        return float(os.environ.get("PIO_ATTRIBUTION_S", "")
                     or _DEFAULT_ATTRIBUTION_S)
    except ValueError:
        return _DEFAULT_ATTRIBUTION_S


def canary_sample() -> int:
    try:
        return int(os.environ.get("PIO_CANARY_SAMPLE", "")
                   or _DEFAULT_CANARY_SAMPLE)
    except ValueError:
        return _DEFAULT_CANARY_SAMPLE


def canary_min_overlap() -> float:
    try:
        return float(os.environ.get("PIO_CANARY_MIN_OVERLAP", "") or 0.0)
    except ValueError:
        return 0.0


# -- mergeable quantile sketch ------------------------------------------------

class QuantileSketch:
    """Bounded mergeable quantile sketch (KLL-style compactor cascade).

    Level `i` holds values of weight `2**i`; when a level fills to `k`
    items it is sorted and every other item (random offset) is promoted
    to the next level. Odd-length buffers keep their maximum behind as
    a leftover so total weight is preserved exactly. Memory is
    O(k log(n/k)) regardless of the stream length, and two sketches
    merge by concatenating levels and re-compacting — merge order only
    changes which random halves survive, not the error bound.
    """

    __slots__ = ("k", "levels", "n", "vmin", "vmax", "_rng")

    def __init__(self, k: Optional[int] = None,
                 rng: Optional[random.Random] = None):
        self.k = max(8, int(k if k is not None else sketch_k()))
        self.levels: List[List[float]] = [[]]
        self.n = 0
        self.vmin = math.inf
        self.vmax = -math.inf
        self._rng = rng if rng is not None else random.Random()

    def update(self, v: float) -> None:
        v = float(v)
        self.n += 1
        if v < self.vmin:
            self.vmin = v
        if v > self.vmax:
            self.vmax = v
        buf = self.levels[0]
        buf.append(v)
        if len(buf) >= self.k:
            self._compact(0)

    def _compact(self, lvl: int) -> None:
        while lvl < len(self.levels) and len(self.levels[lvl]) >= self.k:
            buf = self.levels[lvl]
            buf.sort()
            leftover = [buf.pop()] if len(buf) % 2 else []
            promoted = buf[self._rng.randrange(2)::2]
            self.levels[lvl] = leftover
            if lvl + 1 == len(self.levels):
                self.levels.append([])
            self.levels[lvl + 1].extend(promoted)
            lvl += 1

    def merge(self, other: "QuantileSketch") -> "QuantileSketch":
        self.n += other.n
        self.vmin = min(self.vmin, other.vmin)
        self.vmax = max(self.vmax, other.vmax)
        while len(self.levels) < len(other.levels):
            self.levels.append([])
        for i, buf in enumerate(other.levels):
            self.levels[i].extend(buf)
        for i in range(len(self.levels)):
            if len(self.levels[i]) >= self.k:
                self._compact(i)
        return self

    def _weighted(self) -> List[Tuple[float, int]]:
        pairs: List[Tuple[float, int]] = []
        for lvl, buf in enumerate(self.levels):
            w = 1 << lvl
            for v in buf:
                pairs.append((v, w))
        pairs.sort()
        return pairs

    def quantile(self, q: float) -> Optional[float]:
        """Approximate q-quantile; None on an empty sketch. Exact at
        the extremes (vmin/vmax are tracked outside the cascade)."""
        pairs = self._weighted()
        if not pairs:
            return None
        if q <= 0.0:
            return self.vmin
        if q >= 1.0:
            return self.vmax
        total = sum(w for _, w in pairs)
        target = q * total
        acc = 0
        for v, w in pairs:
            acc += w
            if acc >= target:
                return min(max(v, self.vmin), self.vmax)
        return self.vmax

    def cdf(self, x: float) -> float:
        """Approximate P(value <= x); 0.0 on an empty sketch."""
        total = 0
        le = 0
        for lvl, buf in enumerate(self.levels):
            w = 1 << lvl
            for v in buf:
                total += w
                if v <= x:
                    le += w
        return le / total if total else 0.0


# -- drift math ---------------------------------------------------------------

def _probs(counts: Sequence[float], eps: float = 1e-4) -> List[float]:
    """Counts/probs -> probability vector with an epsilon floor (both
    PSI and KL blow up on empty bins) re-normalised to sum to 1. An
    all-zero vector degrades to uniform."""
    n = len(counts)
    if n == 0:
        return []
    total = float(sum(counts))
    if total <= 0.0:
        return [1.0 / n] * n
    p = [max(c / total, eps) for c in counts]
    s = sum(p)
    return [x / s for x in p]


def psi(expected: Sequence[float], actual: Sequence[float]) -> float:
    """Population stability index: sum((a - e) * ln(a / e)). >= 0;
    the classic operating bands are ~0.1 (watch) and ~0.25 (act)."""
    p = _probs(expected)
    q = _probs(actual)
    return sum((b - a) * math.log(b / a) for a, b in zip(p, q))


def js_divergence(p_counts: Sequence[float],
                  q_counts: Sequence[float]) -> float:
    """Jensen-Shannon divergence, base 2: symmetric, bounded [0, 1]."""
    p = _probs(p_counts)
    q = _probs(q_counts)
    m = [(a + b) / 2.0 for a, b in zip(p, q)]

    def _kl(a: List[float], b: List[float]) -> float:
        return sum(x * math.log2(x / y) for x, y in zip(a, b) if x > 0)

    return 0.5 * _kl(p, m) + 0.5 * _kl(q, m)


class _DriftState:
    """A reference histogram frozen from a sketch + a minute ring of
    per-bin live counts. Bin edges are the reference deciles; bin `i`
    is `(edge[i-1], edge[i]]`, matching the sketch's cdf convention
    (`bisect_left` => v lands in the first bin whose edge is >= v)."""

    __slots__ = ("edges", "ref_probs", "frozen_at", "ref_n",
                 "_buckets", "_cursor")

    def __init__(self, sketch: QuantileSketch, now_min: int):
        edges: List[float] = []
        for i in range(1, _N_BINS):
            v = sketch.quantile(i / _N_BINS)
            if v is not None and (not edges or v > edges[-1]):
                edges.append(v)
        if not edges:
            # constant reference: one edge, two bins (<= v, > v)
            v = sketch.quantile(0.5)
            edges = [v if v is not None else 0.0]
        self.edges = edges
        probs: List[float] = []
        prev = 0.0
        for e in edges:
            c = sketch.cdf(e)
            probs.append(max(c - prev, 0.0))
            prev = c
        probs.append(max(1.0 - prev, 0.0))
        self.ref_probs = probs
        self.frozen_at = time.time()
        self.ref_n = sketch.n
        nb = len(edges) + 1
        self._buckets = [[0] * nb for _ in range(_N_BUCKETS)]
        self._cursor = now_min

    def _advance(self, now_min: int) -> None:
        gap = now_min - self._cursor
        if gap <= 0:
            return
        if gap >= _N_BUCKETS:
            for b in self._buckets:
                for i in range(len(b)):
                    b[i] = 0
        else:
            for j in range(1, gap + 1):
                b = self._buckets[(self._cursor + j) % _N_BUCKETS]
                for i in range(len(b)):
                    b[i] = 0
        self._cursor = now_min

    def observe(self, v: float, now_min: int) -> None:
        self._advance(now_min)
        idx = bisect.bisect_left(self.edges, v)
        self._buckets[now_min % _N_BUCKETS][idx] += 1

    def window_counts(self, now_min: int, minutes: int) -> List[int]:
        self._advance(now_min)
        nb = len(self.edges) + 1
        counts = [0] * nb
        for j in range(minutes):
            b = self._buckets[(now_min - j) % _N_BUCKETS]
            for i in range(nb):
                counts[i] += b[i]
        return counts

    def drift(self, now_min: int, minutes: int) -> Tuple[float, float]:
        """(PSI, JS) of the live window vs the reference; (0, 0) when
        the window is empty — no traffic is not drift."""
        counts = self.window_counts(now_min, minutes)
        if sum(counts) == 0:
            return (0.0, 0.0)
        return (psi(self.ref_probs, counts),
                js_divergence(self.ref_probs, counts))


# -- per-app accumulator ------------------------------------------------------

class _AppQuality:
    """All quality state for one app label: live sketches, the frozen
    drift references, and minute rings of result-shape counters."""

    __slots__ = ("sk_top1", "sk_margin", "d_top1", "d_margin",
                 "ring_n", "ring_empty", "ring_unknown", "_cursor",
                 "n_total", "empty_total", "unknown_total",
                 "pending_freeze", "_k")

    def __init__(self, k: int, now_min: int):
        self._k = k
        self.sk_top1 = QuantileSketch(k)
        self.sk_margin = QuantileSketch(k)
        self.d_top1: Optional[_DriftState] = None
        self.d_margin: Optional[_DriftState] = None
        self.ring_n = [0] * _N_BUCKETS
        self.ring_empty = [0] * _N_BUCKETS
        self.ring_unknown = [0] * _N_BUCKETS
        self._cursor = now_min
        self.n_total = 0
        self.empty_total = 0
        self.unknown_total = 0
        # first reference freezes itself once enough samples land, so
        # a server that never reloads still gets drift gauges
        self.pending_freeze = True

    def _advance(self, now_min: int) -> None:
        gap = now_min - self._cursor
        if gap <= 0:
            return
        if gap >= _N_BUCKETS:
            for i in range(_N_BUCKETS):
                self.ring_n[i] = 0
                self.ring_empty[i] = 0
                self.ring_unknown[i] = 0
        else:
            for j in range(1, gap + 1):
                i = (self._cursor + j) % _N_BUCKETS
                self.ring_n[i] = 0
                self.ring_empty[i] = 0
                self.ring_unknown[i] = 0
        self._cursor = now_min

    def observe(self, top1: Optional[float], margin: Optional[float],
                empty: bool, unknown: bool, now_min: int) -> None:
        self._advance(now_min)
        i = now_min % _N_BUCKETS
        self.ring_n[i] += 1
        self.n_total += 1
        if empty:
            self.ring_empty[i] += 1
            self.empty_total += 1
        if unknown:
            self.ring_unknown[i] += 1
            self.unknown_total += 1
        if top1 is not None:
            self.sk_top1.update(top1)
            if self.d_top1 is not None:
                self.d_top1.observe(top1, now_min)
        if margin is not None:
            self.sk_margin.update(margin)
            if self.d_margin is not None:
                self.d_margin.observe(margin, now_min)
        if self.pending_freeze and self.sk_top1.n >= _REF_MIN_N:
            self.freeze(now_min)

    def freeze(self, now_min: int) -> None:
        """Snapshot the live sketches into drift references and start a
        fresh live window (called at each successful deploy/reload). An
        empty live sketch keeps the previous reference — no traffic
        since the last freeze is not a new baseline."""
        if self.sk_top1.n > 0:
            self.d_top1 = _DriftState(self.sk_top1, now_min)
            self.sk_top1 = QuantileSketch(self._k)
        if self.sk_margin.n > 0:
            self.d_margin = _DriftState(self.sk_margin, now_min)
            self.sk_margin = QuantileSketch(self._k)
        self.pending_freeze = False

    def ratios(self, now_min: int, minutes: int) -> Tuple[float, float]:
        self._advance(now_min)
        n = e = u = 0
        for j in range(minutes):
            i = (now_min - j) % _N_BUCKETS
            n += self.ring_n[i]
            e += self.ring_empty[i]
            u += self.ring_unknown[i]
        if n == 0:
            return (0.0, 0.0)
        return (e / n, u / n)


# -- the serve-path accumulator front end -------------------------------------

class QualityStats:
    """Per-app quality accumulators + drift gauges, LRU-bounded.

    `observe_result` is the hot-path entry point (covered by the
    hot-route lint rules): it extracts the scores while the result
    object is still cache-warm and appends ONE tuple to the
    observation buffer — `list.append` is atomic under the GIL, so the
    hot path takes NO lock. (A per-request lock convoys badly on a
    saturated small host: a holder preempted inside even a tiny
    critical section stalls every serve thread for a scheduling
    quantum.) The sketch/ring fold runs under the lock but only from
    the read paths — gauge sync (once per 5 s), snapshots, reference
    freezes, and a `_BUF_MAX` backstop — so the cold walk over the
    accumulator structures is amortised over thousands of requests,
    and nothing contends with a long-held lock. Every read path folds
    first, so snapshots and gauge syncs always see every observation.
    Zero dict literals on the hot path."""

    def __init__(self, metrics: Optional[MetricsRegistry] = None,
                 max_apps: int = 64, k: Optional[int] = None):
        reg = metrics if metrics is not None else get_registry()
        self._lock = threading.Lock()
        self._apps: "OrderedDict[str, _AppQuality]" = OrderedDict()
        self._max_apps = max_apps
        # single-entry hot cache over _apps: the single-tenant serve
        # path (the common case) hits it every call and never walks
        # the LRU dict; invalidated on eviction
        self._last_app: Optional[str] = None
        self._last_st: Optional[_AppQuality] = None
        self._buf: List[tuple] = []
        self._k = max(8, int(k if k is not None else sketch_k()))
        self._gauge_synced = 0.0
        self._g_drift = reg.gauge(
            "pio_pred_drift",
            "prediction-score drift vs the deploy-time reference "
            "(PSI / Jensen-Shannon), per window",
            labels=("app", "metric", "window"))
        self._g_ratio = reg.gauge(
            "pio_pred_ratio",
            "result-shape ratios (empty results, unknown entities), "
            "per window", labels=("app", "kind", "window"))

    def observe_result(self, app, result, user, user_maps):
        """Stamp one served result into the app's accumulators. Hot
        path: bounded work, no allocation beyond sketch appends."""
        iss = getattr(result, "itemScores", None)
        if iss is None:
            iss = ()
        n = len(iss)
        top1 = iss[0].score if n else None
        margin = iss[0].score - iss[1].score if n >= 2 else None
        unknown = False
        if user is not None and user_maps:
            unknown = True
            for um in user_maps:
                if um.get(user) is not None:
                    unknown = False
                    break
        now = time.time()
        # lock-free: a single GIL-atomic append; the fold happens off
        # the hot path (gauge sync / snapshot / backstop)
        self._buf.append((app, top1, margin, n == 0, unknown,
                          int(now // 60.0)))
        if len(self._buf) >= _BUF_MAX:
            with self._lock:
                self._fold_locked()
        if now - self._gauge_synced >= 5.0:
            self._sync_gauges(now, int(now // 60.0))

    def _fold_locked(self) -> None:
        """Drain the observation buffer into the per-app accumulators
        (caller holds the lock). One cold walk over the sketch/ring
        structures serves the whole batch. The buffer is drained by
        index — slice, then `del buf[:n]` — both atomic under the GIL,
        so concurrent lock-free appends land behind the drained prefix
        and are never lost."""
        buf = self._buf
        n = len(buf)
        if n == 0:
            return
        items = buf[:n]
        del buf[:n]
        for app, top1, margin, empty, unknown, now_min in items:
            if app == self._last_app:
                st = self._last_st
            else:
                # cache switch: the outgoing app was hot until now —
                # refresh its LRU recency before anything can evict it
                if self._last_app is not None:
                    self._apps.move_to_end(self._last_app)
                st = self._apps.get(app)
                if st is None:
                    if len(self._apps) >= self._max_apps:
                        evicted, _ = self._apps.popitem(last=False)
                        if evicted == self._last_app:
                            self._last_app = None
                            self._last_st = None
                    st = _AppQuality(self._k, now_min)
                    self._apps[app] = st    # lint: ok (LRU-evicted above)
                else:
                    self._apps.move_to_end(app)
                self._last_app = app
                self._last_st = st
            st.observe(top1, margin, empty, unknown, now_min)

    def _sync_gauges(self, now: float, now_min: int) -> None:
        drift_rows = []
        ratio_rows = []
        with self._lock:
            if now - self._gauge_synced < 5.0:
                return
            self._gauge_synced = now
            self._fold_locked()
            for app, st in self._apps.items():
                for wname, minutes in _WINDOWS:
                    er, ur = st.ratios(now_min, minutes)
                    ratio_rows.append((app, "empty", wname, er))
                    ratio_rows.append((app, "unknown", wname, ur))
                    for mname, d in (("top1", st.d_top1),
                                     ("margin", st.d_margin)):
                        if d is None:
                            continue
                        p, j = d.drift(now_min, minutes)
                        drift_rows.append(
                            (app, mname + "_psi", wname, p))
                        drift_rows.append(
                            (app, mname + "_js", wname, j))
        # gauges set outside the lock (the SLO tracker discipline)
        for app, kind, wname, v in ratio_rows:
            self._g_ratio.labels(app=app, kind=kind, window=wname).set(v)
        for app, metric, wname, v in drift_rows:
            self._g_drift.labels(app=app, metric=metric,
                                 window=wname).set(v)

    def freeze_reference(self) -> None:
        """Refreeze every app's reference window (successful reload)."""
        now_min = int(time.time() // 60.0)
        with self._lock:
            self._fold_locked()
            for st in self._apps.values():
                st.freeze(now_min)

    def trim(self) -> int:
        """Soft-memory-pressure hook: drop every per-app accumulator
        (sketches, drift references, minute rings) and the pending
        observation buffer; they rebuild from live traffic. Returns
        the approximate bytes released."""
        with self._lock:
            freed = len(self._buf) * 96
            # sketches + rings + drift refs per app: coarse estimate
            freed += len(self._apps) * (self._k * 2 * 8 + _N_BUCKETS * 72)
            self._buf = []
            self._apps.clear()
            self._last_app = None
            self._last_st = None
        return freed

    def snapshot(self) -> Dict:
        """The `/quality.json` app section."""
        now = time.time()
        now_min = int(now // 60.0)
        out: Dict[str, Dict] = {}
        with self._lock:
            self._fold_locked()
            for app, st in self._apps.items():
                windows = {}
                for wname, minutes in _WINDOWS:
                    er, ur = st.ratios(now_min, minutes)
                    w = {"empty_ratio": er, "unknown_ratio": ur}
                    for mname, d in (("top1", st.d_top1),
                                     ("margin", st.d_margin)):
                        if d is None:
                            continue
                        p, j = d.drift(now_min, minutes)
                        w[mname + "_psi"] = p
                        w[mname + "_js"] = j
                    windows[wname] = w
                quant = {}
                for label, sk in (("top1", st.sk_top1),
                                  ("margin", st.sk_margin)):
                    if sk.n == 0:
                        continue
                    quant[label] = {
                        "n": sk.n,
                        "p50": sk.quantile(0.5),
                        "p90": sk.quantile(0.9),
                        "p99": sk.quantile(0.99),
                        "min": sk.vmin,
                        "max": sk.vmax,
                    }
                ref = None
                if st.d_top1 is not None:
                    ref = {"frozen_at": st.d_top1.frozen_at,
                           "n": st.d_top1.ref_n}
                out[app] = {
                    "n": st.n_total,
                    "empty_total": st.empty_total,
                    "unknown_total": st.unknown_total,
                    "quantiles": quant,
                    "windows": windows,
                    "reference": ref,
                }
        return out


# -- feedback join ------------------------------------------------------------

class _JoinStats:
    """Per-app minute rings of joined/unjoined outcomes."""

    __slots__ = ("ring_joined", "ring_unjoined", "_cursor",
                 "joined_total", "unjoined_total", "last_lag_s")

    def __init__(self, now_min: int):
        self.ring_joined = [0] * _N_BUCKETS
        self.ring_unjoined = [0] * _N_BUCKETS
        self._cursor = now_min
        self.joined_total = 0
        self.unjoined_total = 0
        self.last_lag_s: Optional[float] = None

    def _advance(self, now_min: int) -> None:
        gap = now_min - self._cursor
        if gap <= 0:
            return
        if gap >= _N_BUCKETS:
            for i in range(_N_BUCKETS):
                self.ring_joined[i] = 0
                self.ring_unjoined[i] = 0
        else:
            for j in range(1, gap + 1):
                i = (self._cursor + j) % _N_BUCKETS
                self.ring_joined[i] = 0
                self.ring_unjoined[i] = 0
        self._cursor = now_min

    def note(self, joined: bool, now_min: int) -> None:
        self._advance(now_min)
        i = now_min % _N_BUCKETS
        if joined:
            self.ring_joined[i] += 1
            self.joined_total += 1
        else:
            self.ring_unjoined[i] += 1
            self.unjoined_total += 1

    def rates(self, now_min: int) -> Tuple[float, float]:
        """(reward_rate, unjoined_ratio) over the full ring (1h)."""
        self._advance(now_min)
        j = sum(self.ring_joined)
        u = sum(self.ring_unjoined)
        if j + u == 0:
            return (0.0, 0.0)
        return (j / (j + u), u / (j + u))


class QualityJoiner:
    """Joins feedback events back to served predictions by `prId`.

    Rides the same locate/watermark machinery as the streaming
    refresher: each tick snapshots the ingest watermark, scans events
    appended since the last tick, notes `predict` events (entity
    `pio_pr`) as pending, and joins any other event carrying a `prId`
    property within the attribution window. Pending entries that age
    past the window (or are evicted by the bounded-map cap) count as
    unjoined — an unjoined prediction is the signal, not an error.
    """

    def __init__(self, server, attribution_s: Optional[float] = None,
                 interval_s: float = 1.0,
                 metrics: Optional[MetricsRegistry] = None):
        self.server = server
        self.attribution_s = float(
            attribution_s if attribution_s is not None and
            attribution_s > 0 else default_attribution_s())
        self.interval_s = float(interval_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # prId -> (predict event epoch s, app label)
        self._pending: "OrderedDict[str, Tuple[float, str]]" = \
            OrderedDict()
        self._stats_by_app: "OrderedDict[str, _JoinStats]" = \
            OrderedDict()
        self._max_apps = 64
        self._since: Optional[datetime] = None
        self._wm = None
        self._lock = threading.Lock()
        self.last_outcome = ""          # test/introspection surface
        self.beat = None                # watchdog liveness stamp
        reg = metrics if metrics is not None else get_registry()
        self._c_join = reg.counter(
            "pio_feedback_join_total",
            "feedback-join outcomes (joined/expired/evicted)",
            labels=("app", "outcome"))
        self._h_lag = reg.histogram(
            "pio_feedback_join_lag_seconds",
            "feedback event time minus predict event time at join")
        self._g_reward = reg.gauge(
            "pio_pred_reward_rate",
            "joined / (joined + unjoined) predictions over the last "
            "hour", labels=("app",))
        self._g_unjoined = reg.gauge(
            "pio_pred_unjoined_ratio",
            "predictions that aged out of the attribution window "
            "unjoined, over the last hour", labels=("app",))

    # -- lifecycle --------------------------------------------------------
    def start(self) -> None:
        if self.beat is None:
            from predictionio_tpu.resilience.watchdog import watchdog
            self.beat = watchdog().register(
                "joiner", budget_s=self.interval_s * 3.0 + 5.0,
                restart=self._spawn)
        self._spawn()

    def _spawn(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, name="pio-quality-join", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        beat, self.beat = self.beat, None
        if beat is not None:
            beat.close()
        t = self._thread
        if t is not None:
            t.join(min(10.0, self.interval_s + 5.0))

    def _loop(self) -> None:
        beat = self.beat
        if beat is not None:
            beat.guard(self._loop_body)
        else:
            self._loop_body()

    def _loop_body(self) -> None:
        beat = self.beat
        while not self._stop.is_set():
            if beat is not None:
                beat.tick()
            try:
                self.tick()
            except Exception:
                self.last_outcome = "failed"
                _log.exception("quality_join_tick_failed")
            if self._stop.wait(self.interval_s):
                return

    # -- one tick ---------------------------------------------------------
    def tick(self) -> str:
        """One join pass; safe to call directly from tests."""
        outcome = self._tick_inner()
        self.last_outcome = outcome
        return outcome

    def _tick_inner(self) -> str:
        from predictionio_tpu.streaming.refresher import (
            locate_event_store,
        )
        server = self.server
        dep = getattr(server, "_dep", None)
        if dep is None:
            return "no_deployment"
        located = locate_event_store(dep, server.ctx.registry)
        if located is None:
            return "no_app"
        events, app_id, channel_id, ds_params = located
        app = ds_params.get("app_name") or ""
        now = time.time()
        if self._since is None:
            # baseline: predictions served before the joiner started
            # are not joinable — start the scan at the first tick
            self._since = datetime.now(timezone.utc)
            return "baseline"
        wm = events.ingest_watermark(app_id, channel_id)
        if wm is not None and wm == self._wm:
            self._expire(now)
            self._sync_gauges(now)
            return "noop"
        self._wm = wm
        newest = self._since
        scanned = 0
        with self._lock:
            for ev in events.find(app_id, channel_id,
                                  start_time=self._since):
                scanned += 1
                et = ev.event_time
                if et > newest:
                    newest = et
                if ev.event == "predict" and \
                        ev.entity_type == "pio_pr":
                    self._note_predict(ev.entity_id, et.timestamp(),
                                       app)
                    continue
                pr = ev.properties.get("prId") \
                    if ev.properties is not None else None
                if pr:
                    self._note_join(str(pr), et.timestamp(), now)
        if scanned:
            self._since = newest + timedelta(microseconds=1)
        self._expire(now)
        self._sync_gauges(now)
        return "scanned" if scanned else "noop"

    def _note_predict(self, pr_id: str, ev_epoch: float,
                      app: str) -> None:
        if len(self._pending) >= _MAX_PENDING:
            _, (_, old_app) = self._pending.popitem(last=False)
            self._outcome(old_app, False, "evicted")
        self._pending[pr_id] = (ev_epoch, app)

    def _note_join(self, pr_id: str, ev_epoch: float,
                   now: float) -> None:
        entry = self._pending.pop(pr_id, None)
        if entry is None:
            return                      # duplicate or pre-baseline
        pred_epoch, app = entry
        lag = max(0.0, ev_epoch - pred_epoch)
        if lag > self.attribution_s:
            self._outcome(app, False, "expired")
            return
        self._h_lag.observe(lag)
        self._outcome(app, True, "joined")

    def _expire(self, now: float) -> None:
        with self._lock:
            while self._pending:
                pr_id, (pred_epoch, app) = \
                    next(iter(self._pending.items()))
                if now - pred_epoch <= self.attribution_s:
                    break
                del self._pending[pr_id]
                self._outcome(app, False, "expired")

    def _outcome(self, app: str, joined: bool, label: str) -> None:
        now_min = int(time.time() // 60.0)
        st = self._stats_by_app.get(app)
        if st is None:
            if len(self._stats_by_app) >= self._max_apps:
                self._stats_by_app.popitem(last=False)
            st = _JoinStats(now_min)
            self._stats_by_app[app] = st    # lint: ok (LRU above)
        else:
            self._stats_by_app.move_to_end(app)
        st.note(joined, now_min)
        self._c_join.labels(app=app, outcome=label).inc()

    def _sync_gauges(self, now: float) -> None:
        now_min = int(now // 60.0)
        rows = []
        with self._lock:
            for app, st in self._stats_by_app.items():
                rows.append((app,) + st.rates(now_min))
        for app, reward, unjoined in rows:
            self._g_reward.labels(app=app).set(reward)
            self._g_unjoined.labels(app=app).set(unjoined)

    def snapshot(self) -> Dict:
        now_min = int(time.time() // 60.0)
        apps = {}
        with self._lock:
            pending = len(self._pending)
            for app, st in self._stats_by_app.items():
                reward, unjoined = st.rates(now_min)
                apps[app] = {        # lint: ok (bounded source map)
                    "reward_rate": reward,
                    "unjoined_ratio": unjoined,
                    "joined_total": st.joined_total,
                    "unjoined_total": st.unjoined_total,
                }
        return {
            "attribution_s": self.attribution_s,
            "pending": pending,
            "last_outcome": self.last_outcome,
            "apps": apps,
        }


# -- canary comparison --------------------------------------------------------

class CanaryVeto(RuntimeError):
    """Raised by `CanaryGate.check` when the replayed overlap falls
    below `PIO_CANARY_MIN_OVERLAP`; the server's reload path treats it
    exactly like a load failure (previous deployment keeps serving)."""


class CanaryGate:
    """Replays recently-kept traced queries against old + new plans.

    Per-query overlap is |old ∩ new| / max(|old|, |new|) over the
    returned item ids (two empty results agree perfectly); the score
    delta is |old top-1 - new top-1| where both sides returned items.
    With `min_overlap` at 0 the gate is report-only.
    """

    def __init__(self, sample: int = -1, min_overlap: float = -1.0,
                 metrics: Optional[MetricsRegistry] = None):
        self.sample = sample if sample >= 0 else canary_sample()
        self.min_overlap = (min_overlap if min_overlap >= 0
                            else canary_min_overlap())
        reg = metrics if metrics is not None else get_registry()
        self._g_overlap = reg.gauge(
            "pio_canary_overlap",
            "top-k overlap between the old and the candidate plans "
            "on replayed traced queries, last roll", labels=("app",))
        self._g_delta = reg.gauge(
            "pio_canary_score_delta",
            "mean |top-1 score delta| old vs candidate on replayed "
            "traced queries, last roll", labels=("app",))
        self._c_total = reg.counter(
            "pio_canary_total", "canary checks by outcome",
            labels=("outcome",))
        self.last: Optional[Dict] = None

    def check(self, prev_dep, new_dep,
              replay: Callable[[object, List[Dict]], List]) -> \
            Optional[Dict]:
        """Compare `prev_dep` vs `new_dep` on sampled traced queries.

        `replay(dep, query_dicts)` is supplied by the server (it owns
        query parsing and the predict path) and returns one predicted
        result per query dict. Returns the report (also kept on
        `.last`), or None when there is nothing to compare. Raises
        `CanaryVeto` on breach.
        """
        if self.sample <= 0 or prev_dep is None or new_dep is None:
            self._c_total.labels(outcome="skipped").inc()
            return None
        entries = [e for e in trace.get_recorder().snapshot()
                   if e.get("kind") == "serve"
                   and isinstance(e.get("query"), dict)]
        entries = entries[:self.sample]
        if not entries:
            self._c_total.labels(outcome="skipped").inc()
            return None
        qdicts = [e["query"] for e in entries]
        apps = [e.get("app") or "" for e in entries]
        try:
            old_res = replay(prev_dep, qdicts)
            new_res = replay(new_dep, qdicts)
        except Exception:
            # the candidate failing to serve at all IS a load failure;
            # let the reload error path handle it
            raise
        overlaps: List[float] = []
        deltas: List[float] = []
        per_app: Dict[str, List[float]] = {}
        for app, old, new in zip(apps, old_res, new_res):
            old_ids = [s.item for s in
                       (getattr(old, "itemScores", None) or ())]
            new_ids = [s.item for s in
                       (getattr(new, "itemScores", None) or ())]
            if not old_ids and not new_ids:
                ov = 1.0
            else:
                inter = len(set(old_ids) & set(new_ids))
                ov = inter / max(len(old_ids), len(new_ids))
            overlaps.append(ov)
            per_app.setdefault(app, []).append(ov)  # lint: ok (<= sample)
            if old_ids and new_ids:
                deltas.append(abs(old.itemScores[0].score
                                  - new.itemScores[0].score))
        overlap = sum(overlaps) / len(overlaps)
        delta = sum(deltas) / len(deltas) if deltas else 0.0
        report = {
            "sampled": len(overlaps),
            "overlap": overlap,
            "score_delta": delta,
            "min_overlap": self.min_overlap,
            "per_app": {a: sum(v) / len(v)
                        for a, v in per_app.items()},
            "ts": time.time(),
        }
        self.last = report
        self._g_overlap.labels(app="").set(overlap)
        self._g_delta.labels(app="").set(delta)
        for a, v in report["per_app"].items():
            if a:
                self._g_overlap.labels(app=a).set(v)
        if self.min_overlap > 0 and overlap < self.min_overlap:
            report["outcome"] = "fail"
            self._c_total.labels(outcome="fail").inc()
            raise CanaryVeto(
                "canary overlap %.3f below PIO_CANARY_MIN_OVERLAP "
                "%.3f on %d replayed queries"
                % (overlap, self.min_overlap, len(overlaps)))
        report["outcome"] = "pass"
        self._c_total.labels(outcome="pass").inc()
        return report

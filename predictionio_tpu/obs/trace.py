"""Flight recorder: allocation-light request tracing for the serve path.

Every request travelling the selector wire gets a `PendingTrace` — a
preallocated list of monotonic stamp slots plus a handful of scalar
attribute fields — attached to the `RawRequest`. Hot-path code only
*stamps* (`st[slot] = perf_counter()`) and never builds dicts or
strings; the span tree is materialized once, after the response bytes
hit the socket, and only for requests the sampler keeps (tools/lint.py
enforces the stamps-only discipline on the hot routes).

Sampling is head-rate (`PIO_TRACE_SAMPLE`, fraction of requests marked
`sampled` at arrival) plus tail-based keep: errored requests and the
slowest decile (a frugal-streaming p90 estimate, O(1) state) are kept
even when the head sampler passed them by. Kept traces land in a
bounded ring (`PIO_TRACE_RING`) served by `/traces.json`, and the kept
trace id is attached to the matching `pio_serve_seconds` bucket as an
exemplar so the p99 bucket links to a real trace.

Fleet stitching: routers forward `X-PIO-Trace`
(`traceid-spanid-flag[-hmac]`, signed with the same shared key as the
`X-PIO-App` identity header) on proxy hops and standby 307 redirects;
a replica adopts the incoming trace id and records its spans under it,
so one `/queries.json` call through a fleet yields router + replica
entries that stitch under a single 128-bit trace id.

Background work (refresher ticks/fold-ins, rolling reloads) records
spans through `background()` into the same ring with `kind=
"background"`.
"""

from __future__ import annotations

import contextvars
import hashlib
import hmac
import json
import os
import random
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

from predictionio_tpu.obs.logs import get_logger
from predictionio_tpu.obs.metrics import MetricsRegistry, get_registry

TRACE_HEADER = "X-PIO-Trace"

# Stamp slots, in request order. A slot left at 0.0 means the request
# never passed that stage (e.g. shed before enqueue); materialization
# spans consecutive *present* stamps so the tree always tiles the full
# first->last interval regardless of which stages ran.
S_WIRE_READ = 0      # first socket read of the bytes framing this request
S_FRAMED = 1         # request framed out of the connection buffer
S_HANDLER = 2        # worker picked it up, handler entered
S_AUTH = 3           # authenticated + admitted (tenancy)
S_ENQ = 4            # enqueued on its micro-batch lane
S_DRAIN = 5          # drained out of the lane into a batch
S_EXEC = 6           # model executed (device exec + d2h complete)
S_SPLICE = 7         # response payload spliced/encoded
S_DONE = 8           # handler returned the response object
S_SENT = 9           # response bytes written to the socket
N_STAMPS = 10

# Segment names, keyed by the stamp that *ends* the segment.
_SEG_NAMES = {
    S_FRAMED: "wire_frame",
    S_HANDLER: "worker_queue",
    S_AUTH: "auth_admission",
    S_ENQ: "batch_submit",
    S_DRAIN: "lane_wait",
    S_EXEC: "device_exec",
    S_SPLICE: "response_splice",
    S_DONE: "respond",
    S_SENT: "wire_write",
}

_log = get_logger("trace")

# Latency buckets for pio_serve_seconds (end-to-end, wire to wire);
# public: the server creates the same family for the tracing-off path.
SERVE_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                 0.25, 0.5, 1.0, 2.5, 5.0)


class PendingTrace:
    """Per-request stamp slots + scalar attributes; no dicts, no
    strings built until (and unless) the sampler keeps the request."""

    __slots__ = ("st", "trace_id", "span_id", "parent_id", "sampled",
                 "kind", "app", "route", "status", "dispatch", "error",
                 "batch_id", "batch_size", "rid", "extra", "reactor",
                 "query")

    def __init__(self):
        self.st = [0.0] * N_STAMPS
        self.trace_id = ""
        self.span_id = ""
        self.parent_id = ""
        self.sampled = False
        self.kind = ""           # "serve" | "router" | "" (generic)
        self.app = ""
        self.route = ""
        self.status = 0
        self.dispatch = ""       # host|device|sharded|fused
        self.error = ""
        self.batch_id = 0
        self.batch_size = 0
        self.rid = ""
        self.extra = None        # optional [(name, t0, t1), ...]
        self.reactor = -1        # accept-shard index (set by the wire)
        self.query = None        # (user, num) tuple or query dict —
        #                          replayable by the reload canary


# -- X-PIO-Trace codec (signed-header compatible with X-PIO-App) -------------

def _sign(payload: str, key: str) -> str:
    return hmac.new(key.encode(), payload.encode(),
                    hashlib.sha256).hexdigest()[:16]


def encode_header(trace_id: str, span_id: str, sampled: bool,
                  key: str = "") -> str:
    """`traceid-spanid-flag[-hmac16]`: the value a router asserts to
    its replicas (and a standby attaches to its 307 redirect)."""
    payload = f"{trace_id}-{span_id}-{'1' if sampled else '0'}"
    if not key:
        return payload
    return f"{payload}-{_sign(payload, key)}"


def decode_header(value: Optional[str],
                  key: str = "") -> Optional[Tuple[str, str, bool]]:
    """Parse + verify an X-PIO-Trace value -> (trace_id, parent_span,
    sampled), or None on malformed/unverified input (the request then
    starts a fresh trace — refuse-by-default, like X-PIO-App)."""
    if not value:
        return None
    parts = value.strip().split("-")
    if len(parts) not in (3, 4):
        return None
    tid, sid, flag = parts[0], parts[1], parts[2]
    if len(tid) != 32 or len(sid) != 16 or flag not in ("0", "1"):
        return None
    try:
        int(tid, 16)
        int(sid, 16)
    except ValueError:
        return None
    if key:
        if len(parts) != 4:
            return None
        payload = f"{tid}-{sid}-{flag}"
        if not hmac.compare_digest(parts[3], _sign(payload, key)):
            return None
    return tid, sid, flag == "1"


def _new_trace_id() -> str:
    return os.urandom(16).hex()


def _new_span_id() -> str:
    return os.urandom(8).hex()


# -- the recorder ------------------------------------------------------------

class TraceRecorder:
    """Process-global flight recorder: head/tail sampling, the bounded
    keep ring, serve-latency exemplars, and the slow-request log."""

    def __init__(self, sample: float = 0.0, ring: int = 512,
                 slow_ms: float = 0.0, key: str = "",
                 metrics: Optional[MetricsRegistry] = None):
        self.sample = max(0.0, min(1.0, float(sample)))
        self.enabled = self.sample > 0.0
        self.slow_ms = max(0.0, float(slow_ms))
        self.key = key or ""
        self._metrics = metrics if metrics is not None else get_registry()
        self._ring: "deque" = deque(maxlen=max(1, int(ring)))
        self._lock = threading.Lock()
        # frugal-streaming p90 estimate of request duration: O(1)
        # state, no reservoir — accurate enough to flag the slow tail
        self._q90 = 0.0
        self._q_n = 0
        self._kept = self._metrics.counter(
            "pio_trace_kept_total", "Traces kept in the ring, by reason",
            labels=("why",))
        self._serve_hist = self._metrics.histogram(
            "pio_serve_seconds",
            "End-to-end serve latency (wire read to wire write)",
            labels=("app",), buckets=SERVE_BUCKETS)
        # app -> histogram child: labels() rebuilds key tuples and takes
        # the family lock per call; finish() runs once per request, so
        # resolve each app's child once (cardinality already bounded by
        # admission's label sanitization; capped regardless)
        self._hist_by_app: Dict[str, Any] = {}

    # -- hot-path entry points (called via the wire hooks) -------------------
    def new_stamps(self, t0: float) -> Optional[PendingTrace]:
        """Allocate stamp slots for an arriving request; None when
        tracing is off (the wire then skips all further trace work)."""
        if not self.enabled:
            return None
        p = PendingTrace()
        if t0 > 0.0:
            p.st[S_WIRE_READ] = t0
        # the hook runs as the request is framed out of the buffer
        p.st[S_FRAMED] = time.perf_counter()
        if random.random() < self.sample:
            p.sampled = True
        return p

    def on_sent(self, raw) -> None:
        """Wire write completed: stamp S_SENT and finish the trace."""
        p = raw.trace
        if p is None:
            return
        p.st[S_SENT] = time.perf_counter()
        self.finish(p)

    # -- finish / keep -------------------------------------------------------
    def finish(self, p: PendingTrace) -> None:
        st = p.st
        t0 = 0.0
        tend = 0.0
        for t in st:
            if t > 0.0:
                if t0 == 0.0:
                    t0 = t
                if t > tend:
                    tend = t
        if t0 == 0.0:
            return
        dur = max(tend - t0, 0.0)
        why = ""
        with self._lock:
            slow = self._tail_slow_locked(dur)
            if p.sampled:
                why = "sampled"
            elif p.error or p.status >= 400:
                why = "error"
            elif slow:
                why = "slow"
            if why:
                entry = self._materialize(p, t0, dur, why)
                self._ring.append(entry)
        if why:
            self._kept.labels(why=why).inc()
            if self.slow_ms > 0.0 and dur * 1000.0 >= self.slow_ms:
                self._slow_log(p, dur)
        if p.kind == "serve":
            child = self._hist_by_app.get(p.app)
            if child is None:
                child = self._serve_hist.labels(app=p.app)
                if len(self._hist_by_app) < 1024:
                    self._hist_by_app[p.app] = child
            child.observe(dur, exemplar=p.trace_id if why else None)

    def _tail_slow_locked(self, dur: float) -> bool:
        """Frugal-streaming quantile step toward p90; True once the
        estimate has warmed up and `dur` lands in the slow decile."""
        q = self._q90
        self._q_n += 1
        step = max(q * 0.05, 1e-5)
        if dur > q:
            self._q90 = q + step
        else:
            self._q90 = max(q - step / 9.0, 0.0)
        return self._q_n > 64 and dur >= self._q90

    def _materialize(self, p: PendingTrace, t0: float, dur: float,
                     why: str) -> Dict[str, Any]:
        if not p.trace_id:
            p.trace_id = _new_trace_id()
        if not p.span_id:
            p.span_id = _new_span_id()
        spans: List[Dict[str, Any]] = []
        prev = p.st[S_WIRE_READ] if p.st[S_WIRE_READ] > 0.0 else 0.0
        for slot in range(1, N_STAMPS):
            t = p.st[slot]
            if t <= 0.0:
                continue
            if prev > 0.0 and t >= prev:
                spans.append({
                    "name": _SEG_NAMES.get(slot, f"stage{slot}"),
                    "start_ms": round((prev - t0) * 1000.0, 3),
                    "dur_ms": round((t - prev) * 1000.0, 3),
                })
            prev = t
        if p.extra:
            for name, a, b in p.extra:
                spans.append({
                    "name": name,
                    "start_ms": round((a - t0) * 1000.0, 3),
                    "dur_ms": round((b - a) * 1000.0, 3),
                })
        entry: Dict[str, Any] = {
            "trace_id": p.trace_id,
            "span_id": p.span_id,
            "parent_id": p.parent_id,
            "kind": p.kind or "request",
            "name": p.route or "request",
            "app": p.app,
            "status": p.status,
            "dispatch": p.dispatch,
            "duration_ms": round(dur * 1000.0, 3),
            "keep": why,
            "ts": time.time(),
            "spans": spans,
        }
        if p.batch_size:
            entry["batch_id"] = p.batch_id
            entry["batch_size"] = p.batch_size
        if p.error:
            entry["error"] = p.error
        if p.rid:
            entry["request_id"] = p.rid
        if p.reactor >= 0:
            entry["reactor"] = p.reactor
        q = p.query
        if q is not None:
            if isinstance(q, tuple):
                entry["query"] = {"user": q[0], "num": q[1]}
            else:
                entry["query"] = q
        return entry

    def _slow_log(self, p: PendingTrace, dur: float) -> None:
        """One grep-able JSON line per kept-slow trace (PIO_SLOW_MS)."""
        stages = {}
        st = p.st
        prev = 0.0
        for slot in range(N_STAMPS):
            t = st[slot]
            if t <= 0.0:
                continue
            if prev > 0.0 and slot in _SEG_NAMES:
                stages[_SEG_NAMES[slot]] = round((t - prev) * 1000.0, 3)
            prev = t
        _log.warning("slow_request", trace_id=p.trace_id, app=p.app,
                     route=p.route, status=p.status, dispatch=p.dispatch,
                     duration_ms=round(dur * 1000.0, 3), stages=stages)

    # -- background spans ----------------------------------------------------
    def record_background(self, name: str, t0: float, t1: float,
                          app: str = "", error: str = "") -> None:
        entry = {
            "trace_id": _new_trace_id(),
            "span_id": _new_span_id(),
            "parent_id": "",
            "kind": "background",
            "name": name,
            "app": app,
            "status": 0,
            "dispatch": "",
            "duration_ms": round((t1 - t0) * 1000.0, 3),
            "keep": "background",
            "ts": time.time(),
            "spans": [],
        }
        if error:
            entry["error"] = error
        with self._lock:
            self._ring.append(entry)

    # -- export --------------------------------------------------------------
    def snapshot(self, app: Optional[str] = None,
                 min_ms: Optional[float] = None,
                 trace_id: Optional[str] = None,
                 limit: int = 0) -> List[Dict[str, Any]]:
        """Ring contents newest-first, filtered by app / min duration /
        trace id — the body of `/traces.json`."""
        with self._lock:
            entries = list(self._ring)
        entries.reverse()
        out = []
        for e in entries:
            if app is not None and e.get("app") != app:
                continue
            if min_ms is not None and e.get("duration_ms", 0.0) < min_ms:
                continue
            if trace_id is not None and e.get("trace_id") != trace_id:
                continue
            out.append(e)
            if limit and len(out) >= limit:
                break
        return out

    def ring_len(self) -> int:
        with self._lock:
            return len(self._ring)

    def trim(self, keep_frac: float = 0.5) -> int:
        """Soft-memory-pressure hook: drop the oldest trace entries
        down to `keep_frac` of the current ring; returns approximate
        bytes released (entries are small dicts of spans/stamps)."""
        dropped = 0
        with self._lock:
            keep = max(1, int(len(self._ring) * keep_frac))
            while len(self._ring) > keep:
                self._ring.popleft()
                dropped += 1
        return dropped * 512     # span-list dict estimate


# -- process-global recorder + module-level stamp API ------------------------
# The functions below are the ONLY trace calls the hot-route lint
# allows inside hot functions (see tools/lint.py HOT_TRACE_API).

_REC: Optional[TraceRecorder] = None
_REC_LOCK = threading.Lock()


def configure(sample: Optional[float] = None, ring: Optional[int] = None,
              slow_ms: Optional[float] = None, key: Optional[str] = None,
              metrics: Optional[MetricsRegistry] = None) -> TraceRecorder:
    """(Re)build the process recorder; env supplies any unset knob
    (PIO_TRACE_SAMPLE / PIO_TRACE_RING / PIO_SLOW_MS /
    PIO_SERVER_ACCESS_KEY)."""
    global _REC
    env = os.environ

    def _envf(name: str, default: float) -> float:
        try:
            return float(env.get(name, "") or default)
        except ValueError:
            return default

    if sample is None:
        sample = _envf("PIO_TRACE_SAMPLE", 0.0)
    if ring is None:
        ring = int(_envf("PIO_TRACE_RING", 512))
    if slow_ms is None:
        slow_ms = _envf("PIO_SLOW_MS", 0.0)
    if key is None:
        key = env.get("PIO_SERVER_ACCESS_KEY", "") or ""
    with _REC_LOCK:
        _REC = TraceRecorder(sample=sample, ring=ring, slow_ms=slow_ms,
                             key=key, metrics=metrics)
        return _REC


def get_recorder() -> TraceRecorder:
    rec = _REC
    if rec is None:
        rec = configure()
    return rec


def new_stamps(t0: float) -> Optional[PendingTrace]:
    """Wire hook: stamp slots for an arriving request (None = off)."""
    rec = _REC
    if rec is None or not rec.enabled:
        return None
    return rec.new_stamps(t0)


def on_sent(raw) -> None:
    """Wire hook: response bytes on the socket — finish the trace."""
    rec = _REC
    if rec is not None:
        rec.on_sent(raw)


def stamp(raw, slot: int) -> None:
    """Stamp one stage slot on a RawRequest's pending trace."""
    p = raw.trace
    if p is not None:
        p.st[slot] = time.perf_counter()


def mark(p: Optional[PendingTrace], slot: int) -> None:
    """Stamp one stage slot on a PendingTrace (or None: no-op)."""
    if p is not None:
        p.st[slot] = time.perf_counter()


def begin_raw(raw, header_value: Optional[str] = None,
              kind: str = "") -> Optional[PendingTrace]:
    """Handler entry on the raw fast path: stamp S_HANDLER, adopt any
    incoming X-PIO-Trace context, tag the entry kind."""
    p = raw.trace
    if p is None:
        return None
    p.st[S_HANDLER] = time.perf_counter()
    if kind:
        p.kind = kind
    if header_value:
        adopt(p, header_value)
    return p


def adopt(p: Optional[PendingTrace],
          header_value: Optional[str]) -> None:
    """Join the trace asserted by an upstream hop: same trace id, our
    span parented under the asserting span; an upstream sampled flag
    forces keep so the stitched view is complete."""
    if p is None or not header_value:
        return
    rec = _REC
    ctx = decode_header(header_value, rec.key if rec is not None else "")
    if ctx is None:
        return
    p.trace_id, p.parent_id, flag = ctx
    if flag:
        p.sampled = True


def ensure_ids(p: PendingTrace) -> None:
    if not p.trace_id:
        p.trace_id = _new_trace_id()
    if not p.span_id:
        p.span_id = _new_span_id()


def child_header(p: PendingTrace) -> str:
    """The X-PIO-Trace value to assert downstream of `p`'s span."""
    ensure_ids(p)
    rec = _REC
    return encode_header(p.trace_id, p.span_id, p.sampled,
                         rec.key if rec is not None else "")


def annotate(raw, status: int = 0, app: Optional[str] = None,
             route: Optional[str] = None, dispatch: Optional[str] = None,
             error: Optional[str] = None,
             kind: Optional[str] = None, query=None) -> None:
    """Attach scalar attributes to a RawRequest's pending trace —
    keyword scalars only, nothing allocated on the hot path."""
    p = raw.trace
    if p is None:
        return
    if status:
        p.status = status
    if app is not None:
        p.app = app
    if route is not None:
        p.route = route
    if dispatch is not None:
        p.dispatch = dispatch
    if error is not None:
        p.error = error
    if kind is not None:
        p.kind = kind
    if query is not None:
        p.query = query


def annotate_pending(p: Optional[PendingTrace], status: int = 0,
                     app: Optional[str] = None, route: Optional[str] = None,
                     dispatch: Optional[str] = None,
                     error: Optional[str] = None,
                     kind: Optional[str] = None, query=None) -> None:
    """`annotate` for call sites that hold the PendingTrace itself."""
    if p is None:
        return
    if status:
        p.status = status
    if app is not None:
        p.app = app
    if route is not None:
        p.route = route
    if dispatch is not None:
        p.dispatch = dispatch
    if error is not None:
        p.error = error
    if kind is not None:
        p.kind = kind
    if query is not None:
        p.query = query


def add_span(p: Optional[PendingTrace], name: str, t0: float,
             t1: float) -> None:
    """Append a named sub-span (router proxy attempts, redirects)."""
    if p is None:
        return
    if p.extra is None:
        p.extra = []
    p.extra.append((name, t0, t1))


# -- contextvar plumbing for the generic (non-fast) route --------------------
_current: "contextvars.ContextVar[Optional[PendingTrace]]" = \
    contextvars.ContextVar("pio_trace", default=None)


def set_current(p: Optional[PendingTrace]):
    return _current.set(p)


def reset_current(token) -> None:
    _current.reset(token)


def current() -> Optional[PendingTrace]:
    return _current.get()


@contextmanager
def background(name: str, app: str = ""):
    """Record a background span (refresher tick/fold-in, rolling
    reload) into the ring; no-op when tracing is off."""
    rec = _REC
    if rec is None or not rec.enabled:
        yield None
        return
    t0 = time.perf_counter()
    err = ""
    try:
        yield None
    except BaseException as e:
        err = type(e).__name__
        raise
    finally:
        rec.record_background(name, t0, time.perf_counter(), app=app,
                              error=err)


def traces_json_body(query_get) -> bytes:
    """Build the `/traces.json` response body. `query_get(name)` pulls
    one query parameter (the Request.query_get shape)."""
    rec = get_recorder()
    app = query_get("app")
    min_ms = query_get("min_ms") or query_get("min_duration_ms")
    tid = query_get("trace_id")
    limit = query_get("limit")
    try:
        min_ms_f = float(min_ms) if min_ms else None
    except ValueError:
        min_ms_f = None
    try:
        limit_i = int(limit) if limit else 0
    except ValueError:
        limit_i = 0
    entries = rec.snapshot(app=app or None, min_ms=min_ms_f,
                           trace_id=tid or None, limit=limit_i)
    return json.dumps({"traces": entries, "count": len(entries),
                       "enabled": rec.enabled}).encode()

"""Thread-safe metrics registry: counters, gauges, fixed-bucket histograms.

The reference system's only telemetry is the event server's hourly
counters (`data/.../api/Stats.scala`); nothing measures the serve chain
or training. This registry is the standard instrumentation surface for
the whole stack: every server exposes it on `GET /metrics` in Prometheus
text format (version 0.0.4), the dashboard renders a snapshot page from
it, and `pio train` reports phase timings out of it. Histograms keep
fixed cumulative buckets (the Prometheus model) plus p50/p90/p99
estimation by in-bucket linear interpolation, so latency summaries never
require storing raw samples.

Everything is safe under concurrent handler threads: one lock per metric
family guards its children and their values.
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_left
from typing import Dict, List, Optional, Sequence, Tuple

# latency-oriented defaults, seconds (Prometheus client defaults)
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


def _fmt(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _escape_label(v: str) -> str:
    return (str(v).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _label_str(names: Sequence[str], values: Sequence[str]) -> str:
    if not names:
        return ""
    inner = ",".join(f'{n}="{_escape_label(v)}"'
                     for n, v in zip(names, values))
    return "{" + inner + "}"


class _Family:
    """One named metric with a fixed label schema; children per labelset."""

    kind = ""

    def __init__(self, name: str, help: str, labelnames: Sequence[str] = ()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}

    def _make_child(self):
        raise NotImplementedError

    def labels(self, **labels):
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {sorted(labels)}")
        key = tuple(str(labels[n]) for n in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._make_child()
                self._children[key] = child
        return child

    def _default(self):
        """The label-less child (only valid when the family has no labels)."""
        if self.labelnames:
            raise ValueError(f"{self.name} requires labels {self.labelnames}")
        return self.labels()

    def _items(self) -> List[Tuple[Tuple[str, ...], object]]:
        with self._lock:
            return sorted(self._children.items())


class _CounterChild:
    __slots__ = ("_lock", "_value")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters can only increase")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class _GaugeChild(_CounterChild):
    __slots__ = ()

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)


class Counter(_Family):
    kind = "counter"

    def _make_child(self) -> _CounterChild:
        return _CounterChild(self._lock)

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    @property
    def value(self) -> float:
        return self._default().value


class Gauge(_Family):
    kind = "gauge"

    def _make_child(self) -> _GaugeChild:
        return _GaugeChild(self._lock)

    def set(self, value: float) -> None:
        self._default().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default().dec(amount)

    @property
    def value(self) -> float:
        return self._default().value


class _HistogramChild:
    __slots__ = ("_lock", "bounds", "bucket_counts", "sum", "count",
                 "exemplars")

    def __init__(self, lock: threading.Lock, bounds: Tuple[float, ...]):
        self._lock = lock
        self.bounds = bounds
        self.bucket_counts = [0] * (len(bounds) + 1)   # last = +Inf
        self.sum = 0.0
        self.count = 0
        # bucket index -> (exemplar_id, value, unix ts); allocated on
        # the first exemplar so untraced histograms pay nothing
        self.exemplars: Optional[Dict[int, Tuple[str, float, float]]] = None

    def observe(self, value: float, exemplar: Optional[str] = None) -> None:
        i = bisect_left(self.bounds, value)   # le-inclusive bucket
        with self._lock:
            self.bucket_counts[i] += 1
            self.sum += value
            self.count += 1
            if exemplar:
                if self.exemplars is None:
                    self.exemplars = {}
                self.exemplars[i] = (exemplar, value, time.time())

    def exemplar_for_quantile(self, q: float
                              ) -> Optional[Tuple[str, float, float]]:
        """The stored exemplar nearest the bucket holding quantile `q`
        (exact bucket first, then higher, then lower) — how the
        dashboard links the p99 bucket of a latency histogram to a real
        kept trace. None when no exemplar has been recorded."""
        with self._lock:
            if not self.exemplars:
                return None
            counts = list(self.bucket_counts)
            total = self.count
            ex = dict(self.exemplars)
        if total <= 0:
            return None
        target = q * total
        cum = 0
        qi = len(counts) - 1
        for i, c in enumerate(counts):
            cum += c
            if cum >= target and c > 0:
                qi = i
                break
        for i in range(qi, len(counts)):
            if i in ex:
                return ex[i]
        for i in range(qi - 1, -1, -1):
            if i in ex:
                return ex[i]
        return None

    class _Timer:
        __slots__ = ("_child", "_t0")

        def __init__(self, child: "_HistogramChild"):
            self._child = child

        def __enter__(self):
            self._t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            self._child.observe(time.perf_counter() - self._t0)
            return False

    def time(self) -> "_HistogramChild._Timer":
        """Context manager observing the enclosed wall time in seconds."""
        return _HistogramChild._Timer(self)

    def quantile(self, q: float) -> float:
        """Estimated q-quantile (0..1) by in-bucket linear interpolation
        (the histogram_quantile() model). Values beyond the last finite
        bound clamp to it; an empty histogram reports 0.0."""
        with self._lock:
            counts = list(self.bucket_counts)
            total = self.count
        if total == 0 or not self.bounds:
            return 0.0
        target = q * total
        cum = 0
        for i, c in enumerate(counts):
            cum += c
            if cum >= target and c > 0:
                if i == len(self.bounds):
                    return self.bounds[-1]
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = self.bounds[i]
                frac = (target - (cum - c)) / c
                return lo + (hi - lo) * min(1.0, max(0.0, frac))
        return self.bounds[-1]


class Histogram(_Family):
    kind = "histogram"

    def __init__(self, name: str, help: str, labelnames: Sequence[str] = (),
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help, labelnames)
        b = tuple(sorted(float(x) for x in buckets))
        if not b:
            raise ValueError(f"{name}: histogram needs at least one bucket")
        self.buckets = b

    def _make_child(self) -> _HistogramChild:
        return _HistogramChild(self._lock, self.buckets)

    def observe(self, value: float, exemplar: Optional[str] = None) -> None:
        self._default().observe(value, exemplar=exemplar)

    def time(self):
        return self._default().time()

    def quantile(self, q: float) -> float:
        return self._default().quantile(q)

    def exemplar_for_quantile(self, q: float):
        return self._default().exemplar_for_quantile(q)


class MetricsRegistry:
    """Named metric families; get-or-create accessors are idempotent so
    every layer can declare the instruments it needs without coordination
    (mismatched type or label schema under one name raises)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}

    def _get_or_create(self, cls, name: str, help: str, **kwargs) -> _Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = cls(name, help, **kwargs)
                self._families[name] = fam
                return fam
        if not isinstance(fam, cls):
            raise ValueError(
                f"{name} already registered as {fam.kind}, not {cls.kind}")
        if "labelnames" in kwargs and \
                tuple(kwargs["labelnames"]) != fam.labelnames:
            raise ValueError(
                f"{name} already registered with labels {fam.labelnames}")
        return fam

    def counter(self, name: str, help: str = "",
                labels: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames=labels)

    def gauge(self, name: str, help: str = "",
              labels: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames=labels)

    def histogram(self, name: str, help: str = "",
                  labels: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help,
                                   labelnames=labels, buckets=buckets)

    def _families_snapshot(self) -> List[_Family]:
        with self._lock:
            return list(self._families.values())

    def value(self, name: str, **labels) -> float:
        """Read one counter/gauge series without creating it: returns 0.0
        when the family or labelset does not exist yet (reading a metric
        must never mutate the registry — chaos tests and /ready assert
        on series that only appear after the first failure)."""
        with self._lock:
            fam = self._families.get(name)
        if fam is None or isinstance(fam, Histogram):
            return 0.0
        key = tuple(str(labels.get(n, "")) for n in fam.labelnames)
        with fam._lock:
            child = fam._children.get(key)
            return child._value if child is not None else 0.0

    # -- exposition ---------------------------------------------------------
    def render(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        out: List[str] = []
        for fam in self._families_snapshot():
            if fam.help:
                out.append(f"# HELP {fam.name} {fam.help}")
            out.append(f"# TYPE {fam.name} {fam.kind}")
            for key, child in fam._items():
                if isinstance(child, _HistogramChild):
                    with child._lock:
                        counts = list(child.bucket_counts)
                        total, s = child.count, child.sum
                    cum = 0
                    for bound, c in zip(fam.buckets, counts):
                        cum += c
                        ls = _label_str(fam.labelnames + ("le",),
                                        key + (_fmt(bound),))
                        out.append(f"{fam.name}_bucket{ls} {cum}")
                    ls = _label_str(fam.labelnames + ("le",), key + ("+Inf",))
                    out.append(f"{fam.name}_bucket{ls} {total}")
                    ls = _label_str(fam.labelnames, key)
                    out.append(f"{fam.name}_sum{ls} {_fmt(s)}")
                    out.append(f"{fam.name}_count{ls} {total}")
                else:
                    ls = _label_str(fam.labelnames, key)
                    out.append(f"{fam.name}{ls} {_fmt(child.value)}")
        return "\n".join(out) + "\n"

    def snapshot(self) -> Dict[str, dict]:
        """JSON-ready view for the dashboard: histograms carry count/sum
        and estimated p50/p90/p99; counters and gauges carry the value."""
        snap: Dict[str, dict] = {}
        for fam in self._families_snapshot():
            series = []
            for key, child in fam._items():
                labels = dict(zip(fam.labelnames, key))
                if isinstance(child, _HistogramChild):
                    row = {
                        "labels": labels, "count": child.count,
                        "sum": child.sum,
                        "p50": child.quantile(0.50),
                        "p90": child.quantile(0.90),
                        "p99": child.quantile(0.99)}
                    with child._lock:
                        ex = (dict(child.exemplars)
                              if child.exemplars else None)
                    if ex:
                        bounds = child.bounds
                        row["exemplars"] = [
                            {"le": (_fmt(bounds[i]) if i < len(bounds)
                                    else "+Inf"),
                             "trace_id": t, "value": v, "ts": ts}
                            for i, (t, v, ts) in sorted(ex.items())]
                    series.append(row)
                else:
                    series.append({"labels": labels, "value": child.value})
            snap[fam.name] = {"type": fam.kind, "help": fam.help,
                              "series": series}
        return snap


_default_lock = threading.Lock()
_default_registry: Optional[MetricsRegistry] = None


def get_registry() -> MetricsRegistry:
    """The process-default registry. Servers default to it (so one
    process exposes one coherent /metrics), and the train workflow
    records phase timings into it."""
    global _default_registry
    with _default_lock:
        if _default_registry is None:
            _default_registry = MetricsRegistry()
        return _default_registry

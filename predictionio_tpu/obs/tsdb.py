"""In-process time-series history: a bounded delta-encoded ring per
metric series.

`/metrics` answers "what is the value now"; this module answers "what
did it look like ten minutes ago" without an external Prometheus. A
`Scraper` thread (`pio-tsdb-scraper`) snapshots the local registry
every `PIO_TSDB_INTERVAL_S` seconds (default 5, `0` disables) into a
`TSDB`: each scalar series keeps `PIO_TSDB_POINTS` points (default
720 ≈ 1 h at 5 s) as (delta-ms-from-base, value) pairs — two small
numbers per point instead of a float64 wall-clock timestamp each.

Semantics per family type:

  - gauges    → raw value per tick;
  - counters  → per-second *rate* between consecutive scrapes (the
    raw monotone total is useless to plot; key suffix ``:rate``);
  - histograms→ ``:p50`` / ``:p99`` quantiles plus an observation
    ``:rate``.

Export: ``GET /tsdb.json?series=<prefix,prefix>&since=<unix-ts>``
returns absolute-timestamped points, decoded from the deltas at read
time. The dashboard's sparkline panels and `pio-tpu top` both read
this endpoint; the fleet router additionally records derived
per-member series into its own ring so `/fleet.html` can chart the
whole fleet's history.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from predictionio_tpu.obs.logs import get_logger
from predictionio_tpu.obs.metrics import MetricsRegistry

_log = get_logger("tsdb")

DEFAULT_INTERVAL_S = 5.0
DEFAULT_POINTS = 720
DEFAULT_MAX_SERIES = 1024


def _envf(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def series_key(name: str, labels: Dict[str, str], suffix: str = "") -> str:
    """Canonical series id: ``name{k=v,...}[:suffix]`` with sorted
    label keys, matching Prometheus selector syntax closely enough to
    paste into a real query."""
    if labels:
        inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
        base = f"{name}{{{inner}}}"
    else:
        base = name
    return f"{base}:{suffix}" if suffix else base


class _Series:
    """One bounded ring of (delta_ms, value) points."""

    __slots__ = ("kind", "base_ts", "points")

    def __init__(self, kind: str, points: int):
        self.kind = kind
        self.base_ts = 0.0
        self.points: deque = deque(maxlen=points)

    def append(self, ts: float, value: float) -> None:
        if not self.points:
            self.base_ts = ts
        self.points.append((int((ts - self.base_ts) * 1000.0), value))

    def decoded(self, since: float = 0.0) -> List[Tuple[float, float]]:
        base = self.base_ts
        return [(base + dt / 1000.0, v) for dt, v in self.points
                if base + dt / 1000.0 >= since]


class TSDB:
    """Bounded in-memory store keyed by `series_key`; thread-safe."""

    def __init__(self, points: Optional[int] = None,
                 max_series: int = DEFAULT_MAX_SERIES):
        self.points = int(_envf("PIO_TSDB_POINTS", DEFAULT_POINTS)
                          if points is None else points)
        self.points = max(2, self.points)
        self.max_series = max(1, int(max_series))
        self._lock = threading.Lock()
        self._series: Dict[str, _Series] = {}
        # last raw counter totals, for rate derivation across scrapes
        self._last_raw: Dict[str, Tuple[float, float]] = {}
        self.dropped_series = 0
        self.scrapes = 0

    # -- recording -----------------------------------------------------------
    def record_value(self, key: str, kind: str, ts: float,
                     value: float) -> None:
        with self._lock:
            s = self._series.get(key)
            if s is None:
                if len(self._series) >= self.max_series:
                    self.dropped_series += 1
                    return
                s = self._series[key] = _Series(kind, self.points)
            s.append(ts, value)

    def _rate(self, key: str, ts: float, raw: float) -> Optional[float]:
        """Per-second rate vs the previous raw total; None on the
        first sighting (no interval to divide over) and on counter
        resets (process restart feeding a shared ring)."""
        prev = self._last_raw.get(key)
        self._last_raw[key] = (ts, raw)
        if prev is None:
            return None
        pts, praw = prev
        dt = ts - pts
        if dt <= 0 or raw < praw:
            return None
        return (raw - praw) / dt

    def record_snapshot(self, snap: Dict[str, Dict],
                        now: Optional[float] = None) -> None:
        """Fold one `MetricsRegistry.snapshot()` into the rings."""
        ts = time.time() if now is None else now
        with self._lock:
            self.scrapes += 1
        for name, fam in snap.items():
            ftype = fam.get("type")
            for series in fam.get("series", ()):
                labels = series.get("labels") or {}
                if ftype == "counter":
                    rate = self._rate(series_key(name, labels), ts,
                                      float(series.get("value", 0.0)))
                    if rate is not None:
                        self.record_value(
                            series_key(name, labels, "rate"),
                            "rate", ts, rate)
                elif ftype == "gauge":
                    self.record_value(series_key(name, labels), "gauge",
                                      ts, float(series.get("value", 0.0)))
                elif ftype == "histogram":
                    for q in ("p50", "p99"):
                        if series.get(q) is not None:
                            self.record_value(
                                series_key(name, labels, q), "quantile",
                                ts, float(series[q]))
                    rate = self._rate(
                        series_key(name, labels, "count"), ts,
                        float(series.get("count", 0.0)))
                    if rate is not None:
                        self.record_value(
                            series_key(name, labels, "rate"),
                            "rate", ts, rate)

    # -- export --------------------------------------------------------------
    def to_json(self, series: Optional[str] = None,
                since: Optional[str] = None) -> Dict:
        """Body of /tsdb.json. `series` is a comma-separated list of
        key prefixes (empty = all); `since` a unix timestamp — only
        points at or after it are returned."""
        prefixes = tuple(p for p in (series or "").split(",") if p)
        try:
            since_ts = float(since) if since else 0.0
        except ValueError:
            since_ts = 0.0
        with self._lock:
            keys = list(self._series.items())
            scrapes, dropped = self.scrapes, self.dropped_series
        out: Dict[str, Dict] = {}
        for key, s in keys:
            if prefixes and not any(key.startswith(p) for p in prefixes):
                continue
            pts = s.decoded(since_ts)
            if not pts:
                continue
            out[key] = {
                "kind": s.kind,
                "points": [[round(t, 3), round(v, 6)] for t, v in pts],
            }
        return {"now": time.time(), "scrapes": scrapes,
                "max_points": self.points, "dropped_series": dropped,
                "series": out}

    def latest(self, key: str) -> Optional[float]:
        """Most recent value of one series, None when absent."""
        with self._lock:
            s = self._series.get(key)
            if s is None or not s.points:
                return None
            return s.points[-1][1]

    def keys(self) -> List[str]:
        with self._lock:
            return list(self._series)

    def trim(self, keep_frac: float = 0.5) -> int:
        """Soft-memory-pressure hook: drop the oldest points of every
        ring down to `keep_frac` of their current length (recent
        history is what operators debug with). Returns the approximate
        bytes released."""
        dropped = 0
        with self._lock:
            for s in self._series.values():
                keep = max(2, int(len(s.points) * keep_frac))
                while len(s.points) > keep:
                    s.points.popleft()
                    dropped += 1
        return dropped * 64      # (delta_ms int, float) tuple estimate


class Scraper:
    """Named background thread driving collectors + a registry scrape
    into a TSDB every `interval_s` seconds. `interval_s=0` (the
    `PIO_TSDB_INTERVAL_S=0` escape) means start() is a no-op — hooks
    installed, loop never exists."""

    def __init__(self, tsdb: TSDB, registry: MetricsRegistry,
                 interval_s: Optional[float] = None,
                 collectors: Iterable[Callable[[], None]] = ()):
        self.tsdb = tsdb
        self.registry = registry
        self.interval_s = (_envf("PIO_TSDB_INTERVAL_S", DEFAULT_INTERVAL_S)
                           if interval_s is None else interval_s)
        self.collectors: List[Callable[[], None]] = list(collectors)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._beat = None

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def tick(self, now: Optional[float] = None) -> None:
        """One scrape cycle: collectors first (they freshen gauges the
        snapshot then captures), then the registry fold. Public so
        tests and the fleet router can force a tick."""
        for fn in self.collectors:
            try:
                fn()
            except Exception as e:    # a broken collector must not
                _log.warning("tsdb_collector_failed",   # stop the scrape
                             collector=getattr(fn, "__name__", "?"),
                             error=f"{type(e).__name__}: {e}")
        self.tsdb.record_snapshot(self.registry.snapshot(), now)

    def _run(self) -> None:
        self._beat.guard(self._run_loop)

    def _run_loop(self) -> None:
        beat = self._beat
        while not self._stop.wait(self.interval_s):
            beat.tick()
            try:
                self.tick()
            except Exception as e:
                _log.warning("tsdb_tick_failed",
                             error=f"{type(e).__name__}: {e}")

    def _spawn(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="pio-tsdb-scraper", daemon=True)
        self._thread.start()

    def start(self) -> bool:
        if self.interval_s <= 0 or self.running:
            return False
        self._stop.clear()
        if self._beat is None:
            from predictionio_tpu.resilience.watchdog import watchdog
            self._beat = watchdog().register(
                "scraper", budget_s=self.interval_s * 3.0 + 5.0,
                restart=self._spawn)
        self._spawn()
        return True

    def stop(self) -> None:
        self._stop.set()
        beat, self._beat = self._beat, None
        if beat is not None:
            beat.close()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2.0)

"""Structured JSON logging with request-id propagation.

One log line per event, each a single JSON object on stderr: grep-able in
production (`grep request_id=... | jq`), machine-parseable in tests. This
replaces the reference's spray `ActorLogging` free-text lines and the
seed's ad-hoc `traceback.print_exc()` — a 500 now carries the request_id
of the request that caused it.

Schema (every line): ts, level, component, event, plus event-specific
fields. HTTP request lines add: request_id, method, path, route, status,
duration_ms. Errors add: error, traceback.

Built on stdlib logging (logger tree "pio.obs.<component>"), so tests can
capture through caplog and deployments can re-route handlers; the level
honors PIO_OBS_LOG_LEVEL (default INFO).
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import traceback
import uuid
from datetime import datetime, timezone
from typing import Dict

_ROOT_NAME = "pio.obs"
_setup_lock = threading.Lock()
_loggers: Dict[str, "StructuredLogger"] = {}


def new_request_id() -> str:
    """A fresh 16-hex-char request id (assigned by the HTTP middleware
    when the client did not send X-Request-ID)."""
    return uuid.uuid4().hex[:16]


def _ensure_root() -> logging.Logger:
    root = logging.getLogger(_ROOT_NAME)
    with _setup_lock:
        if not root.handlers:
            handler = logging.StreamHandler(sys.stderr)
            handler.setFormatter(logging.Formatter("%(message)s"))
            root.addHandler(handler)
            level = os.environ.get("PIO_OBS_LOG_LEVEL", "INFO").upper()
            root.setLevel(getattr(logging, level, logging.INFO))
    return root


class StructuredLogger:
    """Emits one JSON object per call through the stdlib logging tree."""

    def __init__(self, component: str):
        self.component = component
        _ensure_root()
        self._logger = logging.getLogger(f"{_ROOT_NAME}.{component}")

    def _emit(self, level: int, event: str, fields: dict) -> None:
        record = {
            "ts": datetime.now(timezone.utc).isoformat(
                timespec="milliseconds"),
            "level": logging.getLevelName(level).lower(),
            "component": self.component,
            "event": event,
        }
        record.update(fields)
        self._logger.log(level, json.dumps(record, default=str))

    def info(self, event: str, **fields) -> None:
        self._emit(logging.INFO, event, fields)

    def warning(self, event: str, **fields) -> None:
        self._emit(logging.WARNING, event, fields)

    def error(self, event: str, **fields) -> None:
        self._emit(logging.ERROR, event, fields)

    def exception(self, event: str, **fields) -> None:
        """error() + the current exception's traceback as a field."""
        fields.setdefault("traceback", traceback.format_exc())
        self._emit(logging.ERROR, event, fields)


def get_logger(component: str) -> StructuredLogger:
    # no lock around construction (StructuredLogger takes _setup_lock
    # itself); dict get/setdefault are individually atomic
    logger = _loggers.get(component)
    if logger is None:
        _loggers.setdefault(component, StructuredLogger(component))
        logger = _loggers[component]
    return logger

"""Mesh construction, named shardings, and collective helpers.

This layer replaces the reference's distributed execution substrate (Spark
driver↔executor RPC + shuffle; see SURVEY.md §2.8). Where the reference
scales by partitioning RDDs over executor JVMs, this framework scales by
laying out dense `jax.Array`s over a `jax.sharding.Mesh` and letting XLA
emit ICI collectives from sharding annotations.

Canonical mesh axes used throughout the framework:
  "data"  — batch/data parallelism (the analog of RDD partitioning)
  "model" — tensor/model parallelism (factor-matrix sharding for ALS,
            embedding-table sharding for the two-tower template)

Multi-host: `initialize_distributed` wires `jax.distributed` the way the
reference forwarded its env across the spark-submit boundary
(`tools/.../Runner.scala:185-307`); on a single host it is a no-op.
"""

from predictionio_tpu.parallel.mesh import (  # noqa: F401
    MeshSpec,
    make_mesh,
    batch_sharding,
    replicated_sharding,
    shard_put,
    pad_to_multiple,
    pad_rows,
    initialize_distributed,
)

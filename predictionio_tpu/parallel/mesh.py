"""Device mesh + sharding utilities.

The TPU-native replacement for Spark's cluster-manager/executor topology
(reference: `tools/.../Runner.scala:185-307` spark-submit launching,
SURVEY.md §2.8). A `MeshSpec` is carried in engine-instance `runtime_conf`
(the slot the reference used for `sparkConf`) so training and serving agree
on the device layout.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Mapping, Optional

import numpy as np


@dataclass(frozen=True)
class MeshSpec:
    """A declarative mesh shape: axis name -> size; -1 means 'all remaining
    devices'. The default is pure data parallelism over every device, the
    analog of Spark defaulting to one partition per core."""
    axes: Mapping[str, int] = field(default_factory=lambda: {"data": -1})

    def resolve(self, n_devices: int) -> "Tuple[Tuple[str, ...], Tuple[int, ...]]":
        names = tuple(self.axes.keys())
        sizes = list(self.axes.values())
        wild = [i for i, s in enumerate(sizes) if s == -1]
        if len(wild) > 1:
            raise ValueError("At most one mesh axis may be -1")
        fixed = int(np.prod([s for s in sizes if s != -1])) if sizes else 1
        if wild:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {fixed}")
            sizes[wild[0]] = n_devices // fixed
        total = int(np.prod(sizes)) if sizes else 1
        if total > n_devices:
            raise ValueError(
                f"Mesh {dict(zip(names, sizes))} needs {total} devices, "
                f"have {n_devices}")
        return names, tuple(int(s) for s in sizes)

    @staticmethod
    def from_conf(conf: Mapping[str, str]) -> "MeshSpec":
        """Parse 'mesh' key of runtime_conf, e.g. 'data=8' or
        'data=4,model=2'. Missing/empty -> default all-data mesh."""
        s = (conf or {}).get("mesh", "")
        if not s:
            return MeshSpec()
        axes = {}
        for part in s.split(","):
            k, _, v = part.partition("=")
            axes[k.strip()] = int(v)
        return MeshSpec(axes)


def make_mesh(spec: Optional[MeshSpec] = None, devices=None):
    """Build a `jax.sharding.Mesh` from a spec over the available devices.

    Uses only the largest prefix of devices that fills the mesh shape (so a
    7-device pool with data=-1 uses all 7; data=4 uses the first 4)."""
    import jax
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    spec = spec or MeshSpec()
    names, sizes = spec.resolve(len(devices))
    n = int(np.prod(sizes)) if sizes else 1
    dev_array = np.array(devices[:n]).reshape(sizes)
    return Mesh(dev_array, names)


def batch_sharding(mesh, axis: str = "data", rank: int = 1):
    """NamedSharding that shards dim 0 over `axis`, replicates the rest."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    return NamedSharding(mesh, P(axis, *([None] * (rank - 1))))


def replicated_sharding(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P
    return NamedSharding(mesh, P())


def pad_to_multiple(n: int, m: int) -> int:
    """Smallest multiple of m that is >= n (>= m so empty stays shardable)."""
    return max(((n + m - 1) // m) * m, m)


def pad_rows(a: np.ndarray, target: int, fill=0) -> np.ndarray:
    """Pad dim 0 of `a` to `target` rows with `fill`. Static-shape bucketing
    is how ragged event-derived data becomes XLA-friendly (SURVEY.md §7
    'Dynamic event queries → static shapes')."""
    if a.shape[0] == target:
        return a
    if a.shape[0] > target:
        raise ValueError(f"Cannot pad {a.shape[0]} rows down to {target}")
    pad_width = [(0, target - a.shape[0])] + [(0, 0)] * (a.ndim - 1)
    return np.pad(a, pad_width, constant_values=fill)


def shard_put(a: np.ndarray, mesh, axis: str = "data", fill=0):
    """Pad dim 0 to a multiple of the mesh axis size and device_put with a
    batch sharding. Returns (sharded jax.Array, original row count)."""
    import jax
    size = int(mesh.shape[axis])
    n = a.shape[0]
    a = pad_rows(a, pad_to_multiple(n, size), fill)
    return jax.device_put(a, batch_sharding(mesh, axis, a.ndim)), n


_distributed_initialized = False


def initialize_distributed(coordinator: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> bool:
    """Initialize `jax.distributed` for multi-host training; no-op
    (False) when no coordinator is configured. Explicit arguments
    override the PIO_TPU_COORDINATOR / _NUM_PROCESSES / _PROCESS_ID env
    vars. Idempotent: a second call in the same process returns True
    without re-initializing. The analog of the reference forwarding
    PIO_* env through spark-submit to driver/executors
    (`Runner.scala:213-215,298-305`)."""
    global _distributed_initialized
    addr = coordinator or os.environ.get("PIO_TPU_COORDINATOR")
    if not addr:
        return False
    if _distributed_initialized:
        return True

    def setting(explicit, env_key, what):
        if explicit is not None:
            return int(explicit)
        val = os.environ.get(env_key)
        if val is None:
            raise ValueError(
                f"Multi-host init needs {what}: pass it explicitly "
                f"(--num-processes/--process-id) or set {env_key}")
        return int(val)

    n_proc = setting(num_processes, "PIO_TPU_NUM_PROCESSES",
                     "the process count")
    pid = setting(process_id, "PIO_TPU_PROCESS_ID", "this process's id")
    import jax
    jax.distributed.initialize(coordinator_address=addr,
                               num_processes=n_proc, process_id=pid)
    _distributed_initialized = True
    return True

"""Masked top-k scoring — the serve-time hot path of every recommender.

The reference serves queries one at a time and even notes "TODO:
Parallelize" (`core/.../workflow/CreateServer.scala:494`); its per-query
work is a driver-side loop over `recommendProducts`
(`examples/.../ALSAlgorithm.scala:96-112`). Here scoring is one
program: a query batch of user vectors against the full item factor matrix
(a matmul), additive masks for blacklist/seen/whitelist filters, then
top-k — so batching queries is free.

Host/device dispatch: `topk_scores`/`topk_similar` route by score-matrix
size. Small problems (a handful of live queries against a catalog of
thousands) run as host BLAS in microseconds — pushing them through the
accelerator costs a dispatch + a device->host readback round trip that
dwarfs the compute on any hardware, and by orders of magnitude over a
remote/tunneled device. Large batches (offline batchpredict, eval sweeps,
big catalogs) go to the jit'd device kernel where the MXU matmul wins and
the transfer amortizes. Inside a jit trace the device path is always used
(host numpy cannot trace).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = -1e30

# [b, n_items] score cells below which the host path wins. At the
# crossover the host matmul is ~1 GFLOP-scale work (milliseconds);
# above it MXU throughput dominates even counting the readback.
HOST_CROSSOVER_CELLS = 4 << 20


@partial(jax.jit, static_argnames=("k",))
def _topk_scores_device(user_vecs, item_factors, mask, *, k: int):
    # HIGHEST precision: the host path computes exact f32, and the two
    # paths must rank near-tied scores identically (default TPU matmul
    # precision is bf16-pass and would reorder them)
    scores = jnp.matmul(user_vecs, item_factors.T,
                        precision=jax.lax.Precision.HIGHEST)
    scores = jnp.where(mask, scores, NEG_INF)
    return jax.lax.top_k(scores, k)


@partial(jax.jit, static_argnames=("k",))
def _topk_similar_device(query_vecs, item_factors, mask, *, k: int):
    qn = query_vecs / (jnp.linalg.norm(query_vecs, axis=-1, keepdims=True)
                       + 1e-9)
    fn = item_factors / (jnp.linalg.norm(item_factors, axis=-1, keepdims=True)
                         + 1e-9)
    scores = jnp.matmul(qn, fn.T, precision=jax.lax.Precision.HIGHEST)
    scores = jnp.where(mask, scores, NEG_INF)
    return jax.lax.top_k(scores, k)


def _is_traced(*arrays) -> bool:
    return any(isinstance(a, jax.core.Tracer) for a in arrays)


def _on_device(*arrays) -> bool:
    return any(isinstance(a, jax.Array) for a in arrays)


def _topk_host(scores: np.ndarray, k: int):
    """Full stable argsort (cheap at host-path sizes) so tie-breaking
    matches lax.top_k's lowest-index-first guarantee — the host and
    device paths must return identical results for the same query.

    Cross-path parity is exact only for bitwise-equal scores (e.g. the
    integer-valued factors in the parity tests): the host matmul is exact
    f32 BLAS while the device path is XLA Precision.HIGHEST, so near-tied
    (but not equal) scores can still rank differently at the last ulp.
    Indices are cast to int32 to match lax.top_k's return dtype."""
    k = min(k, scores.shape[1])
    ix = np.argsort(-scores, axis=1, kind="stable")[:, :k]
    return np.take_along_axis(scores, ix, axis=1), ix.astype(np.int32)


def topk_scores(user_vecs, item_factors, mask, *, k: int):
    """scores = U @ Y^T with invalid items masked out.

    user_vecs:    [b, rank]
    item_factors: [n_items, rank]
    mask:         [b, n_items] bool — True = item allowed for that query
    Returns (scores [b, k], indexes [b, k]); masked-out slots score NEG_INF.
    Dispatches host/device by problem size (see module docstring).
    """
    traced = _is_traced(user_vecs, item_factors, mask)
    k = min(k, item_factors.shape[0])   # both paths clamp identically
    cells = user_vecs.shape[0] * item_factors.shape[0]
    if traced or _on_device(user_vecs, item_factors) \
            or cells >= HOST_CROSSOVER_CELLS:
        out = _topk_scores_device(user_vecs, item_factors, mask, k=k)
        return out if traced else jax.device_get(out)
    scores = np.asarray(user_vecs) @ np.asarray(item_factors).T
    scores = np.where(np.asarray(mask), scores, np.float32(NEG_INF))
    return _topk_host(scores, k)


def topk_similar(query_vecs, item_factors, mask, *, k: int):
    """Cosine-similarity top-k: used by the similarproduct template
    (`examples/scala-parallel-similarproduct/.../ALSAlgorithm.scala`
    cosine scoring). query_vecs [b, rank] are typically item vectors.
    Dispatches host/device by problem size (see module docstring)."""
    traced = _is_traced(query_vecs, item_factors, mask)
    k = min(k, item_factors.shape[0])   # both paths clamp identically
    cells = query_vecs.shape[0] * item_factors.shape[0]
    if traced or _on_device(query_vecs, item_factors) \
            or cells >= HOST_CROSSOVER_CELLS:
        out = _topk_similar_device(query_vecs, item_factors, mask, k=k)
        return out if traced else jax.device_get(out)
    q = np.asarray(query_vecs)
    f = np.asarray(item_factors)
    qn = q / (np.linalg.norm(q, axis=-1, keepdims=True) + 1e-9)
    fn = f / (np.linalg.norm(f, axis=-1, keepdims=True) + 1e-9)
    scores = np.where(np.asarray(mask), qn @ fn.T, np.float32(NEG_INF))
    return _topk_host(scores, k)


def build_mask(n_items: int,
               blacklist_ix: Sequence[int] = (),
               whitelist_ix: Optional[Sequence[int]] = None,
               batch: int = 1) -> np.ndarray:
    """Host-side mask assembly from index lists (unknown ids are resolved
    to indexes by the caller via BiMap and simply absent here)."""
    if whitelist_ix is not None:
        mask = np.zeros(n_items, bool)
        mask[np.asarray(list(whitelist_ix), int)] = True
    else:
        mask = np.ones(n_items, bool)
    if len(blacklist_ix):
        mask[np.asarray(list(blacklist_ix), int)] = False
    return np.broadcast_to(mask, (batch, n_items))

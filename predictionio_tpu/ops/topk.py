"""Masked top-k scoring — the serve-time hot path of every recommender.

The reference serves queries one at a time and even notes "TODO:
Parallelize" (`core/.../workflow/CreateServer.scala:494`); its per-query
work is a driver-side loop over `recommendProducts`
(`examples/.../ALSAlgorithm.scala:96-112`). Here scoring is one
program: a query batch of user vectors against the full item factor matrix
(a matmul), additive masks for blacklist/seen/whitelist filters, then
top-k — so batching queries is free.

Host/device dispatch: `topk_scores`/`topk_similar` route by score-matrix
size. Small problems (a handful of live queries against a catalog of
thousands) run as host BLAS in microseconds — pushing them through the
accelerator costs a dispatch + a device->host readback round trip that
dwarfs the compute on any hardware, and by orders of magnitude over a
remote/tunneled device. Large batches (offline batchpredict, eval sweeps,
big catalogs) go to the jit'd device kernel where the MXU matmul wins and
the transfer amortizes. Inside a jit trace the device path is always used
(host numpy cannot trace).

Two dispatch refinements on top of the static size rule:

  - `DispatchPolicy` — an amortized policy that keeps latency EWMAs per
    path and can PROMOTE sub-crossover problems to the device once the
    observed device round trip beats the predicted (GIL-contended) host
    time. The static `HOST_CROSSOVER_CELLS` stays the upper bound: at or
    above it the device always wins, exactly as before.
  - `BucketedTopK` — the serving plan: per-bucket AOT-compiled
    executables over a device-resident factor matrix, built at deploy
    warmup. Calls go straight to the compiled executable (never the jit
    tracing cache), so steady-state serving is zero-recompile by
    construction.

A third path lives in `ops/topk_sharded.py`: `ShardedBucketedTopK` /
`ShardedBucketedSimilar` partition the catalog row-wise across a device
mesh (per-shard partial top-k + allgather merge) when a mesh is
configured or the catalog exceeds one device's capacity.

Every dispatch lands in `pio_topk_dispatch_total{path=host|device|
sharded}` (the process-default metrics registry) and in
`DISPATCH_COUNTS`; the `DispatchPolicy` keeps a latency EWMA per path.
"""

from __future__ import annotations

import threading
import time
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = -1e30

# [b, n_items] score cells below which the host path wins. Environment-
# dependent (host BLAS speed x device dispatch overhead): the r4 bench
# measures it empirically (serve_topk_crossover_cells_measured metric —
# ~0.8M cells on a tunneled v5e with single-threaded numpy, where device
# batch-64 scoring is ~1200x the host's). The default stays conservative
# for fast-host/cold-device setups; operators can pin the measured value
# via PIO_TOPK_HOST_CROSSOVER_CELLS.
import os as _os

HOST_CROSSOVER_CELLS = int(_os.environ.get(
    "PIO_TOPK_HOST_CROSSOVER_CELLS", 4 << 20))

# Dispatch evidence: incremented per call by which path actually served
# it (the traced/jit path counts as "device" — it compiles into a device
# program). Read by the bench to PROVE the device path ran, and by tests;
# plain ints under the GIL (worst case a lost increment, never a wrong
# path).
DISPATCH_COUNTS = {"host": 0, "device": 0, "sharded": 0, "fused": 0}

# Below this many score cells the amortized policy never promotes to the
# device, whatever the EWMAs say: tiny unit-test-sized problems must stay
# deterministically on the host path (and the promotion payoff only
# exists for coalesced serve batches anyway).
PROMOTE_FLOOR_CELLS = int(_os.environ.get(
    "PIO_TOPK_PROMOTE_FLOOR_CELLS", 1 << 16))

# Exploration cadence for the amortized policy: with no device
# observation yet, every Nth promotable-sized problem is routed to the
# device purely to SEED its latency EWMA. Without this the policy can
# never promote (promotion needs a device EWMA, but sub-crossover
# problems all go to the host, so the device EWMA is never observed —
# the r05 ecommerce runs served 552 host calls and 0 device batches
# exactly this way). 0 disables probing.
EXPLORE_EVERY = int(_os.environ.get("PIO_TOPK_EXPLORE_EVERY", 32))

_DISPATCH_TOTAL = None


def _dispatch_total():
    """`pio_topk_dispatch_total{path=...}` in the process-default
    registry (lazy: created on the first dispatch, like jaxprobe's
    counters)."""
    global _DISPATCH_TOTAL
    if _DISPATCH_TOTAL is None:
        from predictionio_tpu.obs import get_registry
        _DISPATCH_TOTAL = get_registry().counter(
            "pio_topk_dispatch_total",
            "Top-k serve dispatches by path taken (host BLAS, "
            "single-device program, or mesh-sharded program; traced "
            "calls count as device)", labels=("path",))
    return _DISPATCH_TOTAL


class DispatchPolicy:
    """Amortized host/device dispatch from observed per-path latency.

    Cold start reproduces the legacy one-shot rule exactly: device iff
    cells >= HOST_CROSSOVER_CELLS (read live, so tests and operators can
    pin it). Once BOTH paths have been observed, problems between
    PROMOTE_FLOOR_CELLS and the crossover are routed by predicted
    latency:

        host:   cells * host_s_per_cell_EWMA * (1 + in-flight host calls)
        device: device_call_s_EWMA   (dispatch + readback dominated at
                serve sizes; the matmul itself is microseconds)

    The (1 + in-flight) factor is the batch-coalescing term: concurrent
    host calls serialize on the GIL/BLAS while device dispatches overlap,
    so the more the micro-batcher (or the concurrent per-algorithm loop)
    piles onto the host path, the stronger the pull toward the device.
    Promotion is one-directional — at or above the static crossover the
    device always wins, as before — so a pinned
    PIO_TOPK_HOST_CROSSOVER_CELLS keeps its meaning as an upper bound.
    """

    def __init__(self, alpha: float = 0.25):
        self._alpha = alpha
        self._lock = threading.Lock()
        self._host_s_per_cell: Optional[float] = None
        self._device_call_s: Optional[float] = None
        # the mesh-sharded plan's per-call EWMA: observed so operators
        # (and the persisted snapshot) see all three paths' latency,
        # even though a warmed sharded plan is dispatched whenever the
        # batch fits it (mirroring the single-device plan)
        self._sharded_call_s: Optional[float] = None
        self._host_inflight = 0
        self._probe_tick = 0

    def choose(self, cells: int) -> str:
        if cells >= HOST_CROSSOVER_CELLS:
            return "device"
        if cells < PROMOTE_FLOOR_CELLS:
            # tiny problems are deterministically host — never probed
            return "host"
        with self._lock:
            h, d = self._host_s_per_cell, self._device_call_s
            inflight = self._host_inflight
            if d is None and EXPLORE_EVERY > 0:
                # no device observation yet: probe every Nth call so
                # the EWMA gets seeded and promotion becomes reachable
                self._probe_tick += 1
                if self._probe_tick % EXPLORE_EVERY == 0:
                    return "device"
        if h is None or d is None:
            return "host"
        return "device" if d <= cells * h * (1.0 + inflight) else "host"

    def host_begin(self) -> None:
        with self._lock:
            self._host_inflight += 1

    def host_end(self) -> None:
        with self._lock:
            self._host_inflight = max(0, self._host_inflight - 1)

    def observe(self, path: str, cells: int,
                seconds: Optional[float]) -> None:
        if seconds is None or cells <= 0:
            return
        a = self._alpha
        with self._lock:
            if path == "host":
                per_cell = seconds / cells
                prev = self._host_s_per_cell
                self._host_s_per_cell = (per_cell if prev is None
                                         else prev + a * (per_cell - prev))
            elif path == "sharded":
                prev = self._sharded_call_s
                self._sharded_call_s = (seconds if prev is None
                                        else prev + a * (seconds - prev))
            else:
                prev = self._device_call_s
                self._device_call_s = (seconds if prev is None
                                       else prev + a * (seconds - prev))

    def snapshot(self) -> dict:
        with self._lock:
            return {"host_s_per_cell": self._host_s_per_cell,
                    "device_call_s": self._device_call_s,
                    "sharded_call_s": self._sharded_call_s,
                    "host_inflight": self._host_inflight}

    def restore(self, state: dict) -> None:
        """Re-seed the EWMAs from a persisted `snapshot()` so a server
        restart/reload starts from the learned host/device crossover
        instead of the cold one-shot rule. The in-flight count is
        transient and never restored; junk fields are ignored."""
        with self._lock:
            h = state.get("host_s_per_cell")
            d = state.get("device_call_s")
            s = state.get("sharded_call_s")
            if isinstance(h, (int, float)) and h > 0:
                self._host_s_per_cell = float(h)   # lint: ok — host JSON
            if isinstance(d, (int, float)) and d > 0:
                self._device_call_s = float(d)     # lint: ok — host JSON
            if isinstance(s, (int, float)) and s > 0:
                self._sharded_call_s = float(s)    # lint: ok — host JSON


DISPATCH_POLICY = DispatchPolicy()

# most recent dispatch path taken by any topk call — read by the
# micro-batch drainer to tag member traces (host|device|sharded|fused).
# A plain module global, not thread-local: multi-algorithm fan-out runs
# predict in pool threads while the drainer reads from its own, and the
# benign last-writer-wins race matches DISPATCH_COUNTS' semantics.
_LAST_PATH = ""


def last_dispatch() -> str:
    """The dispatch path of the most recent topk call ("" before any)."""
    return _LAST_PATH


def _record_dispatch(path: str, cells: int,
                     seconds: Optional[float] = None) -> None:
    global _LAST_PATH
    _LAST_PATH = path
    DISPATCH_COUNTS[path] += 1
    try:
        _dispatch_total().labels(path=path).inc()
    except Exception:
        pass  # metrics must never fail a serve call
    DISPATCH_POLICY.observe(path, cells, seconds)


@partial(jax.jit, static_argnames=("k",))
def _topk_scores_device(user_vecs, item_factors, mask, *, k: int):
    # HIGHEST precision: the host path computes exact f32, and the two
    # paths must rank near-tied scores identically (default TPU matmul
    # precision is bf16-pass and would reorder them)
    scores = jnp.matmul(user_vecs, item_factors.T,
                        precision=jax.lax.Precision.HIGHEST)
    scores = jnp.where(mask, scores, NEG_INF)
    return jax.lax.top_k(scores, k)


def _topk_similar_raw(query_vecs, item_factors, mask, *, k: int):
    qn = query_vecs / (jnp.linalg.norm(query_vecs, axis=-1, keepdims=True)
                       + 1e-9)
    fn = item_factors / (jnp.linalg.norm(item_factors, axis=-1, keepdims=True)
                         + 1e-9)
    scores = jnp.matmul(qn, fn.T, precision=jax.lax.Precision.HIGHEST)
    scores = jnp.where(mask, scores, NEG_INF)
    return jax.lax.top_k(scores, k)


_topk_similar_device = partial(
    jax.jit, static_argnames=("k",))(_topk_similar_raw)

# AOT serving-plan variant (BucketedSimilar): donates the per-call query
# block and dense mask off-CPU, mirroring _topk_scores_banned_donated
_topk_similar_donated = partial(
    jax.jit, static_argnames=("k",),
    donate_argnums=(0, 2))(_topk_similar_raw)


def _is_traced(*arrays) -> bool:
    return any(isinstance(a, jax.core.Tracer) for a in arrays)


def _on_device(*arrays) -> bool:
    return any(isinstance(a, jax.Array) for a in arrays)


def _topk_host(scores: np.ndarray, k: int):
    """Full stable argsort (cheap at host-path sizes) so tie-breaking
    matches lax.top_k's lowest-index-first guarantee — the host and
    device paths must return identical results for the same query.

    Cross-path parity is exact only for bitwise-equal scores (e.g. the
    integer-valued factors in the parity tests): the host matmul is exact
    f32 BLAS while the device path is XLA Precision.HIGHEST, so near-tied
    (but not equal) scores can still rank differently at the last ulp.
    Indices are cast to int32 to match lax.top_k's return dtype."""
    k = min(k, scores.shape[1])
    ix = np.argsort(-scores, axis=1, kind="stable")[:, :k]
    return np.take_along_axis(scores, ix, axis=1), ix.astype(np.int32)


# ---------------------------------------------------------------------------
# Device-resident model arrays and the banned-index device path.
#
# The serving hot loop calls topk with the SAME host factor matrix every
# time; without caching, each device dispatch re-uploads it (measured:
# a 500k x 64 catalog is 128 MB -> ~2.5 s/call over a tunneled device,
# and a real PCIe host still pays ~13 ms/call). `device_resident` uploads
# once per (array identity) and returns the cached jax.Array.
# ---------------------------------------------------------------------------

_DEVICE_RESIDENT: dict = {}


def device_resident(arr):
    """Device-put `arr` once and cache by object identity (evicted when
    the host array is garbage-collected). jax arrays pass through."""
    import weakref

    if isinstance(arr, (jax.Array, jax.core.Tracer)):
        return arr
    key = id(arr)
    hit = _DEVICE_RESIDENT.get(key)
    if hit is not None and hit[0]() is arr:
        return hit[1]
    dev = jax.device_put(arr)
    ref = weakref.ref(arr, lambda _, key=key: _DEVICE_RESIDENT.pop(key, None))
    _DEVICE_RESIDENT[key] = (ref, dev)
    return dev


# Live serving plans with device-pinned factor state, weakly held: the
# capacity checks in ops/topk_sharded subtract these bytes (the
# pio_plan_resident_bytes the server samples) before deciding whether a
# NEW catalog still fits one device — without the subtraction,
# back-to-back /reloads of a near-capacity catalog pass the fits check
# against an EMPTY device and OOM once both plans are resident (the old
# deployment stays pinned until the atomic swap completes).
_RESIDENT_PLANS: "weakref.WeakSet" = None  # type: ignore[assignment]


def register_resident_plan(plan) -> None:
    """Track a plan whose factor state is device-resident. Weak
    references only: a dropped deployment's plan leaves the accounting
    as soon as it is garbage-collected."""
    import weakref
    global _RESIDENT_PLANS
    if _RESIDENT_PLANS is None:
        _RESIDENT_PLANS = weakref.WeakSet()
    _RESIDENT_PLANS.add(plan)


def plan_resident_bytes() -> float:
    """Per-device bytes currently pinned by live serving plans."""
    if _RESIDENT_PLANS is None:
        return 0.0
    total = 0.0
    for plan in list(_RESIDENT_PLANS):
        try:
            total += float(plan.resident_per_device_bytes())
        except Exception:   # noqa: BLE001 — accounting is best-effort
            continue
    return total


def _topk_scores_banned(user_vecs, item_factors, banned, *,
                        k: int, has_bans: bool):
    scores = jnp.matmul(user_vecs, item_factors.T,
                        precision=jax.lax.Precision.HIGHEST)
    if has_bans:
        rows = jnp.arange(scores.shape[0])[:, None]
        # out-of-range fill indices (== n_items) are dropped
        scores = scores.at[rows, banned].set(NEG_INF, mode="drop")
    return jax.lax.top_k(scores, k)


_topk_scores_banned_device = partial(
    jax.jit, static_argnames=("k", "has_bans"))(_topk_scores_banned)

# The AOT serving-plan variant donates the per-call uploads (the padded
# query block and its banned-index block) so XLA reuses their buffers
# instead of allocating fresh ones every drain. The factor matrix (arg 1)
# is the device-resident model state and is NOT donated. CPU backends
# can't donate and would warn per compile, so the plan only picks this
# variant off-CPU.
_topk_scores_banned_donated = partial(
    jax.jit, static_argnames=("k", "has_bans"),
    donate_argnums=(0, 2))(_topk_scores_banned)


def _next_pow2(n: int) -> int:
    return 1 << max(n - 1, 0).bit_length() if n > 1 else 1


def topk_scores_filtered(user_vecs, item_factors, banned_lists, *, k: int):
    """Top-k scoring with per-query banned-item index lists (blacklist /
    seen filtering) instead of a dense [b, n_items] mask.

    Host/device dispatch as `topk_scores`, but the device path builds the
    filter ON DEVICE from a small padded [b, max_banned] index array —
    uploading a dense bool mask per batch costs b*n_items bytes (32 MB at
    batch 64 x 500k items) per call, while the index form is a few KB.
    The factor matrix goes through `device_resident`. Batch and
    banned-width are padded to powers of two so the jit cache stays at
    O(log^2) variants instead of one per observed shape.

    Whitelists need the dense-mask form — use `topk_scores` for those.
    """
    n_items = item_factors.shape[0]
    k = min(k, n_items)
    b = user_vecs.shape[0]
    cells = b * n_items
    traced = _is_traced(user_vecs, item_factors)
    on_dev = _on_device(user_vecs, item_factors)
    max_banned = max((len(bl) for bl in banned_lists), default=0)
    wp = _next_pow2(max_banned) if max_banned else 0
    if not traced and not on_dev \
            and DISPATCH_POLICY.choose(cells) == "host":
        # small problems: densify the filter and delegate so the host
        # scoring/tie-breaking path exists in exactly one place
        mask = np.ones((b, n_items), bool)
        for row, banned in enumerate(banned_lists):
            if len(banned):
                mask[row, np.asarray(banned, int)] = False  # lint: ok
        return topk_scores(user_vecs, item_factors, mask, k=k)
    banned_np = np.full((b, max(wp, 1)), n_items, np.int32)
    for row, bl in enumerate(banned_lists):
        if len(bl):
            banned_np[row, :len(bl)] = np.asarray(bl, np.int32)  # lint: ok
    if traced or on_dev:
        # traced / already-on-device inputs: no host-side padding
        # round-trip; shapes are what the trace gives us
        _record_dispatch("device", cells)
        out = _topk_scores_banned_device(
            user_vecs, item_factors, jnp.asarray(banned_np), k=k,
            has_bans=wp > 0)
        return out if traced else jax.device_get(out)
    # host inputs: pad batch to a power of two to bound jit variants
    t0 = time.perf_counter()
    bp = _next_pow2(b)
    vecs = np.zeros((bp, user_vecs.shape[1]), np.float32)
    vecs[:b] = user_vecs
    banned_pad = np.full((bp, max(wp, 1)), n_items, np.int32)
    banned_pad[:b] = banned_np
    out = _topk_scores_banned_device(
        jnp.asarray(vecs), device_resident(item_factors),
        jnp.asarray(banned_pad), k=k, has_bans=wp > 0)
    scores, ixs = jax.device_get(out)
    _record_dispatch("device", cells, time.perf_counter() - t0)
    return scores[:b], ixs[:b]


def topk_scores(user_vecs, item_factors, mask, *, k: int):
    """scores = U @ Y^T with invalid items masked out.

    user_vecs:    [b, rank]
    item_factors: [n_items, rank]
    mask:         [b, n_items] bool — True = item allowed for that query
    Returns (scores [b, k], indexes [b, k]); masked-out slots score NEG_INF.
    Dispatches host/device by problem size (see module docstring).
    """
    traced = _is_traced(user_vecs, item_factors, mask)
    k = min(k, item_factors.shape[0])   # both paths clamp identically
    cells = user_vecs.shape[0] * item_factors.shape[0]
    if traced:
        _record_dispatch("device", cells)
        return _topk_scores_device(user_vecs, item_factors, mask, k=k)
    if _on_device(user_vecs, item_factors) \
            or DISPATCH_POLICY.choose(cells) == "device":
        t0 = time.perf_counter()
        item_factors = device_resident(item_factors)
        out = jax.device_get(
            _topk_scores_device(user_vecs, item_factors, mask, k=k))
        _record_dispatch("device", cells, time.perf_counter() - t0)
        return out
    t0 = time.perf_counter()
    DISPATCH_POLICY.host_begin()
    try:
        scores = np.asarray(user_vecs) @ np.asarray(item_factors).T  # lint: ok
        scores = np.where(np.asarray(mask), scores,  # lint: ok — host mask
                          np.float32(NEG_INF))
        out = _topk_host(scores, k)
    finally:
        DISPATCH_POLICY.host_end()
    _record_dispatch("host", cells, time.perf_counter() - t0)
    return out


def topk_similar(query_vecs, item_factors, mask, *, k: int):
    """Cosine-similarity top-k: used by the similarproduct template
    (`examples/scala-parallel-similarproduct/.../ALSAlgorithm.scala`
    cosine scoring). query_vecs [b, rank] are typically item vectors.
    Dispatches host/device by problem size (see module docstring)."""
    traced = _is_traced(query_vecs, item_factors, mask)
    k = min(k, item_factors.shape[0])   # both paths clamp identically
    cells = query_vecs.shape[0] * item_factors.shape[0]
    if traced:
        _record_dispatch("device", cells)
        return _topk_similar_device(query_vecs, item_factors, mask, k=k)
    if _on_device(query_vecs, item_factors) \
            or DISPATCH_POLICY.choose(cells) == "device":
        t0 = time.perf_counter()
        item_factors = device_resident(item_factors)
        out = jax.device_get(
            _topk_similar_device(query_vecs, item_factors, mask, k=k))
        _record_dispatch("device", cells, time.perf_counter() - t0)
        return out
    t0 = time.perf_counter()
    DISPATCH_POLICY.host_begin()
    try:
        q = np.asarray(query_vecs)      # lint: ok — host-path arrays
        f = np.asarray(item_factors)    # lint: ok — host-path arrays
        qn = q / (np.linalg.norm(q, axis=-1, keepdims=True) + 1e-9)
        fn = f / (np.linalg.norm(f, axis=-1, keepdims=True) + 1e-9)
        scores = np.where(np.asarray(mask), qn @ fn.T,  # lint: ok
                          np.float32(NEG_INF))
        out = _topk_host(scores, k)
    finally:
        DISPATCH_POLICY.host_end()
    _record_dispatch("host", cells, time.perf_counter() - t0)
    return out


def build_mask(n_items: int,
               blacklist_ix: Sequence[int] = (),
               whitelist_ix: Optional[Sequence[int]] = None,
               batch: int = 1) -> np.ndarray:
    """Host-side mask assembly from index lists (unknown ids are resolved
    to indexes by the caller via BiMap and simply absent here)."""
    if whitelist_ix is not None:
        mask = np.zeros(n_items, bool)
        mask[np.asarray(list(whitelist_ix), int)] = True  # lint: ok
    else:
        mask = np.ones(n_items, bool)
    if len(blacklist_ix):
        mask[np.asarray(list(blacklist_ix), int)] = False  # lint: ok
    return np.broadcast_to(mask, (batch, n_items))


# ---------------------------------------------------------------------------
# The deploy-warmed serving plan: bucketed AOT executables.
# ---------------------------------------------------------------------------

# Batch buckets warmed by default (powers of two; the micro-batcher's
# batch_max caps which of these a deployment actually compiles).
DEFAULT_SERVE_BUCKETS = (1, 2, 4, 8, 16, 32, 64)


class BucketedTopK:
    """Per-model serving plan: banned-index top-k over a device-resident
    factor matrix, one AOT-compiled executable per batch bucket.

    Built once at deploy warmup (`Algorithm.warm_serving` via
    `CoreWorkflow.prepare_deploy`):

      - the factor matrix is device-put ONCE and pinned for the plan's
        lifetime (no per-call re-transfer);
      - every bucket in `buckets` is `.lower(...).compile()`d up front
        with a FIXED banned width, so a serve call dispatches straight to
        a compiled executable — the jit tracing cache is never consulted
        and steady state is zero-recompile by construction (jaxprobe's
        `pio_jax_backend_compiles_total` stays flat across drains);
      - off-CPU, the padded query block and banned block are donated
        (their buffers are dead after the call by construction).

    A call pads the batch up to the smallest warmed bucket (padded lanes:
    zero vectors + all-filler bans; they are sliced off before return and
    can never leak into results) and pads/fills the banned block to the
    fixed width with `n_items`, which the scatter drops. Batches larger
    than the biggest bucket are chunked. Queries that DON'T fit the plan
    (k above `self.k`, more bans than `banned_width`, whitelists or
    category filters needing a dense mask) go through the generic
    `topk_scores*` entry points instead — callers gate on `fits()`.
    """

    def __init__(self, item_factors, *, k: int,
                 buckets: Sequence[int] = DEFAULT_SERVE_BUCKETS,
                 banned_width: int = 256):
        host = np.ascontiguousarray(item_factors, dtype=np.float32)
        self.n_items, self.rank = host.shape
        self.k = max(1, min(k, self.n_items))
        self.buckets = tuple(sorted({_next_pow2(b)
                                     for b in buckets if b > 0})) or (1,)
        self.banned_width = _next_pow2(max(1, banned_width))
        # share the identity-keyed residency cache with the generic paths
        # (keep the host alias alive so the weakref cache entry survives)
        self._host_factors = host
        self.factors = device_resident(host)
        self._exe: dict = {}
        # buckets served by the single-launch fused kernel (see
        # ops/fused_topk.py); the rest keep the XLA chain
        self.fused_buckets = 0
        # which bucket sizes went fused, so dispatch attribution can
        # tag "fused" vs "device" per call
        self._fused_sizes: set = set()
        register_resident_plan(self)

    def resident_per_device_bytes(self) -> float:
        """Bytes this plan pins on ONE device (the whole factor block:
        single-device plans are not sharded)."""
        return float(self._host_factors.nbytes)

    def warm(self) -> int:
        """AOT-lower/compile every bucket executable; returns how many
        were compiled (idempotent: already-warm buckets are skipped).

        Each bucket first tries the single-launch fused kernel
        (`ops/fused_topk.py`, gated by PIO_SERVE_FUSED) and falls back
        to the AOT XLA chain when fusion is off or unsupported — both
        compile to the same `(vecs, factors, banned)` signature, so
        `swap_factors` and the zero-recompile contract hold either
        way."""
        from predictionio_tpu.ops import fused_topk
        fn = (_topk_scores_banned_device
              if jax.default_backend() == "cpu"
              else _topk_scores_banned_donated)
        compiled = 0
        for b in self.buckets:
            if b in self._exe:
                continue
            exe = fused_topk.maybe_build_bucket(
                self.factors, n_items=self.n_items, rank=self.rank,
                k=self.k, bucket=b, banned_width=self.banned_width)
            if exe is not None:
                self.fused_buckets += 1
                self._fused_sizes.add(b)
            else:
                vec_spec = jax.ShapeDtypeStruct((b, self.rank),
                                                np.float32)
                ban_spec = jax.ShapeDtypeStruct((b, self.banned_width),
                                                np.int32)
                exe = fn.lower(vec_spec, self.factors, ban_spec,
                               k=self.k, has_bans=True).compile()
            self._exe[b] = exe
            compiled += 1
        return compiled

    def swap_factors(self, item_factors) -> np.ndarray:
        """Hot-swap the resident factor block (the streaming refresher's
        commit). The bucket executables take the factor operand
        POSITIONALLY per call, so a same-shape/dtype replacement reuses
        every AOT executable — only the new block crosses host->device,
        zero recompiles. Returns the PREVIOUS host factors (the
        rollback token). Shape changes must re-warm instead."""
        host = np.ascontiguousarray(item_factors, dtype=np.float32)
        if host.shape != (self.n_items, self.rank):
            raise ValueError(
                f"swap_factors shape {host.shape} != "
                f"{(self.n_items, self.rank)}: catalog changed — a hot "
                "swap cannot resize the AOT plan; re-warm instead")
        prev = self._host_factors
        self._host_factors = host
        self.factors = device_resident(host)
        return prev

    @property
    def max_bucket(self) -> int:
        return self.buckets[-1]

    def fits(self, *, max_banned: int, k: int) -> bool:
        """Whether a batch with these parameters can use the plan."""
        return (bool(self._exe)
                and k <= self.k and max_banned <= self.banned_width)

    def _bucket_for(self, b: int) -> int:
        for bucket in self.buckets:
            if bucket >= b:
                return bucket
        return self.max_bucket

    def __call__(self, user_vecs, banned_lists: Sequence[Sequence[int]]):
        """Score `user_vecs` [b, rank] against the resident factors with
        per-row banned-index lists; returns host (scores [b, k],
        indexes [b, k]). Pads to the bucket grid; chunks past the biggest
        bucket."""
        user_vecs = np.asarray(user_vecs, np.float32)  # lint: ok — host in
        b = user_vecs.shape[0]
        if b > self.max_bucket:
            parts = [self(user_vecs[lo:lo + self.max_bucket],
                          banned_lists[lo:lo + self.max_bucket])
                     for lo in range(0, b, self.max_bucket)]
            return (np.concatenate([p[0] for p in parts]),
                    np.concatenate([p[1] for p in parts]))
        bucket = self._bucket_for(b)
        exe = self._exe.get(bucket)
        if exe is None:
            raise RuntimeError(
                f"BucketedTopK bucket {bucket} not warmed; call warm() "
                "at deploy time")
        t0 = time.perf_counter()
        vecs = np.zeros((bucket, self.rank), np.float32)
        vecs[:b] = user_vecs
        banned = np.full((bucket, self.banned_width), self.n_items,
                         np.int32)
        for row, bl in enumerate(banned_lists):
            if len(bl):
                banned[row, :len(bl)] = np.asarray(bl, np.int32)  # lint: ok
        scores, ixs = jax.device_get(exe(vecs, self.factors, banned))
        _record_dispatch(
            "fused" if bucket in self._fused_sizes else "device",
            bucket * self.n_items, time.perf_counter() - t0)
        return scores[:b], ixs[:b]


class BucketedSimilar:
    """Serving plan for the dense-mask cosine path (the similar-product
    template's `batch_predict`): item factors pinned device-resident and
    one AOT-compiled `_topk_similar_raw` executable per batch bucket, so
    a warmed deployment serves its first similar-items request — and
    every coalesced batch after it — without touching the jit tracing
    cache.

    Unlike `BucketedTopK` the filter here is the template's dense
    [b, n_items] category/white/black mask, so the mask block is padded
    to the bucket with all-False rows (their lanes score NEG_INF and are
    sliced off before return). Batches above the biggest bucket chunk.
    """

    def __init__(self, item_factors, *, k: int,
                 buckets: Sequence[int] = DEFAULT_SERVE_BUCKETS):
        host = np.ascontiguousarray(item_factors, dtype=np.float32)
        self.n_items, self.rank = host.shape
        self.k = max(1, min(k, self.n_items))
        self.buckets = tuple(sorted({_next_pow2(b)
                                     for b in buckets if b > 0})) or (1,)
        self._host_factors = host
        self.factors = device_resident(host)
        self._exe: dict = {}
        register_resident_plan(self)

    def resident_per_device_bytes(self) -> float:
        return float(self._host_factors.nbytes)

    def warm(self) -> int:
        """AOT-lower/compile every bucket executable (idempotent)."""
        fn = (_topk_similar_device if jax.default_backend() == "cpu"
              else _topk_similar_donated)
        compiled = 0
        for b in self.buckets:
            if b in self._exe:
                continue
            vec_spec = jax.ShapeDtypeStruct((b, self.rank), np.float32)
            mask_spec = jax.ShapeDtypeStruct((b, self.n_items), np.bool_)
            self._exe[b] = fn.lower(vec_spec, self.factors, mask_spec,
                                    k=self.k).compile()
            compiled += 1
        return compiled

    def swap_factors(self, item_factors) -> np.ndarray:
        """Hot-swap the resident factor block without recompiling (the
        executables take the factors positionally); returns the
        previous host factors as the rollback token. See
        `BucketedTopK.swap_factors`."""
        host = np.ascontiguousarray(item_factors, dtype=np.float32)
        if host.shape != (self.n_items, self.rank):
            raise ValueError(
                f"swap_factors shape {host.shape} != "
                f"{(self.n_items, self.rank)}: catalog changed — a hot "
                "swap cannot resize the AOT plan; re-warm instead")
        prev = self._host_factors
        self._host_factors = host
        self.factors = device_resident(host)
        return prev

    @property
    def max_bucket(self) -> int:
        return self.buckets[-1]

    def fits(self, *, k: int) -> bool:
        return bool(self._exe) and k <= self.k

    def _bucket_for(self, b: int) -> int:
        for bucket in self.buckets:
            if bucket >= b:
                return bucket
        return self.max_bucket

    def __call__(self, query_vecs, mask):
        """Cosine top-k of `query_vecs` [b, rank] against the resident
        factors under dense mask [b, n_items]; returns host (scores
        [b, k], indexes [b, k])."""
        query_vecs = np.asarray(query_vecs, np.float32)  # lint: ok — host in
        mask = np.asarray(mask, bool)                    # lint: ok — host in
        b = query_vecs.shape[0]
        if b > self.max_bucket:
            parts = [self(query_vecs[lo:lo + self.max_bucket],
                          mask[lo:lo + self.max_bucket])
                     for lo in range(0, b, self.max_bucket)]
            return (np.concatenate([p[0] for p in parts]),
                    np.concatenate([p[1] for p in parts]))
        bucket = self._bucket_for(b)
        exe = self._exe.get(bucket)
        if exe is None:
            raise RuntimeError(
                f"BucketedSimilar bucket {bucket} not warmed; call warm() "
                "at deploy time")
        t0 = time.perf_counter()
        vecs = np.zeros((bucket, self.rank), np.float32)
        vecs[:b] = query_vecs
        mask_p = np.zeros((bucket, self.n_items), bool)
        mask_p[:b] = mask
        scores, ixs = jax.device_get(exe(vecs, self.factors, mask_p))
        _record_dispatch("device", bucket * self.n_items,
                         time.perf_counter() - t0)
        return scores[:b], ixs[:b]

"""Masked top-k scoring — the serve-time hot path of every recommender.

The reference serves queries one at a time and even notes "TODO:
Parallelize" (`core/.../workflow/CreateServer.scala:494`); its per-query
work is a driver-side loop over `recommendProducts`
(`examples/.../ALSAlgorithm.scala:96-112`). Here scoring is one
program: a query batch of user vectors against the full item factor matrix
(a matmul), additive masks for blacklist/seen/whitelist filters, then
top-k — so batching queries is free.

Host/device dispatch: `topk_scores`/`topk_similar` route by score-matrix
size. Small problems (a handful of live queries against a catalog of
thousands) run as host BLAS in microseconds — pushing them through the
accelerator costs a dispatch + a device->host readback round trip that
dwarfs the compute on any hardware, and by orders of magnitude over a
remote/tunneled device. Large batches (offline batchpredict, eval sweeps,
big catalogs) go to the jit'd device kernel where the MXU matmul wins and
the transfer amortizes. Inside a jit trace the device path is always used
(host numpy cannot trace).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = -1e30

# [b, n_items] score cells below which the host path wins. Environment-
# dependent (host BLAS speed x device dispatch overhead): the r4 bench
# measures it empirically (serve_topk_crossover_cells_measured metric —
# ~0.8M cells on a tunneled v5e with single-threaded numpy, where device
# batch-64 scoring is ~1200x the host's). The default stays conservative
# for fast-host/cold-device setups; operators can pin the measured value
# via PIO_TOPK_HOST_CROSSOVER_CELLS.
import os as _os

HOST_CROSSOVER_CELLS = int(_os.environ.get(
    "PIO_TOPK_HOST_CROSSOVER_CELLS", 4 << 20))

# Dispatch evidence: incremented per call by which path actually served
# it (the traced/jit path counts as "device" — it compiles into a device
# program). Read by the bench to PROVE the device path ran, and by tests;
# plain ints under the GIL (worst case a lost increment, never a wrong
# path).
DISPATCH_COUNTS = {"host": 0, "device": 0}


@partial(jax.jit, static_argnames=("k",))
def _topk_scores_device(user_vecs, item_factors, mask, *, k: int):
    # HIGHEST precision: the host path computes exact f32, and the two
    # paths must rank near-tied scores identically (default TPU matmul
    # precision is bf16-pass and would reorder them)
    scores = jnp.matmul(user_vecs, item_factors.T,
                        precision=jax.lax.Precision.HIGHEST)
    scores = jnp.where(mask, scores, NEG_INF)
    return jax.lax.top_k(scores, k)


@partial(jax.jit, static_argnames=("k",))
def _topk_similar_device(query_vecs, item_factors, mask, *, k: int):
    qn = query_vecs / (jnp.linalg.norm(query_vecs, axis=-1, keepdims=True)
                       + 1e-9)
    fn = item_factors / (jnp.linalg.norm(item_factors, axis=-1, keepdims=True)
                         + 1e-9)
    scores = jnp.matmul(qn, fn.T, precision=jax.lax.Precision.HIGHEST)
    scores = jnp.where(mask, scores, NEG_INF)
    return jax.lax.top_k(scores, k)


def _is_traced(*arrays) -> bool:
    return any(isinstance(a, jax.core.Tracer) for a in arrays)


def _on_device(*arrays) -> bool:
    return any(isinstance(a, jax.Array) for a in arrays)


def _topk_host(scores: np.ndarray, k: int):
    """Full stable argsort (cheap at host-path sizes) so tie-breaking
    matches lax.top_k's lowest-index-first guarantee — the host and
    device paths must return identical results for the same query.

    Cross-path parity is exact only for bitwise-equal scores (e.g. the
    integer-valued factors in the parity tests): the host matmul is exact
    f32 BLAS while the device path is XLA Precision.HIGHEST, so near-tied
    (but not equal) scores can still rank differently at the last ulp.
    Indices are cast to int32 to match lax.top_k's return dtype."""
    k = min(k, scores.shape[1])
    ix = np.argsort(-scores, axis=1, kind="stable")[:, :k]
    return np.take_along_axis(scores, ix, axis=1), ix.astype(np.int32)


# ---------------------------------------------------------------------------
# Device-resident model arrays and the banned-index device path.
#
# The serving hot loop calls topk with the SAME host factor matrix every
# time; without caching, each device dispatch re-uploads it (measured:
# a 500k x 64 catalog is 128 MB -> ~2.5 s/call over a tunneled device,
# and a real PCIe host still pays ~13 ms/call). `device_resident` uploads
# once per (array identity) and returns the cached jax.Array.
# ---------------------------------------------------------------------------

_DEVICE_RESIDENT: dict = {}


def device_resident(arr):
    """Device-put `arr` once and cache by object identity (evicted when
    the host array is garbage-collected). jax arrays pass through."""
    import weakref

    if isinstance(arr, (jax.Array, jax.core.Tracer)):
        return arr
    key = id(arr)
    hit = _DEVICE_RESIDENT.get(key)
    if hit is not None and hit[0]() is arr:
        return hit[1]
    dev = jax.device_put(arr)
    ref = weakref.ref(arr, lambda _, key=key: _DEVICE_RESIDENT.pop(key, None))
    _DEVICE_RESIDENT[key] = (ref, dev)
    return dev


@partial(jax.jit, static_argnames=("k", "has_bans"))
def _topk_scores_banned_device(user_vecs, item_factors, banned, *,
                               k: int, has_bans: bool):
    scores = jnp.matmul(user_vecs, item_factors.T,
                        precision=jax.lax.Precision.HIGHEST)
    if has_bans:
        rows = jnp.arange(scores.shape[0])[:, None]
        # out-of-range fill indices (== n_items) are dropped
        scores = scores.at[rows, banned].set(NEG_INF, mode="drop")
    return jax.lax.top_k(scores, k)


def _next_pow2(n: int) -> int:
    return 1 << max(n - 1, 0).bit_length() if n > 1 else 1


def topk_scores_filtered(user_vecs, item_factors, banned_lists, *, k: int):
    """Top-k scoring with per-query banned-item index lists (blacklist /
    seen filtering) instead of a dense [b, n_items] mask.

    Host/device dispatch as `topk_scores`, but the device path builds the
    filter ON DEVICE from a small padded [b, max_banned] index array —
    uploading a dense bool mask per batch costs b*n_items bytes (32 MB at
    batch 64 x 500k items) per call, while the index form is a few KB.
    The factor matrix goes through `device_resident`. Batch and
    banned-width are padded to powers of two so the jit cache stays at
    O(log^2) variants instead of one per observed shape.

    Whitelists need the dense-mask form — use `topk_scores` for those.
    """
    n_items = item_factors.shape[0]
    k = min(k, n_items)
    b = user_vecs.shape[0]
    cells = b * n_items
    traced = _is_traced(user_vecs, item_factors)
    on_dev = _on_device(user_vecs, item_factors)
    max_banned = max((len(bl) for bl in banned_lists), default=0)
    wp = _next_pow2(max_banned) if max_banned else 0
    if not traced and not on_dev and cells < HOST_CROSSOVER_CELLS:
        # small problems: densify the filter and delegate so the host
        # scoring/tie-breaking path exists in exactly one place
        mask = np.ones((b, n_items), bool)
        for row, banned in enumerate(banned_lists):
            if len(banned):
                mask[row, np.asarray(banned, int)] = False
        return topk_scores(user_vecs, item_factors, mask, k=k)
    DISPATCH_COUNTS["device"] += 1
    banned_np = np.full((b, max(wp, 1)), n_items, np.int32)
    for row, bl in enumerate(banned_lists):
        if len(bl):
            banned_np[row, :len(bl)] = np.asarray(bl, np.int32)
    if traced or on_dev:
        # traced / already-on-device inputs: no host-side padding
        # round-trip; shapes are what the trace gives us
        out = _topk_scores_banned_device(
            user_vecs, item_factors, jnp.asarray(banned_np), k=k,
            has_bans=wp > 0)
        return out if traced else jax.device_get(out)
    # host inputs: pad batch to a power of two to bound jit variants
    bp = _next_pow2(b)
    vecs = np.zeros((bp, user_vecs.shape[1]), np.float32)
    vecs[:b] = user_vecs
    banned_pad = np.full((bp, max(wp, 1)), n_items, np.int32)
    banned_pad[:b] = banned_np
    out = _topk_scores_banned_device(
        jnp.asarray(vecs), device_resident(item_factors),
        jnp.asarray(banned_pad), k=k, has_bans=wp > 0)
    scores, ixs = jax.device_get(out)
    return scores[:b], ixs[:b]


def topk_scores(user_vecs, item_factors, mask, *, k: int):
    """scores = U @ Y^T with invalid items masked out.

    user_vecs:    [b, rank]
    item_factors: [n_items, rank]
    mask:         [b, n_items] bool — True = item allowed for that query
    Returns (scores [b, k], indexes [b, k]); masked-out slots score NEG_INF.
    Dispatches host/device by problem size (see module docstring).
    """
    traced = _is_traced(user_vecs, item_factors, mask)
    k = min(k, item_factors.shape[0])   # both paths clamp identically
    cells = user_vecs.shape[0] * item_factors.shape[0]
    if traced or _on_device(user_vecs, item_factors) \
            or cells >= HOST_CROSSOVER_CELLS:
        DISPATCH_COUNTS["device"] += 1
        if not traced:
            item_factors = device_resident(item_factors)
        out = _topk_scores_device(user_vecs, item_factors, mask, k=k)
        return out if traced else jax.device_get(out)
    DISPATCH_COUNTS["host"] += 1
    scores = np.asarray(user_vecs) @ np.asarray(item_factors).T
    scores = np.where(np.asarray(mask), scores, np.float32(NEG_INF))
    return _topk_host(scores, k)


def topk_similar(query_vecs, item_factors, mask, *, k: int):
    """Cosine-similarity top-k: used by the similarproduct template
    (`examples/scala-parallel-similarproduct/.../ALSAlgorithm.scala`
    cosine scoring). query_vecs [b, rank] are typically item vectors.
    Dispatches host/device by problem size (see module docstring)."""
    traced = _is_traced(query_vecs, item_factors, mask)
    k = min(k, item_factors.shape[0])   # both paths clamp identically
    cells = query_vecs.shape[0] * item_factors.shape[0]
    if traced or _on_device(query_vecs, item_factors) \
            or cells >= HOST_CROSSOVER_CELLS:
        DISPATCH_COUNTS["device"] += 1
        if not traced:
            item_factors = device_resident(item_factors)
        out = _topk_similar_device(query_vecs, item_factors, mask, k=k)
        return out if traced else jax.device_get(out)
    DISPATCH_COUNTS["host"] += 1
    q = np.asarray(query_vecs)
    f = np.asarray(item_factors)
    qn = q / (np.linalg.norm(q, axis=-1, keepdims=True) + 1e-9)
    fn = f / (np.linalg.norm(f, axis=-1, keepdims=True) + 1e-9)
    scores = np.where(np.asarray(mask), qn @ fn.T, np.float32(NEG_INF))
    return _topk_host(scores, k)


def build_mask(n_items: int,
               blacklist_ix: Sequence[int] = (),
               whitelist_ix: Optional[Sequence[int]] = None,
               batch: int = 1) -> np.ndarray:
    """Host-side mask assembly from index lists (unknown ids are resolved
    to indexes by the caller via BiMap and simply absent here)."""
    if whitelist_ix is not None:
        mask = np.zeros(n_items, bool)
        mask[np.asarray(list(whitelist_ix), int)] = True
    else:
        mask = np.ones(n_items, bool)
    if len(blacklist_ix):
        mask[np.asarray(list(blacklist_ix), int)] = False
    return np.broadcast_to(mask, (batch, n_items))

"""Masked top-k scoring — the serve-time hot path of every recommender.

The reference serves queries one at a time and even notes "TODO:
Parallelize" (`core/.../workflow/CreateServer.scala:494`); its per-query
work is a driver-side loop over `recommendProducts`
(`examples/.../ALSAlgorithm.scala:96-112`). Here scoring is one jit'd
program: a query batch of user vectors against the full item factor matrix
(an MXU matmul), additive masks for blacklist/seen/whitelist filters, then
`lax.top_k` — so batching queries is free.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = -1e30


@partial(jax.jit, static_argnames=("k",))
def topk_scores(user_vecs, item_factors, mask, *, k: int):
    """scores = U @ Y^T with invalid items masked out.

    user_vecs:    [b, rank]
    item_factors: [n_items, rank]
    mask:         [b, n_items] bool — True = item allowed for that query
    Returns (scores [b, k], indexes [b, k]); masked-out slots score NEG_INF.
    """
    scores = user_vecs @ item_factors.T
    scores = jnp.where(mask, scores, NEG_INF)
    return jax.lax.top_k(scores, k)


@partial(jax.jit, static_argnames=("k",))
def topk_similar(query_vecs, item_factors, mask, *, k: int):
    """Cosine-similarity top-k: used by the similarproduct template
    (`examples/scala-parallel-similarproduct/.../ALSAlgorithm.scala`
    cosine scoring). query_vecs [b, rank] are typically item vectors."""
    qn = query_vecs / (jnp.linalg.norm(query_vecs, axis=-1, keepdims=True)
                       + 1e-9)
    fn = item_factors / (jnp.linalg.norm(item_factors, axis=-1, keepdims=True)
                         + 1e-9)
    scores = qn @ fn.T
    scores = jnp.where(mask, scores, NEG_INF)
    return jax.lax.top_k(scores, k)


def build_mask(n_items: int,
               blacklist_ix: Sequence[int] = (),
               whitelist_ix: Optional[Sequence[int]] = None,
               batch: int = 1) -> np.ndarray:
    """Host-side mask assembly from index lists (unknown ids are resolved
    to indexes by the caller via BiMap and simply absent here)."""
    if whitelist_ix is not None:
        mask = np.zeros(n_items, bool)
        mask[np.asarray(list(whitelist_ix), int)] = True
    else:
        mask = np.ones(n_items, bool)
    if len(blacklist_ix):
        mask[np.asarray(list(blacklist_ix), int)] = False
    return np.broadcast_to(mask, (batch, n_items))

"""Two-tower neural retrieval model.

A NEW capability beyond the reference (SURVEY.md §7 phase 7 / BASELINE.md
config 5): embedding towers for users and items trained with in-batch
sampled softmax on interaction events — the standard neural retrieval
architecture the reference's ALS templates graduate to.

TPU design: one jit'd train step (embedding lookups -> MLP towers ->
in-batch softmax loss -> adam update), batch dimension sharded over the
mesh "data" axis so gradients all-reduce over ICI; inference materializes
both towers' embeddings once and serves via the same masked top-k matmul
as every other recommender.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class TwoTowerModel:
    user_emb: np.ndarray    # [n_users, dim] final tower outputs
    item_emb: np.ndarray    # [n_items, dim]
    # raw tower weights, kept so streaming fold-in can run a warm-start
    # mini-epoch from the converged state (None on artifacts trained
    # before the streaming subsystem existed — those fall back to a
    # full rebuild)
    params: Optional[dict] = None

    def sanity_check(self):
        assert np.isfinite(self.user_emb).all()
        assert np.isfinite(self.item_emb).all()


def _init_params(key, n_users: int, n_items: int, emb_dim: int,
                 hidden: int, out_dim: int):
    ks = jax.random.split(key, 6)
    scale = 1.0 / np.sqrt(emb_dim)

    def dense(k, fan_in, fan_out):
        return (jax.random.normal(k, (fan_in, fan_out), jnp.float32)
                / np.sqrt(fan_in))

    return {
        "user_table": jax.random.normal(
            ks[0], (n_users, emb_dim), jnp.float32) * scale,
        "item_table": jax.random.normal(
            ks[1], (n_items, emb_dim), jnp.float32) * scale,
        "user_w1": dense(ks[2], emb_dim, hidden),
        "user_w2": dense(ks[3], hidden, out_dim),
        "item_w1": dense(ks[4], emb_dim, hidden),
        "item_w2": dense(ks[5], hidden, out_dim),
    }


def _tower(table, w1, w2, ix):
    h = jax.nn.relu(table[ix] @ w1)
    out = h @ w2
    return out / (jnp.linalg.norm(out, axis=-1, keepdims=True) + 1e-8)


def _loss_fn(params, u_ix, i_ix, temperature):
    """In-batch sampled softmax: each (u, i) pair treats the other items
    in the batch as negatives."""
    u = _tower(params["user_table"], params["user_w1"], params["user_w2"],
               u_ix)
    v = _tower(params["item_table"], params["item_w1"], params["item_w2"],
               i_ix)
    logits = (u @ v.T) / temperature                  # [b, b]
    labels = jnp.arange(u_ix.shape[0])
    return -jnp.mean(jax.nn.log_softmax(logits, axis=1)[labels, labels])


def twotower_train(u_ix: np.ndarray, i_ix: np.ndarray, *,
                   n_users: int, n_items: int,
                   emb_dim: int = 32, hidden: int = 64, out_dim: int = 32,
                   batch_size: int = 1024, epochs: int = 10,
                   lr: float = 1e-2, temperature: float = 0.1,
                   seed: int = 0, mesh=None,
                   init_params: Optional[dict] = None) -> TwoTowerModel:
    """Train on interaction pairs; returns materialized tower embeddings.

    `init_params` resumes from a prior model's weights (the streaming
    warm-start mini-epoch); optimizer state starts fresh, so a single
    epoch from converged weights moves them only slightly.
    """
    import optax

    n = len(u_ix)
    if n == 0:
        raise ValueError("no interaction pairs")
    batch_size = min(batch_size, n)
    key = jax.random.PRNGKey(seed)
    if init_params is not None:
        params = {k: jnp.asarray(v) for k, v in init_params.items()}
    else:
        params = _init_params(key, n_users, n_items, emb_dim, hidden,
                              out_dim)
    if mesh is not None and "model" in mesh.axis_names:
        # tensor parallelism: embedding tables row-sharded over "model"
        # (vocab dim), tower MLPs Megatron-style (w1 col-, w2 row-sharded);
        # XLA inserts the gathers/reduces over ICI
        from jax.sharding import NamedSharding, PartitionSpec as P

        def put(name, arr):
            spec = {"user_table": P("model", None),
                    "item_table": P("model", None),
                    "user_w1": P(None, "model"),
                    "item_w1": P(None, "model"),
                    "user_w2": P("model", None),
                    "item_w2": P("model", None)}[name]
            return jax.device_put(arr, NamedSharding(mesh, spec))

        params = {k: put(k, v) for k, v in params.items()}
    tx = optax.adam(lr)
    opt_state = tx.init(params)

    def step(params, opt_state, ub, ib):
        loss, grads = jax.value_and_grad(_loss_fn)(params, ub, ib,
                                                   temperature)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    rng = np.random.RandomState(seed)
    steps_per_epoch = max(n // batch_size, 1)
    if mesh is not None:
        # sharded batches arrive via device_put per step (the epoch data
        # is resharded by the mesh's batch sharding); dispatch overhead
        # is irrelevant under the virtual test meshes
        from predictionio_tpu.parallel import batch_sharding
        sharding = batch_sharding(mesh)          # dim 0 over "data"
        data_size = int(mesh.shape.get("data", 1))
        step = jax.jit(step)
        for _ in range(epochs):
            order = rng.permutation(n)
            for s in range(steps_per_epoch):
                sel = order[s * batch_size:(s + 1) * batch_size]
                ub, ib = jnp.asarray(u_ix[sel]), jnp.asarray(i_ix[sel])
                if len(sel) % data_size == 0:
                    ub = jax.device_put(ub, sharding)
                    ib = jax.device_put(ib, sharding)
                params, opt_state, loss = step(params, opt_state, ub, ib)
    else:
        # single-device: ONE dispatch per epoch via lax.scan over the
        # pre-uploaded shuffled batches. A per-step dispatch pays the
        # host round trip hundreds of times per epoch (~100 ms each on
        # the tunneled bench runtime — the epoch would be RTT-bound,
        # not compute-bound)
        @jax.jit
        def epoch(params, opt_state, ub_all, ib_all):
            def body(carry, batch):
                p, o = carry
                ub, ib = batch
                p, o, loss = step(p, o, ub, ib)
                return (p, o), loss
            (params, opt_state), losses = jax.lax.scan(
                body, (params, opt_state), (ub_all, ib_all))
            return params, opt_state, losses

        m = steps_per_epoch * batch_size
        for _ in range(epochs):
            order = rng.permutation(n)[:m]
            ub_all = jnp.asarray(
                u_ix[order].reshape(steps_per_epoch, batch_size))
            ib_all = jnp.asarray(
                i_ix[order].reshape(steps_per_epoch, batch_size))
            params, opt_state, _ = epoch(params, opt_state, ub_all, ib_all)

    # one jitted program per tower (eager op-by-op materialization
    # compiles a handful of micro-programs per call; observed to tickle
    # a flaky XLA-CPU compiler crash in long-lived test processes)
    tower = jax.jit(_tower)
    user_emb = tower(params["user_table"], params["user_w1"],
                     params["user_w2"], jnp.arange(n_users))
    item_emb = tower(params["item_table"], params["item_w1"],
                     params["item_w2"], jnp.arange(n_items))
    return TwoTowerModel(np.asarray(user_emb), np.asarray(item_emb),
                         params={k: np.asarray(v)
                                 for k, v in params.items()})

"""Single-launch fused serve kernel: gather -> matmul -> ban-mask -> top-k.

The AOT serving plans in `ops/topk.py` run the banned-index hot path as
an XLA chain: a full [b, n_items] score matrix is materialized in HBM,
a scatter stamps NEG_INF over the banned columns, and `lax.top_k` sorts
every row. At serve batch sizes the matmul itself is microseconds — the
cost is the HBM round trip of the score matrix plus the multi-kernel
launch train. This module collapses the whole chain into ONE Pallas
launch per batch bucket:

  - the item catalog streams through VMEM in `PIO_FUSED_TILE_ITEMS`-row
    tiles (grid over item tiles; the full score matrix never exists in
    HBM);
  - each tile's scores are computed on the MXU
    (`preferred_element_type=f32`, `Precision.HIGHEST` — identical math
    to the XLA chain), banned GLOBAL ids are masked by comparison
    against the tile's id range (the `n_items` filler never matches a
    real id), catalog-padding rows are masked to NEG_INF;
  - a running [b, k] (score, id) scoreboard carried in the output
    blocks merges each tile via k selection steps with an explicit
    (max score, lowest id) key — exactly `lax.top_k`'s documented
    lowest-index-first tie-break, so the fused outputs are
    BIT-IDENTICAL to the `_topk_scores_banned` oracle whenever the
    per-cell dot products are (always true for the integer-valued
    factors the parity tests use; real factors agree to the last ulp
    of the two matmuls). Removed scoreboard entries are parked at
    -inf, strictly below the NEG_INF ban value, so a banned item can
    be emitted (matching the oracle) but never emitted twice.

Availability is gated by `PIO_SERVE_FUSED`:

  auto  (default) fuse only on TPU backends — Mosaic is the target;
                  CPU/GPU keep the proven XLA chain;
  on              fuse everywhere; non-TPU backends run the kernel in
                  Pallas interpret mode (traced to plain XLA ops — the
                  parity tests exercise exactly this);
  off             never fuse.

Every builder is fallible by design: `maybe_build_bucket` /
`shard_local_candidates` return None (and `BucketedTopK.warm` /
`ShardedBucketedTopK` fall back to the AOT XLA chain) when fusion is
off or the kernel fails to lower on this backend. The compiled
executable keeps the exact `(vecs, factors, banned)` positional
signature of the chain it replaces, so `swap_factors` hot-swaps and the
zero-recompile steady state are preserved unchanged; off-CPU the
per-call query and banned blocks are donated exactly as before.
"""

from __future__ import annotations

import functools
import logging
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # TPU memory-space enum; absent on exotic builds — SMEM scalar
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover - pallas.tpu ships with jax
    pltpu = None

from predictionio_tpu.ops.topk import NEG_INF

log = logging.getLogger("pio.ops.fused")

# items per VMEM tile (clamped up to k so every merge sees >= k real
# candidates and the scoreboard fillers can never leak into results)
DEFAULT_TILE_ITEMS = 512

# scoreboard sentinels: removed entries park BELOW the NEG_INF ban
# value so they are never re-picked; filler ids park ABOVE every real
# id so the lowest-id tie-break prefers any real item
_REMOVED = np.float32(-np.inf)
_FILLER_ID = np.int32(2**31 - 1)


def fused_mode() -> str:
    """Normalized PIO_SERVE_FUSED: "auto" | "on" | "off"."""
    raw = (os.environ.get("PIO_SERVE_FUSED", "auto") or "auto").lower()
    if raw in ("off", "0", "false", "no"):
        return "off"
    if raw in ("on", "1", "true", "yes"):
        return "on"
    return "auto"


def fused_wanted() -> bool:
    """Whether serve plans should attempt the fused kernel at warmup."""
    mode = fused_mode()
    if mode == "off":
        return False
    if mode == "on":
        return True
    return jax.default_backend() == "tpu"


def _interpret() -> bool:
    """Pallas interpret mode (kernel traced to plain XLA) everywhere
    except real TPU backends, where Mosaic compiles it natively."""
    return jax.default_backend() != "tpu"


def _tile_items(k: int) -> int:
    tile = int(os.environ.get("PIO_FUSED_TILE_ITEMS", "0") or 0)
    if tile <= 0:
        tile = DEFAULT_TILE_ITEMS
    return max(tile, k)


def _merge_body(n_valid, t, vecs_ref, fac_ref, ban_ref,
                out_s_ref, out_i_ref, *, k: int, tile: int,
                n_banned: int) -> None:
    """One grid step: score this item tile, mask bans/padding, merge
    into the running scoreboard carried by the output blocks."""
    b = vecs_ref.shape[0]

    @pl.when(t == 0)
    def _init():
        out_s_ref[...] = jnp.full((b, k), _REMOVED, jnp.float32)
        out_i_ref[...] = jnp.full((b, k), _FILLER_ID, jnp.int32)

    # [b, tile] tile scores — same contraction/precision as the chain
    scores = jax.lax.dot_general(
        vecs_ref[...], fac_ref[...], (((1,), (1,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32)
    gidx = t * tile + jax.lax.broadcasted_iota(jnp.int32, (b, tile), 1)
    scores = jnp.where(gidx < n_valid, scores, np.float32(NEG_INF))

    ban = ban_ref[...]

    def ban_body(w, sc):
        col = jax.lax.dynamic_slice_in_dim(ban, w, 1, axis=1)  # [b,1]
        return jnp.where(col == gidx, np.float32(NEG_INF), sc)

    scores = jax.lax.fori_loop(0, n_banned, ban_body, scores)

    # k-step selection over scoreboard + tile with the explicit
    # (max score, lowest id) key of lax.top_k
    comb_s = jnp.concatenate([out_s_ref[...], scores], axis=1)
    comb_i = jnp.concatenate([out_i_ref[...], gidx], axis=1)
    kcol = jax.lax.broadcasted_iota(jnp.int32, (b, k), 1)

    def step(j, carry):
        cs, outs, outi = carry
        m = jnp.max(cs, axis=1, keepdims=True)
        is_m = cs == m
        pick = jnp.min(jnp.where(is_m, comb_i, _FILLER_ID),
                       axis=1, keepdims=True)
        cs = jnp.where(is_m & (comb_i == pick), _REMOVED, cs)
        outs = jnp.where(kcol == j, m, outs)
        outi = jnp.where(kcol == j, pick, outi)
        return cs, outs, outi

    _, outs, outi = jax.lax.fori_loop(
        0, k, step, (comb_s,
                     jnp.zeros((b, k), jnp.float32),
                     jnp.zeros((b, k), jnp.int32)))
    out_s_ref[...] = outs
    out_i_ref[...] = outi


def _kernel_static(vecs_ref, fac_ref, ban_ref, out_s_ref, out_i_ref, *,
                   n_valid: int, k: int, tile: int,
                   n_banned: int) -> None:
    """Single-device form: the valid-row bound is the static catalog
    size baked into the trace."""
    _merge_body(n_valid, pl.program_id(0), vecs_ref, fac_ref, ban_ref,
                out_s_ref, out_i_ref, k=k, tile=tile, n_banned=n_banned)


def _kernel_dynamic(nv_ref, vecs_ref, fac_ref, ban_ref, out_s_ref,
                    out_i_ref, *, k: int, tile: int,
                    n_banned: int) -> None:
    """Sharded form: each shard's valid-row bound depends on its mesh
    position, so it arrives as a scalar operand (SMEM on TPU)."""
    _merge_body(nv_ref[0], pl.program_id(0), vecs_ref, fac_ref, ban_ref,
                out_s_ref, out_i_ref, k=k, tile=tile, n_banned=n_banned)


def _pallas_topk(n_rows: int, rank: int, *, k: int, bucket: int,
                 banned_width: int, n_valid: Optional[int],
                 interpret: bool):
    """The raw fused callable for one bucket. With `n_valid` set the
    bound is static (single-device); with `n_valid=None` the callable
    takes a leading [1] int32 bound operand (per-shard form)."""
    tile = _tile_items(k)
    nt = -(-n_rows // tile)
    specs = [pl.BlockSpec((bucket, rank), lambda i: (0, 0)),
             pl.BlockSpec((tile, rank), lambda i: (i, 0)),
             pl.BlockSpec((bucket, banned_width), lambda i: (0, 0))]
    if n_valid is None:
        kern = functools.partial(_kernel_dynamic, k=k, tile=tile,
                                 n_banned=banned_width)
        smem = (pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.SMEM)
                if (pltpu is not None and not interpret)
                else pl.BlockSpec(memory_space=None))
        specs = [smem] + specs
    else:
        kern = functools.partial(_kernel_static, n_valid=n_valid, k=k,
                                 tile=tile, n_banned=banned_width)
    return pl.pallas_call(
        kern,
        grid=(nt,),
        in_specs=specs,
        out_specs=(pl.BlockSpec((bucket, k), lambda i: (0, 0)),
                   pl.BlockSpec((bucket, k), lambda i: (0, 0))),
        out_shape=(jax.ShapeDtypeStruct((bucket, k), jnp.float32),
                   jax.ShapeDtypeStruct((bucket, k), jnp.int32)),
        interpret=interpret)


def build_fused_topk(factors, *, n_items: int, rank: int, k: int,
                     bucket: int, banned_width: int,
                     interpret: Optional[bool] = None,
                     donate: Optional[bool] = None):
    """AOT-lower/compile the fused executable for one batch bucket
    against the resident `factors`. The compiled signature is
    `(vecs [bucket, rank] f32, factors, banned [bucket, W] i32)` —
    positionally identical to the XLA chain it replaces, so
    `swap_factors` keeps working with zero recompiles. Raises on
    backends that cannot lower the kernel (callers fall back)."""
    if interpret is None:
        interpret = _interpret()
    if donate is None:
        donate = jax.default_backend() != "cpu"
    call = _pallas_topk(n_items, rank, k=k, bucket=bucket,
                        banned_width=banned_width, n_valid=n_items,
                        interpret=interpret)
    fn = jax.jit(call, donate_argnums=(0, 2)) if donate else jax.jit(call)
    vec_spec = jax.ShapeDtypeStruct((bucket, rank), np.float32)
    ban_spec = jax.ShapeDtypeStruct((bucket, banned_width), np.int32)
    return fn.lower(vec_spec, factors, ban_spec).compile()


_WARNED = False


def _warn_once(exc: Exception) -> None:
    global _WARNED
    if not _WARNED:
        _WARNED = True
        log.warning("fused serve kernel unavailable on backend %r "
                    "(falling back to the XLA chain): %s",
                    jax.default_backend(), exc)


def maybe_build_bucket(factors, *, n_items: int, rank: int, k: int,
                       bucket: int, banned_width: int):
    """`build_fused_topk` behind the PIO_SERVE_FUSED gate: None when
    fusion is off for this backend or the kernel fails to lower — the
    caller keeps the AOT XLA chain for that bucket."""
    if not fused_wanted():
        return None
    try:
        return build_fused_topk(factors, n_items=n_items, rank=rank,
                                k=k, bucket=bucket,
                                banned_width=banned_width)
    except Exception as exc:  # lowering/compile failure -> XLA chain
        _warn_once(exc)
        return None


def shard_local_candidates(per_shard: int, rank: int, *, k: int,
                           bucket: int, banned_width: int):
    """The per-shard fused local-candidate program for
    `ShardedBucketedTopK`: `(n_valid [1] i32, vecs, factors_local
    [per_shard, rank], banned_local [bucket, W] i32) -> (scores
    [bucket, k], LOCAL ids [bucket, k])`, for use inside shard_map
    (ban translation to local ids and the allgather merge stay with
    the caller). None when fusion is off; lowering failures surface
    when the enclosing program compiles — the sharded plan catches
    them and rebuilds unfused."""
    if not fused_wanted():
        return None
    try:
        return _pallas_topk(per_shard, rank, k=k, bucket=bucket,
                            banned_width=banned_width, n_valid=None,
                            interpret=_interpret())
    except Exception as exc:
        _warn_once(exc)
        return None

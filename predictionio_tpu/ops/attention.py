"""Blockwise ring attention: sequence-parallel attention over a mesh.

The round mandate makes long-context a first-class capability: sequences
too long for one device's HBM shard over a mesh axis, and attention runs
as a RING — each device computes its local queries against the
circulating key/value block while `ppermute` rotates K/V around the ICI
ring, accumulating the softmax in streaming (flash) form, so the full
[S, S] score matrix never materializes and no device ever holds more
than its 1/p sequence slice of K/V (Liu et al., "Ring Attention with
Blockwise Transformers", 2023 — reimplemented here from the paper's
recurrence, not ported code).

The reference framework has no attention at all (its models are
ALS/MLlib-era); this op backs the sequential recommender
(`models/seqrec.py`), the post-ALS architecture its templates graduate
to, the same way `ops/twotower.py` backs BASELINE config 5.

TPU notes:
  - the per-step einsums are [B*Sq, Dh] x [Dh, Skv] matmuls — MXU work;
    the streaming-softmax rescale fuses into their epilogues.
  - the K/V rotation is one `ppermute` per ring step: p-1 hops of
    S/p-sized blocks over ICI, overlapping compute on real multi-chip
    topologies (XLA schedules the collective ahead of the next block's
    matmul).
  - autodiff works through shard_map + ppermute (the transpose of a
    ring rotation is the reverse rotation), so the same primitive
    serves training.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp

from predictionio_tpu.ops import compat

_NEG = -1e30


def attention_reference(q, k, v, *, causal: bool = False, kv_mask=None):
    """Plain softmax attention, [B, S, H, Dh] -> [B, S, H, Dh] — the
    oracle the ring implementation is tested against (and the
    single-device path when no mesh axis shards the sequence).
    `kv_mask` [B, S] bool marks VALID key positions (False = padding
    slot that must not receive attention)."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    mask = None
    if causal:
        S = q.shape[1]
        mask = jnp.tril(jnp.ones((S, S), bool))[None, None]
    if kv_mask is not None:
        km = kv_mask[:, None, None, :]
        mask = km if mask is None else (mask & km)
    if mask is None:
        return jnp.einsum("bhqk,bkhd->bqhd",
                          jax.nn.softmax(s, axis=-1), v)
    s = jnp.where(mask, s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    # a fully-masked row (a padding query with no visible key) reads
    # uniform from softmax; zero it with the COMBINED mask so the dead
    # row is exactly 0, matching the streaming path
    p = jnp.where(mask, p, 0.0)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _stream_block(carry, k_blk, v_blk, kv_ok, q, q_pos, k_pos, scale,
                  causal: bool):
    """One flash-softmax accumulation step against a circulated block.
    carry = (m [B,H,Sq], num [B,Sq,H,Dh], den [B,H,Sq]); kv_ok
    [B, Skv] bool marks valid (non-padding) key slots of the block."""
    m, num, den = carry
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk) * scale   # [B,H,Sq,Skv]
    mask = kv_ok[:, None, None, :]                        # [B,1,1,Skv]
    if causal:
        mask = mask & (q_pos[:, None] >= k_pos[None, :])[None, None]
    s = jnp.where(mask, s, _NEG)
    m_blk = s.max(axis=-1)                                # [B,H,Sq]
    m_new = jnp.maximum(m, m_blk)
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    # a fully-masked row would otherwise read exp(_NEG - _NEG) = 1
    p = jnp.where(mask, p, 0.0)
    num = num * alpha.transpose(0, 2, 1)[..., None] \
        + jnp.einsum("bhqk,bkhd->bqhd", p, v_blk)
    den = den * alpha + p.sum(axis=-1)
    return m_new, num, den


def _ring_attention_local(q, k, v, kv_mask, *, causal: bool, axis: str,
                          n_shards: int):
    """shard_map body: local [B, S/p, H, Dh] blocks; K/V (and their
    validity mask) circulate."""
    idx = jax.lax.axis_index(axis)
    s_loc = q.shape[1]
    scale = 1.0 / math.sqrt(q.shape[-1])
    iota = jnp.arange(s_loc)
    q_pos = idx * s_loc + iota
    # accumulators derive from q so they carry q's varying-device type
    # (a plain constant init trips shard_map's scan carry check)
    zero_bhq = q[..., 0].transpose(0, 2, 1) * 0.0        # [B,H,Sq]
    init = (zero_bhq + _NEG, jnp.zeros_like(q), zero_bhq)
    perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]

    def step(carry, srcstep):
        acc, k_blk, v_blk, ok_blk = carry
        kv_owner = (idx - srcstep) % n_shards
        k_pos = kv_owner * s_loc + iota
        acc = _stream_block(acc, k_blk, v_blk, ok_blk, q, q_pos, k_pos,
                            scale, causal)
        # rotate AFTER consuming: device i's block moves to i+1, so next
        # step sees the block of (owner - 1) — one hop per step, p-1
        # total (the last rotation's result is unused but keeps the scan
        # body uniform; XLA drops the dead final permute pair)
        k_blk = jax.lax.ppermute(k_blk, axis, perm)
        v_blk = jax.lax.ppermute(v_blk, axis, perm)
        ok_blk = jax.lax.ppermute(ok_blk, axis, perm)
        return (acc, k_blk, v_blk, ok_blk), None

    (acc, _, _, _), _ = jax.lax.scan(
        step, (init, k, v, kv_mask), jnp.arange(n_shards))
    m, num, den = acc
    # dead rows (a padding query with no visible key) have num = 0 and
    # den = 0: divide by a where'd 1, not max(den, eps) — eps makes the
    # BACKWARD pass scale upstream gradients by 1/eps and the training
    # step NaNs out
    den_safe = jnp.where(den > 0, den, 1.0)
    return num / den_safe.transpose(0, 2, 1)[..., None]


def ring_attention(q, k, v, mesh, *, axis: str = "sp",
                   batch_axis: str = "data", causal: bool = False,
                   kv_mask=None):
    """Sequence-parallel attention: [B, S, H, Dh] inputs whose S
    dimension shards over `mesh` axis `axis` — and whose BATCH shards
    over `batch_axis` when the mesh has one (without it, a dp x sp mesh
    would all-gather the batch and replicate attention across every
    data group). Equivalent (up to float association) to
    `attention_reference`; with a trivial axis (size 1 or absent) it
    falls through to the reference path. `kv_mask` [B, S] bool marks
    valid key positions (False = padding)."""
    from jax.sharding import PartitionSpec as P

    if mesh is None or axis not in mesh.shape or mesh.shape[axis] == 1:
        return attention_reference(q, k, v, causal=causal,
                                   kv_mask=kv_mask)
    n_shards = int(mesh.shape[axis])
    if q.shape[1] % n_shards:
        raise ValueError(
            f"sequence length {q.shape[1]} must divide over "
            f"{n_shards} '{axis}' shards")
    if kv_mask is None:
        kv_mask = jnp.ones(q.shape[:2], bool)
    body = partial(_ring_attention_local, causal=causal, axis=axis,
                   n_shards=n_shards)
    b = batch_axis if (batch_axis in mesh.shape
                       and q.shape[0] % mesh.shape[batch_axis] == 0) \
        else None
    spec = P(b, axis, None, None)
    mspec = P(b, axis)
    return compat.shard_map(body, mesh=mesh,
                            in_specs=(spec, spec, spec, mspec),
                            out_specs=spec)(q, k, v, kv_mask)

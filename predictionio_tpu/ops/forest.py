"""Random-forest classifier, TPU-first.

Replaces MLlib's `RandomForest.trainClassifier` used by the reference's
classification template (`examples/scala-parallel-classification/
add-algorithm/src/main/scala/RandomForestAlgorithm.scala:41-72`).

MLlib grows trees by distributed recursive node splitting with per-node
candidate shuffles. The TPU formulation is **level-wise and dense** — the
whole forest advances one depth level per compiled step, with no
per-node control flow:

  1. Features are quantile-binned host-side into int32 bins `[n, f]`
     (the `maxBins` analog; split candidates = bin boundaries).
  2. All trees grow together. The class histogram
     `hist[tree, node, feature, bin, class]` for a level is built by one
     weight scatter-add keyed by (node*C + class, feature, bin) — the
     per-sample transients are the int32 key matrix `[n, f]`, the same
     size as the binned features themselves, so memory scales O(n*f)
     (a 1M x 100-feature train at 32 bins peaks well under 1 GB where a
     dense one-hot formulation would need 12.8 GB).
  3. Split selection is a vectorized argmax of impurity gain (gini or
     entropy) over `[f x B]` candidates per (tree, node), under a random
     per-node feature-subset mask (`featureSubsetStrategy`).
  4. Nodes whose best gain is <= 0 degrade to an always-left split, so
     every tree keeps the same static depth; leaves predict the majority
     class of their final histogram and the forest predicts by majority
     vote over trees.

Bagging matches MLlib: Poisson(1) bootstrap weights per (tree, sample)
when `n_trees > 1`, no bootstrap for a single tree.

Multi-chip: with a `mesh`, samples are block-sharded over the "data"
axis; each device scatter-adds a partial histogram from its local
samples and a [t, nd, f, B, C] `psum` over ICI reconstitutes the global
histogram (MLlib's per-node-group executor aggregation, as one
collective). Split selection is replicated (tiny), and sample routing to
child nodes stays local. Agreement with the single-device path is exact
and tested.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from predictionio_tpu.ops import compat


# rows sampled for quantile estimation: exact quantiles over millions
# of rows cost ~10x more host time for bin edges that differ in the
# third decimal (MLlib likewise samples its input for split finding,
# DecisionTree.findSplitsBins)
_QUANTILE_SAMPLE = 200_000


def quantile_bins(features: np.ndarray, max_bins: int,
                  seed: int = 0) -> np.ndarray:
    """Per-feature quantile bin edges `[f, max_bins - 1]` (host-side,
    once per training run; estimated from a row sample past
    `_QUANTILE_SAMPLE` rows)."""
    n = features.shape[0]
    if n > _QUANTILE_SAMPLE:
        ix = np.random.RandomState(seed).choice(
            n, _QUANTILE_SAMPLE, replace=False)
        features = features[ix]
    qs = np.linspace(0, 1, max_bins + 1)[1:-1]
    return np.quantile(features, qs, axis=0).T.astype(np.float32)


def apply_bins(features: np.ndarray, edges: np.ndarray) -> np.ndarray:
    """Bin features `[n, f]` into [0, B), in the smallest integer dtype
    that holds the bins (uint8 below 256 bins — also the transfer-lean
    form — else int32). Works on a transposed copy so every searchsorted
    reads a contiguous column (measured ~1.4x on the 1Mx100 bench
    host)."""
    xt = np.ascontiguousarray(np.asarray(features, np.float32).T)
    f, n = xt.shape
    out = np.empty((f, n), np.uint8 if edges.shape[1] < 256 else np.int32)
    for j in range(f):
        out[j] = np.searchsorted(edges[j], xt[j], side="right")
    return np.ascontiguousarray(out.T)


def _subset_size(strategy: str, n_features: int, n_trees: int) -> int:
    """featureSubsetStrategy -> features considered per node (MLlib
    semantics: 'auto' = all for one tree, sqrt for a forest)."""
    if strategy == "auto":
        strategy = "all" if n_trees == 1 else "sqrt"
    if strategy == "all":
        return n_features
    if strategy == "sqrt":
        return max(1, int(math.sqrt(n_features)))
    if strategy == "log2":
        return max(1, int(math.log2(n_features)))
    if strategy == "onethird":
        return max(1, n_features // 3)
    raise ValueError(f"Unknown featureSubsetStrategy {strategy!r}")


def _impurity(counts, total, kind: str):
    """counts [..., C], total [..., 1] -> impurity [...]."""
    p = counts / jnp.maximum(total, 1e-9)
    if kind == "gini":
        return 1.0 - (p * p).sum(-1)
    if kind == "entropy":
        return -(p * jnp.where(p > 0, jnp.log2(jnp.maximum(p, 1e-12)),
                               0.0)).sum(-1)
    raise ValueError(f"Unknown impurity {kind!r}")


# transient budget for the histogram scatter keys: the [t, chunk, f]
# int32 key block (and its weight broadcast) stays under this many bytes,
# so a 1M x 100 x 10-tree level never materializes the full [t, n*f]
# index space (which OOMs at ~6 GB x 3 temps on a 16 GiB chip)
_HIST_KEY_BUDGET = 256 << 20


def _histogram(s, w, fb_cols, *, n_nodes: int, c: int, f: int, b: int):
    """Partial class histogram from (this device's) samples.

    s:       [t, n]  node*C + class per (tree, sample)
    w:       [t, n]  bootstrap weights
    fb_cols: [n, f]  flat feature-bin column f*B + bin
    Returns [t, nd, f, B, C]. Scatter-adds keyed by (s, feature-bin) —
    never a dense one-hot. Large sample counts are processed in
    lax.scan chunks so the [t, chunk, f] key transients respect
    `_HIST_KEY_BUDGET`.
    """
    t, n = s.shape
    size = n_nodes * c * f * b

    def add_block(hist, s_blk, w_blk, fb_blk):
        def one_tree(h_t, s_t, w_t):
            keys = s_t[:, None] * (f * b) + fb_blk       # [chunk, f]
            upd = jnp.broadcast_to(w_t[:, None], keys.shape)
            return h_t.at[keys.reshape(-1)].add(upd.reshape(-1))

        return jax.vmap(one_tree)(hist, s_blk, w_blk)

    chunk = max(1, _HIST_KEY_BUDGET // (max(t, 1) * max(f, 1) * 4))
    if chunk >= n:
        hist = add_block(jnp.zeros((t, size), jnp.float32), s, w, fb_cols)
    else:
        n_chunks = -(-n // chunk)
        npad = n_chunks * chunk
        # pad with weight-0 samples keyed to slot 0 (invisible)
        s_p = jnp.pad(s, ((0, 0), (0, npad - n)))
        w_p = jnp.pad(w, ((0, 0), (0, npad - n)))
        fb_p = jnp.pad(fb_cols, ((0, npad - n), (0, 0)))
        xs = (s_p.reshape(t, n_chunks, chunk).transpose(1, 0, 2),
              w_p.reshape(t, n_chunks, chunk).transpose(1, 0, 2),
              fb_p.reshape(n_chunks, chunk, f))

        def body(hist, blk):
            return add_block(hist, *blk), None

        hist, _ = jax.lax.scan(body, jnp.zeros((t, size), jnp.float32), xs)
    return hist.reshape(t, n_nodes, c, f, b).transpose(0, 1, 3, 4, 2)


def _select_splits(key, hist, *, n_nodes: int, c: int, f: int, b: int,
                   subset: int, impurity: str):
    """Vectorized split selection from the GLOBAL histogram
    [t, nd, f, B, C]; pure replicated math."""
    t = hist.shape[0]
    # threshold "<= bin" -> left counts = cumsum over B
    left = jnp.cumsum(hist, axis=3)
    total = left[:, :, :, -1, :]                   # [t, nd, f, C]
    right = total[:, :, :, None, :] - left
    nl = left.sum(-1)                              # [t, nd, f, B]
    nr = right.sum(-1)
    nt = nl + nr
    imp_l = _impurity(left, nl[..., None], impurity)
    imp_r = _impurity(right, nr[..., None], impurity)
    parent = total[:, :, 0, :]                     # [t, nd, C]
    n_parent = parent.sum(-1)                      # [t, nd]
    imp_p = _impurity(parent, n_parent[..., None], impurity)
    child = (nl * imp_l + nr * imp_r) / jnp.maximum(nt, 1e-9)
    gain = imp_p[:, :, None, None] - child         # [t, nd, f, B]

    # the last bin is "everything left" = no split; forbid it as a
    # candidate, and forbid features outside the random subset
    gain = gain.at[:, :, :, -1].set(-jnp.inf)
    ranks = jnp.argsort(
        jax.random.uniform(key, (t, n_nodes, f)), axis=-1).argsort(-1)
    gain = jnp.where((ranks < subset)[:, :, :, None], gain, -jnp.inf)

    flat = gain.reshape(t, n_nodes, f * b)
    best = jnp.argmax(flat, axis=-1)               # [t, nd]
    best_gain = jnp.take_along_axis(flat, best[..., None], -1)[..., 0]
    split_f = best // b
    split_b = best % b
    # non-positive gain (or empty node) -> always-left split
    degenerate = ~(best_gain > 0)
    split_f = jnp.where(degenerate, 0, split_f).astype(jnp.int32)
    split_b = jnp.where(degenerate, b - 1, split_b).astype(jnp.int32)
    return split_f, split_b


def _route(xb, node, split_f, split_b):
    """Move each (tree, sample) to its child node; purely local."""
    t = node.shape[0]
    feat_vals = xb[jnp.arange(xb.shape[0])[None, :], split_f[
        jnp.arange(t)[:, None], node]]             # [t, n]
    go_right = feat_vals > split_b[jnp.arange(t)[:, None], node]
    return node * 2 + go_right.astype(jnp.int32)


@partial(jax.jit, static_argnames=("n_nodes", "n_classes", "n_features",
                                   "n_bins", "subset", "impurity", "mesh"))
def _grow_level(key, fb_cols, node, y, w, xb, *, n_nodes: int,
                n_classes: int, n_features: int, n_bins: int, subset: int,
                impurity: str, mesh=None):
    """One level for every tree at once.

    fb_cols: [n, f]   flat feature-bin columns (shared across trees)
    node:    [t, n]   current node of each sample in each tree
    y:       [n]      class ids
    w:       [t, n]   bootstrap weights
    xb:      [n, f]   binned features
    Returns (split_feature [t, nd], split_bin [t, nd], new node [t, n]).
    With a mesh, the sample dimension is sharded over "data": per-device
    partial histograms + one psum, replicated split selection, local
    routing.
    """
    f, b, c = n_features, n_bins, n_classes
    kw = dict(n_nodes=n_nodes, c=c, f=f, b=b)

    def level(key, fb_cols, node, y, w, xb, *, hist_reduce):
        s = node * c + y[None, :]
        hist = hist_reduce(_histogram(s, w, fb_cols, **kw))
        split_f, split_b = _select_splits(
            key, hist, subset=subset, impurity=impurity, **kw)
        return split_f, split_b, _route(xb, node, split_f, split_b)

    if mesh is None:
        return level(key, fb_cols, node, y, w, xb, hist_reduce=lambda h: h)

    from jax.sharding import PartitionSpec as P

    body = partial(level,
                   hist_reduce=lambda h: jax.lax.psum(h, "data"))
    return compat.shard_map(
        body, mesh=mesh,
        in_specs=(P(), P("data", None), P(None, "data"), P("data"),
                  P(None, "data"), P("data", None)),
        out_specs=(P(), P(), P(None, "data")))(
            key, fb_cols, node, y, w, xb)


@partial(jax.jit, static_argnames=("n_nodes", "n_classes", "mesh"))
def _leaf_counts(node, y, w, *, n_nodes: int, n_classes: int, mesh=None):
    def counts(node, y, w, *, reduce):
        s = node * n_classes + y[None, :]

        def one_tree(s_t, w_t):
            return jnp.zeros((n_nodes * n_classes,),
                             jnp.float32).at[s_t].add(w_t)

        return reduce(jax.vmap(one_tree)(s, w)).reshape(
            -1, n_nodes, n_classes)

    if mesh is None:
        return counts(node, y, w, reduce=lambda x: x)

    from jax.sharding import PartitionSpec as P

    body = partial(counts, reduce=lambda x: jax.lax.psum(x, "data"))
    return compat.shard_map(
        body, mesh=mesh,
        in_specs=(P(None, "data"), P("data"), P(None, "data")),
        out_specs=P())(node, y, w)


@dataclass
class ForestModel:
    """Level-order flattened forest: internal node i at level l sits at
    global index 2^l - 1 + i."""
    bin_edges: np.ndarray       # [f, B-1]
    split_feature: np.ndarray   # [t, 2^depth - 1]
    split_bin: np.ndarray       # [t, 2^depth - 1]
    leaf_class: np.ndarray      # [t, 2^depth]
    classes: np.ndarray         # [C] original label values
    max_depth: int

    @property
    def n_trees(self) -> int:
        return self.split_feature.shape[0]

    def sanity_check(self):
        assert self.split_feature.shape == self.split_bin.shape
        assert self.leaf_class.shape[1] == 2 ** self.max_depth

    # below this many (tree, sample) traversals, host numpy wins (device
    # dispatch overhead dominates single-query serving); above it, the
    # jit'd traversal keeps eval sweeps / batchpredict on the device
    HOST_CROSSOVER_CELLS = 1 << 14

    def predict(self, features: np.ndarray) -> np.ndarray:
        """Majority vote over trees; returns original label values.
        Size-dispatched: big batches run the jit'd device traversal, tiny
        ones the equivalent host loop. Tie-breaking (lowest class index)
        is identical on both paths."""
        xb = apply_bins(np.asarray(features, np.float32), self.bin_edges)
        t, n = self.n_trees, xb.shape[0]
        c = len(self.classes)
        if t * n >= self.HOST_CROSSOVER_CELLS:
            ix = np.asarray(_predict_device(
                jnp.asarray(xb), jnp.asarray(self.split_feature),
                jnp.asarray(self.split_bin), jnp.asarray(self.leaf_class),
                max_depth=self.max_depth, n_classes=c))
            return self.classes[ix]
        node = np.zeros((t, n), np.int32)
        rows = np.arange(n)[None, :]
        trees = np.arange(t)[:, None]
        for level in range(self.max_depth):
            off = (1 << level) - 1
            sf = self.split_feature[trees, off + node]
            sb = self.split_bin[trees, off + node]
            node = node * 2 + (xb[rows, sf] > sb)
        votes = self.leaf_class[trees, node]             # [t, n]
        # per-sample class counts in one bincount: flat id = class*n + col
        counts = np.bincount(
            (votes.astype(np.int64) * n + np.arange(n)).ravel(),
            minlength=c * n).reshape(c, n)
        return self.classes[np.argmax(counts, axis=0)]


@partial(jax.jit, static_argnames=("max_depth", "n_classes"))
def _predict_device(xb, split_feature, split_bin, leaf_class, *,
                    max_depth: int, n_classes: int):
    """Device forest traversal: level-unrolled gathers + one-hot vote
    count; returns class indices [n] (argmax ties -> lowest index, the
    host path's np.argmax convention)."""
    t, n = split_feature.shape[0], xb.shape[0]
    node = jnp.zeros((t, n), jnp.int32)
    rows = jnp.arange(n)[None, :]
    trees = jnp.arange(t)[:, None]
    for level in range(max_depth):
        off = (1 << level) - 1
        sf = split_feature[trees, off + node]
        sb = split_bin[trees, off + node]
        node = node * 2 + (xb[rows, sf] > sb).astype(jnp.int32)
    votes = leaf_class[trees, node]                      # [t, n]
    counts = jax.nn.one_hot(votes, n_classes, dtype=jnp.float32).sum(0)
    return jnp.argmax(counts, axis=1)


def forest_train(features: np.ndarray, labels: np.ndarray, *,
                 n_trees: int = 10, max_depth: int = 5, max_bins: int = 32,
                 impurity: str = "gini",
                 feature_subset_strategy: str = "auto",
                 seed: int = 0, mesh=None,
                 timings: dict = None) -> ForestModel:
    """Train a random forest on dense features [n, f] and labels [n].
    `mesh` shards the sample dimension over the "data" axis (partial
    histograms + psum); None runs single-device. `timings`, if given,
    is filled with bin_s (host quantile binning) and device_s (upload +
    level loop + fetch) wall-clock phases."""
    import time as _time

    t0 = _time.perf_counter()
    features = np.asarray(features, np.float32)
    labels = np.asarray(labels)
    classes, y_np = np.unique(labels, return_inverse=True)
    n, f = features.shape
    c = max(len(classes), 2)
    edges = quantile_bins(features, max_bins)
    xb_np = apply_bins(features, edges)
    subset = _subset_size(feature_subset_strategy, f, n_trees)
    t_bin = _time.perf_counter()

    key = jax.random.PRNGKey(seed)
    kboot, key = jax.random.split(key)
    if n_trees == 1:
        w = jnp.ones((1, n), jnp.float32)
    else:
        w = jax.random.poisson(kboot, 1.0, (n_trees, n)).astype(jnp.float32)

    # binned features cross the host->device link at uint8 (max_bins is
    # bounded at 256) and widen device-side; fb_cols is DERIVED on
    # device — together this cuts the 1Mx100 upload from 720 MB of int32
    # to 90 MB, and the measured bench tunnel moves ~25 MB/s
    xb_small = (np.asarray(xb_np, np.uint8) if max_bins <= 256
                else np.asarray(xb_np, np.int32))
    y_np32 = y_np.astype(np.int32)
    if mesh is not None:
        # pad samples to a device multiple with weight-0 rows (invisible
        # to every histogram) and shard the sample dimension
        from predictionio_tpu.parallel import pad_rows, pad_to_multiple

        n_dev = int(mesh.shape["data"])
        npad = pad_to_multiple(max(n, n_dev), n_dev)
        xb_small = pad_rows(xb_small, npad)
        y_np32 = pad_rows(y_np32, npad)
        w = jnp.pad(w, ((0, 0), (0, npad - n)))
        n = npad
    xb = jnp.asarray(xb_small).astype(jnp.int32)
    fb_cols = xb + jnp.arange(f, dtype=jnp.int32)[None, :] * max_bins
    y = jnp.asarray(y_np32)
    node = jnp.zeros((n_trees, n), jnp.int32)

    split_fs, split_bs = [], []
    for level in range(max_depth):
        key, klevel = jax.random.split(key)
        sf, sb, node = _grow_level(
            klevel, fb_cols, node, y, w, xb, n_nodes=1 << level,
            n_classes=c, n_features=f, n_bins=max_bins, subset=subset,
            impurity=impurity, mesh=mesh)
        # keep sf/sb on device: fetching per level costs a tunnel round
        # trip each; one batched fetch below covers all levels
        split_fs.append(sf)
        split_bs.append(sb)

    counts = _leaf_counts(node, y, w, n_nodes=1 << max_depth, n_classes=c,
                          mesh=mesh)
    split_fs = [np.asarray(a) for a in jax.device_get(split_fs)]
    split_bs = [np.asarray(a) for a in jax.device_get(split_bs)]
    # empty leaves (never reached in training) fall back to the global
    # class distribution — computed from the ORIGINAL labels (the mesh
    # path pads y with class-0 rows, which must not skew the fallback)
    global_counts = jnp.asarray(
        np.bincount(y_np, minlength=c).astype(np.float32))
    counts = counts + 1e-6 * global_counts[None, None, :]
    leaf_class = np.asarray(jnp.argmax(counts, axis=-1), np.int32)
    if timings is not None:
        timings["bin_s"] = t_bin - t0
        timings["device_s"] = _time.perf_counter() - t_bin

    return ForestModel(
        bin_edges=edges,
        split_feature=np.concatenate(split_fs, axis=1),
        split_bin=np.concatenate(split_bs, axis=1),
        leaf_class=leaf_class,
        classes=classes.astype(np.float32),
        max_depth=max_depth)

"""Random-forest classifier, TPU-first.

Replaces MLlib's `RandomForest.trainClassifier` used by the reference's
classification template (`examples/scala-parallel-classification/
add-algorithm/src/main/scala/RandomForestAlgorithm.scala:41-72`).

MLlib grows trees by distributed recursive node splitting with per-node
candidate shuffles. The TPU formulation is **level-wise and dense** — the
whole forest advances one depth level per compiled step, with no
per-node control flow:

  1. Features are quantile-binned host-side into int32 bins `[n, f]`
     (the `maxBins` analog; split candidates = bin boundaries).
  2. All trees grow together. The class histogram
     `hist[tree, node, feature, bin, class]` for a level is built by one
     batched scatter-add of precomputed one-hot feature-bin rows
     `[n, f*B]` keyed by the sample's (node, class) — no `[t, n, nd*C]`
     intermediate ever materializes.
  3. Split selection is a vectorized argmax of impurity gain (gini or
     entropy) over `[f x B]` candidates per (tree, node), under a random
     per-node feature-subset mask (`featureSubsetStrategy`).
  4. Nodes whose best gain is <= 0 degrade to an always-left split, so
     every tree keeps the same static depth; leaves predict the majority
     class of their final histogram and the forest predicts by majority
     vote over trees.

Bagging matches MLlib: Poisson(1) bootstrap weights per (tree, sample)
when `n_trees > 1`, no bootstrap for a single tree.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def quantile_bins(features: np.ndarray, max_bins: int) -> np.ndarray:
    """Per-feature quantile bin edges `[f, max_bins - 1]` (host-side,
    once per training run)."""
    qs = np.linspace(0, 1, max_bins + 1)[1:-1]
    return np.quantile(features, qs, axis=0).T.astype(np.float32)


def apply_bins(features: np.ndarray, edges: np.ndarray) -> np.ndarray:
    """Bin features into int32 `[n, f]` in [0, B)."""
    out = np.empty(features.shape, np.int32)
    for f in range(features.shape[1]):
        out[:, f] = np.searchsorted(edges[f], features[:, f], side="right")
    return out


def _subset_size(strategy: str, n_features: int, n_trees: int) -> int:
    """featureSubsetStrategy -> features considered per node (MLlib
    semantics: 'auto' = all for one tree, sqrt for a forest)."""
    if strategy == "auto":
        strategy = "all" if n_trees == 1 else "sqrt"
    if strategy == "all":
        return n_features
    if strategy == "sqrt":
        return max(1, int(math.sqrt(n_features)))
    if strategy == "log2":
        return max(1, int(math.log2(n_features)))
    if strategy == "onethird":
        return max(1, n_features // 3)
    raise ValueError(f"Unknown featureSubsetStrategy {strategy!r}")


def _impurity(counts, total, kind: str):
    """counts [..., C], total [..., 1] -> impurity [...]."""
    p = counts / jnp.maximum(total, 1e-9)
    if kind == "gini":
        return 1.0 - (p * p).sum(-1)
    if kind == "entropy":
        return -(p * jnp.where(p > 0, jnp.log2(jnp.maximum(p, 1e-12)),
                               0.0)).sum(-1)
    raise ValueError(f"Unknown impurity {kind!r}")


@partial(jax.jit, static_argnames=("n_nodes", "n_classes", "n_features",
                                   "n_bins", "subset", "impurity"))
def _grow_level(key, fb_rows, node, y, w, xb, *, n_nodes: int,
                n_classes: int, n_features: int, n_bins: int, subset: int,
                impurity: str):
    """One level for every tree at once.

    fb_rows: [n, f*B] one-hot feature-bin rows (shared across trees)
    node:    [t, n]   current node of each sample in each tree
    y:       [n]      class ids
    w:       [t, n]   bootstrap weights
    xb:      [n, f]   binned features
    Returns (split_feature [t, nd], split_bin [t, nd], new node [t, n]).
    """
    t = node.shape[0]
    f, b, c = n_features, n_bins, n_classes

    # hist[t, nd*C, f*B] via per-tree scatter-add of fb rows
    s = node * c + y[None, :]                      # [t, n]

    def one_tree(s_t, w_t):
        return jnp.zeros((n_nodes * c, f * b), jnp.float32).at[s_t].add(
            fb_rows * w_t[:, None])

    hist = jax.vmap(one_tree)(s, w)
    hist = hist.reshape(t, n_nodes, c, f, b).transpose(0, 1, 3, 4, 2)
    # [t, nd, f, B, C]; threshold "<= bin" -> left counts = cumsum over B
    left = jnp.cumsum(hist, axis=3)
    total = left[:, :, :, -1, :]                   # [t, nd, f, C]
    right = total[:, :, :, None, :] - left
    nl = left.sum(-1)                              # [t, nd, f, B]
    nr = right.sum(-1)
    nt = nl + nr
    imp_l = _impurity(left, nl[..., None], impurity)
    imp_r = _impurity(right, nr[..., None], impurity)
    parent = total[:, :, 0, :]                     # [t, nd, C]
    n_parent = parent.sum(-1)                      # [t, nd]
    imp_p = _impurity(parent, n_parent[..., None], impurity)
    child = (nl * imp_l + nr * imp_r) / jnp.maximum(nt, 1e-9)
    gain = imp_p[:, :, None, None] - child         # [t, nd, f, B]

    # the last bin is "everything left" = no split; forbid it as a
    # candidate, and forbid features outside the random subset
    gain = gain.at[:, :, :, -1].set(-jnp.inf)
    ranks = jnp.argsort(
        jax.random.uniform(key, (t, n_nodes, f)), axis=-1).argsort(-1)
    gain = jnp.where((ranks < subset)[:, :, :, None], gain, -jnp.inf)

    flat = gain.reshape(t, n_nodes, f * b)
    best = jnp.argmax(flat, axis=-1)               # [t, nd]
    best_gain = jnp.take_along_axis(flat, best[..., None], -1)[..., 0]
    split_f = best // b
    split_b = best % b
    # non-positive gain (or empty node) -> always-left split
    degenerate = ~(best_gain > 0)
    split_f = jnp.where(degenerate, 0, split_f).astype(jnp.int32)
    split_b = jnp.where(degenerate, b - 1, split_b).astype(jnp.int32)

    feat_vals = xb[jnp.arange(xb.shape[0])[None, :], split_f[
        jnp.arange(t)[:, None], node]]             # [t, n]
    go_right = feat_vals > split_b[jnp.arange(t)[:, None], node]
    new_node = node * 2 + go_right.astype(jnp.int32)
    return split_f, split_b, new_node


@partial(jax.jit, static_argnames=("n_nodes", "n_classes"))
def _leaf_counts(node, y, w, *, n_nodes: int, n_classes: int):
    s = node * n_classes + y[None, :]

    def one_tree(s_t, w_t):
        return jnp.zeros((n_nodes * n_classes,), jnp.float32).at[s_t].add(w_t)

    return jax.vmap(one_tree)(s, w).reshape(-1, n_nodes, n_classes)


@dataclass
class ForestModel:
    """Level-order flattened forest: internal node i at level l sits at
    global index 2^l - 1 + i."""
    bin_edges: np.ndarray       # [f, B-1]
    split_feature: np.ndarray   # [t, 2^depth - 1]
    split_bin: np.ndarray       # [t, 2^depth - 1]
    leaf_class: np.ndarray      # [t, 2^depth]
    classes: np.ndarray         # [C] original label values
    max_depth: int

    @property
    def n_trees(self) -> int:
        return self.split_feature.shape[0]

    def sanity_check(self):
        assert self.split_feature.shape == self.split_bin.shape
        assert self.leaf_class.shape[1] == 2 ** self.max_depth

    def predict(self, features: np.ndarray) -> np.ndarray:
        """Majority vote over trees; returns original label values."""
        xb = apply_bins(np.asarray(features, np.float32), self.bin_edges)
        t = self.n_trees
        n = xb.shape[0]
        node = np.zeros((t, n), np.int32)
        rows = np.arange(n)[None, :]
        trees = np.arange(t)[:, None]
        for level in range(self.max_depth):
            off = (1 << level) - 1
            sf = self.split_feature[trees, off + node]
            sb = self.split_bin[trees, off + node]
            node = node * 2 + (xb[rows, sf] > sb)
        votes = self.leaf_class[trees, node]             # [t, n]
        c = len(self.classes)
        # per-sample class counts in one bincount: flat id = class*n + col
        counts = np.bincount(
            (votes.astype(np.int64) * n + np.arange(n)).ravel(),
            minlength=c * n).reshape(c, n)
        return self.classes[np.argmax(counts, axis=0)]


def forest_train(features: np.ndarray, labels: np.ndarray, *,
                 n_trees: int = 10, max_depth: int = 5, max_bins: int = 32,
                 impurity: str = "gini",
                 feature_subset_strategy: str = "auto",
                 seed: int = 0) -> ForestModel:
    """Train a random forest on dense features [n, f] and labels [n]."""
    features = np.asarray(features, np.float32)
    labels = np.asarray(labels)
    classes, y_np = np.unique(labels, return_inverse=True)
    n, f = features.shape
    c = max(len(classes), 2)
    edges = quantile_bins(features, max_bins)
    xb_np = apply_bins(features, edges)
    subset = _subset_size(feature_subset_strategy, f, n_trees)

    key = jax.random.PRNGKey(seed)
    kboot, key = jax.random.split(key)
    if n_trees == 1:
        w = jnp.ones((1, n), jnp.float32)
    else:
        w = jax.random.poisson(kboot, 1.0, (n_trees, n)).astype(jnp.float32)

    # one-hot feature-bin rows [n, f*B], shared by every tree and level;
    # built by scatter (a dense one_hot would materialize [n, f, f*B])
    fb_cols = xb_np + np.arange(f)[None, :] * max_bins
    fb_rows = jnp.zeros((n, f * max_bins), jnp.float32).at[
        jnp.arange(n)[:, None], jnp.asarray(fb_cols)].set(1.0)
    y = jnp.asarray(y_np.astype(np.int32))
    xb = jnp.asarray(xb_np)
    node = jnp.zeros((n_trees, n), jnp.int32)

    split_fs, split_bs = [], []
    for level in range(max_depth):
        key, klevel = jax.random.split(key)
        sf, sb, node = _grow_level(
            klevel, fb_rows, node, y, w, xb, n_nodes=1 << level,
            n_classes=c, n_features=f, n_bins=max_bins, subset=subset,
            impurity=impurity)
        split_fs.append(np.asarray(sf))
        split_bs.append(np.asarray(sb))

    counts = _leaf_counts(node, y, w, n_nodes=1 << max_depth, n_classes=c)
    # empty leaves (never reached in training) fall back to the global
    # class distribution
    global_counts = jnp.bincount(y, length=c).astype(jnp.float32)
    counts = counts + 1e-6 * global_counts[None, None, :]
    leaf_class = np.asarray(jnp.argmax(counts, axis=-1), np.int32)

    return ForestModel(
        bin_edges=edges,
        split_feature=np.concatenate(split_fs, axis=1),
        split_bin=np.concatenate(split_bs, axis=1),
        leaf_class=leaf_class,
        classes=classes.astype(np.float32),
        max_depth=max_depth)

"""Sequential recommendation: a causal transformer over item histories.

A NEW capability beyond the reference, like `ops/twotower.py`
(SURVEY.md §7 phase 7): the reference's recommenders are order-blind
(ALS factorizes a rating matrix, `examples/scala-parallel-recommendation`),
while this model predicts the NEXT item from the ORDER of a user's
events — the SASRec-style architecture (Kang & McAuley 2018,
reimplemented from the paper's description) that ALS deployments
graduate to, and the framework's long-context/sequence-parallel proof
point.

TPU design:
  - ONE jit'd train step over pre-uploaded batches via `lax.scan`
    (per-step dispatch over the tunneled runtime measured ~100x slower
    for two-tower; same recipe here).
  - attention runs through `ops.attention.ring_attention`: the sequence
    dimension shards over the mesh "sp" axis and K/V circulate over ICI
    `ppermute`, so context length scales with the ring — the batch
    dimension shards over "data" with gradient psums, both expressed as
    shardings on ONE jit (GSPMD inserts the collectives).
  - the item embedding table is TIED between input encoding and the
    output softmax (halves the parameter bytes that cross the link).
  - in-batch sampled softmax against the batch's target items (the
    two-tower recipe) — no [B, n_items] logits materialize in training.

Serving encodes the user's RECENT history read from the event store at
query time (the e-commerce template's serve-time-read pattern,
ECommAlgorithm.scala:331-430) and scores all items with one masked
top-k matmul (`ops.topk`).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from predictionio_tpu.ops.attention import ring_attention


@dataclass
class SeqRecModel:
    params: dict           # transformer weights (numpy pytree)
    seq_len: int
    n_items: int
    n_heads: int

    @property
    def item_emb(self) -> np.ndarray:
        """[n_items, D] tied output/input item table (PAD row dropped)."""
        return np.asarray(self.params["item_table"])[:self.n_items]

    def sanity_check(self):
        assert all(np.isfinite(v).all() for v in
                   jax.tree_util.tree_leaves(self.params))

    def __getstate__(self):
        # the serve-time device-param cache (_devp) must not be pickled
        # with the model (persistence stores numpy weights only)
        d = dict(self.__dict__)
        d.pop("_devp", None)
        return d


def _init_params(key, n_items: int, seq_len: int, dim: int,
                 n_layers: int):
    ks = iter(jax.random.split(key, 4 + 7 * n_layers))

    def dense(fan_in, fan_out):
        return (jax.random.normal(next(ks), (fan_in, fan_out),
                                  jnp.float32) / np.sqrt(fan_in))

    p = {
        # row n_items is the PAD embedding (kept at its random init;
        # attention masks PAD keys so it never leaks into real rows)
        "item_table": jax.random.normal(
            next(ks), (n_items + 1, dim), jnp.float32) / np.sqrt(dim),
        "pos_emb": jax.random.normal(
            next(ks), (seq_len, dim), jnp.float32) * 0.02,
        "ln_f": jnp.ones(dim), "ln_f_b": jnp.zeros(dim),
    }
    for layer in range(n_layers):
        p[f"l{layer}"] = {
            "ln1": jnp.ones(dim), "ln1_b": jnp.zeros(dim),
            "wq": dense(dim, dim), "wk": dense(dim, dim),
            "wv": dense(dim, dim), "wo": dense(dim, dim),
            "ln2": jnp.ones(dim), "ln2_b": jnp.zeros(dim),
            "w1": dense(dim, 2 * dim), "w2": dense(2 * dim, dim),
        }
    return p


def _ln(x, g, b):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + 1e-6) * g + b


def _encode(params, seqs, *, n_items: int, n_heads: int, n_layers: int,
            mesh=None):
    """seqs [B, S] int32 (PAD = n_items, right-aligned) -> [B, D] the
    final-position representation."""
    B, S = seqs.shape
    D = params["pos_emb"].shape[1]
    Dh = D // n_heads
    valid = seqs != n_items                                # [B, S]
    x = params["item_table"][seqs] * np.sqrt(D) + params["pos_emb"]

    # ring_attention's trivial-axis fall-through handles mesh=None too
    attend = partial(ring_attention, mesh=mesh)
    for layer in range(n_layers):
        lp = params[f"l{layer}"]
        h = _ln(x, lp["ln1"], lp["ln1_b"])
        q = (h @ lp["wq"]).reshape(B, S, n_heads, Dh)
        k = (h @ lp["wk"]).reshape(B, S, n_heads, Dh)
        v = (h @ lp["wv"]).reshape(B, S, n_heads, Dh)
        a = attend(q, k, v, causal=True, kv_mask=valid)
        x = x + a.reshape(B, S, D) @ lp["wo"]
        h = _ln(x, lp["ln2"], lp["ln2_b"])
        x = x + jax.nn.relu(h @ lp["w1"]) @ lp["w2"]
    x = _ln(x, params["ln_f"], params["ln_f_b"])
    return x[:, -1, :]                     # right-aligned: last = newest


def _loss_fn(params, seqs, targets, temperature, *, n_items, n_heads,
             n_layers, mesh):
    u = _encode(params, seqs, n_items=n_items, n_heads=n_heads,
                n_layers=n_layers, mesh=mesh)
    t = params["item_table"][targets]                      # [B, D]
    logits = (u @ t.T) / temperature                       # in-batch
    labels = jnp.arange(seqs.shape[0])
    return -jnp.mean(jax.nn.log_softmax(logits)[labels, labels])


def seqrec_train(sequences: np.ndarray, targets: np.ndarray, *,
                 n_items: int, seq_len: int, dim: int = 64,
                 n_heads: int = 2, n_layers: int = 2,
                 batch_size: int = 256, epochs: int = 5,
                 lr: float = 3e-3, temperature: float = 0.07,
                 seed: int = 0, mesh=None,
                 init_params=None) -> SeqRecModel:
    """Train on [N, seq_len] right-aligned item-id sequences (PAD =
    n_items) with [N] next-item targets. `mesh` shards the batch over
    "data" and — when the mesh has an "sp" axis — the sequence over it
    via ring attention. `init_params` resumes from a prior model's
    weights (the streaming warm-start mini-epoch); optimizer state
    starts fresh."""
    import optax

    assert sequences.shape[1] == seq_len
    if init_params is not None:
        params = jax.tree_util.tree_map(jnp.asarray, init_params)
    else:
        params = _init_params(jax.random.PRNGKey(seed), n_items,
                              seq_len, dim, n_layers)
    opt = optax.adam(lr)
    opt_state = opt.init(params)
    n = (len(sequences) // batch_size) * batch_size
    if n == 0:
        raise ValueError(
            f"need at least one full batch ({batch_size}) of sequences")
    seq_all = jnp.asarray(sequences[:n].reshape(-1, batch_size, seq_len)
                          .astype(np.int32))
    tgt_all = jnp.asarray(targets[:n].reshape(-1, batch_size)
                          .astype(np.int32))

    loss = partial(_loss_fn, temperature=jnp.float32(temperature),
                   n_items=n_items, n_heads=n_heads, n_layers=n_layers,
                   mesh=mesh)

    @jax.jit
    def epoch(params, opt_state, seq_all, tgt_all):
        def body(carry, batch):
            params, opt_state = carry
            seqs, tgts = batch
            g = jax.grad(loss)(params, seqs, tgts)
            updates, opt_state = opt.update(g, opt_state, params)
            return (optax.apply_updates(params, updates),
                    opt_state), None

        (params, opt_state), _ = jax.lax.scan(
            body, (params, opt_state), (seq_all, tgt_all))
        return params, opt_state

    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P
        seq_all = jax.device_put(
            seq_all, NamedSharding(mesh, P(None, "data", None)))
        tgt_all = jax.device_put(
            tgt_all, NamedSharding(mesh, P(None, "data")))
    for _ in range(epochs):
        params, opt_state = epoch(params, opt_state, seq_all, tgt_all)
    params_np = jax.tree_util.tree_map(np.asarray, params)
    return SeqRecModel(params=params_np, seq_len=seq_len,
                       n_items=n_items, n_heads=n_heads)


@partial(jax.jit, static_argnames=("n_items", "n_heads", "n_layers"))
def _encode_jit(params, seqs, *, n_items, n_heads, n_layers):
    return _encode(params, seqs, n_items=n_items, n_heads=n_heads,
                   n_layers=n_layers, mesh=None)


def seqrec_encode(model: SeqRecModel, seqs: np.ndarray) -> np.ndarray:
    """[B, seq_len] histories -> [B, D] user representations. The
    SERVING hot path: device-resident params are cached on the model
    (outside its pickled state, see SeqRecModel.__getstate__) and the
    encoder runs as one jitted program — eager per-op dispatch over the
    tunneled runtime measured ~100x slower (module docstring)."""
    devp = getattr(model, "_devp", None)
    if devp is None:
        devp = jax.tree_util.tree_map(jnp.asarray, model.params)
        model._devp = devp
    n_layers = sum(1 for k in model.params if k.startswith("l")
                   and k[1:].isdigit())
    out = _encode_jit(devp, jnp.asarray(seqs.astype(np.int32)),
                      n_items=model.n_items, n_heads=model.n_heads,
                      n_layers=n_layers)
    return np.asarray(out)


def build_sequences(user_ix: np.ndarray, item_ix: np.ndarray,
                    t_millis: np.ndarray, *, n_items: int, seq_len: int,
                    min_len: int = 2):
    """Group events into per-user time-ordered item sequences and emit
    (sequences [N, seq_len] right-aligned PAD=n_items, targets [N]):
    for each user with >= min_len events, the history-before-last is
    the sequence and the last item the target. Host-side, vectorized
    (no per-user Python loop)."""
    order = np.lexsort((t_millis, user_ix))
    u, i = user_ix[order], item_ix[order]
    starts = np.r_[0, np.flatnonzero(np.diff(u)) + 1]
    ends = np.r_[starts[1:], len(u)]
    lens = ends - starts
    keep = lens >= min_len
    starts, ends, lens = starts[keep], ends[keep], lens[keep]
    n = len(starts)
    seqs = np.full((n, seq_len), n_items, np.int32)
    # history = up to seq_len items BEFORE the last; right-aligned
    hist_len = np.minimum(lens - 1, seq_len)
    # flat gather: for row r, take items [end-1-hist .. end-1)
    rows = np.repeat(np.arange(n), hist_len)
    offs = (np.arange(int(hist_len.sum()))
            - np.repeat(np.cumsum(hist_len) - hist_len, hist_len))
    src = np.repeat(ends - 1 - hist_len, hist_len) + offs
    cols = np.repeat(seq_len - hist_len, hist_len) + offs
    seqs[rows, cols] = i[src]
    targets = i[ends - 1].astype(np.int32)
    return seqs, targets

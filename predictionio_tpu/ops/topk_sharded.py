"""Mesh-sharded serving plans: partial top-k per shard + allgather merge.

A catalog bigger than one chip's HBM cannot be pinned by `BucketedTopK`
— and `MULTICHIP_r0*.json` shows every model's train step already runs
on 8-device meshes while serving ignored the mesh entirely. This module
closes that gap with the sharded-scoring shape "Scalable ML Training
Infrastructure at Google" describes for ads scoring: partition the
embedding (factor) table row-wise, score locally, merge partial top-k.

`ShardedBucketedTopK` / `ShardedBucketedSimilar` are drop-in serving
plans (same `warm()/fits()/__call__` contract as their single-device
counterparts in `ops/topk.py`):

  - item factors are padded to a multiple of the shard count and
    device_put ONCE with a row sharding over the serve mesh's "items"
    axis (`parallel.mesh.shard_put`), so each device holds an
    `n_items/n_shards` slice of the catalog for the plan's lifetime;
  - every batch bucket is AOT-lowered/compiled against that resident
    sharded array: inside the program each shard computes its local
    score block (one matmul at `Precision.HIGHEST`, identical math to
    the single-device path), applies banned-index filtering IN GLOBAL
    ID SPACE (banned ids arrive untranslated; each shard subtracts its
    row base, routes out-of-shard ids to an out-of-bounds slot, and the
    scatter drops them), masks its padding
    rows to NEG_INF, takes a LOCAL `lax.top_k`, then all-gathers the
    `k_shard * n_shards` candidates and merges them with a final
    top-k over globally-offset ids;
  - the merge is bit-identical to the single-device oracle, ties
    included: candidates concatenate in shard-major order (= global id
    order for equal scores, since `lax.top_k` is lowest-index-first
    within a shard), so the final top-k's positional tie-break
    reproduces the full-matrix `lax.top_k` exactly. Survival argument:
    any item in the global top-k has fewer than k items above it
    globally, hence fewer within its own shard, hence it is inside the
    shard's top-`min(k, per_shard)` candidates.

Path selection (`serve_plan`/`similar_plan` + `serve_mesh_from_conf`):
sharding engages when a mesh is explicitly configured (a `mesh` key in
the engine-instance/server runtime_conf, or `PIO_SERVE_SHARD=on`) or
when — under the default `PIO_SERVE_SHARD=auto` — the factor matrix
exceeds a single device's capacity (`PIO_DEVICE_HBM_BYTES` override,
else the backend's reported bytes_limit; unknown capacity, e.g. host
CPU, never auto-shards). `PIO_SERVE_SHARD=off` disables entirely and
`PIO_SERVE_SHARDS` caps the shard count.

Every sharded dispatch lands in `pio_topk_dispatch_total{path=
"sharded"}` and `DISPATCH_COUNTS["sharded"]`, and feeds the
`DispatchPolicy` sharded-path EWMA; plan construction publishes
`pio_serve_shards` and per-shard `pio_serve_shard_bytes{shard=...}`
HBM-residency gauges.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from predictionio_tpu.ops import compat
from predictionio_tpu.ops.topk import (
    DEFAULT_SERVE_BUCKETS, NEG_INF, BucketedSimilar, BucketedTopK,
    _next_pow2, _record_dispatch,
)
from predictionio_tpu.parallel.mesh import shard_put

# the serve mesh's single axis: catalog rows are partitioned over it
SHARD_AXIS = "items"


@dataclass(frozen=True)
class ServeMesh:
    """A serving mesh plus HOW it was chosen: `forced` means sharding
    was explicitly configured (runtime_conf mesh / PIO_SERVE_SHARD=on)
    and engages regardless of catalog size; un-forced meshes only shard
    catalogs that exceed one device's capacity."""
    mesh: "jax.sharding.Mesh"
    forced: bool = False

    @property
    def n_shards(self) -> int:
        return int(self.mesh.shape[SHARD_AXIS])  # lint: ok — host meta


def serve_mesh_from_conf(conf=None) -> Optional[ServeMesh]:
    """The deploy-time serving mesh: the "items" axis over the local
    devices, or None when sharded serving is off or pointless (< 2
    devices). `conf` is the merged engine-instance + server
    runtime_conf; a configured training mesh there forces the sharded
    path (training and serving agree on the device layout)."""
    mode = (os.environ.get("PIO_SERVE_SHARD", "auto") or "auto").lower()
    if mode in ("off", "0", "false"):
        return None
    from jax.sharding import Mesh
    devices = jax.devices()
    want = int(os.environ.get("PIO_SERVE_SHARDS", "0") or 0)  # lint: ok
    n = min(want, len(devices)) if want > 0 else len(devices)
    if n < 2:
        return None
    forced = mode in ("on", "1", "true") or bool((conf or {}).get("mesh"))
    return ServeMesh(Mesh(np.array(devices[:n]),  # lint: ok — host list
                          (SHARD_AXIS,)), forced)


def device_capacity_bytes() -> Optional[float]:
    """Per-device HBM capacity for the fits-one-device check:
    `PIO_DEVICE_HBM_BYTES` wins, else the backend's reported
    bytes_limit, else None (unknown — host CPU backends report
    nothing, and an unknown capacity never auto-shards)."""
    env = os.environ.get("PIO_DEVICE_HBM_BYTES", "").strip()
    if env:
        return float(env)   # lint: ok — host env knob
    try:
        stats = jax.devices()[0].memory_stats() or {}
        limit = stats.get("bytes_limit")
        return float(limit) if limit else None  # lint: ok — host stat
    except Exception:
        return None


def _wants_shard(n_items: int, rank: int,
                 mesh: Optional[ServeMesh]) -> bool:
    """Whether `serve_plan` should build the sharded plan: a usable
    mesh AND (explicitly configured, or the factor matrix does not fit
    one device — `BucketedTopK.fits`-style capacity check, with 20%
    headroom for the score/workspace buffers)."""
    if mesh is None or mesh.n_shards < 2:
        return False
    if mesh.forced:
        return True
    cap = device_capacity_bytes()
    if cap is None:
        return False
    return n_items * rank * 4 > cap * 0.8


def serve_plan(item_factors, *, k: int,
               buckets: Sequence[int] = DEFAULT_SERVE_BUCKETS,
               banned_width: int = 256,
               mesh: Optional[ServeMesh] = None):
    """The banned-index serving plan for this deployment: sharded when
    the mesh warrants it (see `_wants_shard`), else the single-device
    `BucketedTopK`. Both satisfy the same warm/fits/__call__ contract."""
    n_items, rank = np.asarray(item_factors).shape  # lint: ok — host meta
    if _wants_shard(n_items, rank, mesh):
        return ShardedBucketedTopK(item_factors, k=k, buckets=buckets,
                                   banned_width=banned_width,
                                   mesh=mesh.mesh)
    return BucketedTopK(item_factors, k=k, buckets=buckets,
                        banned_width=banned_width)


def similar_plan(item_factors, *, k: int,
                 buckets: Sequence[int] = DEFAULT_SERVE_BUCKETS,
                 mesh: Optional[ServeMesh] = None):
    """The dense-mask cosine serving plan: sharded or single-device by
    the same selection rule as `serve_plan`."""
    n_items, rank = np.asarray(item_factors).shape  # lint: ok — host meta
    if _wants_shard(n_items, rank, mesh):
        return ShardedBucketedSimilar(item_factors, k=k, buckets=buckets,
                                      mesh=mesh.mesh)
    return BucketedSimilar(item_factors, k=k, buckets=buckets)


def _publish_shard_gauges(n_shards: int, per_shard: int,
                          rank: int) -> None:
    """Shard-count + per-shard HBM residency gauges; metrics must never
    fail a deploy."""
    try:
        from predictionio_tpu.obs import get_registry
        reg = get_registry()
        reg.gauge("pio_serve_shards",
                  "Shard count of the current sharded serving plan "
                  "(0/absent = single-device)").set(
                      float(n_shards))  # lint: ok — host int
        g = reg.gauge("pio_serve_shard_bytes",
                      "Resident factor bytes pinned per shard by the "
                      "sharded serving plan", labels=("shard",))
        for s in range(n_shards):
            g.labels(shard=str(s)).set(float(per_shard * rank * 4))
    except Exception:
        pass


class _ShardedPlanBase:
    """Shared bucketing/pad/chunk mechanics of the two sharded plans."""

    def __init__(self, item_factors, *, k: int, buckets: Sequence[int],
                 mesh):
        host = np.ascontiguousarray(item_factors, dtype=np.float32)
        self.n_items, self.rank = host.shape
        self.k = max(1, min(k, self.n_items))
        self.buckets = tuple(sorted({_next_pow2(b)
                                     for b in buckets if b > 0})) or (1,)
        self.mesh = mesh
        self.n_shards = int(mesh.shape[SHARD_AXIS])  # lint: ok — host
        # row-shard the (zero-padded) factors across the mesh ONCE; the
        # sharded array is the plan's resident model state
        self._host_factors = host
        self.factors, _ = shard_put(host, mesh, SHARD_AXIS)
        self.n_pad = int(self.factors.shape[0])  # lint: ok — shape meta
        self.per_shard = self.n_pad // self.n_shards
        # per-shard candidate count: a shard can never contribute more
        # rows than it holds (k > per_shard clamps, the merge still
        # sees >= k real candidates overall)
        self.k_shard = min(self.k, self.per_shard)
        self._exe: dict = {}
        _publish_shard_gauges(self.n_shards, self.per_shard, self.rank)

    def swap_factors(self, item_factors) -> np.ndarray:
        """Hot-swap the sharded resident factors (streaming refresher
        commit): same shape => same mesh/axis sharding => the per-bucket
        executables (which take the factor operand positionally) keep
        serving with zero recompiles; only the new rows cross to the
        devices. Returns the previous host factors (rollback token)."""
        host = np.ascontiguousarray(item_factors, dtype=np.float32)
        if host.shape != (self.n_items, self.rank):
            raise ValueError(
                f"swap_factors shape {host.shape} != "
                f"{(self.n_items, self.rank)}: catalog changed — a hot "
                "swap cannot resize the AOT plan; re-warm instead")
        factors, _ = shard_put(host, self.mesh, SHARD_AXIS)
        prev = self._host_factors
        self._host_factors = host
        self.factors = factors
        return prev

    @property
    def max_bucket(self) -> int:
        return self.buckets[-1]

    def _bucket_for(self, b: int) -> int:
        for bucket in self.buckets:
            if bucket >= b:
                return bucket
        return self.max_bucket

    def _require_exe(self, bucket: int):
        exe = self._exe.get(bucket)
        if exe is None:
            raise RuntimeError(
                f"{type(self).__name__} bucket {bucket} not warmed; "
                "call warm() at deploy time")
        return exe


class ShardedBucketedTopK(_ShardedPlanBase):
    """Banned-index top-k over a row-sharded resident factor matrix:
    per-shard partial top-k on-device, allgather + merge to the global
    top-k (module docstring has the full program shape and the
    tie-parity argument). Drop-in for `BucketedTopK`."""

    def __init__(self, item_factors, *, k: int,
                 buckets: Sequence[int] = DEFAULT_SERVE_BUCKETS,
                 banned_width: int = 256, mesh=None):
        super().__init__(item_factors, k=k, buckets=buckets, mesh=mesh)
        self.banned_width = _next_pow2(max(1, banned_width))
        # whether the per-shard local-candidate stage runs as the
        # single-launch fused kernel (ops/fused_topk.py); flips back to
        # False if the kernel fails to lower at warm() time
        self.fused = False
        self._fn = self._build()

    def _build(self, bucket: Optional[int] = None):
        from jax.sharding import PartitionSpec as P
        from predictionio_tpu.ops import fused_topk
        per, n_items, kk, k = (self.per_shard, self.n_items,
                               self.k_shard, self.k)

        # the fused per-shard local-candidate kernel needs the batch
        # bucket at build time (its grid is shape-specialized); the XLA
        # body below shape-polymorphically covers every bucket
        local = None
        if bucket is not None:
            local = fused_topk.shard_local_candidates(
                per, self.rank, k=kk, bucket=bucket,
                banned_width=self.banned_width)
            if local is None:
                return None
            self.fused = True

        def body(vecs, factors_local, banned):
            # vecs [b, rank] + banned [b, W] replicated; factors_local
            # [per_shard, rank] is this shard's catalog slice
            base = jax.lax.axis_index(SHARD_AXIS) * per
            # banned ids are GLOBAL: translate to this shard's local
            # columns. Out-of-shard ids (and the n_items filler) must be
            # routed to an explicitly out-of-bounds slot BEFORE the
            # scatter — `.at[]` wraps negative indices NumPy-style even
            # under mode="drop", so a bare `banned - base` would make a
            # banned id g also ban g + per_shard on the next shard.
            loc = banned - base
            loc = jnp.where((loc >= 0) & (loc < per), loc, per)
            if local is not None:
                # single launch: matmul + ban-mask + local top-k fused;
                # the shard's valid-row bound is mesh-position-dependent
                # and rides in as a scalar operand
                nv = jnp.clip(n_items - base, 0,
                              per).astype(jnp.int32).reshape((1,))
                s, ix = local(nv, vecs, factors_local, loc)
            else:
                scores = jnp.matmul(vecs, factors_local.T,
                                    precision=jax.lax.Precision.HIGHEST)
                rows = jnp.arange(scores.shape[0])[:, None]
                scores = scores.at[rows, loc].set(NEG_INF, mode="drop")
                gids = base + jnp.arange(per)
                scores = jnp.where(gids[None, :] < n_items, scores,
                                   NEG_INF)
                s, ix = jax.lax.top_k(scores, kk)
            s_all = jax.lax.all_gather(s, SHARD_AXIS)
            g_all = jax.lax.all_gather(ix + base, SHARD_AXIS)
            # shard-major concatenation = global-id order for ties
            s_cat = jnp.swapaxes(s_all, 0, 1).reshape(s.shape[0], -1)
            g_cat = jnp.swapaxes(g_all, 0, 1).reshape(s.shape[0], -1)
            sv, si = jax.lax.top_k(s_cat, k)
            return sv, jnp.take_along_axis(g_cat, si, axis=1)

        smapped = compat.shard_map(
            body, mesh=self.mesh,
            in_specs=(P(), P(SHARD_AXIS, None), P()),
            out_specs=(P(), P()))
        if jax.default_backend() == "cpu":
            return jax.jit(smapped)
        # off-CPU: donate the per-call query + banned uploads, exactly
        # as the single-device plan does
        return jax.jit(smapped, donate_argnums=(0, 2))

    def warm(self) -> int:
        """AOT-lower/compile every bucket executable against the
        resident sharded factors (idempotent). Each bucket tries the
        fused per-shard kernel first (PIO_SERVE_FUSED gate) and falls
        back to the XLA body when fusion is off or fails to lower."""
        compiled = 0
        for b in self.buckets:
            if b in self._exe:
                continue
            vec_spec = jax.ShapeDtypeStruct((b, self.rank), np.float32)
            ban_spec = jax.ShapeDtypeStruct((b, self.banned_width),
                                            np.int32)
            exe = None
            fn = self._build(bucket=b)
            if fn is not None:
                try:
                    exe = fn.lower(vec_spec, self.factors,
                                   ban_spec).compile()
                except Exception:
                    # kernel lowered at trace time but died in the
                    # backend compiler: unfuse and fall through
                    self.fused = False
            if exe is None:
                exe = self._fn.lower(vec_spec, self.factors,
                                     ban_spec).compile()
            self._exe[b] = exe
            compiled += 1
        return compiled

    def fits(self, *, max_banned: int, k: int) -> bool:
        """Same gate as `BucketedTopK.fits`."""
        return (bool(self._exe)
                and k <= self.k and max_banned <= self.banned_width)

    def __call__(self, user_vecs, banned_lists: Sequence[Sequence[int]]):
        """Score [b, rank] queries against the sharded catalog with
        per-row GLOBAL banned-id lists; returns host (scores [b, k],
        ids [b, k]). Pads to the bucket grid; chunks past the biggest
        bucket."""
        user_vecs = np.asarray(user_vecs, np.float32)  # lint: ok — host in
        b = user_vecs.shape[0]
        if b > self.max_bucket:
            parts = [self(user_vecs[lo:lo + self.max_bucket],
                          banned_lists[lo:lo + self.max_bucket])
                     for lo in range(0, b, self.max_bucket)]
            return (np.concatenate([p[0] for p in parts]),
                    np.concatenate([p[1] for p in parts]))
        bucket = self._bucket_for(b)
        exe = self._require_exe(bucket)
        t0 = time.perf_counter()
        vecs = np.zeros((bucket, self.rank), np.float32)
        vecs[:b] = user_vecs
        banned = np.full((bucket, self.banned_width), self.n_items,
                         np.int32)
        for row, bl in enumerate(banned_lists):
            if len(bl):
                banned[row, :len(bl)] = np.asarray(bl, np.int32)  # lint: ok
        scores, ixs = jax.device_get(exe(vecs, self.factors, banned))
        _record_dispatch("sharded", bucket * self.n_items,
                         time.perf_counter() - t0)
        return scores[:b], ixs[:b]


class ShardedBucketedSimilar(_ShardedPlanBase):
    """Dense-mask cosine top-k over a row-sharded resident factor
    matrix (the similar-product template's filter shape): the mask is
    column-sharded to match the catalog rows, each shard normalizes
    its own factor slice (row-local math, identical to the
    single-device program), partial top-k, allgather + merge. Drop-in
    for `BucketedSimilar`."""

    def __init__(self, item_factors, *, k: int,
                 buckets: Sequence[int] = DEFAULT_SERVE_BUCKETS,
                 mesh=None):
        super().__init__(item_factors, k=k, buckets=buckets, mesh=mesh)
        self._fn = self._build()

    def _build(self):
        from jax.sharding import PartitionSpec as P
        per, kk, k = self.per_shard, self.k_shard, self.k

        def body(query_vecs, factors_local, mask_local):
            base = jax.lax.axis_index(SHARD_AXIS) * per
            qn = query_vecs / (jnp.linalg.norm(query_vecs, axis=-1,
                                               keepdims=True) + 1e-9)
            fn = factors_local / (jnp.linalg.norm(factors_local, axis=-1,
                                                  keepdims=True) + 1e-9)
            scores = jnp.matmul(qn, fn.T,
                                precision=jax.lax.Precision.HIGHEST)
            # padding rows arrive masked False (the caller pads the
            # mask columns with False), so no gid test is needed here
            scores = jnp.where(mask_local, scores, NEG_INF)
            s, ix = jax.lax.top_k(scores, kk)
            s_all = jax.lax.all_gather(s, SHARD_AXIS)
            g_all = jax.lax.all_gather(ix + base, SHARD_AXIS)
            s_cat = jnp.swapaxes(s_all, 0, 1).reshape(s.shape[0], -1)
            g_cat = jnp.swapaxes(g_all, 0, 1).reshape(s.shape[0], -1)
            sv, si = jax.lax.top_k(s_cat, k)
            return sv, jnp.take_along_axis(g_cat, si, axis=1)

        smapped = compat.shard_map(
            body, mesh=self.mesh,
            in_specs=(P(), P(SHARD_AXIS, None), P(None, SHARD_AXIS)),
            out_specs=(P(), P()))
        if jax.default_backend() == "cpu":
            return jax.jit(smapped)
        return jax.jit(smapped, donate_argnums=(0, 2))

    def warm(self) -> int:
        """AOT-lower/compile every bucket executable (idempotent)."""
        compiled = 0
        for b in self.buckets:
            if b in self._exe:
                continue
            vec_spec = jax.ShapeDtypeStruct((b, self.rank), np.float32)
            mask_spec = jax.ShapeDtypeStruct((b, self.n_pad), np.bool_)
            self._exe[b] = self._fn.lower(vec_spec, self.factors,
                                          mask_spec).compile()
            compiled += 1
        return compiled

    def fits(self, *, k: int) -> bool:
        return bool(self._exe) and k <= self.k

    def __call__(self, query_vecs, mask):
        """Cosine top-k of [b, rank] queries against the sharded
        catalog under a dense [b, n_items] mask; returns host (scores
        [b, k], ids [b, k])."""
        query_vecs = np.asarray(query_vecs, np.float32)  # lint: ok — host in
        mask = np.asarray(mask, bool)                    # lint: ok — host in
        b = query_vecs.shape[0]
        if b > self.max_bucket:
            parts = [self(query_vecs[lo:lo + self.max_bucket],
                          mask[lo:lo + self.max_bucket])
                     for lo in range(0, b, self.max_bucket)]
            return (np.concatenate([p[0] for p in parts]),
                    np.concatenate([p[1] for p in parts]))
        bucket = self._bucket_for(b)
        exe = self._require_exe(bucket)
        t0 = time.perf_counter()
        vecs = np.zeros((bucket, self.rank), np.float32)
        vecs[:b] = query_vecs
        # padding lanes AND padding catalog columns are all-False
        mask_p = np.zeros((bucket, self.n_pad), bool)
        mask_p[:b, :self.n_items] = mask
        scores, ixs = jax.device_get(exe(vecs, self.factors, mask_p))
        _record_dispatch("sharded", bucket * self.n_items,
                         time.perf_counter() - t0)
        return scores[:b], ixs[:b]

"""Mesh-sharded serving plans: partial top-k per shard + allgather merge.

A catalog bigger than one chip's HBM cannot be pinned by `BucketedTopK`
— and `MULTICHIP_r0*.json` shows every model's train step already runs
on 8-device meshes while serving ignored the mesh entirely. This module
closes that gap with the sharded-scoring shape "Scalable ML Training
Infrastructure at Google" describes for ads scoring: partition the
embedding (factor) table row-wise, score locally, merge partial top-k.

`ShardedBucketedTopK` / `ShardedBucketedSimilar` are drop-in serving
plans (same `warm()/fits()/__call__` contract as their single-device
counterparts in `ops/topk.py`):

  - item factors are padded to a multiple of the shard count and
    device_put ONCE with a row sharding over the serve mesh's "items"
    axis (`parallel.mesh.shard_put`), so each device holds an
    `n_items/n_shards` slice of the catalog for the plan's lifetime;
  - every batch bucket is AOT-lowered/compiled against that resident
    sharded array: inside the program each shard computes its local
    score block (one matmul at `Precision.HIGHEST`, identical math to
    the single-device path), applies banned-index filtering IN GLOBAL
    ID SPACE (banned ids arrive untranslated; each shard subtracts its
    row base, routes out-of-shard ids to an out-of-bounds slot, and the
    scatter drops them), masks its padding
    rows to NEG_INF, takes a LOCAL `lax.top_k`, then all-gathers the
    `k_shard * n_shards` candidates and merges them with a final
    top-k over globally-offset ids;
  - the merge is bit-identical to the single-device oracle, ties
    included: candidates concatenate in shard-major order (= global id
    order for equal scores, since `lax.top_k` is lowest-index-first
    within a shard), so the final top-k's positional tie-break
    reproduces the full-matrix `lax.top_k` exactly. Survival argument:
    any item in the global top-k has fewer than k items above it
    globally, hence fewer within its own shard, hence it is inside the
    shard's top-`min(k, per_shard)` candidates.

Path selection (`serve_plan`/`similar_plan` + `serve_mesh_from_conf`):
sharding engages when a mesh is explicitly configured (a `mesh` key in
the engine-instance/server runtime_conf, or `PIO_SERVE_SHARD=on`) or
when — under the default `PIO_SERVE_SHARD=auto` — the factor matrix
exceeds a single device's capacity (`PIO_DEVICE_HBM_BYTES` override,
else the backend's reported bytes_limit; unknown capacity, e.g. host
CPU, never auto-shards). `PIO_SERVE_SHARD=off` disables entirely and
`PIO_SERVE_SHARDS` caps the shard count.

Every sharded dispatch lands in `pio_topk_dispatch_total{path=
"sharded"}` and `DISPATCH_COUNTS["sharded"]`, and feeds the
`DispatchPolicy` sharded-path EWMA; plan construction publishes
`pio_serve_shards` and per-shard `pio_serve_shard_bytes{shard=...}`
HBM-residency gauges.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from predictionio_tpu.ops import compat, topk
from predictionio_tpu.ops.topk import (
    DEFAULT_SERVE_BUCKETS, NEG_INF, BucketedSimilar, BucketedTopK,
    _next_pow2, _record_dispatch,
)
from predictionio_tpu.parallel.mesh import shard_put

# the serve mesh's single axis: catalog rows are partitioned over it
SHARD_AXIS = "items"


@dataclass(frozen=True)
class ServeMesh:
    """A serving mesh plus HOW it was chosen: `forced` means sharding
    was explicitly configured (runtime_conf mesh / PIO_SERVE_SHARD=on)
    and engages regardless of catalog size; un-forced meshes only shard
    catalogs that exceed one device's capacity."""
    mesh: "jax.sharding.Mesh"
    forced: bool = False

    @property
    def n_shards(self) -> int:
        return int(self.mesh.shape[SHARD_AXIS])  # lint: ok — host meta


@dataclass(frozen=True)
class ShardSlice:
    """A CROSS-HOST fleet shard assignment: this member owns one
    contiguous row-slice of the catalog (shard `index` of `n_shards`,
    same ceil-divided block partition the local sharded plans use).
    Flows through `serve_plan`'s mesh slot, so the deploy warm path
    builds a `ShardSliceTopK` instead of a whole-catalog plan."""
    n_shards: int
    index: int


def parse_fleet_mesh(spec: str):
    """Parse a cross-host mesh spec: `items=N@fleet` (router side:
    merge over N member-owned shards) or `items=N@fleet:i` (member
    side: this process owns shard i). Returns (n_shards, index-or-None)
    or None when `spec` is not a fleet mesh."""
    import re
    m = re.match(r"\s*items\s*=\s*(\d+)\s*@\s*fleet(?::(\d+))?\s*$",
                 spec or "")
    if m is None:
        return None
    n = int(m.group(1))
    idx = int(m.group(2)) if m.group(2) is not None else None
    if n < 1 or (idx is not None and not 0 <= idx < n):
        raise ValueError(f"bad fleet mesh spec {spec!r}: need "
                         "items=N@fleet[:i] with 0 <= i < N")
    return n, idx


def serve_mesh_from_conf(conf=None):
    """The deploy-time serving mesh: the "items" axis over the local
    devices, or None when sharded serving is off or pointless (< 2
    devices). `conf` is the merged engine-instance + server
    runtime_conf; a configured training mesh there forces the sharded
    path (training and serving agree on the device layout). A
    cross-host `items=N@fleet:i` mesh returns a `ShardSlice` instead —
    this member serves only its owned catalog rows and the fleet
    router merges across members."""
    conf_mesh = str((conf or {}).get("mesh", "") or "")
    fleet = parse_fleet_mesh(conf_mesh)
    if fleet is not None:
        n, idx = fleet
        if idx is not None:
            return ShardSlice(n_shards=n, index=idx)
        # router-level spec: not a local device layout — never forces
        # local sharding on the process that merges
        conf_mesh = ""
    mode = (os.environ.get("PIO_SERVE_SHARD", "auto") or "auto").lower()
    if mode in ("off", "0", "false"):
        return None
    from jax.sharding import Mesh
    devices = jax.devices()
    want = int(os.environ.get("PIO_SERVE_SHARDS", "0") or 0)  # lint: ok
    n = min(want, len(devices)) if want > 0 else len(devices)
    if n < 2:
        return None
    forced = mode in ("on", "1", "true") or bool(conf_mesh)
    return ServeMesh(Mesh(np.array(devices[:n]),  # lint: ok — host list
                          (SHARD_AXIS,)), forced)


def device_capacity_bytes() -> Optional[float]:
    """Per-device HBM capacity for the fits-one-device check:
    `PIO_DEVICE_HBM_BYTES` wins, else the backend's reported
    bytes_limit, else None (unknown — host CPU backends report
    nothing, and an unknown capacity never auto-shards)."""
    env = os.environ.get("PIO_DEVICE_HBM_BYTES", "").strip()
    if env:
        return float(env)   # lint: ok — host env knob
    try:
        stats = jax.devices()[0].memory_stats() or {}
        limit = stats.get("bytes_limit")
        return float(limit) if limit else None  # lint: ok — host stat
    except Exception:
        return None


def effective_device_capacity() -> Optional[float]:
    """The byte budget a NEW plan may still pin on one device: raw
    capacity with 20% headroom for score/workspace buffers, MINUS the
    bytes live plans already hold resident (the server's
    pio_plan_resident_bytes). Without the subtraction, back-to-back
    /reloads of a near-capacity catalog pass the fits check against an
    EMPTY device and OOM once old + new deployments are both pinned
    (the old plan stays resident until the atomic swap completes)."""
    cap = device_capacity_bytes()
    if cap is None:
        return None
    return cap * 0.8 - topk.plan_resident_bytes()


def _wants_shard(n_items: int, rank: int,
                 mesh: Optional[ServeMesh]) -> bool:
    """Whether `serve_plan` should build the sharded plan: a usable
    mesh AND (explicitly configured, or the factor matrix does not fit
    one device — `BucketedTopK.fits`-style capacity check, with 20%
    headroom and resident-plan bytes subtracted, see
    `effective_device_capacity`)."""
    if mesh is None or not isinstance(mesh, ServeMesh) \
            or mesh.n_shards < 2:
        return False
    if mesh.forced:
        return True
    cap = effective_device_capacity()
    if cap is None:
        return False
    return n_items * rank * 4 > cap


def _tier_hot_items(n_items: int, rank: int) -> Optional[int]:
    """Hot-slab size when tiered storage should engage, else None.
    `PIO_SERVE_TIER=on` always tiers; `auto` (default) tiers only when
    the factor matrix exceeds the effective device budget; `off`
    never. `PIO_TIER_HOT_FRAC` sizes the slab explicitly; unset, the
    slab fills the effective budget (quarter-catalog fallback when the
    budget is unknown but tiering is forced on)."""
    from predictionio_tpu.ops import topk_tiered
    mode = topk_tiered.tier_mode()
    if mode == "off":
        return None
    cap = effective_device_capacity()
    nbytes = n_items * rank * 4
    if mode == "auto" and (cap is None or nbytes <= cap):
        return None
    frac = topk_tiered.hot_frac()
    if frac is not None:
        hot = int(n_items * frac)
    elif cap is not None and cap > 0:
        hot = int(cap // (rank * 4))
    else:
        hot = n_items // 4
    return max(1, min(hot, n_items))


def serve_plan(item_factors, *, k: int,
               buckets: Sequence[int] = DEFAULT_SERVE_BUCKETS,
               banned_width: int = 256,
               mesh=None):
    """The banned-index serving plan for this deployment. Selection
    order: a cross-host `ShardSlice` builds the member-local slice plan
    (whose inner plan recurses through this selection — a giant shard
    slice tiers itself); a local mesh that warrants it shards
    (`_wants_shard`); a catalog past the effective device budget tiers
    (`_tier_hot_items` / PIO_SERVE_TIER); else the single-device
    `BucketedTopK`. All satisfy the same warm/fits/__call__ contract."""
    n_items, rank = np.asarray(item_factors).shape  # lint: ok — host meta
    if isinstance(mesh, ShardSlice):
        return ShardSliceTopK(item_factors, k=k, buckets=buckets,
                              banned_width=banned_width, slice_spec=mesh)
    if _wants_shard(n_items, rank, mesh):
        return ShardedBucketedTopK(item_factors, k=k, buckets=buckets,
                                   banned_width=banned_width,
                                   mesh=mesh.mesh)
    hot = _tier_hot_items(n_items, rank)
    if hot is not None:
        from predictionio_tpu.ops.topk_tiered import TieredTopK
        return TieredTopK(item_factors, k=k, buckets=buckets,
                          banned_width=banned_width, hot_items=hot)
    return BucketedTopK(item_factors, k=k, buckets=buckets,
                        banned_width=banned_width)


def similar_plan(item_factors, *, k: int,
                 buckets: Sequence[int] = DEFAULT_SERVE_BUCKETS,
                 mesh=None):
    """The dense-mask cosine serving plan: sharded or single-device by
    the same selection rule as `serve_plan`. A cross-host `ShardSlice`
    keeps the single-device plan over the FULL catalog (the dense-mask
    path has no slice variant); every member then returns identical
    similar-items candidates and the router merge deduplicates — exact,
    just not memory-partitioned."""
    n_items, rank = np.asarray(item_factors).shape  # lint: ok — host meta
    if not isinstance(mesh, ShardSlice) and _wants_shard(n_items, rank,
                                                         mesh):
        return ShardedBucketedSimilar(item_factors, k=k, buckets=buckets,
                                      mesh=mesh.mesh)
    return BucketedSimilar(item_factors, k=k, buckets=buckets)


def _publish_shard_gauges(n_shards: int, per_shard: int,
                          rank: int) -> None:
    """Shard-count + per-shard HBM residency gauges; metrics must never
    fail a deploy."""
    try:
        from predictionio_tpu.obs import get_registry
        reg = get_registry()
        reg.gauge("pio_serve_shards",
                  "Shard count of the current sharded serving plan "
                  "(0/absent = single-device)").set(
                      float(n_shards))  # lint: ok — host int
        g = reg.gauge("pio_serve_shard_bytes",
                      "Resident factor bytes pinned per shard by the "
                      "sharded serving plan", labels=("shard",))
        for s in range(n_shards):
            g.labels(shard=str(s)).set(float(per_shard * rank * 4))
    except Exception:
        pass


class _ShardedPlanBase:
    """Shared bucketing/pad/chunk mechanics of the two sharded plans."""

    def __init__(self, item_factors, *, k: int, buckets: Sequence[int],
                 mesh):
        host = np.ascontiguousarray(item_factors, dtype=np.float32)
        self.n_items, self.rank = host.shape
        self.k = max(1, min(k, self.n_items))
        self.buckets = tuple(sorted({_next_pow2(b)
                                     for b in buckets if b > 0})) or (1,)
        self.mesh = mesh
        self.n_shards = int(mesh.shape[SHARD_AXIS])  # lint: ok — host
        # row-shard the (zero-padded) factors across the mesh ONCE; the
        # sharded array is the plan's resident model state
        self._host_factors = host
        self.factors, _ = shard_put(host, mesh, SHARD_AXIS)
        self.n_pad = int(self.factors.shape[0])  # lint: ok — shape meta
        self.per_shard = self.n_pad // self.n_shards
        # per-shard candidate count: a shard can never contribute more
        # rows than it holds (k > per_shard clamps, the merge still
        # sees >= k real candidates overall)
        self.k_shard = min(self.k, self.per_shard)
        self._exe: dict = {}
        topk.register_resident_plan(self)
        _publish_shard_gauges(self.n_shards, self.per_shard, self.rank)

    def resident_per_device_bytes(self) -> float:
        """Bytes this plan pins per device: one padded shard's rows."""
        return float(self.per_shard * self.rank * 4)

    def swap_factors(self, item_factors) -> np.ndarray:
        """Hot-swap the sharded resident factors (streaming refresher
        commit): same shape => same mesh/axis sharding => the per-bucket
        executables (which take the factor operand positionally) keep
        serving with zero recompiles; only the new rows cross to the
        devices. Returns the previous host factors (rollback token)."""
        host = np.ascontiguousarray(item_factors, dtype=np.float32)
        if host.shape != (self.n_items, self.rank):
            raise ValueError(
                f"swap_factors shape {host.shape} != "
                f"{(self.n_items, self.rank)}: catalog changed — a hot "
                "swap cannot resize the AOT plan; re-warm instead")
        factors, _ = shard_put(host, self.mesh, SHARD_AXIS)
        prev = self._host_factors
        self._host_factors = host
        self.factors = factors
        return prev

    @property
    def max_bucket(self) -> int:
        return self.buckets[-1]

    def _bucket_for(self, b: int) -> int:
        for bucket in self.buckets:
            if bucket >= b:
                return bucket
        return self.max_bucket

    def _require_exe(self, bucket: int):
        exe = self._exe.get(bucket)
        if exe is None:
            raise RuntimeError(
                f"{type(self).__name__} bucket {bucket} not warmed; "
                "call warm() at deploy time")
        return exe


class ShardedBucketedTopK(_ShardedPlanBase):
    """Banned-index top-k over a row-sharded resident factor matrix:
    per-shard partial top-k on-device, allgather + merge to the global
    top-k (module docstring has the full program shape and the
    tie-parity argument). Drop-in for `BucketedTopK`."""

    def __init__(self, item_factors, *, k: int,
                 buckets: Sequence[int] = DEFAULT_SERVE_BUCKETS,
                 banned_width: int = 256, mesh=None):
        super().__init__(item_factors, k=k, buckets=buckets, mesh=mesh)
        self.banned_width = _next_pow2(max(1, banned_width))
        # whether the per-shard local-candidate stage runs as the
        # single-launch fused kernel (ops/fused_topk.py); flips back to
        # False if the kernel fails to lower at warm() time
        self.fused = False
        self._fn = self._build()

    def _build(self, bucket: Optional[int] = None):
        from jax.sharding import PartitionSpec as P
        from predictionio_tpu.ops import fused_topk
        per, n_items, kk, k = (self.per_shard, self.n_items,
                               self.k_shard, self.k)

        # the fused per-shard local-candidate kernel needs the batch
        # bucket at build time (its grid is shape-specialized); the XLA
        # body below shape-polymorphically covers every bucket
        local = None
        if bucket is not None:
            local = fused_topk.shard_local_candidates(
                per, self.rank, k=kk, bucket=bucket,
                banned_width=self.banned_width)
            if local is None:
                return None
            self.fused = True

        def body(vecs, factors_local, banned):
            # vecs [b, rank] + banned [b, W] replicated; factors_local
            # [per_shard, rank] is this shard's catalog slice
            base = jax.lax.axis_index(SHARD_AXIS) * per
            # banned ids are GLOBAL: translate to this shard's local
            # columns. Out-of-shard ids (and the n_items filler) must be
            # routed to an explicitly out-of-bounds slot BEFORE the
            # scatter — `.at[]` wraps negative indices NumPy-style even
            # under mode="drop", so a bare `banned - base` would make a
            # banned id g also ban g + per_shard on the next shard.
            loc = banned - base
            loc = jnp.where((loc >= 0) & (loc < per), loc, per)
            if local is not None:
                # single launch: matmul + ban-mask + local top-k fused;
                # the shard's valid-row bound is mesh-position-dependent
                # and rides in as a scalar operand
                nv = jnp.clip(n_items - base, 0,
                              per).astype(jnp.int32).reshape((1,))
                s, ix = local(nv, vecs, factors_local, loc)
            else:
                scores = jnp.matmul(vecs, factors_local.T,
                                    precision=jax.lax.Precision.HIGHEST)
                rows = jnp.arange(scores.shape[0])[:, None]
                scores = scores.at[rows, loc].set(NEG_INF, mode="drop")
                gids = base + jnp.arange(per)
                scores = jnp.where(gids[None, :] < n_items, scores,
                                   NEG_INF)
                s, ix = jax.lax.top_k(scores, kk)
            s_all = jax.lax.all_gather(s, SHARD_AXIS)
            g_all = jax.lax.all_gather(ix + base, SHARD_AXIS)
            # shard-major concatenation = global-id order for ties
            s_cat = jnp.swapaxes(s_all, 0, 1).reshape(s.shape[0], -1)
            g_cat = jnp.swapaxes(g_all, 0, 1).reshape(s.shape[0], -1)
            sv, si = jax.lax.top_k(s_cat, k)
            return sv, jnp.take_along_axis(g_cat, si, axis=1)

        smapped = compat.shard_map(
            body, mesh=self.mesh,
            in_specs=(P(), P(SHARD_AXIS, None), P()),
            out_specs=(P(), P()))
        if jax.default_backend() == "cpu":
            return jax.jit(smapped)
        # off-CPU: donate the per-call query + banned uploads, exactly
        # as the single-device plan does
        return jax.jit(smapped, donate_argnums=(0, 2))

    def warm(self) -> int:
        """AOT-lower/compile every bucket executable against the
        resident sharded factors (idempotent). Each bucket tries the
        fused per-shard kernel first (PIO_SERVE_FUSED gate) and falls
        back to the XLA body when fusion is off or fails to lower."""
        compiled = 0
        for b in self.buckets:
            if b in self._exe:
                continue
            vec_spec = jax.ShapeDtypeStruct((b, self.rank), np.float32)
            ban_spec = jax.ShapeDtypeStruct((b, self.banned_width),
                                            np.int32)
            exe = None
            fn = self._build(bucket=b)
            if fn is not None:
                try:
                    exe = fn.lower(vec_spec, self.factors,
                                   ban_spec).compile()
                except Exception:
                    # kernel lowered at trace time but died in the
                    # backend compiler: unfuse and fall through
                    self.fused = False
            if exe is None:
                exe = self._fn.lower(vec_spec, self.factors,
                                     ban_spec).compile()
            self._exe[b] = exe
            compiled += 1
        return compiled

    def fits(self, *, max_banned: int, k: int) -> bool:
        """Same gate as `BucketedTopK.fits`."""
        return (bool(self._exe)
                and k <= self.k and max_banned <= self.banned_width)

    def __call__(self, user_vecs, banned_lists: Sequence[Sequence[int]]):
        """Score [b, rank] queries against the sharded catalog with
        per-row GLOBAL banned-id lists; returns host (scores [b, k],
        ids [b, k]). Pads to the bucket grid; chunks past the biggest
        bucket."""
        user_vecs = np.asarray(user_vecs, np.float32)  # lint: ok — host in
        b = user_vecs.shape[0]
        if b > self.max_bucket:
            parts = [self(user_vecs[lo:lo + self.max_bucket],
                          banned_lists[lo:lo + self.max_bucket])
                     for lo in range(0, b, self.max_bucket)]
            return (np.concatenate([p[0] for p in parts]),
                    np.concatenate([p[1] for p in parts]))
        bucket = self._bucket_for(b)
        exe = self._require_exe(bucket)
        t0 = time.perf_counter()
        vecs = np.zeros((bucket, self.rank), np.float32)
        vecs[:b] = user_vecs
        banned = np.full((bucket, self.banned_width), self.n_items,
                         np.int32)
        for row, bl in enumerate(banned_lists):
            if len(bl):
                banned[row, :len(bl)] = np.asarray(bl, np.int32)  # lint: ok
        scores, ixs = jax.device_get(exe(vecs, self.factors, banned))
        _record_dispatch("sharded", bucket * self.n_items,
                         time.perf_counter() - t0)
        return scores[:b], ixs[:b]


class ShardedBucketedSimilar(_ShardedPlanBase):
    """Dense-mask cosine top-k over a row-sharded resident factor
    matrix (the similar-product template's filter shape): the mask is
    column-sharded to match the catalog rows, each shard normalizes
    its own factor slice (row-local math, identical to the
    single-device program), partial top-k, allgather + merge. Drop-in
    for `BucketedSimilar`."""

    def __init__(self, item_factors, *, k: int,
                 buckets: Sequence[int] = DEFAULT_SERVE_BUCKETS,
                 mesh=None):
        super().__init__(item_factors, k=k, buckets=buckets, mesh=mesh)
        self._fn = self._build()

    def _build(self):
        from jax.sharding import PartitionSpec as P
        per, kk, k = self.per_shard, self.k_shard, self.k

        def body(query_vecs, factors_local, mask_local):
            base = jax.lax.axis_index(SHARD_AXIS) * per
            qn = query_vecs / (jnp.linalg.norm(query_vecs, axis=-1,
                                               keepdims=True) + 1e-9)
            fn = factors_local / (jnp.linalg.norm(factors_local, axis=-1,
                                                  keepdims=True) + 1e-9)
            scores = jnp.matmul(qn, fn.T,
                                precision=jax.lax.Precision.HIGHEST)
            # padding rows arrive masked False (the caller pads the
            # mask columns with False), so no gid test is needed here
            scores = jnp.where(mask_local, scores, NEG_INF)
            s, ix = jax.lax.top_k(scores, kk)
            s_all = jax.lax.all_gather(s, SHARD_AXIS)
            g_all = jax.lax.all_gather(ix + base, SHARD_AXIS)
            s_cat = jnp.swapaxes(s_all, 0, 1).reshape(s.shape[0], -1)
            g_cat = jnp.swapaxes(g_all, 0, 1).reshape(s.shape[0], -1)
            sv, si = jax.lax.top_k(s_cat, k)
            return sv, jnp.take_along_axis(g_cat, si, axis=1)

        smapped = compat.shard_map(
            body, mesh=self.mesh,
            in_specs=(P(), P(SHARD_AXIS, None), P(None, SHARD_AXIS)),
            out_specs=(P(), P()))
        if jax.default_backend() == "cpu":
            return jax.jit(smapped)
        return jax.jit(smapped, donate_argnums=(0, 2))

    def warm(self) -> int:
        """AOT-lower/compile every bucket executable (idempotent)."""
        compiled = 0
        for b in self.buckets:
            if b in self._exe:
                continue
            vec_spec = jax.ShapeDtypeStruct((b, self.rank), np.float32)
            mask_spec = jax.ShapeDtypeStruct((b, self.n_pad), np.bool_)
            self._exe[b] = self._fn.lower(vec_spec, self.factors,
                                          mask_spec).compile()
            compiled += 1
        return compiled

    def fits(self, *, k: int) -> bool:
        return bool(self._exe) and k <= self.k

    def __call__(self, query_vecs, mask):
        """Cosine top-k of [b, rank] queries against the sharded
        catalog under a dense [b, n_items] mask; returns host (scores
        [b, k], ids [b, k])."""
        query_vecs = np.asarray(query_vecs, np.float32)  # lint: ok — host in
        mask = np.asarray(mask, bool)                    # lint: ok — host in
        b = query_vecs.shape[0]
        if b > self.max_bucket:
            parts = [self(query_vecs[lo:lo + self.max_bucket],
                          mask[lo:lo + self.max_bucket])
                     for lo in range(0, b, self.max_bucket)]
            return (np.concatenate([p[0] for p in parts]),
                    np.concatenate([p[1] for p in parts]))
        bucket = self._bucket_for(b)
        exe = self._require_exe(bucket)
        t0 = time.perf_counter()
        vecs = np.zeros((bucket, self.rank), np.float32)
        vecs[:b] = query_vecs
        # padding lanes AND padding catalog columns are all-False
        mask_p = np.zeros((bucket, self.n_pad), bool)
        mask_p[:b, :self.n_items] = mask
        scores, ixs = jax.device_get(exe(vecs, self.factors, mask_p))
        _record_dispatch("sharded", bucket * self.n_items,
                         time.perf_counter() - t0)
        return scores[:b], ixs[:b]


class ShardSliceTopK:
    """The cross-host MEMBER-side plan: this process owns one
    contiguous ceil-divided row block of the catalog and serves
    shard-local candidates in GLOBAL id space; the fleet router merges
    candidates across members (shard-major, (-score, global id)
    tie-break — bit-identical to the single-device oracle by the same
    survival argument as the local sharded merge).

    The inner plan over the slice recurses through `serve_plan` with no
    mesh, so a slice that still exceeds the member's device budget
    tiers itself (`TieredTopK`) — the composition the giant-catalog
    path needs. Banned ids arrive untranslated (global); out-of-slice
    ids are dropped host-side before the inner plan sees them, so a
    boundary-straddling ban can neither leak nor alias a neighbor."""

    def __init__(self, item_factors, *, k: int,
                 buckets: Sequence[int] = DEFAULT_SERVE_BUCKETS,
                 banned_width: int = 256, slice_spec: ShardSlice = None):
        full = np.ascontiguousarray(item_factors, dtype=np.float32)  # lint: ok — host copy
        n_total, rank = full.shape
        n = int(slice_spec.n_shards)
        idx = int(slice_spec.index)
        per = -(-n_total // n)        # ceil: same block partition as
        self.base = min(per * idx, n_total)   # the local sharded plans
        self._hi = min(self.base + per, n_total)
        if self._hi <= self.base:
            raise ValueError(
                f"fleet shard {idx}/{n} is empty for {n_total} items — "
                "lower the shard count")
        self.slice_spec = slice_spec
        self.n_items = n_total        # global catalog size
        self.rank = rank
        self.slice_items = self._hi - self.base
        self.k = max(1, min(k, n_total))
        self.banned_width = banned_width
        self._inner = serve_plan(full[self.base:self._hi], k=k,
                                 buckets=buckets,
                                 banned_width=banned_width, mesh=None)

    # -- plan contract (delegates) ------------------------------------------
    @property
    def factors(self):
        return self._inner.factors

    @property
    def buckets(self):
        return self._inner.buckets

    @property
    def max_bucket(self) -> int:
        return self._inner.max_bucket

    def resident_per_device_bytes(self) -> float:
        # the inner plan registered itself; avoid double-counting
        return 0.0

    def warm(self) -> int:
        return self._inner.warm()

    def fits(self, *, max_banned: int, k: int) -> bool:
        # k above the slice's own candidate count still FITS: the
        # member legitimately contributes min(k, slice_items)
        # candidates and the router merge fills from other shards — a
        # fallback to the generic full-catalog path here would leak
        # out-of-slice items and duplicate candidates across members
        return (k <= self.k and max_banned <= self.banned_width
                and self._inner.fits(
                    max_banned=max_banned,
                    k=min(k, getattr(self._inner, "k", k))))

    def swap_factors(self, item_factors) -> np.ndarray:
        """Hot swap: accepts the FULL new catalog (streaming refresher)
        or a slice-shaped block (rollback token replay)."""
        host = np.ascontiguousarray(item_factors, dtype=np.float32)  # lint: ok — host copy
        if host.shape == (self.n_items, self.rank):
            return self._inner.swap_factors(host[self.base:self._hi])
        return self._inner.swap_factors(host)

    def __call__(self, user_vecs, banned_lists: Sequence[Sequence[int]]):
        """Shard-local top-k in global id space: returns (scores
        [b, k_local], GLOBAL ids [b, k_local]) for this member's rows
        only."""
        local = []
        for bl in banned_lists:
            if len(bl):
                arr = np.asarray(bl, np.int64)  # lint: ok — host ids
                arr = arr[(arr >= self.base) & (arr < self._hi)]
                local.append((arr - self.base).tolist())
            else:
                local.append(())
        scores, ixs = self._inner(user_vecs, local)
        return scores, ixs + np.int32(self.base)

"""Independent numpy normal-equation ALS oracle.

Used by the test suite and `bench.py` as the MLlib-equivalent reference
implementation for RMSE-parity gating (BASELINE.md "RMSE parity as the
quality gate"; SURVEY.md §7 'Hard parts' — parity against an
MLlib-equivalent reference). Deliberately the dumbest correct
implementation: per-row dense normal equations solved with
`np.linalg.solve`, float64, no bucketing, no padding — shares nothing
with `ops.als` except the starting factors.
"""

from __future__ import annotations

import numpy as np


def user_step(y: np.ndarray, u_ix: np.ndarray, i_ix: np.ndarray,
              val: np.ndarray, n_users: int, reg: float) -> np.ndarray:
    """One explicit half-step: solve every user row against fixed y
    (ALS-WR regularization, lambda scaled by the row's rating count)."""
    rank = y.shape[1]
    x = np.zeros((n_users, rank), np.float64)
    for u in range(n_users):
        sel = u_ix == u
        if not sel.any():
            continue
        yu = y[i_ix[sel]]
        a = yu.T @ yu + reg * sel.sum() * np.eye(rank)
        b = yu.T @ val[sel]
        x[u] = np.linalg.solve(a, b)
    return x


def user_step_implicit(y: np.ndarray, u_ix: np.ndarray, i_ix: np.ndarray,
                       val: np.ndarray, n_users: int, reg: float,
                       alpha: float) -> np.ndarray:
    """One implicit (Hu-Koren-Volinsky) half-step against fixed y."""
    rank = y.shape[1]
    yty = y.T @ y
    x = np.zeros((n_users, rank), np.float64)
    for u in range(n_users):
        sel = u_ix == u
        if not sel.any():
            continue
        yu = y[i_ix[sel]]
        c1 = alpha * val[sel]
        a = yty + (yu * c1[:, None]).T @ yu + reg * sel.sum() * np.eye(rank)
        b = yu.T @ (1.0 + c1)
        x[u] = np.linalg.solve(a, b)
    return x


def als_train(u_ix: np.ndarray, i_ix: np.ndarray, val: np.ndarray,
              n_users: int, n_items: int, *, rank: int, iterations: int,
              reg: float, x0: np.ndarray, y0: np.ndarray):
    """Full alternating loop from the given starting factors (pass the
    same init as `ops.als.init_factors` for parity comparisons)."""
    x = np.asarray(x0, np.float64).copy()
    y = np.asarray(y0, np.float64).copy()
    for _ in range(iterations):
        x = user_step(y, u_ix, i_ix, val, n_users, reg)
        y = user_step(x, i_ix, u_ix, val, n_items, reg)
    return x, y


def als_train_implicit(u_ix: np.ndarray, i_ix: np.ndarray, val: np.ndarray,
                       n_users: int, n_items: int, *, rank: int,
                       iterations: int, reg: float, alpha: float,
                       x0: np.ndarray, y0: np.ndarray):
    """Full implicit (HKV) alternating loop from the given starting
    factors — the MLlib `trainImplicit` reference for parity checks
    (positive-preference data; `user_step_implicit` semantics)."""
    x = np.asarray(x0, np.float64).copy()
    y = np.asarray(y0, np.float64).copy()
    for _ in range(iterations):
        x = user_step_implicit(y, u_ix, i_ix, val, n_users, reg, alpha)
        y = user_step_implicit(x, i_ix, u_ix, val, n_items, reg, alpha)
    return x, y


def rmse(x: np.ndarray, y: np.ndarray, u_ix: np.ndarray, i_ix: np.ndarray,
         val: np.ndarray) -> float:
    pred = np.einsum("nr,nr->n", x[u_ix], y[i_ix])
    return float(np.sqrt(np.mean((pred - val) ** 2)))

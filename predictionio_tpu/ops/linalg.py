"""Batched dense linear algebra built from MXU-batched matmuls.

Why this exists: `jax.scipy.linalg.cho_factor/cho_solve` lower to XLA's
generic blocked Cholesky, which on TPU executes at ~0.02 TFLOP/s for
large batches of small SPD systems (measured: 32 ms for 4096 64x64
solves on a v5e) — it became the dominant cost of the ALS half-step
(`ops/als.py`), ahead of even the factor gather. The reference never hits
this: MLlib solves its normal equations one at a time on CPU BLAS
(`org.apache.spark.ml.recommendation.ALS` NormalEquation/CholeskySolver).

The TPU-first replacement keeps everything a *batched matmul*:

  1. Blocked right-looking Cholesky (block = 16): trailing updates are
     [B, r, 16] @ [B, 16, r] batched matmuls (MXU); only the 16-wide
     diagonal factorization is sequential (unrolled, 16 tiny batched
     steps).
  2. Diagonal-block triangular inversion by unrolled substitution
     (16 small batched steps), giving explicit 16x16 L^-1 blocks.
  3. cho_solve becomes blockwise substitution whose inner ops are
     batched matmuls/einsums against those explicit inverse blocks.

Everything is unrolled over a STATIC number of blocks, so the whole solve
fuses into the surrounding jit program. Exact direct solve — the ALS
oracle-parity gates (numpy `np.linalg.solve` comparison at rtol 2e-3)
hold unchanged.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

_BLOCK = 16

# All solver matmuls pin Precision.HIGHEST: TPU default matmul precision
# is bf16 (eps 2^-8), which destroys a direct solver; these ops are
# R^3-scale (tiny next to the P*R^2 Gram work), so full f32 passes cost
# nothing measurable.
_HI = jax.lax.Precision.HIGHEST


def _mm(a, b):
    return jnp.matmul(a, b, precision=_HI)


def _small_chol(d: jnp.ndarray) -> jnp.ndarray:
    """Unrolled Cholesky-Banachiewicz for a batch of small SPD blocks.
    d: [B, m, m] -> lower-triangular [B, m, m]. m is tiny (<= _BLOCK);
    the m sequential steps are batched [B, m]-sized vector ops."""
    m = d.shape[-1]
    L = jnp.zeros_like(d)
    for j in range(m):
        v = d[:, :, j]
        if j:
            # v -= L[:, :, :j] @ L[j, :j]
            v = v - jnp.einsum("bik,bk->bi", L[:, :, :j], L[:, j, :j],
                               precision=_HI)
        diag = jnp.sqrt(jnp.maximum(v[:, j], 1e-30))
        col = v / diag[:, None]
        keep = (np.arange(m) >= j)
        L = L.at[:, :, j].set(jnp.where(keep[None, :], col, 0.0))
    return L


def _tri_lower_inv(L: jnp.ndarray) -> jnp.ndarray:
    """Inverse of batched lower-triangular [B, m, m] by unrolled forward
    substitution, row at a time (standard TRTRI recurrence — numerically
    stable, unlike the nilpotent-product identity which amplifies
    rounding through repeated squaring). m is tiny (<= _BLOCK), so the m
    sequential steps are small batched einsums."""
    m = L.shape[-1]
    eye = np.eye(m, dtype=np.float32)
    X = jnp.zeros_like(L)
    for i in range(m):
        row = jnp.broadcast_to(jnp.asarray(eye[i])[None, :],
                               L.shape[:1] + (m,))
        if i:
            row = row - jnp.einsum("bk,bkj->bj", L[:, i, :i], X[:, :i, :],
                                   precision=_HI)
        X = X.at[:, i, :].set(row / L[:, i, i][:, None])
    return X


@partial(jax.jit, static_argnames=("block",))
def spd_solve(a: jnp.ndarray, b: jnp.ndarray, *,
              block: int = _BLOCK) -> jnp.ndarray:
    """Solve a batch of SPD systems a @ x = b.

    a: [B, R, R] SPD (well-regularized, e.g. ALS-WR normal equations),
    b: [B, R]. R is padded up to a multiple of `block` with identity
    (solution rows of the padding are zero and sliced off). Like LAPACK
    POTRF, only the LOWER triangle of `a` is read.
    """
    B, R = b.shape
    nb = -(-R // block)
    Rp = nb * block
    if Rp != R:
        pad = Rp - R
        eye_pad = jnp.eye(Rp, dtype=a.dtype)[R:]
        a = jnp.concatenate([
            jnp.concatenate([a, jnp.zeros((B, R, pad), a.dtype)], axis=2),
            jnp.broadcast_to(eye_pad[None], (B, pad, Rp))], axis=1)
        b = jnp.concatenate([b, jnp.zeros((B, pad), b.dtype)], axis=1)

    def blk(x, i, j):
        return x[:, i * block:(i + 1) * block, j * block:(j + 1) * block]

    # 1) blocked Cholesky: L (block grid), with inverted diagonal blocks
    L = [[None] * nb for _ in range(nb)]
    Linv = [None] * nb
    for j in range(nb):
        d = blk(a, j, j)
        for k in range(j):
            d = d - _mm(L[j][k], L[j][k].transpose(0, 2, 1))
        ljj = _small_chol(d)
        L[j][j] = ljj
        Linv[j] = _tri_lower_inv(ljj)
        for i in range(j + 1, nb):
            s = blk(a, i, j)
            for k in range(j):
                s = s - _mm(L[i][k], L[j][k].transpose(0, 2, 1))
            L[i][j] = _mm(s, Linv[j].transpose(0, 2, 1))

    # 2) forward substitution L z = b, blockwise
    z = [None] * nb
    for j in range(nb):
        t = b[:, j * block:(j + 1) * block]
        for k in range(j):
            t = t - jnp.einsum("bij,bj->bi", L[j][k], z[k],
                               precision=_HI)
        z[j] = jnp.einsum("bij,bj->bi", Linv[j], t, precision=_HI)

    # 3) back substitution L^T x = z, blockwise
    x = [None] * nb
    for j in reversed(range(nb)):
        t = z[j]
        for k in range(j + 1, nb):
            t = t - jnp.einsum("bji,bj->bi", L[k][j], x[k],
                               precision=_HI)
        x[j] = jnp.einsum("bji,bj->bi", Linv[j], t, precision=_HI)
    out = jnp.concatenate(x, axis=1)
    return out[:, :R]


@partial(jax.jit,
         static_argnames=("iters", "rtol", "return_info",
                          "matvec_precision"))
def pcg_solve(a: jnp.ndarray, b: jnp.ndarray, *,
              iters: int = 32,
              x0: jnp.ndarray = None,
              rtol: float = 0.0,
              return_info: bool = False,
              matvec_precision=None):
    """Jacobi-preconditioned conjugate gradient for batches of SPD
    systems — the FAST path for the ALS normal equations.

    Why not always `spd_solve`: an exact blocked Cholesky is ~R
    inherently sequential small steps (~450 XLA ops for R=64), and on
    TPU the per-op cost of those tiny steps dominates (measured ~11 us
    per 64x64 system on a v5e — no better than jax.scipy). CG is ~5
    batched einsums per iteration regardless of R, so the whole solve is
    MXU/VPU-shaped. ALS-WR regularization (lambda * n_row added to the
    diagonal) keeps the systems well-conditioned, and Jacobi scaling
    normalizes the per-row rating-count spread. Matvecs pin f32
    precision — TPU-default bf16 matvecs would stall CG's residual
    recurrence at ~1e-3.

    a: [B, R, R] SPD (full matrix read), b: [B, R]. Rows with a == I,
    b == 0 (padding) converge to 0 in one step.

    `x0` warm-starts the iteration (the ALS loop passes the previous
    sweep's factors, which cuts the iterations needed for a given
    residual by ~3-4x). `rtol` > 0 adds an early exit once EVERY row's
    true-recurrence residual norm is below rtol * ||b||; `iters` is
    always the hard cap, so ill-conditioned batches (low reg — see the
    conditioning note in ops/als.py) degrade gracefully instead of
    silently stopping at a fixed iteration count. With
    `return_info=True` returns (x, rel_residual[B], iters_used), where
    rel_residual is computed from one extra true matvec (not the
    recurrence, which drifts) — callers use it to detect and flag
    non-converged solves.
    """
    diag = jnp.diagonal(a, axis1=-2, axis2=-1)
    inv_d = 1.0 / jnp.maximum(diag, 1e-30)
    # matvec precision defaults to HIGHEST (exact callers). The ALS
    # bf16 path overrides to DEFAULT: its A is built from bf16 operands
    # (~1e-3 relative), so multi-pass f32 matvecs buy nothing there and
    # measured ~3x the per-iteration cost; the final true-residual
    # check below ALWAYS runs at HIGHEST so a stalled recurrence is
    # reported honestly.
    mv_prec = _HI if matvec_precision is None else matvec_precision

    def matvec(v):
        return jnp.einsum("brs,bs->br", a, v, precision=mv_prec)

    if x0 is None:
        x = jnp.zeros_like(b)
        r = b
    else:
        x = x0
        r = b - matvec(x0)
    z = inv_d * r
    p = z
    rz = jnp.einsum("br,br->b", r, z, precision=_HI)
    bnorm2 = jnp.einsum("br,br->b", b, b, precision=_HI)

    def step(state):
        k, x, r, p, rz = state
        ap = matvec(p)
        denom = jnp.einsum("br,br->b", p, ap, precision=_HI)
        alpha = rz / jnp.where(denom > 0, denom, 1.0)
        x = x + alpha[:, None] * p
        r = r - alpha[:, None] * ap
        z = inv_d * r
        rz_new = jnp.einsum("br,br->b", r, z, precision=_HI)
        beta = rz_new / jnp.where(rz > 0, rz, 1.0)
        p = z + beta[:, None] * p
        return (k + 1, x, r, p, rz_new)

    if rtol > 0.0:
        # early-exit variant: a while_loop is a fusion barrier on TPU
        # (measured ~30% slower than the unrolled fori at equal trip
        # count in the ALS hot loop), so it is opt-in via rtol
        def cond(state):
            k, x, r, p, rz = state
            rnorm2 = jnp.einsum("br,br->b", r, r, precision=_HI)
            return jnp.logical_and(
                k < iters, jnp.any(rnorm2 > (rtol * rtol) * bnorm2))

        k, x, _, _, _ = jax.lax.while_loop(
            cond, step, (jnp.int32(0), x, r, p, rz))
    else:
        k, x, _, _, _ = jax.lax.fori_loop(
            0, iters, lambda _, s: step(s), (jnp.int32(0), x, r, p, rz))
    if not return_info:
        return x
    true_r = b - jnp.einsum("brs,bs->br", a, x, precision=_HI)
    rel = jnp.sqrt(jnp.einsum("br,br->b", true_r, true_r, precision=_HI)
                   / jnp.maximum(bnorm2, 1e-30))
    return x, rel, k

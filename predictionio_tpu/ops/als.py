"""Alternating least squares, TPU-first.

Replaces Spark MLlib's `ALS` / `ALS.trainImplicit` used by the reference's
recommendation templates (`examples/scala-parallel-recommendation/
blacklist-items/src/main/scala/ALSAlgorithm.scala:51-93`,
`examples/scala-parallel-similarproduct/.../ALSAlgorithm.scala:120`).

MLlib's ALS is a shuffle-heavy blocked solver over dynamically partitioned
rating blocks. The TPU formulation instead makes every step a dense, static
XLA program:

  1. Ratings arrive as COO triples (`ingest.RatingColumns`). Each side
     (user rows / item rows) is packed ONCE into degree-bucketed padded CSR
     slabs: rows with similar degree share a `[rows_b, cap_b]` slab padded
     to the bucket cap. Buckets mean the heavy tail of prolific users costs
     one big slab instead of padding every user to the global max degree.
  2. One half-iteration gathers the opposite side's factors `Y[idx]`
     (`[rows_b, cap_b, rank]`), forms per-row normal equations with one
     einsum (MXU-batched), adds ALS-WR regularization `lambda * n_row * I`
     (MLlib's default scaling), and solves all rows with one batched
     Cholesky (`jax.scipy.linalg.cho_solve`).
  3. Implicit feedback uses the Hu-Koren-Volinsky trick: A_row =
     Y^T Y + sum_k alpha*r_k * y_k y_k^T (+ reg), b_row = sum_k
     (1 + alpha*r_k) y_k, so cost scales with observed entries only.
  4. Factors live on device across iterations; each bucket slab is sharded
     over the mesh's "data" axis while the opposite factor matrix is
     replicated — the all-gather the reference does via Spark shuffle is
     XLA's job here.

The returned model is `ALSModel` (factor matrices + BiMaps), the analog of
the template's fork of `MatrixFactorizationModel` (`ALSModel.scala`).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np

from predictionio_tpu.ingest import BiMap, RatingColumns

# degree-bucket caps grow geometrically; a row of degree d lands in the
# smallest bucket with cap >= d
_BUCKET_BASE = 16
_BUCKET_GROWTH = 4


@dataclass
class _SideBuckets:
    """Padded CSR slabs for one side (one entry per bucket)."""
    rows: List[np.ndarray]     # [rows_b] row indexes into this side
    idx: List[np.ndarray]      # [rows_b, cap_b] opposite-side indexes
    val: List[np.ndarray]      # [rows_b, cap_b] ratings (0 = padding)
    msk: List[np.ndarray]      # [rows_b, cap_b] 1.0 valid / 0.0 padding
    n_rows: int


def _pack_side(row_ix: np.ndarray, col_ix: np.ndarray, val: np.ndarray,
               n_rows: int) -> _SideBuckets:
    """Group COO entries by row, then bucket rows by degree into padded
    slabs. Host-side preprocessing, done once per training run — fully
    vectorized (no per-row Python) so ML-25M-scale packing stays cheap."""
    order = np.argsort(row_ix, kind="stable")
    r, c, v = row_ix[order], col_ix[order], val[order]
    uniq, starts, counts = np.unique(r, return_index=True, return_counts=True)
    # bucket cap per unique row: smallest BASE * GROWTH^k >= count
    caps_per_row = np.full(len(uniq), _BUCKET_BASE, np.int64)
    grow = counts > caps_per_row
    while grow.any():
        caps_per_row[grow] *= _BUCKET_GROWTH
        grow = counts > caps_per_row
    out = _SideBuckets([], [], [], [], n_rows)
    for cap in np.unique(caps_per_row):
        sel = caps_per_row == cap
        rows = uniq[sel].astype(np.int32)
        m_starts, m_counts = starts[sel], counts[sel]
        nb = len(rows)
        # ragged -> padded scatter: flat source index for every entry and
        # its (member, intra-row offset) destination, all vectorized
        total = int(m_counts.sum())
        member_of = np.repeat(np.arange(nb), m_counts)
        intra = np.arange(total) - np.repeat(
            np.cumsum(m_counts) - m_counts, m_counts)
        src = np.repeat(m_starts, m_counts) + intra
        idx = np.zeros((nb, cap), np.int32)
        vals = np.zeros((nb, cap), np.float32)
        msk = np.zeros((nb, cap), np.float32)
        idx[member_of, intra] = c[src]
        vals[member_of, intra] = v[src]
        msk[member_of, intra] = 1.0
        out.rows.append(rows)
        out.idx.append(idx)
        out.val.append(vals)
        out.msk.append(msk)
    return out


@partial(jax.jit, static_argnames=("implicit",))
def _solve_bucket(factors, idx, val, msk, reg, alpha, yty, *, implicit: bool):
    """Solve normal equations for one bucket slab.

    factors: [n_opposite, rank] opposite-side factors (replicated)
    idx/val/msk: [rows_b, cap_b]
    yty: [rank, rank] Gram matrix of opposite factors (implicit only)
    Returns [rows_b, rank] solutions.
    """
    import jax.numpy as jnp
    from jax.scipy.linalg import cho_factor, cho_solve

    rank = factors.shape[1]
    yg = factors[idx]                                   # [B, K, R] gather
    if implicit:
        # MLlib trainImplicit semantics: confidence c = 1 + alpha*|r|,
        # preference p = 1 iff r > 0 (negative r = confident dislike)
        conf = alpha * jnp.abs(val) * msk               # c - 1
        pref = (val > 0).astype(factors.dtype)
        a = jnp.einsum("bkr,bks,bk->brs", yg, yg, conf) + yty
        b = jnp.einsum("bkr,bk->br", yg, pref * (1.0 + conf) * msk)
    else:
        a = jnp.einsum("bkr,bks,bk->brs", yg, yg, msk)
        b = jnp.einsum("bkr,bk->br", yg, val * msk)
    n_row = msk.sum(axis=1)                             # ALS-WR scaling
    eye = jnp.eye(rank, dtype=factors.dtype)
    a = a + (reg * n_row)[:, None, None] * eye
    # pad rows (n_row == 0) get an identity system -> solution 0
    a = jnp.where((n_row > 0)[:, None, None], a, eye)
    cf = cho_factor(a, lower=True)
    x = cho_solve(cf, b)
    return jnp.where((n_row > 0)[:, None], x, 0.0)


@partial(jax.jit, static_argnames=("implicit", "rank"))
def _run_als(x, y, user_slabs, item_slabs, reg, alpha, n_iter, *,
             implicit: bool, rank: int):
    """The full ALS training loop as one compiled program (module-level
    jit: the cache persists across als_train calls with the same slab
    shapes). Slabs are pytrees of (rows, idx, val, msk) tuples."""
    import jax.numpy as jnp

    def half_step(own, opposite, slabs):
        yty = (opposite.T @ opposite if implicit
               else jnp.zeros((rank, rank), jnp.float32))
        for rows_dev, idx, vals, msk in slabs:
            sol = _solve_bucket(opposite, idx, vals, msk, reg, alpha,
                                yty, implicit=implicit)
            # slab-padding rows carry an out-of-bounds row index; 'drop'
            # discards their updates instead of clamping onto row n-1
            own = own.at[rows_dev].set(sol, mode="drop")
        return own

    def body(_, xy):
        x, y = xy
        x = half_step(x, y, user_slabs)
        y = half_step(y, x, item_slabs)
        return (x, y)

    return jax.lax.fori_loop(0, n_iter, body, (x, y))


@jax.jit
def _predict_elements(x, y, u_ix, i_ix):
    import jax.numpy as jnp
    return jnp.einsum("nr,nr->n", x[u_ix], y[i_ix])


def als_train(ratings: "RatingColumns | Tuple[np.ndarray, np.ndarray, np.ndarray]",
              n_users: Optional[int] = None,
              n_items: Optional[int] = None, *,
              rank: int = 10,
              iterations: int = 10,
              reg: float = 0.01,
              implicit: bool = False,
              alpha: float = 1.0,
              seed: int = 0,
              mesh=None) -> Tuple[np.ndarray, np.ndarray]:
    """Train factor matrices (X [n_users, rank], Y [n_items, rank]).

    Matches MLlib semantics: ALS-WR regularization (lambda scaled by the
    row's rating count), random normalized init, `iterations` full
    alternations. `mesh` shards each slab's row dimension over the "data"
    axis; None runs single-device.
    """
    import jax.numpy as jnp

    if isinstance(ratings, RatingColumns):
        u_ix, i_ix, val = ratings.user_ix, ratings.item_ix, ratings.rating
        n_users = n_users or len(ratings.users)
        n_items = n_items or len(ratings.items)
    else:
        u_ix, i_ix, val = ratings
        assert n_users is not None and n_items is not None
    user_side = _pack_side(u_ix, i_ix, val, n_users)
    item_side = _pack_side(i_ix, u_ix, val, n_items)

    key = jax.random.PRNGKey(seed)
    ku, ki = jax.random.split(key)
    # MLlib init: abs(normal) / sqrt(rank) keeps initial predictions O(1).
    # Rows with no ratings are zeroed from the start: they are never
    # solved, and a nonzero phantom row would bias the implicit-mode Gram
    # matrix Y^T Y (MLlib has no factor row at all for such ids).
    x = jnp.abs(jax.random.normal(ku, (max(n_users, 1), rank),
                                  jnp.float32)) / math.sqrt(rank)
    y = jnp.abs(jax.random.normal(ki, (max(n_items, 1), rank),
                                  jnp.float32)) / math.sqrt(rank)

    def present_mask(side, n_rows):
        present = np.zeros(max(n_rows, 1), bool)
        for rows in side.rows:
            present[rows] = True
        return present

    user_present = present_mask(user_side, n_users)
    item_present = present_mask(item_side, n_items)
    x = jnp.where(jnp.asarray(user_present)[:, None], x, 0.0)
    y = jnp.where(jnp.asarray(item_present)[:, None], y, 0.0)

    dev_sides = []
    for side, n_side in ((user_side, n_users), (item_side, n_items)):
        slabs = []
        for rows, idx, vals, msk in zip(side.rows, side.idx, side.val,
                                        side.msk):
            if mesh is not None:
                from predictionio_tpu.parallel import shard_put
                idx, _ = shard_put(idx, mesh)
                vals, _ = shard_put(vals, mesh)
                msk, _ = shard_put(msk, mesh)
                # slab-padding rows scatter out of bounds -> dropped
                rows_dev, _ = shard_put(rows, mesh, fill=n_side)
            else:
                rows_dev = jnp.asarray(rows)
            slabs.append((rows_dev, jnp.asarray(idx), jnp.asarray(vals),
                          jnp.asarray(msk)))
        dev_sides.append(slabs)

    x, y = _run_als(x, y, dev_sides[0], dev_sides[1], jnp.float32(reg),
                    jnp.float32(alpha), jnp.int32(iterations),
                    implicit=implicit, rank=rank)
    return np.asarray(x), np.asarray(y)


def rmse(x: np.ndarray, y: np.ndarray, u_ix: np.ndarray, i_ix: np.ndarray,
         val: np.ndarray) -> float:
    """Root mean squared error over the given elements (the parity gate
    metric from BASELINE.md)."""
    import jax.numpy as jnp
    pred = _predict_elements(jnp.asarray(x), jnp.asarray(y),
                             jnp.asarray(u_ix), jnp.asarray(i_ix))
    return float(np.sqrt(np.mean((np.asarray(pred) - val) ** 2)))


@dataclass
class ALSModel:
    """Factor matrices + BiMaps — the serving-side model
    (`examples/.../ALSModel.scala` fork of MatrixFactorizationModel)."""
    user_factors: np.ndarray    # [n_users, rank]
    item_factors: np.ndarray    # [n_items, rank]
    users: BiMap
    items: BiMap
    # items each user has interacted with at train time (for seen-filtering)
    seen: Optional[dict] = None

    def sanity_check(self):
        assert self.user_factors.ndim == 2 and self.item_factors.ndim == 2
        assert np.isfinite(self.user_factors).all(), "non-finite user factors"
        assert np.isfinite(self.item_factors).all(), "non-finite item factors"

"""Alternating least squares, TPU-first.

Replaces Spark MLlib's `ALS` / `ALS.trainImplicit` used by the reference's
recommendation templates (`examples/scala-parallel-recommendation/
blacklist-items/src/main/scala/ALSAlgorithm.scala:51-93`,
`examples/scala-parallel-similarproduct/.../ALSAlgorithm.scala:120`).

MLlib's ALS is a shuffle-heavy blocked solver over dynamically partitioned
rating blocks. The TPU formulation instead makes every step a dense, static
XLA program:

  1. Ratings arrive as COO triples (`ingest.RatingColumns`). Each side
     (user rows / item rows) is packed ONCE into degree-bucketed padded CSR
     slabs: rows with similar degree share a `[rows_b, cap_b]` slab padded
     to the bucket cap. Buckets mean the heavy tail of prolific users costs
     one big slab instead of padding every user to the global max degree.
  2. One half-iteration gathers the opposite side's factors `Y[idx]`
     (`[rows_b, cap_b, rank]`), forms per-row normal equations, adds
     ALS-WR regularization `lambda * n_row * I` (MLlib's default
     scaling), and solves all rows. The hot path (rank > 16) is
     `_solve_slab_paired`: bf16 gathered operands, consecutive-row
     PAIRING so the Gram einsum produces full 128x128 MXU tiles, f32
     accumulation, and warm-started Jacobi-CG with residual tracking.
     Rank <= 16 uses the exact blocked Cholesky (`ops.linalg.spd_solve`).
     Why, from the v5e roofline (all measured, r4): the factor gather is
     ROW-RATE-bound (~390M rows/s f32 / ~450M bf16, independent of row
     width <= 128 lanes) and is the hard floor of the whole step;
     RxR-batched einsums reach <2 TFLOP/s (each batch element fills only
     a 64x64 corner of the MXU) while the paired form is ~3x faster;
     XLA's batched Cholesky runs at ~0.02 TFLOP/s; and a fixed-32-iter
     CG re-reads every normal matrix from HBM per iteration, while warm
     starting cuts the iterations ~4x at equal final RMSE.
  3. Implicit feedback uses the Hu-Koren-Volinsky trick: A_row =
     Y^T Y + sum_k alpha*r_k * y_k y_k^T (+ reg), b_row = sum_k
     (1 + alpha*r_k) y_k, so cost scales with observed entries only.
  4. Factors live on device across iterations. Under a mesh, BOTH factor
     matrices are block-sharded over the "data" axis (device d owns the
     contiguous row block [d*B, (d+1)*B)) and every slab is partitioned by
     the device that owns the rows it solves, so each half-step is: one
     all-gather of the opposite side's factor shard (transient), a local
     gather+einsum+Cholesky, and a purely LOCAL factor-row write — no
     cross-device scatter. The implicit-mode Gram matrix is a [rank,rank]
     psum of local grams. This is the shard_map analog of MLlib's
     shuffle-based factor exchange.

Memory model (per device, D devices, f32):
  persistent:  |X|/D + |Y|/D factor shards, + slab columns /D
               (idx 4B + val 4B per padded entry, both sides; the mask
               derives from the -1 idx sentinel, never materialized)
  transient :  the all-gathered opposite factor matrix (|Y| or |X|) +
               the gathered slab factors [rows_b, cap_b, rank] per bucket
               (~ratings_on_device * rank * 4B for the largest bucket).
ML-25M at rank 64 on a v5e-16 slice (16 GiB HBM/chip), counting bucket
padding (padded entries <= BASE*n_rows + GROWTH*n_ratings per side):
X = 162541*64*4 = 41.6 MB, Y = 59047*64*4 = 15.1 MB, padded slabs
~= 2*103e6*12 B / 16 * skew2 ~= 305 MB/device, transient slab gather
<= 103e6/16 * 64 * 4 * skew2 ~= 3.3 GB — peak ~3.7 GB, inside budget;
see `hbm_footprint` for the formula and its test.

The returned model is `ALSModel` (factor matrices + BiMaps), the analog of
the template's fork of `MatrixFactorizationModel` (`ALSModel.scala`).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial
from typing import List, Optional, Tuple

import jax
import numpy as np

from predictionio_tpu.ingest import BiMap, RatingColumns
from predictionio_tpu.ops import compat

# degree-bucket caps grow geometrically; a row of degree d lands in the
# smallest bucket with cap >= d. The x1.5 ladder (rounded up to a
# multiple of 8 for TPU sublane alignment) bounds padding at 1.5x the
# real entry count — the r3 x4 ladder padded ML-25M to ~2x, and the
# gather that reads every padded slot is the measured bottleneck of the
# whole training step (row-rate-bound at ~390-450M rows/s on a v5e; see
# module docstring), so padding is gather wall-clock 1:1.
_BUCKET_BASE = 16
# cap-ladder growth. 1.25 holds ML-25M's padded/real entry ratio to
# ~1.12 (1.5 measured 1.27 — r4 bench roofline), cutting EVERY phase of
# the row-rate-bound step ~11%; the cost is more distinct slab shapes
# (26 vs 15 item-side at ML-25M) in the one compiled program, which the
# persistent XLA compile cache amortizes across runs.
_BUCKET_GROWTH = 1.25

# sentinel row index for slab padding rows (scatter mode="drop" discards
# them; _pack_by_owner maps them to an in-range dropped local slot)
_FILL_ROW = np.int32(2**31 - 1)

# ranks <= this solve via the exact blocked Cholesky (ops.linalg.
# spd_solve): at one 16-wide block it is a short, fully batched program
# and beats CG (this is also what keeps the ML-100k rank-10 path on the
# exact solver — the r3 regression was CG burning 4x the FLOPs there).
_SMALL_RANK = 16

# warm-started CG iteration cap for the rank > _SMALL_RANK path. With
# the previous sweep's factors as x0, 8 iterations reach ~2e-4 max
# relative residual on the ML-25M workload (measured); the residual is
# tracked and surfaced so a badly conditioned problem (tiny reg) is
# flagged instead of silently wrong.
_CG_ITERS = 8


def _cap_ladder(max_count: int) -> np.ndarray:
    """Bucket caps: BASE, then x_BUCKET_GROWTH steps rounded up to a
    multiple of 8, up to max_count."""
    caps = [_BUCKET_BASE]
    while caps[-1] < max_count:
        caps.append(int(math.ceil(caps[-1] * _BUCKET_GROWTH / 8) * 8))
    return np.asarray(caps, np.int64)

# Per-slab transient memory budgets (bytes, f32). A bucket slab of B rows
# x cap K at rank R materializes a [B, K, R] factor gather and [B, R, R]
# normal matrices during its solve; unboundedly large buckets (ML-25M has
# ~150k users in one degree bucket) would blow HBM. Slabs are therefore
# split so that  B*K*R*4 <= _SLAB_GATHER_BUDGET  and
# B*R*R*4 <= _SLAB_NORMAL_BUDGET. At rank 10 the caps are ~53M entries /
# ~1.3M rows (no effect on small problems); at rank 64 they bound the
# gather to 2 GiB and the normal-equation batch to 512 MiB.
_SLAB_GATHER_BUDGET = 2 << 30
_SLAB_NORMAL_BUDGET = 512 << 20


@dataclass
class _SideBuckets:
    """Degree-bucketed CSR for one side (one entry per bucket chunk).

    Entries are stored RAGGED (per-row counts + concatenated idx/val):
    the host->device link is the scarce resource on this runtime
    (~25 MB/s tunnel, measured r4), so only real entries ever cross it —
    padded slab forms are materialized ON DEVICE by `_pad_slab_device`
    (hot path) or on host by `padded()` (mesh re-partitioner, direct
    solver tests). Slot padding carries idx == -1; the mask is derived
    from it device-side, never stored or transferred."""
    rows: List[np.ndarray]     # [rows_b] row indexes into this side
    counts: List[np.ndarray]   # [rows_b] real entries per row
    idx: List[np.ndarray]      # [entries_b] ragged opposite-side indexes
    val: List[np.ndarray]      # [entries_b] ragged ratings
    caps: List[int]            # bucket cap (padded row width) per chunk
    n_rows: int

    def padded(self, j: int) -> Tuple[np.ndarray, np.ndarray]:
        """Host materialization of chunk j as ([rows_b, cap] idx with -1
        padding, [rows_b, cap] val)."""
        counts, cap = self.counts[j], self.caps[j]
        nb = len(counts)
        member, intra = _group_offsets(counts)
        idx = np.full((nb, cap), -1, np.int32)
        val = np.zeros((nb, cap), np.float32)
        idx[member, intra] = self.idx[j]
        val[member, intra] = self.val[j]
        return idx, val


def _group_offsets(counts: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Destination coordinates for a ragged->padded scatter of items laid
    out in stable group order: `member[j]` is item j's group index,
    `intra[j]` its offset within the group."""
    total = int(counts.sum())
    member = np.repeat(np.arange(len(counts)), counts)
    intra = np.arange(total) - np.repeat(np.cumsum(counts) - counts, counts)
    return member, intra


def _pack_side(row_ix: np.ndarray, col_ix: np.ndarray, val: np.ndarray,
               n_rows: int, rank: Optional[int] = None) -> _SideBuckets:
    """Group COO entries by row, then bucket rows by degree into padded
    slabs. Host-side preprocessing, done once per training run — fully
    vectorized (no per-row Python) so ML-25M-scale packing stays cheap.

    When `rank` is given, oversized buckets are split into row chunks so
    each slab's solve-time transients ([B, cap, rank] gather and
    [B, rank, rank] normal matrices) stay inside the module budgets."""
    order = np.argsort(row_ix, kind="stable")
    r, c, v = row_ix[order], col_ix[order], val[order]
    uniq, starts, counts = np.unique(r, return_index=True, return_counts=True)
    # bucket cap per unique row: smallest ladder cap >= count
    ladder = _cap_ladder(int(counts.max()) if len(counts) else _BUCKET_BASE)
    caps_per_row = ladder[np.searchsorted(ladder, counts)]
    out = _SideBuckets([], [], [], [], [], n_rows)
    for cap in np.unique(caps_per_row):
        sel = caps_per_row == cap
        rows = uniq[sel].astype(np.int32)
        m_starts, m_counts = starts[sel], counts[sel]
        nb = len(rows)
        # ragged entries in row order: flat source index for every entry
        member_of, intra = _group_offsets(m_counts)
        src = np.repeat(m_starts, m_counts) + intra
        ends = np.cumsum(m_counts)
        if rank is None:
            chunk = nb
        else:
            chunk = max(2, min(_SLAB_NORMAL_BUDGET // (rank * rank * 4),
                               _SLAB_GATHER_BUDGET // (int(cap) * rank * 4)))
            chunk -= chunk % 2   # paired solver consumes rows two at a time
        for s in range(0, nb, max(chunk, 1)):
            e = min(s + chunk, nb)
            rws, cnts = rows[s:e], m_counts[s:e].astype(np.int32)
            lo = ends[s - 1] if s else 0
            src_se = src[lo:ends[e - 1]]
            if len(rws) % 2:
                # pad to even rows for the paired solver; the fill row
                # (count 0) is dropped at scatter time (see _FILL_ROW)
                rws = np.concatenate([rws, np.asarray([_FILL_ROW], np.int32)])
                cnts = np.concatenate([cnts, np.zeros(1, np.int32)])
            out.rows.append(rws)
            out.counts.append(cnts)
            out.idx.append(c[src_se].astype(np.int32))
            out.val.append(v[src_se].astype(np.float32))
            out.caps.append(int(cap))
    return out


@partial(jax.jit, static_argnames=("meta",))
def _pad_side_device(rows_c, counts_c, idx_c, val_c, *, meta):
    """Device-side ragged -> padded materialization of a whole side in
    ONE compiled program (a per-chunk program would compile ~40 tiny
    kernels, each paying the runtime's compile round trip — measured
    +440 s cold on the ML-25M pack). Inputs are the side's chunks
    CONCATENATED; `meta` is the static ((rows_j, entries_j, cap_j), ...)
    chunk table. Returns a tuple of (rows, idx, val) per chunk, idx
    carrying -1 slot padding (the mask derives from it downstream)."""
    import jax.numpy as jnp

    out = []
    ro = eo = 0
    for nb, ne, cap in meta:
        rows = jax.lax.slice(rows_c, (ro,), (ro + nb,))
        counts = jax.lax.slice(counts_c, (ro,), (ro + nb,))
        ridx = jax.lax.slice(idx_c, (eo,), (eo + ne,))
        rval = jax.lax.slice(val_c, (eo,), (eo + ne,))
        member = jnp.repeat(jnp.arange(nb, dtype=jnp.int32), counts,
                            total_repeat_length=ne)
        starts = jnp.cumsum(counts) - counts
        intra = jnp.arange(ne, dtype=jnp.int32) - jnp.repeat(
            starts.astype(jnp.int32), counts, total_repeat_length=ne)
        idx = jnp.full((nb, cap), -1, jnp.int32)
        idx = idx.at[member, intra].set(ridx.astype(jnp.int32))
        val = jnp.zeros((nb, cap), rval.dtype)
        val = val.at[member, intra].set(rval)
        out.append((rows, idx, val))
        ro += nb
        eo += ne
    return tuple(out)


def device_slabs(side: _SideBuckets, n_opposite: int,
                 val_dtype=np.float32) -> List[tuple]:
    """Upload one side's slabs as (rows, padded idx, padded val) device
    tuples. Transfer-lean: ragged entries only (no padding, no mask
    plane), indexes narrowed to uint16 when the opposite side fits, and
    `val_dtype` (bfloat16 on the paired hot path) halving value bytes —
    the measured v5e tunnel moves ~25 MB/s, so these bytes are
    wall-clock 1:1 at ML-25M scale. Four uploads + one compiled pad
    program per side signature."""
    import jax.numpy as jnp

    idx_t = np.uint16 if n_opposite <= np.iinfo(np.uint16).max else np.int32
    meta = tuple((len(side.counts[j]), len(side.idx[j]), side.caps[j])
                 for j in range(len(side.rows)))
    if not meta:
        return []
    padded = _pad_side_device(
        jnp.asarray(np.concatenate(side.rows)),
        jnp.asarray(np.concatenate(side.counts)),
        jnp.asarray(np.concatenate(side.idx).astype(idx_t)),
        jnp.asarray(np.concatenate(side.val).astype(val_dtype)),
        meta=meta)
    return list(padded)


@dataclass
class PackedRatings:
    """Degree-bucketed padded slabs for both sides of a rating matrix —
    the reusable output of `pack_ratings` (pack once, train many times:
    eval sweeps, repeated benches)."""
    user_side: _SideBuckets
    item_side: _SideBuckets
    n_users: int
    n_items: int
    rank: int


def pack_ratings(u_ix: np.ndarray, i_ix: np.ndarray, val: np.ndarray,
                 n_users: int, n_items: int, rank: int) -> PackedRatings:
    """Host-side packing of COO ratings into solver slabs for both
    alternation sides, with rank-aware memory-budget slab splitting."""
    return PackedRatings(
        user_side=_pack_side(u_ix, i_ix, val, n_users, rank),
        item_side=_pack_side(i_ix, u_ix, val, n_items, rank),
        n_users=n_users, n_items=n_items, rank=rank)


def iteration_flops(packed: PackedRatings,
                    cg_iters: int = _CG_ITERS) -> int:
    """Closed-form FLOPs of ONE full ALS iteration (both half-steps) over
    the PADDED slab shapes — the denominator work for achieved-FLOP/s /
    MFU accounting, counting the work that actually EXECUTES. Convention:
    multiply-add = 2 FLOPs. Per slab of B rows x cap K at rank R:

    rank > _SMALL_RANK (the paired-MXU path, see _solve_slab_paired):
      paired Gram  gkp,gkq->gpq : 2*(B/2)*K*(2R)^2 = 4*B*K*R^2
        (2x the useful 2*B*K*R^2 — the off-diagonal blocks of each
        128-wide pair are junk, the price of full 128x128 MXU tiles)
      rhs einsums               : 2*B*K*R
      warm CG (stays in PAIRED form: dense [2R,2R] matvecs, so per row
      per iteration 4*R^2 mult-adds and 2R-wide vector ops):
      B*cg_iters*(4*R^2 + 16*R) + warm-start/residual matvecs B*8*R^2

    rank <= _SMALL_RANK (exact spd_solve path): Gram 2*B*K*R^2 + rhs +
      Cholesky ~2*(R^3/3 + 2R^2) per row."""
    r = packed.rank
    total = 0
    paired = r > _SMALL_RANK
    for side in (packed.user_side, packed.item_side):
        for rows, k in zip(side.rows, side.caps):
            b = len(rows)
            if paired:
                total += 4 * b * k * r * r + 2 * b * k * r
                total += b * cg_iters * (4 * r * r + 16 * r)
                total += b * 8 * r * r   # warm-start + residual matvecs
            else:
                total += 2 * b * k * r * r + 2 * b * k * r
                total += b * 2 * (r ** 3 // 3 + 2 * r * r)
    return total


@partial(jax.jit, static_argnames=("implicit",))
def _solve_bucket(factors, idx, val, reg, alpha, yty, *, implicit: bool):
    """Solve normal equations for one bucket slab — the exact f32 path.

    factors: [n_opposite, rank] opposite-side factors (replicated)
    idx/val: [rows_b, cap_b]; slot padding carries idx == -1 (the mask
    is derived here — it never crosses the host->device link)
    yty: [rank, rank] Gram matrix of opposite factors (implicit only)
    Returns [rows_b, rank] solutions.

    Solver choice: rank <= _SMALL_RANK uses the exact blocked Cholesky
    (`spd_solve` — one 16-wide block, short batched program, exact
    regardless of conditioning); larger ranks use Jacobi-preconditioned
    CG at a conservative min(32, rank+8) cap. The TPU training hot loop
    uses `_solve_slab_paired` instead; this function is the reference /
    small-rank / CPU path, and the direct API the unit tests drive.
    """
    import jax.numpy as jnp

    from predictionio_tpu.ops.linalg import pcg_solve, spd_solve

    rank = factors.shape[1]
    msk = (idx >= 0).astype(factors.dtype)              # [B, K]
    val = val.astype(factors.dtype)
    yg = factors[jnp.maximum(idx, 0)]                   # [B, K, R] gather
    if implicit:
        # MLlib trainImplicit semantics: confidence c = 1 + alpha*|r|,
        # preference p = 1 iff r > 0 (negative r = confident dislike)
        conf = alpha * jnp.abs(val) * msk               # c - 1
        pref = (val > 0).astype(factors.dtype)
        a = jnp.einsum("bkr,bks,bk->brs", yg, yg, conf) + yty
        b = jnp.einsum("bkr,bk->br", yg, pref * (1.0 + conf) * msk)
    else:
        a = jnp.einsum("bkr,bks,bk->brs", yg, yg, msk)
        b = jnp.einsum("bkr,bk->br", yg, val * msk)
    n_row = msk.sum(axis=1)                             # ALS-WR scaling
    eye = jnp.eye(rank, dtype=factors.dtype)
    a = a + (reg * n_row)[:, None, None] * eye
    # pad rows (n_row == 0) get an identity system -> solution 0
    a = jnp.where((n_row > 0)[:, None, None], a, eye)
    if rank <= _SMALL_RANK:
        x = spd_solve(a, b)
    else:
        x = pcg_solve(a, b, iters=min(32, rank + 8))
    return jnp.where((n_row > 0)[:, None], x, 0.0)


@partial(jax.jit, static_argnames=("implicit", "cg_iters", "cast"))
def _solve_slab_paired(own, opp_cast, rows, idx, val, reg, alpha, yty,
                       *, implicit: bool, cg_iters: int, cast):
    """The TPU hot-loop slab solver: paired-rows Gram on full MXU tiles +
    warm-started CG. Returns ([rows_b, R] solutions, [rows_b] relative
    residuals).

    Why this shape (each choice measured on a v5e against the ML-25M
    workload, see r4 bench roofline):
      * The factor gather is row-rate-bound (~390M rows/s f32, ~450M
        bf16, independent of row WIDTH up to 128 lanes) — it is the
        step's hard floor, so the gathered operand is cast (`cast`,
        normally bfloat16) and every padded slot counts.

        WHY THE GATHER FLOOR IS PHYSICAL (the r4->r5 Pallas question):
        the measured rate is invariant in row width up to 128 lanes,
        i.e. the cost is per ROW FETCHED, not per byte — the random-row
        fetch issue rate of the memory system, at ~0.4-0.5 rows/cycle.
        A hand-written Pallas kernel has exactly one primitive for the
        same access pattern (a dynamic-slice row copy per index, issued
        from a scalar loop), which bottlenecks on the same issue path;
        a VMEM-resident table is out (the ML-25M user table alone is
        21 MB bf16 > 16 MB VMEM, and splitting it doubles index
        traffic); and a one-hot-matmul "gather on the MXU" pays
        N*R/(2R^2) ~ 460x junk FLOPs at ML-25M shapes. Entry-level
        Zipf reuse can't be cached either: the top-512-item hot set
        covers only ~9% of entries at the catalog's s=0.5 skew. What
        DOES shrink the floor is gathering fewer rows — the cap-ladder
        growth of 1.25 (padding ~1.12x, was 1.27x) is that lever; a
        fused gather+Gram kernel would only relocate, not remove, the
        per-row fetch cost.
      * A batched [K,R]x[K,R] Gram per row runs the MXU at <2 TFLOP/s
        because each batch element only fills a RxR corner of the
        128x128 systolic array. Pairing consecutive rows (lane-concat of
        their gathered factors -> [B/2, K, 2R]) makes the einsum produce
        [2R, 2R] tiles: 2x redundant FLOPs (the cross blocks are junk)
        for ~3x wall-clock at R=64.
      * Masks are {0,1} so m^2 = m: ONE masked gathered copy serves both
        Gram operands (for implicit, sqrt-confidence weights do the same
        trick), with f32 accumulation via preferred_element_type.
      * The whole solve stays in PAIRED form: the junk cross blocks of
        each [2R, 2R] system are zeroed once (fused into the Gram
        epilogue), which block-diagonalizes the pair so CG solves both
        halves independently-but-together in 128-wide matvecs.
        Un-pairing A first was measured SLOWER (a 3.6 GB relayout copy
        plus worse 64-wide matvec shapes).
      * CG warm-starts from the CURRENT factor rows (inexact ALS:
        block-coordinate descent tolerates approximate solves; measured
        RMSE matches the exact solve at cg_iters=8 on ML-25M). The
        returned residuals let `als_train` flag non-convergence
        (low-reg / ill-conditioned systems) instead of going silently
        wrong.
    """
    import jax.numpy as jnp

    from predictionio_tpu.ops.linalg import pcg_solve

    R = own.shape[1]
    B = idx.shape[0]
    G = B // 2
    a2, b2, n2 = _paired_normal_eqs(opp_cast, idx, val, reg, alpha,
                                    yty, implicit=implicit, cast=cast)
    live2 = n2 > 0                                       # [G, 2R]
    r2 = rows.reshape(G, 2)
    safe = jnp.minimum(r2, own.shape[0] - 1)             # _FILL_ROW-safe
    x0 = jnp.where(live2,
                   jnp.concatenate([own[safe[:, 0]], own[safe[:, 1]]],
                                   axis=-1), 0.0)
    # fixed-trip CG (rtol=0): the early-exit while_loop is a fusion
    # barrier that measured ~30% on the whole ML-25M step; the residual
    # still comes back via the extra true-residual matvec. Matvec
    # precision tracks the Gram precision (see pcg_solve note).
    mv_prec = (jax.lax.Precision.DEFAULT if cast == jnp.bfloat16
               else None)
    x2, rel, _ = pcg_solve(a2, b2, iters=cg_iters, x0=x0, rtol=0.0,
                           return_info=True, matvec_precision=mv_prec)
    x2 = jnp.where(live2, x2, 0.0)
    sol = jnp.stack([x2[:, :R], x2[:, R:]], axis=1).reshape(B, R)
    rel_b = jnp.broadcast_to(rel[:, None], (G, 2)).reshape(B)
    return sol, jnp.where(n2.reshape(G, 2, R)[:, :, 0].reshape(B) > 0,
                          rel_b, 0.0)


def _paired_normal_eqs(opp_cast, idx, val, reg, alpha, yty, *,
                       implicit: bool, cast):
    """Build the per-PAIR normal equations (A2 [B/2, 2R, 2R] f32
    block-diagonal, b2 [B/2, 2R] f32, n2 [B/2, 2R] per-lane row counts)
    through the paired-MXU formulation — the measured-hot
    gather+Gram+rhs stage, shared by `_solve_slab_paired` and the bench
    phase breakdown so the roofline numbers measure exactly the
    production code. The junk cross blocks from pairing are zeroed here
    (fused by XLA into the einsum epilogue), so each returned system is
    exactly blockdiag(A_even, A_odd) + ALS-WR diag (identity on empty /
    padding rows)."""
    import jax.numpy as jnp

    R = opp_cast.shape[1]
    B, K = idx.shape
    G = B // 2
    # multiply precision tracks the operand dtype: bf16 operands gain
    # nothing from multi-pass passes; f32 mode pins HIGHEST so
    # precision="f32" really is the exact-normal-equations escape hatch
    prec = (jax.lax.Precision.DEFAULT if cast == jnp.bfloat16
            else jax.lax.Precision.HIGHEST)
    i2 = idx.reshape(G, 2, K)
    # slot padding carries idx == -1; derive the mask on device and
    # clamp for the gather (mask zeroes the garbage row's contribution)
    m2 = (i2 >= 0).astype(jnp.float32)
    i2 = jnp.maximum(i2, 0)
    v2 = val.reshape(G, 2, K).astype(jnp.float32)
    if implicit:
        # eps keeps c==0 observed entries alive through the sqrt trick:
        # their A-weight becomes eps (harmless) and the b-weight below
        # rescales by 1/sqrt(eps), so pref*(1+c)*y is exact even when
        # alpha == 0 (MLlib allows it: all-equal-confidence model)
        _EPS = 1e-12
        conf_e = alpha * jnp.abs(v2[:, 0]) * m2[:, 0] + _EPS * m2[:, 0]
        conf_o = alpha * jnp.abs(v2[:, 1]) * m2[:, 1] + _EPS * m2[:, 1]
        w_e = jnp.sqrt(conf_e).astype(cast)[..., None]
        w_o = jnp.sqrt(conf_o).astype(cast)[..., None]
    else:
        w_e = m2[:, 0].astype(cast)[..., None]
        w_o = m2[:, 1].astype(cast)[..., None]
    ygm = jnp.concatenate([opp_cast[i2[:, 0]] * w_e,
                           opp_cast[i2[:, 1]] * w_o], axis=-1)  # [G,K,2R]
    a2 = jnp.einsum("gkp,gkq->gpq", ygm, ygm, precision=prec,
                    preferred_element_type=jnp.float32)        # [G,2R,2R]
    if implicit:
        # b weights against the sqrt-conf-weighted copy:
        # pref*(1+c) * y = (sqrt(c) * y) * pref*(1+c)/sqrt(c)
        def bw(v, c):   # c >= eps on observed entries, 0 on padding
            return jnp.where(c > 0, (v > 0) * (1.0 + c) *
                             jax.lax.rsqrt(jnp.maximum(c, 1e-30)), 0.0)
        wb_e = bw(v2[:, 0], conf_e)
        wb_o = bw(v2[:, 1], conf_o)
    else:
        wb_e = v2[:, 0] * m2[:, 0]
        wb_o = v2[:, 1] * m2[:, 1]
    be = jnp.einsum("gkr,gk->gr", ygm[..., :R], wb_e.astype(cast),
                    precision=prec, preferred_element_type=jnp.float32)
    bo = jnp.einsum("gkr,gk->gr", ygm[..., R:], wb_o.astype(cast),
                    precision=prec, preferred_element_type=jnp.float32)
    b2 = jnp.concatenate([be, bo], axis=-1)              # [G, 2R]
    blockmask = np.zeros((2 * R, 2 * R), np.float32)
    blockmask[:R, :R] = 1.0
    blockmask[R:, R:] = 1.0
    a2 = a2 * blockmask
    if implicit:
        yty2 = jnp.zeros((2 * R, 2 * R), jnp.float32)
        yty2 = yty2.at[:R, :R].set(yty).at[R:, R:].set(yty)
        a2 = a2 + yty2
    n_e, n_o = m2[:, 0].sum(axis=1), m2[:, 1].sum(axis=1)
    n2 = jnp.concatenate([jnp.repeat(n_e[:, None], R, axis=1),
                          jnp.repeat(n_o[:, None], R, axis=1)], axis=-1)
    d2 = reg * n2 + (n2 == 0).astype(jnp.float32)        # pad rows -> I
    a2 = a2 + d2[:, :, None] * jnp.eye(2 * R, dtype=jnp.float32)
    return a2, b2, n2


def _pack_by_owner(side: _SideBuckets, block: int, n_dev: int):
    """Re-partition each bucket slab by owning device (owner = row //
    block) into [n_dev * rows_b, ...] arrays whose dim 0 shards evenly
    over the mesh: device d's chunk holds only rows it owns, addressed by
    LOCAL index (row - d*block, fill = block -> dropped scatter).
    Host-side, vectorized."""
    packed = []
    for j, rows in enumerate(side.rows):
        idx, vals = side.padded(j)
        real = rows != _FILL_ROW           # _pack_side even-padding rows
        rows, idx, vals = rows[real], idx[real], vals[real]
        owner = rows // block
        counts = np.bincount(owner, minlength=n_dev)
        rb = max(int(counts.max()), 1)
        rb += rb % 2                       # even rows per device (pairing)
        order = np.argsort(owner, kind="stable")
        member, intra = _group_offsets(counts)
        local_rows = np.full((n_dev, rb), block, np.int32)
        # fill slabs keep the -1 idx sentinel (mask derives from it)
        d_idx = np.full((n_dev, rb) + idx.shape[1:], -1, idx.dtype)
        d_val = np.zeros((n_dev, rb) + vals.shape[1:], vals.dtype)
        local_rows[member, intra] = rows[order] - member * block
        d_idx[member, intra] = idx[order]
        d_val[member, intra] = vals[order]
        packed.append((local_rows.reshape(n_dev * rb),
                       d_idx.reshape((n_dev * rb,) + idx.shape[1:]),
                       d_val.reshape((n_dev * rb,) + vals.shape[1:])))
    return packed


@partial(jax.jit,
         static_argnames=("implicit", "rank", "mesh", "cg_iters", "cast"))
def _run_als_sharded(x_sh, y_sh, user_slabs, item_slabs, reg, alpha,
                     n_iter, *, implicit: bool, rank: int, mesh,
                     cg_iters: int = _CG_ITERS, cast=None):
    """Sharded ALS loop: factor shards stay put; each half-step
    all-gathers the opposite shard (transient, cast to `cast` BEFORE the
    all-gather so the ICI bytes are halved in bf16 mode), psums the
    [rank, rank] Gram for implicit mode, and writes solved rows locally.
    Returns (x, y, max relative solver residual)."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    paired = rank > _SMALL_RANK

    def body(x_local, y_local, user_slabs, item_slabs):
        def half_step(own_local, opp_local, slabs, res):
            if implicit:
                yty = jax.lax.psum(opp_local.T @ opp_local, "data")
            else:
                yty = jnp.zeros((rank, rank), jnp.float32)
            if paired:
                opp_cast = (opp_local.astype(cast) if cast is not None
                            else opp_local)
                opp_full = jax.lax.all_gather(opp_cast, "data", axis=0,
                                              tiled=True)
                for local_rows, idx, vals in slabs:
                    sol, rel = _solve_slab_paired(
                        own_local, opp_full, local_rows, idx, vals,
                        reg, alpha, yty, implicit=implicit,
                        cg_iters=cg_iters, cast=cast or jnp.float32)
                    own_local = own_local.at[local_rows].set(sol,
                                                             mode="drop")
                    res = jnp.maximum(res, rel.max())
            else:
                opp_full = jax.lax.all_gather(opp_local, "data", axis=0,
                                              tiled=True)
                for local_rows, idx, vals in slabs:
                    sol = _solve_bucket(opp_full, idx, vals, reg,
                                        alpha, yty, implicit=implicit)
                    # fill rows carry local index == block -> dropped
                    own_local = own_local.at[local_rows].set(sol,
                                                             mode="drop")
            return own_local, res

        def zero():
            # per-device residual: mark varying over the mesh axis so
            # the fori carry type is stable (see shard_map scan-vma
            # docs)
            return compat.pcast_varying(jnp.float32(0.0), "data")

        def it(_, state):
            # final-iteration residual only (see _run_als note)
            x_local, y_local, _ = state
            x_local, res = half_step(x_local, y_local, user_slabs, zero())
            y_local, res = half_step(y_local, x_local, item_slabs, res)
            return (x_local, y_local, res)

        x_local, y_local, res = jax.lax.fori_loop(
            0, n_iter, it, (x_local, y_local, zero()))
        return x_local, y_local, jax.lax.pmax(res, "data")

    slab_specs_u = [tuple(P("data", *([None] * (a.ndim - 1)))
                          for a in slab) for slab in user_slabs]
    slab_specs_i = [tuple(P("data", *([None] * (a.ndim - 1)))
                          for a in slab) for slab in item_slabs]
    fsharded = compat.shard_map(
        body, mesh=mesh,
        in_specs=(P("data", None), P("data", None),
                  slab_specs_u, slab_specs_i),
        out_specs=(P("data", None), P("data", None), P()))
    return fsharded(x_sh, y_sh, user_slabs, item_slabs)


@partial(jax.jit, static_argnames=("implicit", "rank", "cg_iters", "cast"))
def _run_als(x, y, user_slabs, item_slabs, reg, alpha, n_iter, *,
             implicit: bool, rank: int, cg_iters: int = _CG_ITERS,
             cast=None):
    """The full ALS training loop as one compiled program (module-level
    jit: the cache persists across als_train calls with the same slab
    shapes). Slabs are pytrees of (rows, idx, val) tuples (mask derives
    from the -1 idx sentinel on device). Returns
    (x, y, max relative solver residual — 0.0 on the exact small-rank
    path)."""
    import jax.numpy as jnp

    paired = rank > _SMALL_RANK

    def half_step(own, opposite, slabs, res):
        yty = (opposite.T @ opposite if implicit
               else jnp.zeros((rank, rank), jnp.float32))
        opp_cast = (opposite.astype(cast) if (paired and cast is not None)
                    else opposite)
        for rows_dev, idx, vals in slabs:
            if paired:
                sol, rel = _solve_slab_paired(
                    own, opp_cast, rows_dev, idx, vals, reg, alpha,
                    yty, implicit=implicit, cg_iters=cg_iters,
                    cast=cast or jnp.float32)
                res = jnp.maximum(res, rel.max())
            else:
                sol = _solve_bucket(opposite, idx, vals, reg, alpha,
                                    yty, implicit=implicit)
            # slab-padding rows carry an out-of-bounds row index; 'drop'
            # discards their updates instead of clamping onto row n-1
            own = own.at[rows_dev].set(sol, mode="drop")
        return own, res

    def body(_, state):
        # residual restarts each iteration: the LAST iteration's solves
        # are what determine the returned factors' quality (early
        # iterations legitimately run with cold warm-starts)
        x, y, _ = state
        x, res = half_step(x, y, user_slabs, jnp.float32(0.0))
        y, res = half_step(y, x, item_slabs, res)
        return (x, y, res)

    return jax.lax.fori_loop(0, n_iter, body, (x, y, jnp.float32(0.0)))


def _train_on_mesh(x, y, user_side, item_side, n_users, n_items, mesh, *,
                   reg, alpha, iterations, implicit, rank,
                   cg_iters=_CG_ITERS, cast=None):
    """Shard inputs and run `_run_als_sharded`; returns the still-sharded
    device factor arrays (padded to a multiple of the mesh size) plus
    the replicated max solver residual."""
    import jax.numpy as jnp

    from predictionio_tpu.parallel import batch_sharding, pad_to_multiple

    n_dev = int(mesh.shape["data"])
    dpad_u = pad_to_multiple(n_users, n_dev)
    dpad_i = pad_to_multiple(n_items, n_dev)
    # padding factor rows are zero (they are never solved and must not
    # bias the psum'd implicit Gram matrix)
    x_sh = jax.device_put(
        jnp.pad(x, ((0, dpad_u - x.shape[0]), (0, 0))),
        batch_sharding(mesh, "data", 2))
    y_sh = jax.device_put(
        jnp.pad(y, ((0, dpad_i - y.shape[0]), (0, 0))),
        batch_sharding(mesh, "data", 2))
    dev_sides = []
    for side, block in ((user_side, dpad_u // n_dev),
                        (item_side, dpad_i // n_dev)):
        slabs = []
        for leaves in _pack_by_owner(side, block, n_dev):
            slabs.append(tuple(
                jax.device_put(a, batch_sharding(mesh, "data", a.ndim))
                for a in leaves))
        dev_sides.append(slabs)
    return _run_als_sharded(
        x_sh, y_sh, dev_sides[0], dev_sides[1], jnp.float32(reg),
        jnp.float32(alpha), jnp.int32(iterations),
        implicit=implicit, rank=rank, mesh=mesh, cg_iters=cg_iters,
        cast=cast)


@jax.jit
def _predict_elements(x, y, u_ix, i_ix):
    import jax.numpy as jnp
    return jnp.einsum("nr,nr->n", x[u_ix], y[i_ix])


def init_factors(n_users: int, n_items: int, rank: int, seed: int,
                 user_present: Optional[np.ndarray] = None,
                 item_present: Optional[np.ndarray] = None
                 ) -> Tuple[np.ndarray, np.ndarray]:
    """Starting factors (numpy): MLlib init abs(normal)/sqrt(rank) keeps
    initial predictions O(1). Rows with no ratings are zeroed from the
    start: they are never solved, and a nonzero phantom row would bias
    the implicit-mode Gram matrix Y^T Y (MLlib has no factor row at all
    for such ids). Exposed so the independent numpy oracle
    (`ops.oracle`) can start from identical factors for parity checks."""
    key = jax.random.PRNGKey(seed)
    ku, ki = jax.random.split(key)

    def _rowkeyed(side_key, n_rows):
        # per-row keyed draws: row r depends only on (seed, r), NOT on
        # the matrix height — threefry bit generation pairs counter
        # halves across the whole block, so a single (n, rank) draw
        # gives row r different values at different n. Shape-stable
        # rows mean a catalog padded with never-rated (zeroed) tail
        # rows starts — and therefore trains — identically to one
        # without them (the phantom-item invariance the tests pin).
        rows = np.arange(max(n_rows, 1))
        block = jax.vmap(lambda r: jax.random.normal(
            jax.random.fold_in(side_key, r), (rank,)))(rows)
        return np.abs(np.asarray(block))

    x = _rowkeyed(ku, n_users) / math.sqrt(rank)
    y = _rowkeyed(ki, n_items) / math.sqrt(rank)
    if user_present is not None:
        x = np.where(user_present[:, None], x, 0.0)
    if item_present is not None:
        y = np.where(item_present[:, None], y, 0.0)
    return x.astype(np.float32), y.astype(np.float32)


def als_train(ratings: "RatingColumns | Tuple[np.ndarray, np.ndarray, np.ndarray]",
              n_users: Optional[int] = None,
              n_items: Optional[int] = None, *,
              rank: int = 10,
              iterations: int = 10,
              reg: float = 0.01,
              implicit: bool = False,
              alpha: float = 1.0,
              seed: int = 0,
              mesh=None,
              packed: Optional[PackedRatings] = None,
              timings: Optional[dict] = None,
              precision: str = "bf16",
              cg_iters: int = _CG_ITERS) -> Tuple[np.ndarray, np.ndarray]:
    """Train factor matrices (X [n_users, rank], Y [n_items, rank]).

    Matches MLlib semantics: ALS-WR regularization (lambda scaled by the
    row's rating count), random normalized init, `iterations` full
    alternations. `mesh` shards each slab's row dimension over the "data"
    axis; None runs single-device. `packed` (from `pack_ratings`) skips
    host-side packing; `timings`, if given, is filled with pack_s /
    solve_s / fetch_s wall-clock phases plus `solver_residual` (the max
    relative residual of the inexact solves; 0.0 on the exact path).

    `precision` ("bf16" | "f32") sets the dtype of the GATHERED factor
    operands in the rank > 16 paired path (normal-equation accumulation
    and the CG solve are always f32) — bf16 is the TPU-first default and
    is gated by the bench's RMSE-parity check; rank <= 16 and the
    reference `_solve_bucket` path are exact f32 regardless. Rating
    VALUES additionally cross the link in bf16 on that path, but only
    when every rating round-trips bfloat16 exactly (half-star ratings
    do); otherwise values stay f32, so no rating is ever silently
    rounded. `cg_iters`
    caps the warm-started CG (see _CG_ITERS).

    Conditioning note (MLlib parity): MLlib's CholeskySolver is exact
    for any regParam; the paired path is iterative, so with reg near 0
    AND ill-conditioned data the solve may not converge within
    `cg_iters`. That case is detected (residual > 1e-2) and logged as a
    warning; raise `cg_iters` or use rank <= 16 / `_solve_bucket` for
    exact behavior.
    """
    import time as _time

    import jax.numpy as jnp

    cast = {"bf16": jnp.bfloat16, "f32": None}[precision]
    t0 = _time.perf_counter()
    if packed is not None:
        user_side, item_side = packed.user_side, packed.item_side
        n_users, n_items = packed.n_users, packed.n_items
        assert packed.rank == rank, "packed slabs were split for a different rank"
    else:
        if isinstance(ratings, RatingColumns):
            u_ix, i_ix, val = ratings.user_ix, ratings.item_ix, ratings.rating
            n_users = n_users or len(ratings.users)
            n_items = n_items or len(ratings.items)
        else:
            u_ix, i_ix, val = ratings
            assert n_users is not None and n_items is not None
        user_side = _pack_side(u_ix, i_ix, val, n_users, rank)
        item_side = _pack_side(i_ix, u_ix, val, n_items, rank)
    t_pack = _time.perf_counter()

    def present_mask(side, n_rows):
        present = np.zeros(max(n_rows, 1), bool)
        for rows in side.rows:
            present[rows[rows != _FILL_ROW]] = True
        return present

    x, y = init_factors(n_users, n_items, rank, seed,
                        user_present=present_mask(user_side, n_users),
                        item_present=present_mask(item_side, n_items))
    x, y = jnp.asarray(x), jnp.asarray(y)

    if mesh is not None:
        x_sh, y_sh, res_sh = _train_on_mesh(
            x, y, user_side, item_side, n_users, n_items, mesh,
            reg=reg, alpha=alpha, iterations=iterations,
            implicit=implicit, rank=rank, cg_iters=cg_iters, cast=cast)
        jax.block_until_ready((x_sh, y_sh))
        t_solve = _time.perf_counter()

        def fetch(arr):
            # multi-host mesh: shards on other processes are not
            # addressable here; all-gather across hosts first
            # (Runner.scala's executors ship results to the driver —
            # here every host ends with the full factors)
            if arr.is_fully_addressable:
                return np.asarray(arr)
            from jax.experimental import multihost_utils
            return np.asarray(
                multihost_utils.process_allgather(arr, tiled=True))

        out = (fetch(x_sh)[:n_users], fetch(y_sh)[:n_items])
        _check_residual(float(np.asarray(res_sh)), timings)
        if timings is not None:
            timings.update(pack_s=t_pack - t0, solve_s=t_solve - t_pack,
                           fetch_s=_time.perf_counter() - t_solve)
        return out

    # transfer-lean upload: ragged entries only, uint16 idx when the
    # opposite side fits, bf16 values on the EXPLICIT paired hot path —
    # but ONLY when every rating round-trips bfloat16 exactly (half-star
    # ratings do; arbitrary scores like 4.7 do not, and silently
    # rounding them in the normal equations is a behavior change the
    # caller never asked for). Non-exact values fall back to f32
    # transfer. Implicit mode keeps f32 values: confidences c = alpha*|r|
    # are computed in f32 from the raw ratings, and count-valued ratings
    # above 256 would round in bf16.
    paired = rank > _SMALL_RANK
    val_dt = (jnp.bfloat16
              if (paired and cast is jnp.bfloat16 and not implicit
                  and _bf16_exact(user_side.val))
              else np.float32)
    dev_sides = [device_slabs(user_side, n_items, val_dt),
                 device_slabs(item_side, n_users, val_dt)]
    jax.block_until_ready(dev_sides)
    t_xfer = _time.perf_counter()

    x, y, res = _run_als(x, y, dev_sides[0], dev_sides[1], jnp.float32(reg),
                         jnp.float32(alpha), jnp.int32(iterations),
                         implicit=implicit, rank=rank, cg_iters=cg_iters,
                         cast=cast)
    jax.block_until_ready((x, y))
    t_solve = _time.perf_counter()
    out = (np.asarray(x), np.asarray(y))
    _check_residual(float(np.asarray(res)), timings)
    if timings is not None:
        timings.update(pack_s=t_pack - t0, transfer_s=t_xfer - t_pack,
                       solve_s=t_solve - t_xfer,
                       fetch_s=_time.perf_counter() - t_solve)
    return out


def _bf16_exact(arrays) -> bool:
    """True iff every value in the per-bucket arrays round-trips
    bfloat16 exactly (host-side, chunked: no values-sized temporary).
    Guards the bf16 value transfer in `als_train` — ratings that bf16
    cannot represent (4.7, percentages) must cross in f32."""
    import jax.numpy as jnp
    step = 1 << 22
    for a in arrays:
        a = np.asarray(a)
        for s in range(0, len(a), step):
            c = np.asarray(a[s:s + step], np.float32)
            if not np.array_equal(
                    c, c.astype(jnp.bfloat16).astype(np.float32)):
                return False
    return True


def _check_residual(res: float, timings: Optional[dict]) -> None:
    """Surface the inexact-solver residual (see als_train conditioning
    note): record it, and warn loudly when the warm-CG solve failed to
    converge — the exact-Cholesky reference (MLlib CholeskySolver) has
    no such failure mode, so silence here would be a parity trap."""
    if timings is not None:
        # keep the WORST residual across a run's solves (two-sided
        # similar-product trains solve twice into one phase dict): the
        # bench convergence gate must see any failed solve, not just
        # the last one
        timings["solver_residual"] = max(
            res, timings.get("solver_residual", 0.0))
    if res > 1e-2:
        import logging
        logging.getLogger(__name__).warning(
            "ALS normal-equation solve did not converge (max relative "
            "residual %.2e > 1e-2): the system is ill-conditioned — "
            "likely reg is near zero. Raise cg_iters, raise reg, or use "
            "rank <= %d for the exact solver.", res, _SMALL_RANK)


def rmse(x: np.ndarray, y: np.ndarray, u_ix: np.ndarray, i_ix: np.ndarray,
         val: np.ndarray) -> float:
    """Root mean squared error over the given elements (the parity gate
    metric from BASELINE.md)."""
    import jax.numpy as jnp
    pred = _predict_elements(jnp.asarray(x), jnp.asarray(y),
                             jnp.asarray(u_ix), jnp.asarray(i_ix))
    return float(np.sqrt(np.mean((np.asarray(pred) - val) ** 2)))


def hbm_footprint(n_users: int, n_items: int, n_ratings: int, rank: int,
                  n_devices: int, *, owner_skew: float = 2.0) -> dict:
    """Per-device HBM upper bound (bytes, f32) for the sharded ALS layout
    — the documented memory model (see module docstring).

    Bucket padding is bounded in closed form: a row of degree d lands in
    a slab of cap(d) <= max(BASE, GROWTH*d + 8) (the x1.5 ladder rounds
    caps up to a multiple of 8), so a side's padded entry count is
    <= BASE*n_rows + GROWTH*n_ratings + 8*n_rows. `owner_skew` bounds
    the extra padding from `_pack_by_owner` equalizing per-device row
    counts (contiguous id blocks; ~1 for hashed/uniform ids, worst case
    n_devices for fully skewed ownership). `peak` is persistent + the
    worst transient: all-gathered opposite factors (bf16 in the default
    paired path, counted at f32 here as the conservative bound), plus
    the per-slab solve transients — the [B, cap, rank] gathered+masked
    factor copy (bf16: cap*rank*2B per row, counted via the gather
    budget at 2.75x for the pre-concat halves and cross-slab
    double-buffering) and the paired [B/2, 2R, 2R] f32 normal-equation
    systems that the solve stays in (counted at 9x the normal budget:
    the Gram is 2 budget-units, live twice across slab pipelining, plus
    2R-wide CG state), each capped by the slab-split budgets
    (`_SLAB_GATHER_BUDGET` / `_SLAB_NORMAL_BUDGET`), since `_pack_side`
    splits any bucket whose transients would exceed them and XLA's
    buffer assignment reuses the previous slab's buffers. See the
    multiplier note below for the measured anchor."""
    fb = 4  # f32 / int32 bytes
    pad_side = _BUCKET_BASE + 8
    padded_user = pad_side * n_users + _BUCKET_GROWTH * n_ratings
    padded_item = pad_side * n_items + _BUCKET_GROWTH * n_ratings
    factors_local = (n_users + n_items) * rank * fb / n_devices
    # idx (int32) + val (f32 bound; the bf16 hot path halves it) per
    # PADDED entry, both sides, sharded with skew — the mask plane is
    # derived from the -1 idx sentinel and never materialized
    # persistently
    slabs_local = ((padded_user + padded_item) * 2 * fb / n_devices
                   * owner_skew)
    gathered_opposite = max(n_users, n_items) * rank * fb
    # Multipliers anchored to the compiler's buffer assignment for the
    # ML-25M rank-64 program (memory_analysis peak 10.66 GiB, r4 bench):
    # 2.75x the gather-stage budget (the paired bf16 [G,K,2R] copy, its
    # two pre-concat producer halves, and cross-slab double-buffering)
    # and 9x the normal-equation budget (the paired [G,2R,2R] f32 Gram
    # = 2 budget-units, live twice across slab pipelining, plus CG state
    # vectors in 2R width). The bench asserts compiler-reported peak <=
    # this bound.
    slab_gather = 2.75 * min(
        max(padded_user, padded_item) * rank * fb / n_devices * owner_skew,
        _SLAB_GATHER_BUDGET)
    normal_bufs = 9 * min(
        max(n_users, n_items) * rank * rank * fb / n_devices * owner_skew,
        _SLAB_NORMAL_BUDGET)
    persistent = factors_local + slabs_local
    transient = gathered_opposite + slab_gather + normal_bufs
    return {
        "persistent": persistent,
        "transient": transient,
        "peak": persistent + transient,
    }


@dataclass
class ALSModel:
    """Factor matrices + BiMaps — the serving-side model
    (`examples/.../ALSModel.scala` fork of MatrixFactorizationModel)."""
    user_factors: np.ndarray    # [n_users, rank]
    item_factors: np.ndarray    # [n_items, rank]
    users: BiMap
    items: BiMap
    # items each user has interacted with at train time (for seen-filtering)
    seen: Optional[dict] = None

    def sanity_check(self):
        assert self.user_factors.ndim == 2 and self.item_factors.ndim == 2
        assert np.isfinite(self.user_factors).all(), "non-finite user factors"
        assert np.isfinite(self.item_factors).all(), "non-finite item factors"


# -- streaming fold-in --------------------------------------------------------

# per-row event cap for fold-in (newest kept) — bounds the padded slab
_FOLD_HISTORY_CAP = 8192


def fold_in_rows(opposite: np.ndarray, histories, *, reg: float,
                 implicit: bool = False, alpha: float = 1.0) -> np.ndarray:
    """Closed-form least-squares fold-in: re-solve factor rows against
    FIXED opposite-side factors — one exact ALS half-step, the classic
    trick for projecting new/updated users into a trained space without
    a retrain. `histories` is a sequence of `(opposite_ix, value)`
    array pairs, one per row to solve; returns `[len(histories), rank]`
    f32 rows.

    Exactness: this drives the same `_solve_bucket` program the
    reference training sweep runs, with identical reg/alpha semantics
    (ALS-WR row-count scaling, implicit confidence c = 1 + alpha*|r|),
    so a folded row equals that row's training solve given the same
    opposite factors. Shapes are padded to pow2 buckets so repeated
    refresh ticks hit the jit cache instead of recompiling per tick;
    histories longer than `_FOLD_HISTORY_CAP` keep their newest events
    (a documented approximation — such users converge on the next full
    retrain)."""
    import jax.numpy as jnp

    opp = np.ascontiguousarray(opposite, np.float32)
    rank = opp.shape[1]
    n_rows = len(histories)
    if n_rows == 0:
        return np.zeros((0, rank), np.float32)
    cap = 8
    for ix, _ in histories:
        cap = max(cap, min(len(ix), _FOLD_HISTORY_CAP))
    cap = 1 << (cap - 1).bit_length()
    b_pad = 1 << (max(8, n_rows) - 1).bit_length()
    idx = np.full((b_pad, cap), -1, np.int32)
    val = np.zeros((b_pad, cap), np.float32)
    for r, (ix, v) in enumerate(histories):
        ix = np.asarray(ix, np.int32)[-cap:]
        v = np.asarray(v, np.float32)[-cap:]
        idx[r, :len(ix)] = ix
        val[r, :len(v)] = v
    # YtY only feeds the implicit branch; the explicit trace still
    # wants the operand, so ship zeros there
    yty = opp.T @ opp if implicit else np.zeros((rank, rank), np.float32)
    sol = _solve_bucket(jnp.asarray(opp), jnp.asarray(idx),
                        jnp.asarray(val), jnp.float32(reg),
                        jnp.float32(alpha), jnp.asarray(yty),
                        implicit=implicit)
    # slice on HOST: an on-device sol[:n_rows] bakes n_rows into a
    # dynamic_slice program, recompiling for every novel touched-row
    # count — exactly the per-tick churn the pow2 padding exists to avoid
    return np.asarray(sol)[:n_rows]

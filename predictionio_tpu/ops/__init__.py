"""Numerical kernels: the MLlib replacement, expressed as XLA programs.

Each module provides the math for one algorithm family, consuming the
dense column structs from `predictionio_tpu.ingest` and producing
plain array models:

  als.py          explicit + implicit alternating least squares
                  (replaces Spark MLlib `ALS` / `ALS.trainImplicit`)
  naive_bayes.py  multinomial/categorical Naive Bayes
                  (replaces MLlib `NaiveBayes`, e2 CategoricalNaiveBayes)
  logreg.py       logistic regression / softmax classifier
  cooccur.py      item-item cooccurrence scoring
                  (replaces the similarproduct template's self-join)
  topk.py         masked top-k scoring used by every recommender's serve

Design rules (see SURVEY.md §7): static bucket-padded shapes, batched
linear algebra on the MXU, host Python only between jit'd steps, sharding
by jax.sharding annotations — never per-element Python.
"""

"""Softmax (multinomial logistic) regression by full-batch gradient
descent with optax.

The classification-template alternative algorithm (the reference's
templates use MLlib LogisticRegression in downstream variants; SURVEY.md
§2 lists LogisticRegression among the MLlib kernels to replace). The
entire train loop is one `lax.scan` over optimizer steps — no Python per
iteration — and data parallelism comes from sharding the batch dimension.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class LogRegModel:
    w: np.ndarray         # [d, n_classes]
    b: np.ndarray         # [n_classes]
    labels: np.ndarray    # [n_classes] original label values

    def sanity_check(self):
        assert np.isfinite(self.w).all() and np.isfinite(self.b).all()


@partial(jax.jit, static_argnames=("n_classes", "steps"))
def _fit(features, class_ix, mask, *, n_classes: int, steps: int,
         lr: float, reg: float):
    import optax

    n, d = features.shape
    w0 = jnp.zeros((d, n_classes), jnp.float32)
    b0 = jnp.zeros((n_classes,), jnp.float32)
    onehot = jax.nn.one_hot(class_ix, n_classes)
    tx = optax.adam(lr)

    def loss_fn(params):
        w, b = params
        logits = features @ w + b
        per_ex = jnp.sum(onehot * jax.nn.log_softmax(logits), axis=1)
        # masked mean: sharding-padding rows (mask 0) don't bias the loss
        ce = -jnp.sum(mask * per_ex) / jnp.maximum(mask.sum(), 1.0)
        return ce + reg * jnp.sum(w * w)

    def step(carry, _):
        params, opt_state = carry
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return (params, opt_state), loss

    (params, _), losses = jax.lax.scan(
        step, ((w0, b0), tx.init((w0, b0))), None, length=steps)
    return params[0], params[1], losses


def logreg_train(features: np.ndarray, labels: np.ndarray, *,
                 steps: int = 200, lr: float = 0.1,
                 reg: float = 1e-4, mesh=None) -> LogRegModel:
    """`mesh` shards the batch dimension over "data": full-batch
    gradients become per-device partials + GSPMD all-reduce; parameters
    stay replicated."""
    if features.shape[0] == 0:
        raise ValueError("no training points")
    uniq = np.unique(labels)
    class_ix = np.searchsorted(uniq, labels).astype(np.int32)
    # standardize features for conditioning; fold the transform into w/b
    mu = features.mean(axis=0)
    sd = features.std(axis=0) + 1e-8
    fs = ((features - mu) / sd).astype(np.float32)
    mask = np.ones(len(labels), np.float32)
    if mesh is not None:
        from predictionio_tpu.parallel import shard_put
        fs_d, _ = shard_put(fs, mesh)
        cix_d, _ = shard_put(class_ix, mesh)
        mask_d, _ = shard_put(mask, mesh)
    else:
        fs_d, cix_d, mask_d = (jnp.asarray(fs), jnp.asarray(class_ix),
                               jnp.asarray(mask))
    w, b, _ = _fit(fs_d, cix_d, mask_d,
                   n_classes=len(uniq), steps=steps, lr=lr, reg=reg)
    w = np.asarray(w) / sd[:, None]
    b = np.asarray(b) - mu @ w
    return LogRegModel(w, b, uniq)


@jax.jit
def _logits(w, b, features):
    return features @ w + b


def logreg_predict(model: LogRegModel, features: np.ndarray) -> np.ndarray:
    logits = np.asarray(_logits(jnp.asarray(model.w), jnp.asarray(model.b),
                                jnp.asarray(features, jnp.float32)))
    return model.labels[np.argmax(logits, axis=1)]

"""Tiered factor storage: a device-resident demand-paged hot set over a
host-RAM master copy, with EXACT top-k.

A catalog that exceeds even the (multi-host) mesh budget cannot be
device-resident. `TieredTopK` keeps the full `[n_items, rank]` factor
matrix in host RAM and pins only a fixed-size HOT slab `[hot_items,
rank]` on device, chosen by EWMA'd per-item access counts folded off
the serve path (serving/paging.PageManager). A serve call is:

  1. DEVICE: the hot slab scores through the inner `BucketedTopK` —
     same AOT bucket executables, banned filter, zero steady-state
     recompiles. Hot slots are kept SORTED ASCENDING BY GLOBAL ID, so
     `lax.top_k`'s lowest-index-first tie-break in slot space IS the
     global-id tie-break.
  2. HOST: cold items score through exact-f32 host BLAS with an O(n)
     argpartition top-k (`_topk_cold`, bit-identical to `_topk_host`'s
     stable tie semantics), the hot columns masked strictly BELOW
     `NEG_INF` so a masked row can never displace a legitimately-banned
     candidate.
  3. MERGE: the ≥k hot+cold candidates re-rank by (-score, global id)
     — bit-identical to the single-device `BucketedTopK` oracle under
     the same bitwise-score caveat as the sharded plans.

Paging swaps the slab through `BucketedTopK.swap_factors` (the factor
operand is positional, so every bucket executable is reused — zero
recompiles by construction); promotions/evictions are batched, run on
the async page thread, and hysteresis-biased toward incumbents so a
near-tie between a hot and a cold item does not thrash the slab.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional, Sequence

import numpy as np

from predictionio_tpu.ops.topk import (
    NEG_INF, BucketedTopK, DEFAULT_SERVE_BUCKETS, _record_dispatch,
    _topk_host,
)

# Strictly below NEG_INF: marks hot columns in the cold host pass and
# row-padding in the merge pool. Legitimate candidates (including banned
# ones at exactly NEG_INF) always outrank it, so a sentinel reaches the
# final top-k only when the candidate pool is smaller than k — which
# cannot happen while hot+cold tiers together hold >= k items.
_MASKED = np.float32(-np.inf)


def _topk_cold(scores: np.ndarray, k: int):
    """O(n) per-row top-k with `_topk_host`'s exact lowest-index-first
    tie semantics. The cold tier spans the WHOLE master minus the slab
    — a full stable argsort there is O(n log n) per query and dominates
    serve latency on giant catalogs. `argpartition` preselects in O(n);
    every item tied with the k-th score re-enters the pool so the final
    stable (-score, index) cut is bit-identical to the argsort path
    (degenerate all-tied rows fall back to sorting the whole row, which
    is exactly what the argsort would have done)."""
    b, n = scores.shape
    k = min(k, n)
    if k >= n:
        return _topk_host(scores, k)
    out_s = np.empty((b, k), np.float32)
    out_ix = np.empty((b, k), np.int64)
    for row in range(b):
        s = scores[row]
        part = np.argpartition(-s, k - 1)[:k]
        cand = np.flatnonzero(s >= s[part].min())
        order = np.lexsort((cand, -s[cand]))[:k]
        pick = cand[order]
        out_s[row] = s[pick]
        out_ix[row] = pick
    return out_s, out_ix.astype(np.int32)


class TieredTopK:
    """Serving plan for catalogs bigger than the device budget: host
    master + device hot slab + exact hot/cold merge. Satisfies the
    `BucketedTopK` warm/fits/swap_factors/__call__ contract, so the
    templates, the micro-batcher, and the streaming refresher use it
    unchanged."""

    def __init__(self, item_factors, *, k: int,
                 buckets: Sequence[int] = DEFAULT_SERVE_BUCKETS,
                 banned_width: int = 256, hot_items: int = 0,
                 ewma_decay: float = 0.8):
        master = np.ascontiguousarray(item_factors, dtype=np.float32)  # lint: ok — host master copy
        self.n_items, self.rank = master.shape
        self.k = max(1, min(k, self.n_items))
        self.banned_width = banned_width
        self.master = master
        hot = (int(hot_items) if hot_items > 0  # lint: ok — host int
               else max(1, self.n_items // 4))
        self.hot_items = max(1, min(hot, self.n_items))
        # the page swap and the serve read of (slot_gids, slab) must be
        # atomic together — slot ids decoded against a swapped slab
        # would alias wrong global ids
        self._page_lock = threading.Lock()
        self.slot_gids = np.arange(self.hot_items, dtype=np.int64)
        self._hot = BucketedTopK(master[self.slot_gids],
                                 k=min(self.k, self.hot_items),
                                 buckets=buckets,
                                 banned_width=banned_width)
        # access accounting, folded by the pager off the serve path:
        # GIL-atomic list appends of served-gid arrays (bounded by the
        # pager's drain cadence; drain swaps the list wholesale)
        self._access_buf: List[np.ndarray] = []
        self._ewma = np.zeros(self.n_items, np.float64)
        self.ewma_decay = float(ewma_decay)  # lint: ok — host float
        # hit/served tallies for pio_tier_hit_ratio: plain ints under
        # the GIL (worst case one lost increment, never a wrong ratio)
        self.hits = 0
        self.served = 0
        self.promotions_total = 0
        self.page_count = 0
        self.last_page_seconds = 0.0

    # -- plan contract ------------------------------------------------------
    @property
    def factors(self):
        """The device-resident state (the hot slab): what
        `_sample_plan_bytes` reports as pio_plan_resident_bytes."""
        return self._hot.factors

    @property
    def buckets(self):
        return self._hot.buckets

    @property
    def max_bucket(self) -> int:
        return self._hot.max_bucket

    def resident_per_device_bytes(self) -> float:
        # the inner BucketedTopK registered itself; report 0 here so
        # the slab is not double-counted by plan_resident_bytes()
        return 0.0

    def warm(self) -> int:
        return self._hot.warm()

    def fits(self, *, max_banned: int, k: int) -> bool:
        return (self._hot.fits(max_banned=max_banned, k=self._hot.k)
                and k <= self.k and max_banned <= self.banned_width)

    def swap_factors(self, item_factors) -> np.ndarray:
        """Whole-model hot swap (the streaming refresher / reload
        rollback): replace the host master and rebuild the slab from
        the CURRENT slot assignment — same shapes, so every bucket
        executable is reused, zero recompiles."""
        host = np.ascontiguousarray(item_factors, dtype=np.float32)  # lint: ok — host master copy
        if host.shape != (self.n_items, self.rank):
            raise ValueError(
                f"swap_factors shape {host.shape} != "
                f"{(self.n_items, self.rank)}: catalog changed — re-warm "
                "instead")
        with self._page_lock:
            prev = self.master
            self.master = host
            self._hot.swap_factors(host[self.slot_gids])
        return prev

    def __call__(self, user_vecs, banned_lists: Sequence[Sequence[int]]):
        """Score `[b, rank]` queries against the full catalog; returns
        host (scores [b, k], GLOBAL ids [b, k]) bit-identical to the
        single-device oracle."""
        user_vecs = np.asarray(user_vecs, np.float32)  # lint: ok — host in
        b = user_vecs.shape[0]
        k = self.k
        # -- hot tier: device slab through the AOT bucket machinery ---------
        with self._page_lock:
            gids = self.slot_gids
            master = self.master
            # global banned ids -> slot ids; out-of-slab bans drop here
            # (the cold pass applies them in global id space)
            hot_banned = []
            for bl in banned_lists:
                if len(bl):
                    arr = np.asarray(bl, np.int64)  # lint: ok — host ids
                    pos = np.searchsorted(gids, arr)
                    pos = pos[(pos < gids.shape[0])
                              & (gids[np.minimum(pos, gids.shape[0] - 1)]
                                 == arr)]
                    hot_banned.append(pos.tolist())
                else:
                    hot_banned.append(())
            hot_s, hot_slots = self._hot(user_vecs, hot_banned)
            hot_g = gids[hot_slots.astype(np.int64)]
        # -- cold tier: exact host BLAS over the master ----------------------
        t0 = time.perf_counter()
        cold = user_vecs @ master.T
        for row, bl in enumerate(banned_lists):
            if len(bl):
                cold[row, np.asarray(bl, np.int64)] = NEG_INF  # lint: ok — host ids
        # hot columns mask AFTER bans: a banned hot item must sit at
        # _MASKED (not NEG_INF) here, or it would surface from BOTH
        # tiers and duplicate a gid in the merged tail
        cold[:, gids] = _MASKED
        cold_s, cold_g = _topk_cold(cold, k)
        _record_dispatch("host", b * max(self.n_items - self.hot_items, 1),
                         time.perf_counter() - t0)
        # -- exact merge by (-score, global id) ------------------------------
        cand_s = np.concatenate([hot_s, cold_s], axis=1)
        cand_g = np.concatenate([hot_g, cold_g.astype(np.int64)], axis=1)
        n_hot = hot_s.shape[1]
        out_s = np.empty((b, k), np.float32)
        out_g = np.empty((b, k), np.int64)
        hot_hits = 0
        for row in range(b):
            order = np.lexsort((cand_g[row], -cand_s[row]))[:k]
            out_s[row] = cand_s[row, order]
            out_g[row] = cand_g[row, order]
            hot_hits += int(np.count_nonzero(order < n_hot))
        # access + hit accounting for the pager (GIL-atomic append)
        self._access_buf.append(out_g.ravel())
        self.hits += hot_hits
        self.served += b * k
        return out_s, out_g.astype(np.int32)

    # -- paging (called from the async page thread ONLY) --------------------
    def fold_accesses(self) -> int:
        """Drain the serve-path access buffer into the per-item EWMA;
        returns how many top-k slots were folded."""
        buf, self._access_buf = self._access_buf, []
        if not buf:
            self._ewma *= self.ewma_decay
            return 0
        gids = np.concatenate(buf)
        counts = np.bincount(gids, minlength=self.n_items)
        self._ewma = self._ewma * self.ewma_decay \
            + counts[:self.n_items].astype(np.float64)
        return int(gids.shape[0])  # lint: ok — host shape

    def rebalance(self, hysteresis: float = 0.25,
                  min_swap: int = 1) -> int:
        """One batched promotion/eviction pass: pick the EWMA top
        `hot_items` (incumbents get a `hysteresis` retention bonus so
        near-ties never thrash), rebuild the slab SORTED by global id,
        and swap it in through the reused bucket executables. Returns
        the number of promotions (0 = slab unchanged)."""
        eff = self._ewma.copy()
        eff[self.slot_gids] *= (1.0 + hysteresis)
        # a vanishing id-ordered tie-break: equal EWMAs (fresh start,
        # uniform traffic) must pick the SAME set every pass, or
        # argpartition's arbitrary tie choice thrashes the slab
        eff -= np.arange(self.n_items, dtype=np.float64) * 1e-12
        desired = np.argpartition(-eff, self.hot_items - 1)[:self.hot_items]
        promoted = np.setdiff1d(desired, self.slot_gids,
                                assume_unique=False)
        if promoted.shape[0] < max(1, min_swap):
            return 0
        t0 = time.perf_counter()
        new_gids = np.sort(desired).astype(np.int64)
        with self._page_lock:
            # slab gathers under the lock: a concurrent whole-model
            # swap_factors must not leave slab rows from the OLD master
            self._hot.swap_factors(self.master[new_gids])
            self.slot_gids = new_gids
        self.promotions_total += int(promoted.shape[0])  # lint: ok — host shape
        self.page_count += 1
        self.last_page_seconds = time.perf_counter() - t0
        return int(promoted.shape[0])  # lint: ok — host shape

    def hit_ratio(self) -> float:
        """Fraction of served top-k entries answered by the hot slab."""
        return self.hits / self.served if self.served else 0.0

    def stats(self) -> dict:
        return {"hot_items": self.hot_items, "n_items": self.n_items,
                "hit_ratio": round(self.hit_ratio(), 4),
                "served": self.served,
                "promotions_total": self.promotions_total,
                "pages": self.page_count}


def tier_mode() -> str:
    """PIO_SERVE_TIER: `auto` (tier when the catalog exceeds the
    effective device budget), `on` (always tier), `off`."""
    import os
    mode = (os.environ.get("PIO_SERVE_TIER", "auto") or "auto").lower()
    if mode in ("on", "1", "true"):
        return "on"
    if mode in ("off", "0", "false"):
        return "off"
    return "auto"


def hot_frac() -> Optional[float]:
    """PIO_TIER_HOT_FRAC: fraction of the catalog to pin hot (clamped
    to (0, 1]); unset -> size the slab from the device budget."""
    import os
    raw = (os.environ.get("PIO_TIER_HOT_FRAC", "") or "").strip()
    if not raw:
        return None
    try:
        return min(max(float(raw), 1e-6), 1.0)  # lint: ok — env str
    except ValueError:
        return None

"""Item-item cooccurrence counting.

Replaces the similarproduct template's RDD self-join
(`examples/scala-parallel-similarproduct/multi-events-multi-algos/src/main/
scala/CooccurrenceAlgorithm.scala:47-110`): count users who interacted
with both items i and j, keep the top-N cooccurring items per item.

Two regimes:

* Template scale (`cooccurrence_matrix`): with A the {0,1} user x item
  interaction matrix, C = A^T A — an MXU matmul accumulated over user
  chunks. Materializes the dense [n_items, n_items] matrix, so it is
  only used below `_DENSE_ITEM_LIMIT` items.

* Catalog scale (`top_cooccurrences_streaming`): never materializes
  n^2. Items are processed in row blocks; for each block the COMPLETE
  rows C[b0:b0+B, :] are built by scatter-adding, for every (user,
  item-in-block) pair, +1 at the columns of that user's full item
  list, then reduced to the per-row top-N before the next block. The
  per-row top-N is exact because each block is fully accumulated
  before reduction. Work is the sparse self-join cost
  sum_u d_u^2 (the reference's shuffle volume), not the dense
  2*U*I^2 matmul FLOPs, and peak memory is
  [row_block, n_items+1] + the degree-bucketed per-user item lists —
  the same padded-bucket discipline as `ops/als.py`.

Heavy users dominate sum_u d_u^2, so `max_items_per_user` optionally
caps each user's distinct items by deterministic subsample (the same
knob Mahout's ItemSimilarityJob exposes as --maxPrefsPerUser). Default
is uncapped: exact parity with the reference self-join.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def _accum(c, a_chunk):
    return c + a_chunk.T @ a_chunk


def cooccurrence_matrix(user_ix: np.ndarray, item_ix: np.ndarray,
                        n_users: int, n_items: int, *,
                        user_chunk: int = 4096) -> np.ndarray:
    """Dense [n_items, n_items] cooccurrence counts (diagonal = item
    popularity). Duplicate (user, item) pairs count once, matching the
    reference's per-user distinct item sets."""
    pairs = np.unique(np.stack([user_ix, item_ix], axis=1), axis=0)
    c = jnp.zeros((n_items, n_items), jnp.float32)
    # np.unique sorts by user, so each chunk is a contiguous slice found
    # by binary search — no full-array scan per chunk
    for start in range(0, n_users, user_chunk):
        end = min(start + user_chunk, n_users)
        lo = np.searchsorted(pairs[:, 0], start, side="left")
        hi = np.searchsorted(pairs[:, 0], end, side="left")
        if lo == hi:
            continue
        rows = pairs[lo:hi, 0] - start
        cols = pairs[lo:hi, 1]
        a = np.zeros((end - start, n_items), np.float32)
        a[rows, cols] = 1.0
        c = _accum(c, jnp.asarray(a))
    return np.asarray(c)


@dataclass
class CooccurrenceModel:
    """Top-N cooccurring items per item (CooccurrenceAlgorithm.scala
    topCooccurrences)."""
    top_items: np.ndarray    # [n_items, n] int32 indexes
    top_counts: np.ndarray   # [n_items, n] float32 counts (0 = no entry)

    def sanity_check(self):
        assert self.top_items.shape == self.top_counts.shape


def merge_pair_counts(model: CooccurrenceModel,
                      pair_updates: Dict[Tuple[int, int], float]
                      ) -> CooccurrenceModel:
    """Fold symmetric pair-count increments into the stored top-N lists
    (the streaming count-merge fold for this model).

    Each ``(i, j) -> c`` update bumps j in i's row and i in j's row. A
    partner not currently in a row's top-N enters with count == the
    increment alone: its true historical count is unknown once the row
    was truncated to top-N, so merged counts are a LOWER bound for new
    entrants. That is the documented approximation of count-merge
    fold-in — the periodic full retrain is ground truth. Rows touched
    by no update are returned untouched (same array rows, bit-equal).
    """
    top_items = model.top_items.copy()
    top_counts = model.top_counts.copy()
    n_items, k = top_items.shape
    per_row: Dict[int, Dict[int, float]] = {}
    for (i, j), inc in pair_updates.items():
        if i == j:
            continue
        for row, col in ((int(i), int(j)), (int(j), int(i))):
            if row >= n_items or col >= n_items:
                raise ValueError(
                    f"pair ({row}, {col}) outside catalog of {n_items} "
                    "items — new items need a full rebuild")
            d = per_row.setdefault(row, {})
            d[col] = d.get(col, 0.0) + float(inc)
    for row, deltas in per_row.items():
        counts = {int(it): float(c)
                  for it, c in zip(top_items[row], top_counts[row])
                  if c > 0}
        for col, inc in deltas.items():
            counts[col] = counts.get(col, 0.0) + inc
        ranked = sorted(counts.items(), key=lambda kv: -kv[1])[:k]
        top_items[row] = 0
        top_counts[row] = 0.0
        for s, (it, c) in enumerate(ranked):
            top_items[row, s] = it
            top_counts[row, s] = c
    return CooccurrenceModel(top_items, top_counts)


def top_cooccurrences(cooccur: np.ndarray, n: int) -> CooccurrenceModel:
    c = jnp.asarray(cooccur)
    c = c * (1.0 - jnp.eye(c.shape[0], dtype=c.dtype))  # drop self-pairs
    k = min(n, c.shape[0])
    counts, items = jax.lax.top_k(c, k)
    return CooccurrenceModel(np.asarray(items, np.int32),
                             np.asarray(counts, np.float32))


# ---------------------------------------------------------------------------
# streaming (catalog-scale) path
# ---------------------------------------------------------------------------

# above this many items the dense [n_items, n_items] counts matrix
# (f32) would cross 64 MiB and the router switches to streaming
_DENSE_ITEM_LIMIT = 4096

# default HBM budget for the [row_block, n_items+1] block accumulator
_BLOCK_BUDGET_BYTES = 256 * 1024 * 1024

# pairs scatter-added per compiled step; fixed so one program is
# compiled per degree bucket regardless of block pair counts
_PAIR_CHUNK = 8192

# per-user item-list buckets: x2 ladder from 8, same padding-bound idea
# as the ALS degree buckets (ops/als.py _cap_ladder)
_USER_BUCKET_BASE = 8


def _user_buckets(degrees: np.ndarray) -> List[int]:
    caps = [_USER_BUCKET_BASE]
    dmax = int(degrees.max()) if degrees.size else 1
    while caps[-1] < dmax:
        caps.append(caps[-1] * 2)
    return caps


@partial(jax.jit, static_argnames=("n_cols",), donate_argnums=(0,))
def _scatter_block(c_b, rows_local, cols, valid, n_cols):
    """c_b[rows_local[p], cols[p, s]] += valid[p] for every pair p and
    item slot s. Sentinel cols (== n_cols-1) land in the dump column."""
    del n_cols
    upd = jnp.broadcast_to(valid[:, None].astype(c_b.dtype), cols.shape)
    return c_b.at[rows_local[:, None], cols].add(upd)


@partial(jax.jit, static_argnames=("k",))
def _block_topk(c_b, b0, k):
    """Top-k of the complete block rows, self-column zeroed."""
    n_items = c_b.shape[1] - 1
    c = c_b[:, :n_items]
    rows = jnp.arange(c.shape[0])
    c = c.at[rows, jnp.minimum(b0 + rows, n_items - 1)].set(0.0)
    return jax.lax.top_k(c, k)


def _cap_users(pairs: np.ndarray, cap: int, seed: int) -> np.ndarray:
    """Deterministically subsample each user's distinct items to `cap`
    (Mahout ItemSimilarityJob --maxPrefsPerUser)."""
    rng = np.random.RandomState(seed)
    order = rng.permutation(len(pairs))
    shuffled = pairs[order]
    # stable sort by user restores user grouping but in shuffled item
    # order, so keeping the first `cap` rows per user is a uniform sample
    shuffled = shuffled[np.argsort(shuffled[:, 0], kind="stable")]
    seg_start = np.r_[0, np.flatnonzero(np.diff(shuffled[:, 0])) + 1]
    rank_in_user = np.arange(len(shuffled)) - np.repeat(
        seg_start, np.diff(np.r_[seg_start, len(shuffled)]))
    return shuffled[rank_in_user < cap]


def top_cooccurrences_streaming(
        user_ix: np.ndarray, item_ix: np.ndarray,
        n_users: int, n_items: int, n: int, *,
        row_block: Optional[int] = None,
        max_items_per_user: Optional[int] = None,
        seed: int = 0,
        block_budget_bytes: int = _BLOCK_BUDGET_BYTES) -> CooccurrenceModel:
    """Exact per-item top-N cooccurrences without the dense n^2 matrix.

    Peak device memory is [row_block, n_items+1] f32 plus the bucketed
    per-user item lists — never [n_items, n_items]. With no
    `max_items_per_user` the result is bit-identical to
    `top_cooccurrences(cooccurrence_matrix(...), n)`.
    """
    del n_users
    k = min(n, n_items)
    pairs = np.unique(np.stack([np.asarray(user_ix, np.int64),
                                np.asarray(item_ix, np.int64)], axis=1),
                      axis=0)
    if max_items_per_user is not None:
        pairs = _cap_users(pairs, max_items_per_user, seed)
        pairs = pairs[np.lexsort((pairs[:, 1], pairs[:, 0]))]
    if row_block is None:
        row_block = int(block_budget_bytes // (4 * (n_items + 1)))
        row_block = max(64, min(n_items, (row_block // 8) * 8))

    top_items = np.zeros((n_items, k), np.int32)
    top_counts = np.zeros((n_items, k), np.float32)
    if not len(pairs):
        return CooccurrenceModel(top_items, top_counts)

    # --- bucket users by degree; per bucket: padded item lists + that
    # bucket's pairs sorted by item with bucket-local user ids ---------
    uniq_users, user_pos, degrees = np.unique(
        pairs[:, 0], return_inverse=True, return_counts=True)
    buckets = []   # (items_pad [n_b, cap] device, by_item_pairs [m_b, 2])
    for cap in _user_buckets(degrees):
        in_b = ((degrees <= cap)
                & (degrees > (cap // 2 if cap > _USER_BUCKET_BASE else 0)))
        sel = np.flatnonzero(in_b)
        if not len(sel):
            continue
        local_of = np.full(len(uniq_users), -1, np.int64)
        local_of[sel] = np.arange(len(sel))
        mask = local_of[user_pos] >= 0
        bp = pairs[mask]
        blocal = local_of[user_pos[mask]]
        # pairs arrive user-sorted, so slots fill in item order per user
        items_pad = np.full((len(sel), cap), n_items, np.int32)
        slot = np.arange(len(bp)) - np.repeat(
            np.r_[0, np.flatnonzero(np.diff(blocal)) + 1],
            np.diff(np.r_[0, np.flatnonzero(np.diff(blocal)) + 1, len(bp)]))
        items_pad[blocal, slot] = bp[:, 1]
        order = np.argsort(bp[:, 1], kind="stable")
        by_item = np.stack([blocal[order], bp[order, 1]], axis=1)
        buckets.append((jnp.asarray(items_pad), by_item))

    # --- stream row blocks: full accumulation, then exact top-k -------
    for b0 in range(0, n_items, row_block):
        bsz = min(row_block, n_items - b0)
        todo = [(ip, bi[np.searchsorted(bi[:, 1], b0):
                        np.searchsorted(bi[:, 1], b0 + bsz)])
                for ip, bi in buckets]
        if not any(len(t[1]) for t in todo):
            continue   # no events touch this block: rows stay zero
        c_b = jnp.zeros((row_block, n_items + 1), jnp.float32)
        for items_pad, blk in todo:
            for s in range(0, len(blk), _PAIR_CHUNK):
                ch = blk[s:s + _PAIR_CHUNK]
                pad = _PAIR_CHUNK - len(ch)
                rows_local = jnp.asarray(
                    np.r_[ch[:, 1] - b0, np.zeros(pad, np.int64)], jnp.int32)
                users = jnp.asarray(
                    np.r_[ch[:, 0], np.zeros(pad, np.int64)], jnp.int32)
                valid = jnp.asarray(
                    np.r_[np.ones(len(ch), bool), np.zeros(pad, bool)])
                c_b = _scatter_block(c_b, rows_local,
                                     items_pad[users], valid, n_items + 1)
        counts, items = _block_topk(c_b, jnp.int32(b0), k)
        top_counts[b0:b0 + bsz] = np.asarray(counts[:bsz], np.float32)
        top_items[b0:b0 + bsz] = np.asarray(items[:bsz], np.int32)
    return CooccurrenceModel(top_items, top_counts)


def top_cooccurrences_from_pairs(
        user_ix: np.ndarray, item_ix: np.ndarray,
        n_users: int, n_items: int, n: int, *,
        max_items_per_user: Optional[int] = None,
        seed: int = 0) -> CooccurrenceModel:
    """Route by catalog size: dense MXU matmul below `_DENSE_ITEM_LIMIT`
    items, streaming row blocks above (no n^2 allocation)."""
    if n_items <= _DENSE_ITEM_LIMIT and max_items_per_user is None:
        c = cooccurrence_matrix(user_ix, item_ix, n_users, n_items)
        return top_cooccurrences(c, n)
    return top_cooccurrences_streaming(
        user_ix, item_ix, n_users, n_items, n,
        max_items_per_user=max_items_per_user, seed=seed)

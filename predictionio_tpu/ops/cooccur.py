"""Item-item cooccurrence counting.

Replaces the similarproduct template's RDD self-join
(`examples/scala-parallel-similarproduct/multi-events-multi-algos/src/main/
scala/CooccurrenceAlgorithm.scala:47-110`): count users who interacted
with both items i and j, keep the top-N cooccurring items per item.

TPU formulation: with A the {0,1} user x item interaction matrix,
the cooccurrence matrix is C = A^T A — an MXU matmul, accumulated over
user chunks so memory stays bounded. The reference's shuffle-heavy
self-join becomes one matmul chain.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def _accum(c, a_chunk):
    return c + a_chunk.T @ a_chunk


def cooccurrence_matrix(user_ix: np.ndarray, item_ix: np.ndarray,
                        n_users: int, n_items: int, *,
                        user_chunk: int = 4096) -> np.ndarray:
    """Dense [n_items, n_items] cooccurrence counts (diagonal = item
    popularity). Duplicate (user, item) pairs count once, matching the
    reference's per-user distinct item sets."""
    pairs = np.unique(np.stack([user_ix, item_ix], axis=1), axis=0)
    c = jnp.zeros((n_items, n_items), jnp.float32)
    # np.unique sorts by user, so each chunk is a contiguous slice found
    # by binary search — no full-array scan per chunk
    for start in range(0, n_users, user_chunk):
        end = min(start + user_chunk, n_users)
        lo = np.searchsorted(pairs[:, 0], start, side="left")
        hi = np.searchsorted(pairs[:, 0], end, side="left")
        if lo == hi:
            continue
        rows = pairs[lo:hi, 0] - start
        cols = pairs[lo:hi, 1]
        a = np.zeros((end - start, n_items), np.float32)
        a[rows, cols] = 1.0
        c = _accum(c, jnp.asarray(a))
    return np.asarray(c)


@dataclass
class CooccurrenceModel:
    """Top-N cooccurring items per item (CooccurrenceAlgorithm.scala
    topCooccurrences)."""
    top_items: np.ndarray    # [n_items, n] int32 indexes
    top_counts: np.ndarray   # [n_items, n] float32 counts (0 = no entry)

    def sanity_check(self):
        assert self.top_items.shape == self.top_counts.shape


def top_cooccurrences(cooccur: np.ndarray, n: int) -> CooccurrenceModel:
    c = jnp.asarray(cooccur)
    c = c * (1.0 - jnp.eye(c.shape[0], dtype=c.dtype))  # drop self-pairs
    k = min(n, c.shape[0])
    counts, items = jax.lax.top_k(c, k)
    return CooccurrenceModel(np.asarray(items, np.int32),
                             np.asarray(counts, np.float32))

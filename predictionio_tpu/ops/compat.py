"""Version shims for jax APIs the kernels rely on.

The kernels target current jax (`jax.shard_map`, varying-mesh-axis
tracking via `jax.lax.pcast`); this module lets them run unchanged on
the pre-0.6 releases some deployment images pin, where shard_map still
lives in `jax.experimental` and its replication checker predates
`fori_loop`/`scan` carry support.
"""

from __future__ import annotations

import jax


def shard_map(body, *, mesh, in_specs, out_specs):
    """`jax.shard_map` where available, else the experimental one with
    its (fori_loop/scan-incompatible) replication checker disabled —
    the psum/ppermute collectives the kernels emit are identical under
    both."""
    fn = getattr(jax, "shard_map", None)
    if fn is not None:
        return fn(body, mesh=mesh, in_specs=in_specs,
                  out_specs=out_specs)
    from jax.experimental.shard_map import shard_map as fn
    return fn(body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
              check_rep=False)


def pcast_varying(x, axis: str):
    """Mark `x` varying over `axis` for scan/fori carry-type stability;
    a no-op on jax without vma tracking (there a replicated constant
    carries fine)."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, (axis,), to="varying")
    return x

"""Multinomial Naive Bayes over dense nonnegative features.

Replaces Spark MLlib `NaiveBayes` as used by the classification template
(`examples/scala-parallel-classification/add-algorithm/src/main/scala/
NaiveBayesAlgorithm.scala:35-56`). MLlib's multinomial NB computes
per-class log priors pi_c = log(N_c / N) and log likelihoods theta_cj =
log((sum of feature j over class c + lambda) / (total over class c +
lambda * d)); prediction is argmax_c (pi_c + x . theta_c).

The whole fit is a couple of segment-sums and logs — one jit'd program.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class NaiveBayesModel:
    pi: np.ndarray        # [n_classes] log priors
    theta: np.ndarray     # [n_classes, d] log likelihoods
    labels: np.ndarray    # [n_classes] original label values

    def sanity_check(self):
        assert np.isfinite(self.pi).all() and np.isfinite(self.theta).all()


@partial(jax.jit, static_argnames=("n_classes",))
def _fit(features, class_ix, valid, lam, *, n_classes: int):
    d = features.shape[1]
    features = features.astype(jnp.float32)   # bf16 transfer widens here
    counts = jax.ops.segment_sum(valid.astype(jnp.float32), class_ix,
                                 num_segments=n_classes)
    feat_sums = jax.ops.segment_sum(features * valid[:, None], class_ix,
                                    num_segments=n_classes)
    pi = jnp.log(counts) - jnp.log(valid.sum())
    theta = (jnp.log(feat_sums + lam)
             - jnp.log(feat_sums.sum(axis=1, keepdims=True) + lam * d))
    return pi, theta


@jax.jit
def _scores(pi, theta, features):
    return pi[None, :] + features @ theta.T


def _integer_valued(a: np.ndarray) -> bool:
    """True iff every element is a whole number. Integer dtypes answer
    without touching the data; float inputs scan in row chunks so no
    features-sized temporary is ever allocated."""
    if np.issubdtype(a.dtype, np.integer) or a.dtype == bool:
        return True
    step = max(1, (1 << 22) // max(1, int(np.prod(a.shape[1:]))))
    for s in range(0, a.shape[0], step):
        chunk = a[s:s + step]
        if not np.equal(np.mod(chunk, 1.0), 0).all():
            return False
    return True


def nb_train(features: np.ndarray, labels: np.ndarray,
             lam: float = 1.0, *, mesh=None) -> NaiveBayesModel:
    """features [n, d] nonnegative; labels [n] arbitrary floats/ints.

    `mesh` shards the sample dimension over the "data" axis: the fit is
    two segment-sums of sufficient statistics, so GSPMD turns the
    sharded inputs into per-device partial sums + an all-reduce (padding
    rows carry valid=0 and vanish from every statistic)."""
    if features.shape[0] == 0:
        raise ValueError("no training points")
    fmin = float(np.asarray(features).min(initial=0.0))
    if fmin < 0:
        raise ValueError("multinomial NB requires nonnegative features")
    uniq = np.unique(labels)
    class_ix = np.searchsorted(uniq, labels).astype(np.int32)
    valid = np.ones(len(labels), np.float32)
    src = np.asarray(features)
    feats_np = np.asarray(src, np.float32)   # zero-copy when already f32
    # count-like features (integers < 256 — word/event counts, the
    # multinomial NB regime) are EXACT in bfloat16: cross the
    # host->device link at half the bytes and widen device-side
    # (accumulation is f32 either way, so the statistics are identical)
    # gate on BOTH bounds: 0 <= x < 256 integers are exact in bf16; the
    # min is already checked loudly above (fmin >= 0 here), restated in
    # the gate so the bf16 choice never outlives that validation
    if 0 <= fmin and feats_np.max(initial=0.0) < 256 \
            and _integer_valued(src):
        feats_np = feats_np.astype(jnp.bfloat16)
    if mesh is not None:
        from predictionio_tpu.parallel import shard_put
        feats_d, _ = shard_put(feats_np, mesh)
        cix_d, _ = shard_put(class_ix, mesh)
        valid_d, _ = shard_put(valid, mesh)
    else:
        feats_d = jnp.asarray(feats_np)
        cix_d = jnp.asarray(class_ix)
        valid_d = jnp.asarray(valid)
    pi, theta = _fit(feats_d, cix_d, valid_d,
                     jnp.float32(lam), n_classes=len(uniq))
    return NaiveBayesModel(np.asarray(pi), np.asarray(theta), uniq)


def nb_predict(model: NaiveBayesModel, features: np.ndarray) -> np.ndarray:
    """Returns predicted original label values, [b]."""
    scores = np.asarray(_scores(jnp.asarray(model.pi),
                                jnp.asarray(model.theta),
                                jnp.asarray(features, jnp.float32)))
    return model.labels[np.argmax(scores, axis=1)]


def nb_predict_proba(model: NaiveBayesModel,
                     features: np.ndarray) -> np.ndarray:
    scores = np.asarray(_scores(jnp.asarray(model.pi),
                                jnp.asarray(model.theta),
                                jnp.asarray(features, jnp.float32)))
    e = np.exp(scores - scores.max(axis=1, keepdims=True))
    return e / e.sum(axis=1, keepdims=True)

"""Multinomial Naive Bayes over dense nonnegative features.

Replaces Spark MLlib `NaiveBayes` as used by the classification template
(`examples/scala-parallel-classification/add-algorithm/src/main/scala/
NaiveBayesAlgorithm.scala:35-56`). MLlib's multinomial NB computes
per-class log priors pi_c = log(N_c / N) and log likelihoods theta_cj =
log((sum of feature j over class c + lambda) / (total over class c +
lambda * d)); prediction is argmax_c (pi_c + x . theta_c).

The whole fit is a couple of segment-sums and logs — one jit'd program.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class NaiveBayesModel:
    pi: np.ndarray        # [n_classes] log priors
    theta: np.ndarray     # [n_classes, d] log likelihoods
    labels: np.ndarray    # [n_classes] original label values

    def sanity_check(self):
        assert np.isfinite(self.pi).all() and np.isfinite(self.theta).all()


@partial(jax.jit, static_argnames=("n_classes",))
def _fit(features, class_ix, valid, lam, *, n_classes: int):
    d = features.shape[1]
    features = features.astype(jnp.float32)   # narrow transfer widens here
    counts = jax.ops.segment_sum(valid.astype(jnp.float32), class_ix,
                                 num_segments=n_classes)
    feat_sums = jax.ops.segment_sum(features * valid[:, None], class_ix,
                                    num_segments=n_classes)
    pi = jnp.log(counts) - jnp.log(valid.sum())
    theta = (jnp.log(feat_sums + lam)
             - jnp.log(feat_sums.sum(axis=1, keepdims=True) + lam * d))
    return pi, theta


@jax.jit
def _scores(pi, theta, features):
    return pi[None, :] + features @ theta.T


def _integer_valued(a: np.ndarray) -> bool:
    """True iff every element is a whole number. Integer dtypes answer
    without touching the data; float inputs scan in row chunks so no
    features-sized temporary is ever allocated."""
    if np.issubdtype(a.dtype, np.integer) or a.dtype == bool:
        return True
    step = max(1, (1 << 22) // max(1, int(np.prod(a.shape[1:]))))
    for s in range(0, a.shape[0], step):
        chunk = a[s:s + step]
        if not np.equal(np.mod(chunk, 1.0), 0).all():
            return False
    return True


def nb_train(features: np.ndarray, labels: np.ndarray,
             lam: float = 1.0, *, mesh=None,
             timings: Optional[dict] = None) -> NaiveBayesModel:
    """features [n, d] nonnegative; labels [n] arbitrary floats/ints.

    `mesh` shards the sample dimension over the "data" axis: the fit is
    two segment-sums of sufficient statistics, so GSPMD turns the
    sharded inputs into per-device partial sums + an all-reduce (padding
    rows carry valid=0 and vanish from every statistic).

    The fit is transfer-bound on a tunneled runtime (the statistics are
    two segment-sums — compute is trivial next to moving [n, d] to the
    device), so the feature upload narrows to the cheapest EXACT dtype:
    uint8 for integer counts < 256 (the multinomial regime — 1/4 the
    f32 bytes), uint16 below 65536, f32 otherwise; accumulation is f32
    in every case, so the statistics are bit-identical. `timings`, if
    given, is filled with transfer_s / solve_s wall-clock phases."""
    import time as _time

    if features.shape[0] == 0:
        raise ValueError("no training points")
    fmin = float(np.asarray(features).min(initial=0.0))
    if fmin < 0:
        raise ValueError("multinomial NB requires nonnegative features")
    uniq = np.unique(labels)
    class_ix = np.searchsorted(uniq, labels).astype(np.int32)
    src = np.asarray(features)
    feats_np = np.asarray(src, np.float32)   # zero-copy when already f32
    if 0 <= fmin and _integer_valued(src):
        fmax = feats_np.max(initial=0.0)
        if fmax < 256:
            feats_np = feats_np.astype(np.uint8)
        elif fmax < 65536:
            feats_np = feats_np.astype(np.uint16)
    t0 = _time.perf_counter()
    if mesh is not None:
        from predictionio_tpu.parallel import shard_put
        feats_d, _ = shard_put(feats_np, mesh)
        cix_d, _ = shard_put(class_ix, mesh)
        # mesh path: `valid` must share the padded sample sharding, so
        # it crosses with the rest of the transfer (n f32 bytes — small
        # next to the feature matrix) and is timed as transfer
        valid_d, _ = shard_put(np.ones(len(class_ix), np.float32), mesh)
    else:
        feats_d = jnp.asarray(feats_np)
        cix_d = jnp.asarray(class_ix)
        # single-device: `valid` is identically 1 — created on device,
        # nothing crosses the link
        valid_d = jnp.ones(len(class_ix), jnp.float32)
    if timings is not None:
        # readback fence: on the tunneled runtime block_until_ready can
        # return before the device holds the bytes; a scalar readback
        # cannot (costs one ~100 ms round trip, small next to the
        # hundreds-of-MB transfer being timed)
        float(feats_d[0, 0].astype(jnp.float32))
        float(cix_d[0])
    t1 = _time.perf_counter()
    pi, theta = _fit(feats_d, cix_d, valid_d,
                     jnp.float32(lam), n_classes=len(uniq))
    out = NaiveBayesModel(np.asarray(pi), np.asarray(theta), uniq)
    if timings is not None:
        timings["transfer_s"] = t1 - t0
        timings["solve_s"] = _time.perf_counter() - t1
    return out


def nb_predict(model: NaiveBayesModel, features: np.ndarray) -> np.ndarray:
    """Returns predicted original label values, [b]."""
    scores = np.asarray(_scores(jnp.asarray(model.pi),
                                jnp.asarray(model.theta),
                                jnp.asarray(features, jnp.float32)))
    return model.labels[np.argmax(scores, axis=1)]


def nb_predict_proba(model: NaiveBayesModel,
                     features: np.ndarray) -> np.ndarray:
    scores = np.asarray(_scores(jnp.asarray(model.pi),
                                jnp.asarray(model.theta),
                                jnp.asarray(features, jnp.float32)))
    e = np.exp(scores - scores.max(axis=1, keepdims=True))
    return e / e.sum(axis=1, keepdims=True)

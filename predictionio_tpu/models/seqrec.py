"""Sequential recommender template (new capability).

No reference analog — the reference's recommenders are order-blind
(ALS over a rating matrix); this template predicts the NEXT item from
the ORDER of a user's events with a causal transformer
(`ops/seqrec.py`), the framework's long-context / sequence-parallel
proof point (ring attention over the mesh "sp" axis).

Uses the recommendation template's event shapes and query/result wire
format (swap `"engineFactory": "recommendation"` for `"seqrec"` in
engine.json and retrain). Serving re-reads the user's RECENT events
from the store at query time — the e-commerce template's
serve-time-read pattern (ECommAlgorithm.scala:331-430) — so a user's
newest activity influences their very next recommendation without
retraining.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from predictionio_tpu.core import (
    Algorithm, DataSource, Engine, EngineFactory, FirstServing,
    IdentityPreparator, Params, RuntimeContext, register_engine,
)
from predictionio_tpu.data import store
from predictionio_tpu.ingest import BiMap, RatingColumns
from predictionio_tpu.models.recommendation import (
    PredictedResult, Query,
)
from predictionio_tpu.ops.seqrec import (
    SeqRecModel, build_sequences, seqrec_encode, seqrec_train,
)


@dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = "default"
    channel: Optional[str] = None
    event_names: Sequence[str] = ("view", "rate", "buy")


class SeqRecDataSource(DataSource):
    params_class = DataSourceParams

    def read_training(self, ctx: RuntimeContext) -> RatingColumns:
        p = self.params
        return store.rating_columns(
            ctx.registry, p.app_name, p.channel,
            event_names=list(p.event_names), value_spec={"*": 1.0})


@dataclass
class SeqRecServingModel:
    net: SeqRecModel
    users: BiMap
    items: BiMap

    def sanity_check(self):
        self.net.sanity_check()


@dataclass(frozen=True)
class SeqRecParams(Params):
    app_name: str = "default"           # serve-time history reads
    channel: Optional[str] = None
    event_names: Sequence[str] = ("view", "rate", "buy")
    seq_len: int = 32
    dim: int = 64
    n_heads: int = 2
    n_layers: int = 2
    batch_size: int = 256
    epochs: int = 20
    lr: float = 3e-3
    temperature: float = 0.07
    seed: Optional[int] = None


class SeqRecAlgorithm(Algorithm):
    params_class = SeqRecParams
    query_class = Query

    def train(self, ctx: RuntimeContext,
              pd: RatingColumns) -> SeqRecServingModel:
        p = self.params
        self._serving_ctx = ctx
        if pd.n == 0:
            raise ValueError("No interaction events found")
        seqs, targets = build_sequences(
            pd.user_ix, pd.item_ix, pd.t_millis,
            n_items=len(pd.items), seq_len=p.seq_len)
        if not len(seqs):
            raise ValueError(
                "No user has >= 2 events; sequences cannot be built")
        bsz = min(p.batch_size, len(seqs))
        net = seqrec_train(
            seqs, targets, n_items=len(pd.items), seq_len=p.seq_len,
            dim=p.dim, n_heads=p.n_heads, n_layers=p.n_layers,
            batch_size=bsz, epochs=p.epochs, lr=p.lr,
            temperature=p.temperature,
            seed=p.seed if p.seed is not None else 0, mesh=ctx.mesh)
        return SeqRecServingModel(net, pd.users, pd.items)

    def fold_in(self, model: SeqRecServingModel, delta,
                fctx) -> Optional[SeqRecServingModel]:
        """Streaming fold-in: ONE warm-start epoch from the previous
        transformer weights over sequences rebuilt from the full event
        set (adam restarts fresh — a mini-epoch, not a retrain; the
        full re-read is the cost ceiling, the delta only gates the
        run). New ITEMS invalidate — the tied item table's shape is
        baked into the net. New users are fine: serving reads each
        user's history at query time, so they never index the net."""
        from predictionio_tpu.data.storage.base import DeltaInvalidated
        p = self.params
        cols = fctx.delta_columns(
            entity_type="user", event_names=list(p.event_names),
            value_spec={"*": 1.0}, require_target=True)
        if cols.n == 0:
            return None
        full = fctx.store.scan_columns(
            fctx.app_id, fctx.channel_id, entity_type="user",
            event_names=list(p.event_names), value_spec={"*": 1.0},
            require_target=True)
        i_of = np.array([model.items.get(t, -1) for t in full.targets],
                        np.int64)
        if (i_of < 0).any():
            raise DeltaInvalidated(
                "new items since train: the tied item-table shape is "
                "baked into the net; full rebuild required")
        seqs, targets = build_sequences(
            full.entity_ix.astype(np.int64), i_of[full.target_ix],
            full.t_millis, n_items=model.net.n_items,
            seq_len=model.net.seq_len)
        if not len(seqs):
            return None
        bsz = min(p.batch_size, len(seqs))
        net = seqrec_train(
            seqs, targets, n_items=model.net.n_items,
            seq_len=model.net.seq_len, dim=p.dim, n_heads=p.n_heads,
            n_layers=p.n_layers, batch_size=bsz, epochs=1, lr=p.lr,
            temperature=p.temperature,
            seed=p.seed if p.seed is not None else 0, mesh=fctx.mesh,
            init_params=model.net.params)
        return SeqRecServingModel(net, model.users, model.items)

    # -- serving -------------------------------------------------------------
    def _ctx(self) -> RuntimeContext:
        ctx = getattr(self, "_serving_ctx", None)
        if ctx is None:
            raise RuntimeError(
                "SeqRecAlgorithm.predict needs a serving context for "
                "its event-store reads; train/deploy through the Engine "
                "workflow, or call with_serving_context(ctx) first")
        return ctx

    def with_serving_context(self, ctx: RuntimeContext) -> None:
        self._serving_ctx = ctx

    def _history(self, model: SeqRecServingModel, user: str) -> List[int]:
        """The user's most recent item ids (store read, newest last).
        Reads a LARGER window than seq_len before filtering: the model's
        item map is frozen at training, so a burst of recent events on
        post-training items must evict into older mappable history, not
        empty it (history is this model's only input)."""
        p = self.params
        try:
            events = list(store.find_by_entity(
                self._ctx().registry, p.app_name, channel_name=p.channel,
                entity_type="user", entity_id=user,
                event_names=list(p.event_names),
                limit=4 * model.net.seq_len, latest_first=True))
        except store.AppNotFoundError:
            return []
        hist = [ix for e in reversed(events)
                if e.target_entity_id is not None
                and (ix := model.items.get(e.target_entity_id)) is not None]
        return hist[-model.net.seq_len:]

    def predict(self, model: SeqRecServingModel,
                query: Query) -> PredictedResult:
        return self.batch_predict(model, [(0, query)])[0][1]

    def batch_predict(self, model: SeqRecServingModel,
                      queries: Sequence[Tuple[int, Query]]
                      ) -> List[Tuple[int, PredictedResult]]:
        out: List[Tuple[int, PredictedResult]] = []
        live = []
        S = model.net.seq_len
        n_items = model.net.n_items
        for i, q in queries:
            hist = self._history(model, q.user)
            if not hist:
                out.append((i, PredictedResult()))
            else:
                live.append((i, q, hist))
        if not live:
            return out
        seqs = np.full((len(live), S), n_items, np.int32)
        for row, (_, _, hist) in enumerate(live):
            seqs[row, S - len(hist):] = hist
        vecs = seqrec_encode(model.net, seqs)
        from predictionio_tpu.models.common import score_and_rank
        out.extend(score_and_rank(vecs, model.net.item_emb,
                                  model.items, live))
        return out


class SeqRecEngine(EngineFactory):
    @classmethod
    def apply(cls) -> Engine:
        return Engine(
            data_source=SeqRecDataSource,
            preparator=IdentityPreparator,
            algorithms={"seqrec": SeqRecAlgorithm, "": SeqRecAlgorithm},
            serving=FirstServing,
        )


def engine() -> Engine:
    return SeqRecEngine.apply()


register_engine("seqrec", SeqRecEngine)

"""Classification template: NaiveBayes + RandomForest (+ LogisticRegression
bonus) on aggregated entity properties.

Parity target: `examples/scala-parallel-classification/`
  - DataSource aggregates `$set` properties of `user` entities into
    labeled points: features attr0..attr2, label `plan`
    (`add-algorithm/src/main/scala/DataSource.scala`); custom property
    names via params (`reading-custom-properties` variant)
  - NaiveBayesAlgorithm (MLlib NB -> `ops.naive_bayes`)
    (`NaiveBayesAlgorithm.scala:35-56`)
  - RandomForestAlgorithm (MLlib RandomForest.trainClassifier ->
    `ops.forest` level-wise histogram forest)
    (`add-algorithm/src/main/scala/RandomForestAlgorithm.scala:41-72`)
  - LogisticRegressionAlgorithm (`ops.logreg`) — bonus beyond the
    reference's algorithm set
  - query `{"attr0": 2, "attr1": 0, "attr2": 0}` ->
    `{"label": 1.0}`

Evaluation: Accuracy (the template's PrecisionEvaluation analog).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence

import numpy as np

from predictionio_tpu.core import (
    Algorithm, AverageMetric, DataSource, Engine, EngineFactory,
    FirstServing, IdentityPreparator, Params, RuntimeContext,
    register_engine,
)
from predictionio_tpu.data import store
from predictionio_tpu.ingest import LabeledPoints, labeled_points_from_properties
from predictionio_tpu.ops import forest as forest_ops
from predictionio_tpu.ops import logreg as lr_ops
from predictionio_tpu.ops import naive_bayes as nb_ops


@dataclass(frozen=True)
class Query(Params):
    attr0: Optional[float] = None
    attr1: Optional[float] = None
    attr2: Optional[float] = None
    features: Optional[Sequence[float]] = None

    def vector(self) -> List[float]:
        if self.features is not None:
            return [float(v) for v in self.features]
        vals = [self.attr0, self.attr1, self.attr2]
        if any(v is None for v in vals):
            raise ValueError(
                "query must provide attr0..attr2 or a features array")
        return [float(v) for v in vals]


@dataclass(frozen=True)
class PredictedResult:
    label: float


@dataclass(frozen=True)
class ActualResult:
    label: float


@dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = "default"
    channel: Optional[str] = None
    entity_type: str = "user"
    attrs: Sequence[str] = ("attr0", "attr1", "attr2")
    label: str = "plan"
    eval_k: Optional[int] = None   # k-fold readEval


class ClassificationDataSource(DataSource):
    params_class = DataSourceParams

    def read_training(self, ctx: RuntimeContext) -> LabeledPoints:
        p = self.params
        props = store.aggregate_properties(
            ctx.registry, p.app_name, channel_name=p.channel,
            entity_type=p.entity_type)
        lp = labeled_points_from_properties(
            props, feature_attrs=list(p.attrs), label_attr=p.label)
        if lp.features.shape[0] == 0:
            raise ValueError(
                f"No '{p.entity_type}' entities with attributes "
                f"{list(p.attrs)} + '{p.label}' found "
                "(DataSource.scala readTraining require)")
        return lp

    def read_eval(self, ctx: RuntimeContext):
        p = self.params
        if not p.eval_k:
            raise ValueError("eval requires DataSourceParams.eval_k")
        from predictionio_tpu.e2 import split_data
        from predictionio_tpu.ingest import BiMap
        lp = self.read_training(ctx)
        rows = [(lp.features[i], lp.label[i], lp.entities.inverse(i))
                for i in range(lp.features.shape[0])]

        def to_training(train_rows):
            feats = np.stack([r[0] for r in train_rows])
            labels = np.array([r[1] for r in train_rows], np.float32)
            return LabeledPoints(feats, labels,
                                 BiMap.from_keys(r[2] for r in train_rows))

        return split_data(
            p.eval_k, rows, to_training=to_training,
            to_qa=lambda r: (Query(features=tuple(map(float, r[0]))),
                             ActualResult(float(r[1]))))


@dataclass(frozen=True)
class NaiveBayesParams(Params):
    lambda_: float = 1.0


class NaiveBayesAlgorithm(Algorithm):
    params_class = NaiveBayesParams
    query_class = Query

    def train(self, ctx: RuntimeContext,
              pd: LabeledPoints) -> nb_ops.NaiveBayesModel:
        return nb_ops.nb_train(pd.features, pd.label, self.params.lambda_,
                               mesh=ctx.mesh)

    def predict(self, model, query: Query) -> PredictedResult:
        return self.batch_predict(model, [(0, query)])[0][1]

    def batch_predict(self, model, queries):
        feats = np.array([q.vector() for _, q in queries], np.float32)
        labels = nb_ops.nb_predict(model, feats)
        return [(i, PredictedResult(float(y)))
                for (i, _), y in zip(queries, labels)]


@dataclass(frozen=True)
class LogisticRegressionParams(Params):
    steps: int = 200
    lr: float = 0.1
    reg: float = 1e-4


class LogisticRegressionAlgorithm(Algorithm):
    params_class = LogisticRegressionParams
    query_class = Query

    def train(self, ctx: RuntimeContext,
              pd: LabeledPoints) -> lr_ops.LogRegModel:
        p = self.params
        return lr_ops.logreg_train(pd.features, pd.label, steps=p.steps,
                                   lr=p.lr, reg=p.reg, mesh=ctx.mesh)

    def predict(self, model, query: Query) -> PredictedResult:
        return self.batch_predict(model, [(0, query)])[0][1]

    def batch_predict(self, model, queries):
        feats = np.array([q.vector() for _, q in queries], np.float32)
        labels = lr_ops.logreg_predict(model, feats)
        return [(i, PredictedResult(float(y)))
                for (i, _), y in zip(queries, labels)]


@dataclass(frozen=True)
class RandomForestParams(Params):
    """(RandomForestAlgorithmParams, RandomForestAlgorithm.scala:30-38:
    numClasses is inferred from the labels rather than declared)."""
    num_trees: int = 10
    max_depth: int = 5
    max_bins: int = 32
    impurity: str = "gini"
    feature_subset_strategy: str = "auto"
    seed: int = 0


class RandomForestAlgorithm(Algorithm):
    params_class = RandomForestParams
    query_class = Query

    def train(self, ctx: RuntimeContext,
              pd: LabeledPoints) -> forest_ops.ForestModel:
        p = self.params
        return forest_ops.forest_train(
            pd.features, pd.label, n_trees=p.num_trees,
            max_depth=p.max_depth, max_bins=p.max_bins,
            impurity=p.impurity,
            feature_subset_strategy=p.feature_subset_strategy, seed=p.seed,
            mesh=ctx.mesh)

    def predict(self, model, query: Query) -> PredictedResult:
        return self.batch_predict(model, [(0, query)])[0][1]

    def batch_predict(self, model, queries):
        feats = np.array([q.vector() for _, q in queries], np.float32)
        labels = model.predict(feats)
        return [(i, PredictedResult(float(y)))
                for (i, _), y in zip(queries, labels)]


class Accuracy(AverageMetric):
    """Fraction of correct predictions (the template's Precision
    evaluation generalized to all classes). Batch-vectorized: a fold is
    scored as one array comparison instead of a Python loop per (Q,P,A)
    tuple (SURVEY.md §7.6)."""

    def calculate_batch(self, qpa):
        n = len(qpa)
        pred = np.fromiter((p.label for _, p, _ in qpa), np.float64, n)
        act = np.fromiter((a.label for _, _, a in qpa), np.float64, n)
        return (pred == act).astype(np.float64)

    def calculate_one(self, q, p: PredictedResult, a: ActualResult) -> float:
        return 1.0 if p.label == a.label else 0.0


class ClassificationEngine(EngineFactory):
    @classmethod
    def apply(cls) -> Engine:
        return Engine(
            data_source=ClassificationDataSource,
            preparator=IdentityPreparator,
            algorithms={"naive": NaiveBayesAlgorithm, "": NaiveBayesAlgorithm,
                        "forest": RandomForestAlgorithm,
                        "logreg": LogisticRegressionAlgorithm},
            serving=FirstServing,
        )


def engine() -> Engine:
    return ClassificationEngine.apply()


register_engine("classification", ClassificationEngine)

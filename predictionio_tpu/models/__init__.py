"""Official engine templates — the workloads from the reference's
`examples/` tree, rebuilt on the TPU ops (SURVEY.md §2.6):

  recommendation.py    explicit ALS recommender with blacklist filtering
                       (`examples/scala-parallel-recommendation/`)
  similarproduct.py    implicit ALS + cooccurrence + like/dislike algos,
                       multi-algorithm engine
                       (`examples/scala-parallel-similarproduct/`)
  classification.py    NaiveBayes / LogisticRegression / RandomForest on
                       aggregated entity properties
                       (`examples/scala-parallel-classification/`)
  ecommerce.py         implicit ALS with serving-time constraint events,
                       popularity fallback
                       (`examples/scala-parallel-ecommercerecommendation/`)
  twotower.py          two-tower neural recommender (new capability)
  seqrec.py            sequential (next-item) transformer recommender
                       with ring-attention sequence parallelism
                       (new capability)

Each module exposes an `engine()` factory and registers it under a short
name with the workflow registry, so `engine.json` can reference either.
"""

"""E-commerce recommendation template: implicit ALS with serving-time
constraints and popularity fallback.

Parity target: `examples/scala-parallel-ecommercerecommendation/
adjust-score/src/main/scala/ECommAlgorithm.scala`
  - train: implicit ALS on view events + buy-count popularity
    (`train:90-160`, `trainDefault:214+`)
  - three-way predict (`predict:331-430`):
      known user  -> dot(user vector, item vectors)   (predictKnownUser:469)
      unknown user-> cosine to recently viewed items  (predictSimilar:539)
      no signal   -> popularity (buy counts)          (predictDefault:506)
  - serving-time event-store reads inside predict: the user's seen items
    (view/buy events) and the latest `$set` of constraint entity
    `unavailableItems` (`:331-430`) — the reference does per-request
    LEventStore reads with 200ms timeouts; here the same reads hit the
    local store synchronously
  - filters: categories, whiteList, blackList, seen, unavailable
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

import numpy as np

from predictionio_tpu.core import (
    Algorithm, DataSource, Engine, EngineFactory, FirstServing,
    IdentityPreparator, Params, RuntimeContext, register_engine,
)
from predictionio_tpu.data import store
from predictionio_tpu.ingest import BiMap, RatingColumns
from predictionio_tpu.ops import als
from predictionio_tpu.ops.topk import (
    NEG_INF, _next_pow2, topk_scores, topk_scores_filtered, topk_similar,
)


@dataclass(frozen=True)
class Query(Params):
    user: str = ""
    num: int = 10
    categories: Optional[Sequence[str]] = None
    whiteList: Optional[Sequence[str]] = None
    blackList: Optional[Sequence[str]] = None


@dataclass(frozen=True)
class ItemScore:
    item: str
    score: float


@dataclass(frozen=True)
class PredictedResult:
    itemScores: Sequence[ItemScore] = ()


@dataclass
class TrainingData:
    views: RatingColumns
    buys: RatingColumns
    item_categories: Dict[str, List[str]]


@dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = "default"
    channel: Optional[str] = None


class ECommDataSource(DataSource):
    params_class = DataSourceParams

    def read_training(self, ctx: RuntimeContext) -> TrainingData:
        p = self.params
        views = store.rating_columns(
            ctx.registry, p.app_name, p.channel,
            event_names=["view"], value_spec={"*": 1.0})
        # buys share the view BiMaps so popularity aligns with factors
        buys = store.rating_columns(
            ctx.registry, p.app_name, p.channel,
            event_names=["buy"], value_spec={"*": 1.0},
            users=views.users, items=views.items)
        cats: Dict[str, List[str]] = {}
        props = store.aggregate_properties(
            ctx.registry, p.app_name, channel_name=p.channel,
            entity_type="item")
        for item_id, pm in props.items():
            c = pm.get_opt("categories")
            if c:
                cats[item_id] = list(c)
        return TrainingData(views, buys, cats)


@dataclass
class ECommModel:
    user_factors: np.ndarray
    item_factors: np.ndarray
    users: BiMap
    items: BiMap
    popularity: np.ndarray          # [n_items] buy counts (trainDefault)
    item_categories: Dict[str, List[str]]

    def sanity_check(self):
        assert np.isfinite(self.user_factors).all()
        assert np.isfinite(self.item_factors).all()


@dataclass(frozen=True)
class ECommParams(Params):
    app_name: str = "default"
    channel: Optional[str] = None
    unseen_only: bool = True
    seen_events: Sequence[str] = ("view", "buy")
    similar_events: Sequence[str] = ("view",)
    num_recent_events: int = 10
    rank: int = 10
    num_iterations: int = 10
    lambda_: float = 0.01
    alpha: float = 1.0
    seed: Optional[int] = None
    # None = solver default; raise for large implicit problems where the
    # normal-equation CG needs more sweeps to converge (high alpha makes
    # the preference system stiff)
    cg_iters: Optional[int] = None


class ECommAlgorithm(Algorithm):
    params_class = ECommParams
    query_class = Query

    def train(self, ctx: RuntimeContext, pd: TrainingData) -> ECommModel:
        # the training context also serves direct train->predict use;
        # prepare_deploy rebinds a fresh one at deploy time
        self._serving_ctx = ctx
        p = self.params
        if pd.views.n == 0:
            raise ValueError("No view events found "
                             "(ECommAlgorithm.train require non-empty)")
        extra = {} if p.cg_iters is None else {"cg_iters": p.cg_iters}
        # timings= lands pack/solve/fetch phases AND solver_residual in
        # the run's phase report — the scale bench's convergence gate
        # reads the residual from there, so omitting this silently
        # disarms it (the r05 runs shipped a 2.58e-1 residual unnoticed)
        x, y = als.als_train(
            pd.views, rank=p.rank, iterations=p.num_iterations,
            reg=p.lambda_, implicit=True, alpha=p.alpha,
            seed=p.seed if p.seed is not None else 0, mesh=ctx.mesh,
            timings=ctx.phase_timings, **extra)
        pop = np.zeros(len(pd.views.items), np.float32)
        np.add.at(pop, pd.buys.item_ix, 1.0)
        return ECommModel(x, y, pd.views.users, pd.views.items, pop,
                          pd.item_categories)

    # -- serving-time store reads (ECommAlgorithm.scala:331-430) -----------
    def _seen_items(self, ctx: RuntimeContext, user: str) -> List[str]:
        p = self.params
        if not p.unseen_only:
            return []
        try:
            return [e.target_entity_id for e in store.find_by_entity(
                ctx.registry, p.app_name, channel_name=p.channel,
                entity_type="user", entity_id=user,
                event_names=list(p.seen_events))
                if e.target_entity_id]
        except store.AppNotFoundError:
            return []

    def _unavailable_items(self, ctx: RuntimeContext) -> List[str]:
        try:
            events = list(store.find_by_entity(
                ctx.registry, self.params.app_name,
                channel_name=self.params.channel,
                entity_type="constraint", entity_id="unavailableItems",
                event_names=["$set"], limit=1, latest_first=True))
        except store.AppNotFoundError:
            return []
        if not events:
            return []
        return list(events[0].properties.get_or_else("items", []))

    def _recent_items(self, ctx: RuntimeContext, user: str) -> List[str]:
        p = self.params
        try:
            return [e.target_entity_id for e in store.find_by_entity(
                ctx.registry, p.app_name, channel_name=p.channel,
                entity_type="user", entity_id=user,
                event_names=list(p.similar_events),
                limit=p.num_recent_events, latest_first=True)
                if e.target_entity_id]
        except store.AppNotFoundError:
            return []

    def _mask(self, ctx: RuntimeContext, model: ECommModel, query: Query,
              unavailable: Sequence[str]) -> np.ndarray:
        from predictionio_tpu.models.common import resolve_item_mask
        extra = [ix for it in unavailable
                 if (ix := model.items.get(it)) is not None]
        extra += [ix for it in self._seen_items(ctx, query.user)
                  if (ix := model.items.get(it)) is not None]
        return resolve_item_mask(
            model.items, model.item_categories, categories=query.categories,
            white_list=query.whiteList, black_list=query.blackList or (),
            extra_blacklist_ix=extra)

    def _ctx(self) -> RuntimeContext:
        ctx = getattr(self, "_serving_ctx", None)
        if ctx is None:
            raise RuntimeError(
                "ECommAlgorithm.predict needs a serving context for its "
                "event-store reads; train/deploy through the Engine "
                "workflow, or call with_serving_context(ctx) first")
        return ctx

    def predict(self, model: ECommModel, query: Query) -> PredictedResult:
        ctx = self._ctx()
        return self._predict_one(ctx, model, query,
                                 self._unavailable_items(ctx))

    def _predict_one(self, ctx: RuntimeContext, model: ECommModel,
                     query: Query,
                     unavailable: Sequence[str]) -> PredictedResult:
        mask = self._mask(ctx, model, query, unavailable)
        n_items = model.item_factors.shape[0]
        k = min(query.num, n_items)
        u_ix = model.users.get(query.user)
        if u_ix is not None and np.any(model.user_factors[u_ix]):
            scores, ixs = topk_scores(
                model.user_factors[u_ix][None, :].astype(np.float32),
                model.item_factors, mask, k=k)           # predictKnownUser
        else:
            recent = [ix for it in self._recent_items(ctx, query.user)
                      if (ix := model.items.get(it)) is not None]
            if recent:
                vec = model.item_factors[recent].mean(axis=0)
                scores, ixs = topk_similar(
                    vec[None, :].astype(np.float32),
                    model.item_factors, mask, k=k)       # predictSimilar
            else:
                scores, ixs = topk_scores(
                    np.ones((1, 1), np.float32),
                    model.popularity[:, None], mask, k=k)  # predictDefault
        scores, ixs = np.asarray(scores)[0], np.asarray(ixs)[0]
        items = [ItemScore(model.items.inverse(int(ix)), float(s))
                 for s, ix in zip(scores, ixs) if s > NEG_INF / 2]
        return PredictedResult(tuple(items))

    def warm_serving(self, model: ECommModel, buckets,
                     mesh=None) -> int:
        """Build the deploy-time serving plan: item factors pinned device
        resident, one AOT executable per batch bucket, banned width sized
        to the CURRENT unavailableItems constraint plus headroom for
        per-user seen/blackList indices. A configured serving mesh (or an
        over-capacity catalog) shards the factors row-wise
        (`ShardedBucketedTopK`); banned ids stay global either way."""
        from predictionio_tpu.ops.topk_sharded import serve_plan
        ctx = getattr(self, "_serving_ctx", None)
        n_unavail = len(self._unavailable_items(ctx)) if ctx else 0
        width = _next_pow2(max(256, n_unavail + 128))
        self._serve_plan = serve_plan(
            model.item_factors, k=Query().num, buckets=buckets,
            banned_width=width, mesh=mesh)
        return self._serve_plan.warm()

    def fold_in(self, model: ECommModel, delta, fctx) -> ECommModel:
        """Streaming fold-in: implicit-ALS half-steps over the rows the
        delta's VIEW events touched, plus a buy-count merge into the
        popularity fallback. The view re-scan derives the touched sets
        under this template's own spec — a buy of a never-viewed item
        is outside the factor model (train builds BiMaps from views)
        and must not force a full rebuild. Count-merged popularity may
        over-count events racing a full rebuild; the periodic full
        retrain remains ground truth."""
        from predictionio_tpu.streaming.updaters import (
            fold_als_items, fold_als_users,
        )
        p = self.params
        views = fctx.delta_columns(
            entity_type="user", event_names=["view"],
            value_spec={"*": 1.0}, require_target=True)
        pop = model.popularity.copy()
        buys = fctx.delta_columns(
            entity_type="user", event_names=["buy"],
            value_spec={"*": 1.0}, require_target=True)
        for tix in buys.target_ix:
            ix = model.items.get(buys.targets[int(tix)])
            if ix is not None:
                pop[ix] += 1.0
        if views.n == 0:
            if buys.n == 0:
                return None
            return ECommModel(model.user_factors, model.item_factors,
                              model.users, model.items, pop,
                              model.item_categories)

        def value_of(ev):
            return 1.0

        uf, users2, _ = fold_als_users(
            fctx, model.users, model.items, model.user_factors,
            model.item_factors, list(views.entities),
            event_names=["view"], value_of=value_of,
            dedup_last_wins=False, reg=p.lambda_, implicit=True,
            alpha=p.alpha)
        yf, _ = fold_als_items(
            fctx, users2, model.items, uf, model.item_factors,
            list(views.targets), event_names=["view"],
            value_of=value_of, dedup_last_wins=False, reg=p.lambda_,
            implicit=True, alpha=p.alpha)
        return ECommModel(uf, yf, users2, model.items, pop,
                          model.item_categories)

    def batch_predict(self, model, queries):
        """Batched serve path. Known-user queries without dense-mask
        needs (no categories/whiteList) coalesce into ONE banned-index
        top-k dispatch — through the deploy-warmed `BucketedTopK` plan
        (device-resident factors, bucket-padded static shape, zero
        recompiles) when the batch fits it, else the generic
        `topk_scores_filtered`. Everything else (unknown users, dense
        filters) falls back to the per-query three-way predict."""
        # the unavailableItems constraint read is shared across the batch
        ctx = self._ctx()
        unavailable = self._unavailable_items(ctx)
        unavail_ix = [ix for it in unavailable
                      if (ix := model.items.get(it)) is not None]
        n_items = model.item_factors.shape[0]
        batched = []    # (orig_i, query, user_ix, banned indices)
        out = []
        for i, q in queries:
            u_ix = model.users.get(q.user)
            if (q.categories is None and q.whiteList is None
                    and u_ix is not None
                    and np.any(model.user_factors[u_ix])):
                banned = list(unavail_ix)
                banned += [ix for it in self._seen_items(ctx, q.user)
                           if (ix := model.items.get(it)) is not None]
                banned += [ix for it in (q.blackList or ())
                           if (ix := model.items.get(it)) is not None]
                batched.append((i, q, u_ix, banned))
            else:
                out.append((i, self._predict_one(ctx, model, q,
                                                 unavailable)))
        if not batched:
            return out
        plan = getattr(self, "_serve_plan", None)

        def _fits_plan(q, banned) -> bool:
            return plan is not None and plan.fits(
                max_banned=len(banned), k=min(q.num, n_items))

        # PER-QUERY plan gating: one heavy user whose seen-history ban
        # list overflows the plan's banned_width must not demote the
        # whole coalesced batch to the generic (host-leaning) path —
        # that all-or-nothing gate is how the r05 scale runs served
        # hundreds of host calls and zero device batches. Only the
        # outlier queries go generic; the rest keep the warmed plan.
        fit = [r for r in batched if _fits_plan(r[1], r[3])]
        rest = [r for r in batched if not _fits_plan(r[1], r[3])]
        for rows, use_plan in ((fit, True), (rest, False)):
            if not rows:
                continue
            vecs = model.user_factors[
                np.array([u for _, _, u, _ in rows])].astype(np.float32)
            banned_lists = [b for _, _, _, b in rows]
            k = max(min(q.num, n_items) for _, q, _, _ in rows)
            if use_plan:
                scores, ixs = plan(vecs, banned_lists)
            else:
                scores, ixs = topk_scores_filtered(
                    vecs, model.item_factors, banned_lists, k=k)
            scores, ixs = np.asarray(scores), np.asarray(ixs)
            for row, (i, q, _, _) in enumerate(rows):
                items = []
                for s, ix in zip(scores[row], ixs[row]):
                    if s <= NEG_INF / 2 or len(items) >= q.num:
                        continue
                    items.append(ItemScore(model.items.inverse(int(ix)),
                                           float(s)))
                out.append((i, PredictedResult(tuple(items))))
        return out

    def with_serving_context(self, ctx: RuntimeContext) -> "ECommAlgorithm":
        self._serving_ctx = ctx
        return self


class ECommerceEngine(EngineFactory):
    @classmethod
    def apply(cls) -> Engine:
        return Engine(
            data_source=ECommDataSource,
            preparator=IdentityPreparator,
            algorithms={"ecomm": ECommAlgorithm, "": ECommAlgorithm},
            serving=FirstServing,
        )


def engine() -> Engine:
    return ECommerceEngine.apply()


register_engine("ecommerce", ECommerceEngine)

"""Recommendation template: explicit ALS with blacklist filtering.

Parity target: `examples/scala-parallel-recommendation/blacklist-items/`
  - DataSource reads `rate` and `buy` events, mapping buy -> rating 4.0
    (`DataSource.scala:43-72`), with k-fold `readEval`
    (`DataSource.scala:76-101`)
  - ALSAlgorithm wraps MLlib explicit ALS (`ALSAlgorithm.scala:51-93`);
    here `ops.als.als_train` — degree-bucketed batched-Cholesky ALS
  - predict = top-N with blacklist filter, empty result for unknown users
    (`ALSAlgorithm.scala:96-112`); batchPredict for eval (`:115-150`)
  - wire format: query `{"user": "1", "num": 4}` ->
    `{"itemScores": [{"item": "i", "score": s}]}`

Query batching is the TPU win: `batch_predict` scores a whole query batch
in one jit'd matmul+top_k, where the reference loops driver-side.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from predictionio_tpu.core import (
    Algorithm, DataSource, Engine, EngineFactory, FirstServing,
    IdentityPreparator, OptionAverageMetric, Params, RuntimeContext,
    register_engine,
)
from predictionio_tpu.data import store
from predictionio_tpu.ingest import RatingColumns
from predictionio_tpu.ops import als
from predictionio_tpu.ops.topk import (NEG_INF, topk_scores,
                                       topk_scores_filtered)


# -- queries and results (wire-format parity) -------------------------------

@dataclass(frozen=True)
class Query(Params):
    user: str
    num: int = 10
    blackList: Optional[Sequence[str]] = None
    whiteList: Optional[Sequence[str]] = None


@dataclass(frozen=True)
class ItemScore:
    item: str
    score: float


@dataclass(frozen=True)
class PredictedResult:
    itemScores: Sequence[ItemScore] = ()


@dataclass(frozen=True)
class ActualResult:
    """Test-fold ratings of the query's user (Evaluation.scala)."""
    ratings: Sequence[Tuple[str, float]] = ()


# -- data source ------------------------------------------------------------

@dataclass(frozen=True)
class EvalParams(Params):
    """(DataSourceEvalParams, DataSource.scala:30)"""
    k_fold: int = 3
    query_num: int = 10


@dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = "default"
    channel: Optional[str] = None
    buy_rating: float = 4.0
    eval_params: Optional[EvalParams] = None


class RecommendationDataSource(DataSource):
    params_class = DataSourceParams

    def _ratings(self, ctx: RuntimeContext) -> RatingColumns:
        p = self.params
        # columnar ingest path — same output as the Event iterator with
        # rating_of {rate -> properties.rating, buy -> buy_rating}
        # (DataSource.scala:61-66), but scanned without Event objects
        return store.rating_columns(
            ctx.registry, p.app_name, p.channel,
            event_names=["rate", "buy"],
            value_spec={"rate": ("prop", "rating"),
                        "buy": float(p.buy_rating)},
            dedup_last_wins=True)

    def read_training(self, ctx: RuntimeContext) -> RatingColumns:
        return self._ratings(ctx)

    def read_eval(self, ctx: RuntimeContext):
        """k-fold split by element index modulo (CrossValidation.scala:26-67
        splitData semantics; queries ask for each test-fold user)."""
        p = self.params
        if p.eval_params is None:
            raise ValueError("eval requires DataSourceParams.eval_params")
        rc = self._ratings(ctx)
        k = p.eval_params.k_fold
        folds = []
        idx = np.arange(rc.n)
        for fold in range(k):
            test_sel = idx % k == fold
            train = RatingColumns(
                rc.user_ix[~test_sel], rc.item_ix[~test_sel],
                rc.rating[~test_sel], rc.t_millis[~test_sel],
                rc.users, rc.items)
            qa: List[Tuple[Query, ActualResult]] = []
            test_users = np.unique(rc.user_ix[test_sel])
            for u in test_users:
                sel = test_sel & (rc.user_ix == u)
                ratings = [(rc.items.inverse(int(i)), float(r))
                           for i, r in zip(rc.item_ix[sel], rc.rating[sel])]
                qa.append((Query(user=rc.users.inverse(int(u)),
                                 num=p.eval_params.query_num),
                           ActualResult(tuple(ratings))))
            folds.append((train, f"fold{fold}", qa))
        return folds


# -- algorithm --------------------------------------------------------------

@dataclass(frozen=True)
class ALSAlgorithmParams(Params):
    rank: int = 10
    num_iterations: int = 10
    lambda_: float = 0.01
    seed: Optional[int] = None


class ALSAlgorithm(Algorithm):
    params_class = ALSAlgorithmParams
    query_class = Query

    def train(self, ctx: RuntimeContext, pd: RatingColumns) -> als.ALSModel:
        p = self.params
        if pd.n == 0:
            raise ValueError(
                "No rating events found; check appName and event import "
                "(parity: ALSAlgorithm.scala:56-61 require non-empty)")
        # timings= feeds solver phases + solver_residual into the phase
        # report, arming the bench's convergence gate
        x, y = als.als_train(
            pd, rank=p.rank, iterations=p.num_iterations, reg=p.lambda_,
            seed=p.seed if p.seed is not None else 0, mesh=ctx.mesh,
            timings=ctx.phase_timings)
        return als.ALSModel(x, y, pd.users, pd.items)

    def predict(self, model: als.ALSModel, query: Query) -> PredictedResult:
        return self.batch_predict(model, [(0, query)])[0][1]

    def warm_serving(self, model: als.ALSModel, buckets,
                     mesh=None) -> int:
        """Deploy warmup: pin item factors device-resident and AOT-compile
        the per-bucket banned-index executables (blackList queries are the
        common case; whiteList queries use the dense-mask path). With a
        configured serving mesh — or a catalog past one device's capacity
        — the plan shards the factors row-wise across the mesh
        (`ShardedBucketedTopK`)."""
        from predictionio_tpu.ops.topk_sharded import serve_plan
        self._serve_plan = serve_plan(
            model.item_factors, k=Query(user="").num, buckets=buckets,
            banned_width=64, mesh=mesh)
        return self._serve_plan.warm()

    def fold_in(self, model: als.ALSModel, delta, fctx) -> als.ALSModel:
        """Streaming fold-in: closed-form ALS half-steps over the
        delta's touched rows only — touched users re-solved against
        fixed item factors, then touched items against the updated user
        factors. Untouched rows stay bit-identical; the periodic full
        retrain remains ground truth (streaming/updaters.py)."""
        from predictionio_tpu.streaming.updaters import (
            fold_als_items, fold_als_users,
        )
        p = self.params
        buy_rating = float(fctx.ds_params.get("buy_rating", 4.0))
        # touched sets under THIS template's event spec — the generic
        # change scan covers every event type, and a user touched only
        # by a foreign event has an empty rating history (folding that
        # would zero a perfectly good row)
        rated = fctx.delta_columns(
            entity_type="user", event_names=["rate", "buy"],
            value_spec={"*": 1.0}, require_target=True)
        if rated.n == 0:
            return None

        def value_of(ev):
            if ev.event == "buy":
                return buy_rating
            return ev.properties.get_or_else("rating", None)

        uf, users2, _ = fold_als_users(
            fctx, model.users, model.items, model.user_factors,
            model.item_factors, list(rated.entities),
            event_names=["rate", "buy"], value_of=value_of,
            dedup_last_wins=True, reg=p.lambda_)
        yf, _ = fold_als_items(
            fctx, users2, model.items, uf, model.item_factors,
            list(rated.targets), event_names=["rate", "buy"],
            value_of=value_of, dedup_last_wins=True, reg=p.lambda_)
        return als.ALSModel(uf, yf, users2, model.items)

    def batch_predict(self, model: als.ALSModel,
                      queries: Sequence[Tuple[int, Query]]
                      ) -> List[Tuple[int, PredictedResult]]:
        """One jit'd matmul+top_k over the whole batch; unknown users get
        empty results (ALSAlgorithm.scala:96-112 semantics)."""
        known = [(i, q, model.users.get(q.user)) for i, q in queries]
        out: List[Tuple[int, PredictedResult]] = [
            (i, PredictedResult()) for i, _, u in known if u is None]
        live = [(i, q, u) for i, q, u in known if u is not None]
        if not live:
            return out
        n_items = model.item_factors.shape[0]
        k = max(min(q.num, n_items) for _, q, _ in live)
        vecs = model.user_factors[np.array([u for _, _, u in live])]
        if all(q.whiteList is None for _, q, _ in live):
            # no whitelists: blacklist filtering via the banned-index
            # device path — the filter is built ON DEVICE from index
            # lists, so big catalogs do not re-upload a dense mask per
            # batch (ops/topk.py topk_scores_filtered)
            banned = [
                [ix for ix in (model.items.get(b) for b in (q.blackList or ()))
                 if ix is not None]
                for _, q, _ in live]
            plan = getattr(self, "_serve_plan", None)
            if plan is not None and plan.fits(
                    max_banned=max(map(len, banned), default=0), k=k):
                scores, ixs = plan(vecs, banned)
            else:
                scores, ixs = topk_scores_filtered(
                    vecs, model.item_factors, banned, k=k)
        else:
            from predictionio_tpu.models.common import resolve_item_mask
            mask = np.concatenate(
                [resolve_item_mask(model.items, white_list=q.whiteList,
                                   black_list=q.blackList or ())
                 for _, q, _ in live], axis=0)
            scores, ixs = topk_scores(vecs, model.item_factors, mask, k=k)
        scores, ixs = np.asarray(scores), np.asarray(ixs)
        for row, (i, q, _) in enumerate(live):
            items = []
            for s, ix in zip(scores[row], ixs[row]):
                if s <= NEG_INF / 2 or len(items) >= q.num:
                    continue
                items.append(ItemScore(model.items.inverse(int(ix)),
                                       float(s)))
            out.append((i, PredictedResult(tuple(items))))
        return out


# -- evaluation metrics (Evaluation.scala of the template) ------------------

class PrecisionAtK(OptionAverageMetric):
    """Precision@K with a rating threshold: of the top-K recommended
    items, the fraction the user actually rated >= threshold; None (skip)
    when the user has no positively-rated items in the test fold
    (`examples/scala-parallel-recommendation/blacklist-items/src/main/scala/
    Evaluation.scala`)."""

    def __init__(self, k: int = 10, rating_threshold: float = 2.0):
        self.k = k
        self.rating_threshold = rating_threshold

    def header(self) -> str:
        return f"Precision@K (k={self.k}, threshold={self.rating_threshold})"

    def calculate_one(self, q: Query, p: PredictedResult,
                      a: ActualResult) -> Optional[float]:
        positives = {item for item, r in a.ratings
                     if r >= self.rating_threshold}
        if not positives:
            return None
        top = [s.item for s in p.itemScores[:self.k]]
        if not top:
            return 0.0
        hits = sum(1 for item in top if item in positives)
        # Denominator is min(k, |positives|) as in the reference metric —
        # NOT the number of returned recommendations.
        return hits / min(self.k, len(positives))


# -- engine -----------------------------------------------------------------

class RecommendationEngine(EngineFactory):
    @classmethod
    def apply(cls) -> Engine:
        return Engine(
            data_source=RecommendationDataSource,
            preparator=IdentityPreparator,
            algorithms={"als": ALSAlgorithm, "": ALSAlgorithm},
            serving=FirstServing,
        )


def engine() -> Engine:
    return RecommendationEngine.apply()


register_engine("recommendation", RecommendationEngine)

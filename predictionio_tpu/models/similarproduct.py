"""Similar-product template: implicit ALS + cooccurrence + like/dislike,
demonstrating a multi-algorithm engine.

Parity target: `examples/scala-parallel-similarproduct/
multi-events-multi-algos/`
  - DataSource reads `$set` item events (with `categories`) and `view` +
    `like`/`dislike` events (`DataSource.scala`)
  - ALSAlgorithm: MLlib implicit ALS on views (`ALSAlgorithm.scala:120`),
    query = set of liked items -> cosine-similar items, with category /
    whiteList / blackList filters and query items excluded
  - LikeAlgorithm: like=+1 / dislike=-1 implicit ALS
    (`LikeAlgorithm.scala:37-101`)
  - CooccurrenceAlgorithm: item-item cooccurrence counts
    (`CooccurrenceAlgorithm.scala:47-110`)
  - Serving averages scores per item across algorithms (`Serving.scala`)
  - wire: query `{"items": ["i1"], "num": 4}` ->
    `{"itemScores": [{"item": ..., "score": ...}]}`
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from predictionio_tpu.core import (
    Algorithm, DataSource, Engine, EngineFactory, IdentityPreparator,
    Params, RuntimeContext, Serving, register_engine,
)
from predictionio_tpu.data import store
from predictionio_tpu.ingest import BiMap, RatingColumns
from predictionio_tpu.ops import als
from predictionio_tpu.ops.cooccur import (
    CooccurrenceModel, top_cooccurrences_from_pairs,
)
from predictionio_tpu.ops.topk import NEG_INF, topk_similar


@dataclass(frozen=True)
class Query(Params):
    items: Sequence[str] = ()
    num: int = 10
    categories: Optional[Sequence[str]] = None
    whiteList: Optional[Sequence[str]] = None
    blackList: Optional[Sequence[str]] = None


@dataclass(frozen=True)
class ItemScore:
    item: str
    score: float


@dataclass(frozen=True)
class PredictedResult:
    itemScores: Sequence[ItemScore] = ()


@dataclass
class TrainingData:
    """views + likes + item categories (the template's TrainingData)."""
    views: RatingColumns
    likes: RatingColumns           # rating +1 like / -1 dislike
    item_categories: Dict[str, List[str]]


@dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = "default"
    channel: Optional[str] = None


class SimilarProductDataSource(DataSource):
    params_class = DataSourceParams

    def read_training(self, ctx: RuntimeContext) -> TrainingData:
        p = self.params
        views = store.rating_columns(
            ctx.registry, p.app_name, p.channel,
            event_names=["view"], value_spec={"*": 1.0})
        likes = store.rating_columns(
            ctx.registry, p.app_name, p.channel,
            event_names=["like", "dislike"],
            value_spec={"like": 1.0, "dislike": -1.0},
            dedup_last_wins=True)   # latest like/dislike wins (template doc)
        cats: Dict[str, List[str]] = {}
        props = store.aggregate_properties(
            ctx.registry, p.app_name, channel_name=p.channel,
            entity_type="item")
        for item_id, pm in props.items():
            c = pm.get_opt("categories")
            if c:
                cats[item_id] = list(c)
        return TrainingData(views, likes, cats)


def _resolve_filters(model_items: BiMap, item_categories,
                     query: Query) -> np.ndarray:
    """Allowed-item mask: categories/white/black lists + the query items
    themselves excluded (ALSAlgorithm.scala predict filters)."""
    from predictionio_tpu.models.common import resolve_item_mask
    query_ix = [ix for it in query.items
                if (ix := model_items.get(it)) is not None]
    return resolve_item_mask(
        model_items, item_categories, categories=query.categories,
        white_list=query.whiteList, black_list=query.blackList or (),
        extra_blacklist_ix=query_ix)


@dataclass
class SimilarModel:
    """Item factors + categories (the P2L productFeatures analog)."""
    item_factors: np.ndarray
    items: BiMap
    item_categories: Dict[str, List[str]]
    # user-side factors, kept since the streaming subsystem so fold-in
    # can run the item half-step against them; None on artifacts
    # trained before then (those force the full-scan path)
    user_factors: Optional[np.ndarray] = None
    users: Optional[BiMap] = None

    def sanity_check(self):
        assert np.isfinite(self.item_factors).all()


class _FactorSimilarityAlgorithm(Algorithm):
    """Shared predict: cosine top-k against the mean of query-item
    factors, one jit'd program per batch."""

    query_class = Query

    def predict(self, model: SimilarModel, query: Query) -> PredictedResult:
        return self.batch_predict(model, [(0, query)])[0][1]

    def warm_serving(self, model: SimilarModel, buckets,
                     mesh=None) -> int:
        """Deploy warmup: pin item factors device-resident and
        AOT-compile the per-bucket cosine-top-k executables, so the
        dense-mask serve path never consults the jit tracing cache.
        A configured serving mesh (or an over-capacity catalog) shards
        the factors row-wise (`ShardedBucketedSimilar`)."""
        from predictionio_tpu.ops.topk_sharded import similar_plan
        self._serve_plan = similar_plan(
            model.item_factors, k=Query().num, buckets=buckets,
            mesh=mesh)
        return self._serve_plan.warm()

    def batch_predict(self, model: SimilarModel,
                      queries: Sequence[Tuple[int, Query]]
                      ) -> List[Tuple[int, PredictedResult]]:
        out: List[Tuple[int, PredictedResult]] = []
        live = []
        for i, q in queries:
            ixs = [ix for it in q.items
                   if (ix := model.items.get(it)) is not None]
            if not ixs:   # no known query item -> empty (template logs warn)
                out.append((i, PredictedResult()))
            else:
                live.append((i, q, ixs))
        if not live:
            return out
        n_items = model.item_factors.shape[0]
        k = max(min(q.num, n_items) for _, q, _ in live)
        vecs = np.stack([model.item_factors[ixs].mean(axis=0)
                         for _, _, ixs in live])
        mask = np.concatenate(
            [_resolve_filters(model.items, model.item_categories, q)
             for _, q, _ in live], axis=0)
        plan = getattr(self, "_serve_plan", None)
        if plan is not None and plan.fits(k=k):
            scores, ixs = plan(vecs.astype(np.float32), mask)
        else:
            scores, ixs = topk_similar(vecs.astype(np.float32),
                                       model.item_factors, mask, k=k)
        scores, ixs = np.asarray(scores), np.asarray(ixs)
        for row, (i, q, _) in enumerate(live):
            items = [ItemScore(model.items.inverse(int(ix)), float(s))
                     for s, ix in zip(scores[row], ixs[row])
                     if s > NEG_INF / 2][:q.num]
            out.append((i, PredictedResult(tuple(items))))
        return out

    def _fold(self, model: SimilarModel, fctx, *, event_names,
              value_spec, value_of,
              dedup_last_wins) -> Optional[SimilarModel]:
        """Shared streaming fold: implicit-ALS half-steps over the rows
        this algorithm's delta events touched (user rows vs fixed item
        factors, then item rows vs the updated user factors). Artifacts
        trained before the streaming subsystem carry no user-side
        factors and fall back to the full-scan path."""
        from predictionio_tpu.data.storage.base import DeltaInvalidated
        from predictionio_tpu.streaming.updaters import (
            fold_als_items, fold_als_users,
        )
        if model.user_factors is None or model.users is None:
            raise DeltaInvalidated(
                "artifact predates streaming (no user-side factors); "
                "full rebuild required")
        p = self.params
        cols = fctx.delta_columns(
            entity_type="user", event_names=list(event_names),
            value_spec=value_spec, require_target=True)
        if cols.n == 0:
            return None
        uf, users2, _ = fold_als_users(
            fctx, model.users, model.items, model.user_factors,
            model.item_factors, list(cols.entities),
            event_names=event_names, value_of=value_of,
            dedup_last_wins=dedup_last_wins, reg=p.lambda_,
            implicit=True, alpha=p.alpha)
        yf, _ = fold_als_items(
            fctx, users2, model.items, uf, model.item_factors,
            list(cols.targets), event_names=event_names,
            value_of=value_of, dedup_last_wins=dedup_last_wins,
            reg=p.lambda_, implicit=True, alpha=p.alpha)
        return SimilarModel(yf, model.items, model.item_categories,
                            user_factors=uf, users=users2)


@dataclass(frozen=True)
class ALSParams(Params):
    rank: int = 10
    num_iterations: int = 10
    lambda_: float = 0.01
    alpha: float = 1.0
    seed: Optional[int] = None


class ALSAlgorithm(_FactorSimilarityAlgorithm):
    """Implicit ALS on view events (ALSAlgorithm.scala:120)."""

    params_class = ALSParams

    def train(self, ctx: RuntimeContext, pd: TrainingData) -> SimilarModel:
        p = self.params
        if pd.views.n == 0:
            raise ValueError("No view events found "
                             "(ALSAlgorithm.scala require non-empty)")
        x, y = als.als_train(
            pd.views, rank=p.rank, iterations=p.num_iterations,
            reg=p.lambda_, implicit=True, alpha=p.alpha,
            seed=p.seed if p.seed is not None else 0, mesh=ctx.mesh,
            timings=ctx.phase_timings)
        return SimilarModel(y, pd.views.items, pd.item_categories,
                            user_factors=x, users=pd.views.users)

    def fold_in(self, model: SimilarModel, delta,
                fctx) -> Optional[SimilarModel]:
        """Streaming fold-in on the delta's view events."""
        return self._fold(model, fctx, event_names=["view"],
                          value_spec={"*": 1.0},
                          value_of=lambda ev: 1.0,
                          dedup_last_wins=False)


class LikeAlgorithm(_FactorSimilarityAlgorithm):
    """Implicit ALS on like(+1)/dislike(-1) events
    (LikeAlgorithm.scala:37-101)."""

    params_class = ALSParams

    def train(self, ctx: RuntimeContext, pd: TrainingData) -> SimilarModel:
        p = self.params
        if pd.likes.n == 0:
            raise ValueError("No like/dislike events found")
        x, y = als.als_train(
            pd.likes, rank=p.rank, iterations=p.num_iterations,
            reg=p.lambda_, implicit=True, alpha=p.alpha,
            seed=p.seed if p.seed is not None else 0, mesh=ctx.mesh,
            timings=ctx.phase_timings)
        return SimilarModel(y, pd.likes.items, pd.item_categories,
                            user_factors=x, users=pd.likes.users)

    def fold_in(self, model: SimilarModel, delta,
                fctx) -> Optional[SimilarModel]:
        """Streaming fold-in on like/dislike events (latest wins,
        matching the training dedup)."""
        return self._fold(
            model, fctx, event_names=["like", "dislike"],
            value_spec={"like": 1.0, "dislike": -1.0},
            value_of=lambda ev: 1.0 if ev.event == "like" else -1.0,
            dedup_last_wins=True)


@dataclass(frozen=True)
class CooccurrenceParams(Params):
    n: int = 20   # cooccurrences kept per item
    # optional per-user distinct-item cap (Mahout --maxPrefsPerUser);
    # None = exact parity with the reference self-join
    max_items_per_user: Optional[int] = None


@dataclass
class CoocModel:
    top: CooccurrenceModel
    items: BiMap
    item_categories: Dict[str, List[str]]


class CooccurrenceAlgorithm(Algorithm):
    """(CooccurrenceAlgorithm.scala:47-110)"""

    params_class = CooccurrenceParams
    query_class = Query

    def train(self, ctx: RuntimeContext, pd: TrainingData) -> CoocModel:
        views = pd.views
        top = top_cooccurrences_from_pairs(
            views.user_ix, views.item_ix,
            len(views.users), len(views.items), self.params.n,
            max_items_per_user=self.params.max_items_per_user)
        return CoocModel(top, views.items, pd.item_categories)

    def fold_in(self, model: CoocModel, delta,
                fctx) -> Optional[CoocModel]:
        """Streaming count-merge fold: for each delta-touched user, an
        item is NEWLY connected when its full-history view count equals
        its delta view count (every view of it by that user is inside
        the delta), and each new item pairs once with the user's other
        distinct items — exactly the pairs the reference self-join
        would gain. Increments merge into the stored top-N lists via
        `ops.cooccur.merge_pair_counts` (its docstring states the
        truncation approximation; full retrain is ground truth)."""
        from predictionio_tpu.data.storage.base import DeltaInvalidated
        from predictionio_tpu.ops.cooccur import merge_pair_counts
        cols = fctx.delta_columns(
            entity_type="user", event_names=["view"],
            value_spec={"*": 1.0}, require_target=True)
        if cols.n == 0:
            return None
        delta_cnt: Dict[str, Dict[str, int]] = {}
        for eix, tix in zip(cols.entity_ix, cols.target_ix):
            u = cols.entities[int(eix)]
            it = cols.targets[int(tix)]
            d = delta_cnt.setdefault(u, {})
            d[it] = d.get(it, 0) + 1
        pairs: Dict[Tuple[int, int], float] = {}
        for u, dcnt in delta_cnt.items():
            full: Dict[int, int] = {}
            for ev in fctx.user_history(u, ["view"]):
                ix = model.items.get(ev.target_entity_id)
                if ix is None:
                    raise DeltaInvalidated(
                        f"user {u!r} viewed unknown item "
                        f"{ev.target_entity_id!r}; full rebuild "
                        "required")
                full[ix] = full.get(ix, 0) + 1
            new: List[int] = []
            for it, c in dcnt.items():
                ix = model.items.get(it)
                if ix is None:
                    raise DeltaInvalidated(
                        f"new item {it!r} in delta; full rebuild "
                        "required")
                if full.get(ix, 0) == c:
                    new.append(ix)
            new_set = set(new)
            old = [ix for ix in full if ix not in new_set]
            for ai, a in enumerate(new):
                for b in old + new[ai + 1:]:
                    key = (a, b) if a < b else (b, a)
                    pairs[key] = pairs.get(key, 0.0) + 1.0
        if not pairs:
            return None
        return CoocModel(merge_pair_counts(model.top, pairs),
                         model.items, model.item_categories)

    def predict(self, model: CoocModel, query: Query) -> PredictedResult:
        n_items = len(model.items)
        scores = np.zeros(n_items, np.float64)
        for it in query.items:
            ix = model.items.get(it)
            if ix is None:
                continue
            scores[model.top.top_items[ix]] += model.top.top_counts[ix]
        mask = _resolve_filters(model.items, model.item_categories, query)[0]
        scores[~mask] = -np.inf
        order = np.argsort(-scores)[:query.num]
        items = [ItemScore(model.items.inverse(int(ix)), float(scores[ix]))
                 for ix in order if np.isfinite(scores[ix]) and scores[ix] > 0]
        return PredictedResult(tuple(items))


class ScoreAverageServing(Serving):
    """Average the score per item across algorithms (Serving.scala of
    multi-events-multi-algos)."""

    def serve(self, query: Query,
              predictions: Sequence[PredictedResult]) -> PredictedResult:
        sums: Dict[str, float] = {}
        counts: Dict[str, int] = {}
        for p in predictions:
            for s in p.itemScores:
                sums[s.item] = sums.get(s.item, 0.0) + s.score
                counts[s.item] = counts.get(s.item, 0) + 1
        averaged = [ItemScore(item, sums[item] / counts[item])
                    for item in sums]
        averaged.sort(key=lambda s: -s.score)
        return PredictedResult(tuple(averaged[:query.num]))


class SimilarProductEngine(EngineFactory):
    @classmethod
    def apply(cls) -> Engine:
        return Engine(
            data_source=SimilarProductDataSource,
            preparator=IdentityPreparator,
            algorithms={"als": ALSAlgorithm, "": ALSAlgorithm,
                        "likealgo": LikeAlgorithm,
                        "cooccurrence": CooccurrenceAlgorithm},
            serving=ScoreAverageServing,
        )


def engine() -> Engine:
    return SimilarProductEngine.apply()


register_engine("similarproduct", SimilarProductEngine)

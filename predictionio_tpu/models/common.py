"""Shared serving helpers for the recommender templates."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from predictionio_tpu.ingest import BiMap
from predictionio_tpu.ops.topk import build_mask


def resolve_item_mask(items: BiMap,
                      item_categories: Optional[Dict[str, List[str]]] = None,
                      *,
                      categories: Optional[Sequence[str]] = None,
                      white_list: Optional[Sequence[str]] = None,
                      black_list: Sequence[str] = (),
                      extra_blacklist_ix: Sequence[int] = ()) -> np.ndarray:
    """One [1, n_items] allowed-mask from the standard template filters:
    whiteList / blackList (item ids; unknown ids ignored), extra blacklist
    indexes (seen/unavailable/query items), and a categories any-of filter
    over per-item category lists. Used by the recommendation,
    similarproduct, e-commerce, and two-tower templates."""
    n = len(items)
    white = None
    if white_list is not None:
        white = [ix for it in white_list if (ix := items.get(it)) is not None]
    black = [ix for it in black_list if (ix := items.get(it)) is not None]
    black += list(extra_blacklist_ix)
    mask = build_mask(n, blacklist_ix=black, whitelist_ix=white).copy()
    if categories is not None:
        want = set(categories)
        cat_ok = np.zeros(n, bool)
        for item_id, cats in (item_categories or {}).items():
            ix = items.get(item_id)
            if ix is not None and want & set(cats):
                cat_ok[ix] = True
        mask &= cat_ok[None, :]
    return mask

"""Shared serving helpers for the recommender templates."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from predictionio_tpu.ingest import BiMap
from predictionio_tpu.ops.topk import build_mask


def score_and_rank(vecs: np.ndarray, item_emb: np.ndarray,
                   items: BiMap, live: Sequence[tuple]):
    """The shared embedding-scoring tail of the neural recommenders
    (two-tower, seqrec): per-query masks from white/black lists, one
    masked top-k matmul over the catalog, ItemScore assembly. `live` is
    [(original_index, query, ...)] — only index and query are read.
    Returns [(original_index, PredictedResult)]."""
    from predictionio_tpu.models.recommendation import (
        ItemScore, PredictedResult,
    )
    from predictionio_tpu.ops.topk import NEG_INF, topk_scores

    n_items = item_emb.shape[0]
    k = max(min(entry[1].num, n_items) for entry in live)
    mask = np.concatenate(
        [resolve_item_mask(items, white_list=entry[1].whiteList,
                           black_list=entry[1].blackList or ())
         for entry in live], axis=0)
    scores, ixs = topk_scores(vecs.astype(np.float32), item_emb, mask,
                              k=k)
    scores, ixs = np.asarray(scores), np.asarray(ixs)
    out = []
    for row, entry in enumerate(live):
        i, q = entry[0], entry[1]
        found = [ItemScore(items.inverse(int(ix)), float(s))
                 for s, ix in zip(scores[row], ixs[row])
                 if s > NEG_INF / 2][:q.num]
        out.append((i, PredictedResult(tuple(found))))
    return out


def resolve_item_mask(items: BiMap,
                      item_categories: Optional[Dict[str, List[str]]] = None,
                      *,
                      categories: Optional[Sequence[str]] = None,
                      white_list: Optional[Sequence[str]] = None,
                      black_list: Sequence[str] = (),
                      extra_blacklist_ix: Sequence[int] = ()) -> np.ndarray:
    """One [1, n_items] allowed-mask from the standard template filters:
    whiteList / blackList (item ids; unknown ids ignored), extra blacklist
    indexes (seen/unavailable/query items), and a categories any-of filter
    over per-item category lists. Used by the recommendation,
    similarproduct, e-commerce, and two-tower templates."""
    n = len(items)
    white = None
    if white_list is not None:
        white = [ix for it in white_list if (ix := items.get(it)) is not None]
    black = [ix for it in black_list if (ix := items.get(it)) is not None]
    black += list(extra_blacklist_ix)
    mask = build_mask(n, blacklist_ix=black, whitelist_ix=white).copy()
    if categories is not None:
        want = set(categories)
        cat_ok = np.zeros(n, bool)
        for item_id, cats in (item_categories or {}).items():
            ix = items.get(item_id)
            if ix is not None and want & set(cats):
                cat_ok[ix] = True
        mask &= cat_ok[None, :]
    return mask

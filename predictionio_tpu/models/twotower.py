"""Two-tower neural recommender template (new capability).

No reference analog — this is the neural upgrade path from the ALS
templates (BASELINE.md config 5). Uses the same DataSource event shapes as
the recommendation template (view/rate/buy interactions) and the same
query/result wire format, so a user can swap `"engineFactory":
"recommendation"` for `"twotower"` in engine.json and retrain.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from predictionio_tpu.core import (
    Algorithm, DataSource, Engine, EngineFactory, FirstServing,
    IdentityPreparator, Params, RuntimeContext, register_engine,
)
from predictionio_tpu.data import store
from predictionio_tpu.ingest import BiMap, RatingColumns
from predictionio_tpu.models.recommendation import (
    PredictedResult, Query,
)
from predictionio_tpu.ops.twotower import TwoTowerModel, twotower_train


@dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = "default"
    channel: Optional[str] = None
    event_names: Sequence[str] = ("view", "rate", "buy")


class TwoTowerDataSource(DataSource):
    params_class = DataSourceParams

    def read_training(self, ctx: RuntimeContext) -> RatingColumns:
        p = self.params
        return store.rating_columns(
            ctx.registry, p.app_name, p.channel,
            event_names=list(p.event_names), value_spec={"*": 1.0})


@dataclass
class TwoTowerServingModel:
    net: TwoTowerModel
    users: BiMap
    items: BiMap

    def sanity_check(self):
        self.net.sanity_check()


@dataclass(frozen=True)
class TwoTowerParams(Params):
    emb_dim: int = 32
    hidden: int = 64
    out_dim: int = 32
    batch_size: int = 1024
    epochs: int = 10
    lr: float = 0.01
    temperature: float = 0.1
    seed: Optional[int] = None


class TwoTowerAlgorithm(Algorithm):
    params_class = TwoTowerParams
    query_class = Query

    def train(self, ctx: RuntimeContext,
              pd: RatingColumns) -> TwoTowerServingModel:
        p = self.params
        if pd.n == 0:
            raise ValueError("No interaction events found")
        net = twotower_train(
            pd.user_ix, pd.item_ix,
            n_users=len(pd.users), n_items=len(pd.items),
            emb_dim=p.emb_dim, hidden=p.hidden, out_dim=p.out_dim,
            batch_size=p.batch_size, epochs=p.epochs, lr=p.lr,
            temperature=p.temperature,
            seed=p.seed if p.seed is not None else 0, mesh=ctx.mesh)
        return TwoTowerServingModel(net, pd.users, pd.items)

    def fold_in(self, model: TwoTowerServingModel, delta,
                fctx) -> Optional[TwoTowerServingModel]:
        """Streaming fold-in: ONE warm-start epoch from the previous
        tower weights over the full interaction set (adam restarts
        fresh, so converged weights move only slightly — a mini-epoch,
        not a retrain). The full re-read is this hook's cost ceiling;
        the delta only gates whether it runs. New users or items change
        the embedding-table shapes and invalidate the delta; artifacts
        without raw weights (pre-streaming) do the same."""
        from predictionio_tpu.data.storage.base import DeltaInvalidated
        p = self.params
        ev_names = list(fctx.ds_params.get(
            "event_names", ("view", "rate", "buy")))
        cols = fctx.delta_columns(
            entity_type="user", event_names=ev_names,
            value_spec={"*": 1.0}, require_target=True)
        if cols.n == 0:
            return None
        if model.net.params is None:
            raise DeltaInvalidated(
                "artifact predates streaming (no raw tower weights); "
                "full rebuild required")
        full = fctx.store.scan_columns(
            fctx.app_id, fctx.channel_id, entity_type="user",
            event_names=ev_names, value_spec={"*": 1.0},
            require_target=True)
        u_of = np.array([model.users.get(e, -1) for e in full.entities],
                        np.int64)
        i_of = np.array([model.items.get(t, -1) for t in full.targets],
                        np.int64)
        if (u_of < 0).any() or (i_of < 0).any():
            raise DeltaInvalidated(
                "new users/items since train: embedding-table shapes "
                "are baked into the net; full rebuild required")
        net = twotower_train(
            u_of[full.entity_ix], i_of[full.target_ix],
            n_users=len(model.users), n_items=len(model.items),
            emb_dim=p.emb_dim, hidden=p.hidden, out_dim=p.out_dim,
            batch_size=p.batch_size, epochs=1, lr=p.lr,
            temperature=p.temperature,
            seed=p.seed if p.seed is not None else 0,
            mesh=fctx.mesh, init_params=model.net.params)
        return TwoTowerServingModel(net, model.users, model.items)

    def predict(self, model: TwoTowerServingModel,
                query: Query) -> PredictedResult:
        return self.batch_predict(model, [(0, query)])[0][1]

    def batch_predict(self, model: TwoTowerServingModel,
                      queries: Sequence[Tuple[int, Query]]
                      ) -> List[Tuple[int, PredictedResult]]:
        out: List[Tuple[int, PredictedResult]] = []
        live = []
        for i, q in queries:
            u = model.users.get(q.user)
            if u is None:
                out.append((i, PredictedResult()))
            else:
                live.append((i, q, u))
        if not live:
            return out
        vecs = model.net.user_emb[np.array([u for _, _, u in live])]
        from predictionio_tpu.models.common import score_and_rank
        out.extend(score_and_rank(vecs, model.net.item_emb,
                                  model.items, live))
        return out


class TwoTowerEngine(EngineFactory):
    @classmethod
    def apply(cls) -> Engine:
        return Engine(
            data_source=TwoTowerDataSource,
            preparator=IdentityPreparator,
            algorithms={"twotower": TwoTowerAlgorithm, "": TwoTowerAlgorithm},
            serving=FirstServing,
        )


def engine() -> Engine:
    return TwoTowerEngine.apply()


register_engine("twotower", TwoTowerEngine)

"""predictionio_tpu — a TPU-native machine learning server framework.

A from-scratch rebuild of the capabilities of Apache PredictionIO
(incubating): a REST event server over a pluggable store, a DASE engine
abstraction (DataSource -> Preparator -> Algorithm(s) -> Serving) with typed
JSON parameters, a CLI (train / deploy / eval / batchpredict / app and
access-key management), model persistence with an engine-instance registry,
a deployable REST prediction server, and a metric-driven evaluation
workflow — with all numerical compute expressed as JAX/XLA programs sharded
over TPU meshes instead of Spark/MLlib jobs.

Layer map (mirrors reference layers, see SURVEY.md §1):
  data/      event model, storage SPI + drivers, event REST server
  ingest/    events -> dense sharded jax.Array columns (the RDD replacement)
  core/      DASE abstractions, Engine, workflow, evaluation, persistence
  ops/       XLA/Pallas numerical kernels (ALS, NB, logreg, cooccurrence...)
  parallel/  mesh construction, named shardings, collectives
  models/    official engine templates (recommendation, similarproduct, ...)
  serving/   prediction REST server
  cli/       the `pio`-equivalent command line tool
  e2/        reusable engine/evaluation helpers
"""

__version__ = "0.1.0"

BUILD_COORDINATES = {
    "name": "predictionio_tpu",
    "version": __version__,
    "reference": "apache/incubator-predictionio 0.11.1-SNAPSHOT",
}

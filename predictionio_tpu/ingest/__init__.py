"""Ingestion: event streams -> dense, sharded jax.Array columns.

This package is the framework's replacement for the reference's
`PEvents.find(...): RDD[Event]` + per-template RDD pipelines
(`data/.../storage/PEvents.scala:80-103`). Instead of a lazy distributed
collection of JVM objects, the data currency is a set of dense numpy/JAX
columns with static, bucket-padded shapes, plus `BiMap`s bridging string
entity IDs to dense indexes (reference `data/.../storage/BiMap.scala`).

Typical flow (the analog of a template's DataSource):
    events  = store.find(app_id, event_names=["rate", "buy"])
    ratings = RatingColumns.from_events(events, rating_of=...)
    dev     = ratings.shard(mesh)   # padded + device_put over the mesh
"""

from predictionio_tpu.ingest.bimap import BiMap  # noqa: F401
from predictionio_tpu.ingest.arrays import (  # noqa: F401
    RatingColumns,
    PairColumns,
    LabeledPoints,
    labeled_points_from_properties,
)
from predictionio_tpu.ingest.pipeline import (  # noqa: F401
    pair_columns_from_store,
    rating_columns_from_store,
    take_phase_timings,
)

"""BiMap: serializable bidirectional string<->dense-index mapping.

Parity target: `data/.../storage/BiMap.scala:28-135` — the universal bridge
every ALS template uses to turn entity IDs into contiguous matrix indexes
(`stringInt`/`stringLong` built via `zipWithUniqueId`). Unlike the
reference's nondeterministic RDD numbering, indexes here are assigned in
first-seen order, so a BiMap built from the same event stream is
deterministic — which keeps checkpoints and evals reproducible.
"""

from __future__ import annotations

import json
from typing import Dict, Iterable, Iterator, List, Optional


class BiMap:
    """Immutable bidirectional map str -> dense int index [0, n)."""

    __slots__ = ("_fwd", "_inv")

    def __init__(self, forward: Dict[str, int]):
        self._fwd = dict(forward)
        self._inv: Optional[List[str]] = None

    @staticmethod
    def from_keys(keys: Iterable[str]) -> "BiMap":
        """Dense indexes in first-seen order (BiMap.stringInt analog)."""
        fwd: Dict[str, int] = {}
        for k in keys:
            if k not in fwd:
                fwd[k] = len(fwd)
        return BiMap(fwd)

    def __len__(self) -> int:
        return len(self._fwd)

    def __contains__(self, key: str) -> bool:
        return key in self._fwd

    def __iter__(self) -> Iterator[str]:
        return iter(self._fwd)

    def __call__(self, key: str) -> int:
        """Apply; KeyError on unknown key (BiMap.apply)."""
        return self._fwd[key]

    def get(self, key: str, default: Optional[int] = None) -> Optional[int]:
        return self._fwd.get(key, default)

    def inverse(self, index: int) -> str:
        """Index -> original key (BiMap.inverse)."""
        inv = self._inverse_list()
        return inv[index]

    def _inverse_list(self) -> List[str]:
        if self._inv is None:
            inv = [""] * len(self._fwd)
            for k, i in self._fwd.items():
                inv[i] = k
            self._inv = inv
        return self._inv

    def keys(self) -> List[str]:
        return list(self._fwd.keys())

    def to_dict(self) -> Dict[str, int]:
        return dict(self._fwd)

    # -- serialization (checkpointed alongside model arrays) ---------------
    def to_json(self) -> str:
        return json.dumps(self._inverse_list())

    @staticmethod
    def from_json(s: str) -> "BiMap":
        inv = json.loads(s)
        return BiMap({k: i for i, k in enumerate(inv)})

    def __eq__(self, other: object) -> bool:
        return isinstance(other, BiMap) and self._fwd == other._fwd

    def __repr__(self) -> str:
        return f"BiMap(n={len(self._fwd)})"

"""Column-block wire protocol for the disaggregated ingest service.

The data currency between `pio-tpu ingestd` and its consumers is the
**column block**: one bounded row-range slice of a finished
`EventColumns` (entity/target int32 indexes, float32 values, int64
event times) plus the *incremental* string-table entries that first
appear inside that range. Because `EventColumns` tables are in
first-seen order over the time-sorted row stream, slicing rows in
order makes the tables grow monotonically — a consumer that appends
each block's `ent_new`/`tgt_new` and fills each row range reassembles
the server's columns bit-for-bit, while holding at most one block of
transfer state above the final arrays.

Framing reuses the PR-3 checksummed envelope (`data.integrity.wrap`,
CRC32 flavor): every block is a self-contained length-prefixed blob
`magic | algo | u64 length | digest | payload`, where the payload is
one JSON header line + the raw little-endian column bytes. A torn or
bit-flipped block fails `integrity.unwrap` and the consumer re-fetches
the same sequence number (resume-from-offset) instead of restarting
the scan.

Import-light on purpose (stdlib + numpy + `data.integrity` +
`data.storage.columns`): both the service and the consumer-side client
pull this in, and neither side may drag jax into spawn workers.
"""

from __future__ import annotations

import hashlib
import json
from datetime import datetime, timedelta, timezone
from typing import Dict, List, Optional, Tuple

import numpy as np

from predictionio_tpu.data import integrity
from predictionio_tpu.data.storage import columns as C
from predictionio_tpu.data.storage.base import _UNSET

PROTO_FORMAT = 1

_EPOCH = datetime(1970, 1, 1, tzinfo=timezone.utc)
_ONE_US = timedelta(microseconds=1)

# (name, numpy dtype) of the four row-aligned columns, wire order
COLUMN_LAYOUT: Tuple[Tuple[str, str], ...] = (
    ("entity_ix", "<i4"), ("target_ix", "<i4"),
    ("value", "<f4"), ("t_us", "<i8"))


class BlockProtocolError(ValueError):
    """The peer sent a structurally valid blob with the wrong contents
    (sequence mismatch, table-base mismatch, unknown format) — a
    protocol bug or a cross-scan mixup, NOT a transport corruption
    (that is `integrity.CorruptBlobError` and retryable)."""


def us_of(t: Optional[datetime]) -> Optional[int]:
    """Exact epoch-µs of a datetime (naive = UTC), matching the
    storage layer's `_event_us` so filters survive the wire exactly."""
    if t is None:
        return None
    if t.tzinfo is None:
        t = t.replace(tzinfo=timezone.utc)
    return (t - _EPOCH) // _ONE_US


def dt_of(us: Optional[int]) -> Optional[datetime]:
    if us is None:
        return None
    return _EPOCH + timedelta(microseconds=int(us))


# -- scan spec ----------------------------------------------------------------

def encode_spec(app_id: int, channel_id: Optional[int], *,
                start_time: Optional[datetime] = None,
                until_time: Optional[datetime] = None,
                entity_type: Optional[str] = None,
                entity_id: Optional[str] = None,
                event_names=None,
                target_entity_type: object = _UNSET,
                target_entity_id: object = _UNSET,
                properties: Optional[Dict[str, object]] = None,
                value_spec=None, require_target: bool = True,
                since: Optional[Dict[str, int]] = None,
                upto: Optional[Dict[str, int]] = None) -> dict:
    """`scan_columns` kwargs -> the JSON-safe wire spec. Target
    filters use the `encode_target` three-state tuples so the
    `_UNSET`-vs-None distinction survives serialization."""
    spec = C.normalize_value_spec(value_spec)
    return {
        "format": PROTO_FORMAT, "app": int(app_id),
        "channel": None if channel_id is None else int(channel_id),
        "start_us": us_of(start_time), "until_us": us_of(until_time),
        "entity_type": entity_type, "entity_id": entity_id,
        "event_names": sorted(event_names) if event_names else None,
        "tet": list(C.encode_target(target_entity_type, _UNSET)),
        "tei": list(C.encode_target(target_entity_id, _UNSET)),
        "properties": properties if properties else None,
        "value_spec": {k: list(v) for k, v in spec.items()},
        "require_target": bool(require_target),
        "since": since, "upto": upto,
    }


def _decode_target(enc) -> object:
    enc = tuple(enc)
    if enc == C.TGT_UNSET:
        return _UNSET
    if enc == C.TGT_NONE:
        return None
    if len(enc) == 2 and enc[0] == "str":
        return enc[1]
    raise BlockProtocolError(f"bad target filter encoding: {enc!r}")


def decode_spec(spec: dict) -> Tuple[int, Optional[int], dict]:
    """Wire spec -> (app_id, channel_id, scan_columns kwargs)."""
    if spec.get("format") != PROTO_FORMAT:
        raise BlockProtocolError(
            f"unsupported spec format {spec.get('format')!r}")
    vs = {k: tuple(v) for k, v in (spec.get("value_spec") or {}).items()}
    kwargs = dict(
        start_time=dt_of(spec.get("start_us")),
        until_time=dt_of(spec.get("until_us")),
        entity_type=spec.get("entity_type"),
        entity_id=spec.get("entity_id"),
        event_names=spec.get("event_names"),
        target_entity_type=_decode_target(spec.get("tet", C.TGT_UNSET)),
        target_entity_id=_decode_target(spec.get("tei", C.TGT_UNSET)),
        properties=spec.get("properties"),
        value_spec=C.normalize_value_spec(vs) if vs else None,
        require_target=bool(spec.get("require_target", True)),
        since=spec.get("since"), upto=spec.get("upto"),
    )
    channel = spec.get("channel")
    return int(spec["app"]), (None if channel is None else int(channel)), \
        kwargs


def spec_key(spec: dict, watermark: Optional[Dict[str, int]]) -> str:
    """Canonical coalescing key: one shared scan per (filter-spec,
    watermark) pair."""
    blob = json.dumps({"spec": spec, "wm": watermark}, sort_keys=True,
                      separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:32]


# -- block codec --------------------------------------------------------------

def encode_block(scan_id: str, seq: int, cols: C.EventColumns,
                 lo: int, hi: int, ent_base: int, ent_hi: int,
                 tgt_base: int, tgt_hi: int) -> bytes:
    """One CRC-framed column block for rows [lo, hi): the four array
    slices plus the table entries whose first occurrence falls in the
    range ([ent_base, ent_hi) / [tgt_base, tgt_hi))."""
    arrays = (cols.entity_ix[lo:hi], cols.target_ix[lo:hi],
              cols.value[lo:hi], cols.t_us[lo:hi])
    header = {
        "format": PROTO_FORMAT, "scan": scan_id, "seq": int(seq),
        "lo": int(lo), "rows": int(hi - lo),
        "ent_base": int(ent_base), "tgt_base": int(tgt_base),
        "ent_new": cols.entities[ent_base:ent_hi],
        "tgt_new": cols.targets[tgt_base:tgt_hi],
        "arrays": [[name, dt, int(a.shape[0])]
                   for (name, dt), a in zip(COLUMN_LAYOUT, arrays)],
    }
    payload = json.dumps(header, separators=(",", ":")).encode() + b"\n" + \
        b"".join(np.ascontiguousarray(a.astype(dt, copy=False)).tobytes()
                 for (_n, dt), a in zip(COLUMN_LAYOUT, arrays))
    return integrity.wrap(payload, algo=integrity.ALGO_CRC32)


def decode_block(blob: bytes) -> Tuple[dict, Dict[str, np.ndarray]]:
    """-> (header, arrays). Raises `integrity.CorruptBlobError` on a
    torn/corrupt frame (retry the same seq), `BlockProtocolError` on a
    well-formed frame with impossible contents (do not retry)."""
    payload = integrity.unwrap(blob)
    try:
        nl = payload.index(b"\n")
        header = json.loads(payload[:nl].decode())
    except (ValueError, UnicodeDecodeError) as e:
        raise BlockProtocolError(f"unparseable block header: {e}")
    if header.get("format") != PROTO_FORMAT:
        raise BlockProtocolError(
            f"unsupported block format {header.get('format')!r}")
    arrays: Dict[str, np.ndarray] = {}
    off = nl + 1
    for name, dtype, n in header.get("arrays", ()):
        dt = np.dtype(dtype)
        end = off + dt.itemsize * int(n)
        a = np.frombuffer(payload[off:end], dtype=dt)
        if a.shape[0] != n:
            raise BlockProtocolError(f"column {name!r} truncated "
                                     f"({a.shape[0]}/{n} rows)")
        arrays[name] = a
        off = end
    return header, arrays


class BlockAssembler:
    """Consumer-side reassembly: preallocate the final arrays from the
    announced row count, fill each block's row range in place, and
    extend the string tables incrementally. Peak transfer state above
    the finished columns is ONE decoded block."""

    def __init__(self, scan_id: str, rows: int):
        self.scan_id = scan_id
        self.rows = int(rows)
        self.next_seq = 0
        self._filled = 0
        self._ent: List[str] = []
        self._tgt: List[str] = []
        self._cols = {name: np.empty(self.rows, np.dtype(dt))
                      for name, dt in COLUMN_LAYOUT}

    def add(self, header: dict, arrays: Dict[str, np.ndarray]) -> None:
        if header.get("scan") != self.scan_id:
            raise BlockProtocolError(
                f"block for scan {header.get('scan')!r}, "
                f"expected {self.scan_id!r}")
        if header.get("seq") != self.next_seq:
            raise BlockProtocolError(
                f"block seq {header.get('seq')} out of order "
                f"(expected {self.next_seq})")
        if header.get("ent_base") != len(self._ent) or \
                header.get("tgt_base") != len(self._tgt):
            raise BlockProtocolError("table base mismatch (blocks from "
                                     "two different scan generations)")
        lo, n = int(header["lo"]), int(header["rows"])
        if lo != self._filled or lo + n > self.rows:
            raise BlockProtocolError(
                f"row range [{lo},{lo + n}) breaks the stream at "
                f"{self._filled}/{self.rows}")
        for name, _dt in COLUMN_LAYOUT:
            a = arrays.get(name)
            if a is None or a.shape[0] != n:
                raise BlockProtocolError(f"column {name!r} missing")
            self._cols[name][lo:lo + n] = a
        self._ent.extend(header.get("ent_new", ()))
        self._tgt.extend(header.get("tgt_new", ()))
        self._filled += n
        self.next_seq += 1

    @property
    def complete(self) -> bool:
        return self._filled == self.rows

    def columns(self) -> C.EventColumns:
        if not self.complete:
            raise BlockProtocolError(
                f"stream incomplete: {self._filled}/{self.rows} rows")
        return C.EventColumns(
            self._cols["entity_ix"], self._cols["target_ix"],
            self._cols["value"], self._cols["t_us"],
            self._ent, self._tgt)
